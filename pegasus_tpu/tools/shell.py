"""pegasus_tpu shell — data access + table administration CLI.

Parity: the reference's interactive shell (src/shell/main.cpp:874, 87
commands in commands.h) and the Go admin-cli/pegic split. One binary
serves both roles here:

    python -m pegasus_tpu.tools.shell --root /data/onebox <command> ...

Run with no command for the interactive REPL (`use <table>` scopes data
verbs, parity: the linenoise REPL + `use`). Command families:
  table mgmt : create_app, drop_app, recall_app, rename, ls, app,
               get/set_replica_count
  data       : set, get, del, exist, ttl, incr, multi_set, multi_get,
               multi_get_range, multi_get_sortkeys, multi_del,
               multi_del_range, check_and_set, check_and_mutate, count,
               scan, hash_scan, full_scan, copy_data, clear_data,
               count_data, hash
  envs       : set/get/del/clear_app_envs
  ops        : manual_compact, partition_split, start_split, flush,
               flush_log, backup, restore, start/query_backup,
               restore_app, *_backup_policy, start/query/pause/restart/
               cancel/clear_bulk_load, add/query/remove/pause/start_dup,
               set_dup_fail_mode, dup_stats, dup_failover [--status]
  cluster    : cluster_info, nodes, server_info, server_stat, app_stat,
               app_disk, ddd_diagnose, propose, rebalance, offline_node,
               get/set_meta_level, detect_hotkey, remote_command,
               slow_queries, metrics, storage_stats, disk_health,
               scrub, hot_partitions, compact_sched
  tracing    : trace <id> (fan out + stitch one cross-node span tree),
               traces --slow (tail-kept slow trace roots, one meta call)
  query-perf : explain <table> <op-spec> (execute one captured op,
               render the plan tree with actual per-stage counters),
               explain --from-trace <id> (same report off a kept slow
               trace's span perf tags), workload <table> (op mix /
               batch + value sizes / scan selectivity / hot share),
               placement [workload] (offload verdict + cost-model
               drift audit)
  offline    : sst_dump, mlog_dump, local_get, rdb_key_str2hex,
               rdb_key_hex2str, rdb_value_hex2str

Bytes arguments accept UTF-8 strings.
"""

from __future__ import annotations

import argparse
import json
import sys


def _b(s: str) -> bytes:
    return s.encode()


_ESCAPE_ALL = False  # REPL `escape_all` setting (parity: shell escape_all)


def _s(b: bytes) -> str:
    """Render bytes for output: UTF-8 with replacement, or fully
    C-escaped when the REPL's escape_all setting is on (parity:
    c_escape_sensitive_string in base/pegasus_utils.h)."""
    if _ESCAPE_ALL:
        return "".join(chr(c) if 32 <= c < 127 else "\\x%02x" % c
                       for c in b)
    return b.decode(errors="replace")


# reference verb spellings -> canonical verbs (argparse keeps the ALIAS
# in args.cmd, so dispatch normalizes through this map)
_CANONICAL = {
    "create": "create_app", "drop": "drop_app", "recall": "recall_app",
    "balance": "rebalance", "query_bulk_load_status": "query_bulk_load",
    "local_partition_split": "partition_split",
}


def _isolate_cpu() -> None:
    """Admin/data CLI work never needs the accelerator: force the CPU
    backend BEFORE any jax init so the shell neither dials a TPU tunnel
    (this image's axon plugin dials even under JAX_PLATFORMS=cpu) nor
    claims a chip another process is using. PEGASUS_SHELL_DEVICE=accel
    opts back in."""
    import os

    if os.environ.get("PEGASUS_SHELL_DEVICE") == "accel":
        return
    try:
        from pegasus_tpu.utils.cpu_isolation import force_cpu

        force_cpu()
    except Exception:  # noqa: BLE001 - jax-free verbs still work
        pass


def main(argv=None) -> int:
    _isolate_cpu()
    parser = argparse.ArgumentParser(prog="pegasus-shell",
                                     description=__doc__)
    parser.add_argument("--root", default=None,
                        help="in-process onebox catalog root directory")
    parser.add_argument("--cluster", default=None,
                        help="multi-process onebox directory (wire mode: "
                             "commands go over TCP through meta and the "
                             "replica servers)")
    parser.add_argument("-i", "--interactive", action="store_true",
                        help="force the REPL even when stdin is not a "
                             "tty (the REPL also starts when no command "
                             "is given on an interactive terminal)")
    sub = parser.add_subparsers(dest="cmd", required=False)

    p = sub.add_parser("create_app", aliases=["create"])
    p.add_argument("name")
    p.add_argument("-p", "--partition_count", type=int, default=8)
    p = sub.add_parser("drop_app", aliases=["drop"])
    p.add_argument("name")
    sub.add_parser("ls")
    p = sub.add_parser("app")
    p.add_argument("name")

    for cmd in ("set", "get", "del", "exist", "ttl"):
        p = sub.add_parser(cmd)
        p.add_argument("table")
        p.add_argument("hash_key")
        p.add_argument("sort_key")
        if cmd == "set":
            p.add_argument("value")
            p.add_argument("--ttl", type=int, default=0)
    p = sub.add_parser("incr")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("sort_key")
    p.add_argument("increment", type=int)
    p = sub.add_parser("multi_set")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("kvs", nargs="+", help="sortkey=value pairs")
    p = sub.add_parser("multi_get")
    p.add_argument("table")
    p.add_argument("hash_key")
    p = sub.add_parser("count")
    p.add_argument("table")
    p.add_argument("hash_key")
    p = sub.add_parser("scan")
    p.add_argument("table")
    p.add_argument("--hash_prefix", default="")
    p.add_argument("--max", type=int, default=100)
    # extended data surface (parity: shell data commands, commands.h)
    p = sub.add_parser("check_and_set")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("check_sort_key")
    p.add_argument("check_type", help="not_exist|exist|match_prefix|"
                                      "match_anywhere|match_postfix|"
                                      "bytes_less|bytes_equal|...")
    p.add_argument("check_operand")
    p.add_argument("set_sort_key")
    p.add_argument("set_value")
    p.add_argument("--ttl", type=int, default=0)
    p = sub.add_parser("check_and_mutate")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("check_sort_key")
    p.add_argument("check_type")
    p.add_argument("check_operand")
    p.add_argument("mutations", nargs="+",
                   help="sortkey=value (put; empty value allowed) or "
                        "del:sortkey (delete)")
    p = sub.add_parser("multi_del")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("sort_keys", nargs="+")
    p = sub.add_parser("multi_del_range")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("--start", default="")
    p.add_argument("--stop", default="")
    p = sub.add_parser("multi_get_range")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("--start", default="")
    p.add_argument("--stop", default="")
    p.add_argument("--max", type=int, default=100)
    p = sub.add_parser("multi_get_sortkeys")
    p.add_argument("table")
    p.add_argument("hash_key")
    p = sub.add_parser("hash_scan")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("--start", default="")
    p.add_argument("--stop", default="")
    p.add_argument("--max", type=int, default=100)
    p = sub.add_parser("full_scan")
    p.add_argument("table")
    p.add_argument("--max", type=int, default=100)
    p = sub.add_parser("copy_data")
    p.add_argument("src_table")
    p.add_argument("dst_table")
    p.add_argument("--max", type=int, default=0,
                   help="0 = everything")
    p = sub.add_parser("clear_data")
    p.add_argument("table")
    p.add_argument("--force", action="store_true",
                   help="required: deletes every record")
    p = sub.add_parser("count_data")
    p.add_argument("table")
    p = sub.add_parser("hash")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("sort_key")
    p = sub.add_parser("local_get")
    p.add_argument("path", help="a replica's sst dir (offline read)")
    p.add_argument("hash_key")
    p.add_argument("sort_key")
    p = sub.add_parser("rdb_key_str2hex")
    p.add_argument("hash_key")
    p.add_argument("sort_key")
    p = sub.add_parser("rdb_key_hex2str")
    p.add_argument("hex_key")
    p = sub.add_parser("rdb_value_hex2str")
    p.add_argument("hex_value")

    p = sub.add_parser("set_app_envs")
    p.add_argument("table")
    p.add_argument("envs", nargs="+", help="key=value pairs")
    p = sub.add_parser("get_app_envs")
    p.add_argument("table")
    p = sub.add_parser("manual_compact")
    p.add_argument("table")
    p = sub.add_parser("partition_split", aliases=["local_partition_split"])
    p.add_argument("table")
    p = sub.add_parser("flush")
    p.add_argument("table")
    p = sub.add_parser("metrics")
    p.add_argument("--entity_type", default=None)
    p = sub.add_parser("backup")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p.add_argument("--backup_id", type=int, required=True)
    p = sub.add_parser("restore")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p.add_argument("--backup_id", type=int, required=True)
    p.add_argument("--new_name", default=None)

    # meta-orchestrated ops (wire mode; parity: the shell's backup/dup/
    # split/bulk-load admin verbs over ddl_client)
    p = sub.add_parser("start_backup")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p = sub.add_parser("query_backup")
    p.add_argument("backup_id", type=int)
    p = sub.add_parser("restore_app")
    p.add_argument("new_name")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p.add_argument("--backup_id", type=int, required=True)
    p = sub.add_parser("start_bulk_load")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--staged_app", default=None)
    p = sub.add_parser("query_bulk_load", aliases=["query_bulk_load_status"])
    p.add_argument("table")
    p = sub.add_parser("add_dup")
    p.add_argument("table")
    p.add_argument("follower_app")
    p.add_argument("--follower_meta", default="meta")
    p = sub.add_parser("query_dup")
    p.add_argument("table")
    p = sub.add_parser("remove_dup")
    p.add_argument("dupid", type=int)
    p = sub.add_parser("start_split")
    p.add_argument("table")
    p = sub.add_parser("query_split")
    p.add_argument("table")
    p = sub.add_parser("nodes")
    p = sub.add_parser("hot_partitions")
    p.add_argument("table", nargs="?", default="",
                   help="one table, or the whole cluster when omitted")
    sub.add_parser("compact_sched",
                   help="the meta compaction coordinator's stagger "
                        "state: granted/waiting nodes + per-node "
                        "demand reports")
    p = sub.add_parser("rebalance", aliases=["balance"])
    p = sub.add_parser("offline_node")
    p.add_argument("node", help="drain all primaries off this node")
    # offline debugging (parity: shell sst_dump / mlog_dump and
    # src/tools/mutation_log_tool.*) — read files directly, no cluster
    p = sub.add_parser("sst_dump")
    p.add_argument("path", help="one .sst file or a replica sst dir")
    p.add_argument("--max", type=int, default=20)
    p = sub.add_parser("mlog_dump")
    p.add_argument("path", help="a replica's plog file (mlog.bin)")
    p.add_argument("--max", type=int, default=20)
    p = sub.add_parser("remote_command")
    p.add_argument("node", help="node name (meta / node0 / ...)")
    p.add_argument("verb", help="registered verb ('help' lists them)")
    p.add_argument("cmd_args", nargs="*")
    p = sub.add_parser("slow_queries")
    p.add_argument("node")
    # distributed tracing: one-command cross-node stitching
    p = sub.add_parser("trace",
                       help="fan trace-dump out to every node, stitch "
                            "the spans into one tree, render the "
                            "timeline with per-hop skew bounds")
    p.add_argument("trace_id")
    p.add_argument("--json", action="store_true",
                   help="print the stitched tree as JSON instead of "
                        "the rendered timeline")
    p = sub.add_parser("traces",
                       help="list recent tail-kept slow trace roots "
                            "(one meta call; nodes report them on "
                            "config-sync)")
    p.add_argument("--slow", action="store_true",
                   help="kept slow traces only (the default view)")
    p.add_argument("--limit", type=int, default=16)
    # cluster flight recorder: watchdog status + incident timelines
    p = sub.add_parser("health",
                       help="cluster health: damped per-node/per-table "
                            "status + firing watchdog rules (one meta "
                            "call off the config-sync digests)")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser("timeline",
                       help="one-command incident report for a node or "
                            "table: flight-recorder ring slices, typed "
                            "health events, and kept slow traces "
                            "stitched into one rendered timeline")
    p.add_argument("target", help="node name or table name")
    p.add_argument("--window", default="5m",
                   help="lookback window, e.g. 90s / 5m / 1h")
    p.add_argument("--json", action="store_true",
                   help="print the raw bundle instead of the rendering")
    # query-level observability: one-command EXPLAIN + workload shapes
    p = sub.add_parser(
        "explain",
        help="execute ONE captured op and render its plan tree with "
             "actual per-stage counters and timings (PerfContext), or "
             "--from-trace to rebuild the report from a kept slow "
             "trace's span perf tags")
    p.add_argument("table", nargs="?", default=None)
    p.add_argument("spec", nargs="*",
                   help="op spec: get <hash_key> [sort_key] | "
                        "multi_get <hash_key> <sk> [sk...] | "
                        "scan [hash_key] [batch_size]")
    p.add_argument("--from-trace", dest="from_trace", default=None,
                   help="rebuild the explain report from this trace id "
                        "instead of executing an op")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser(
        "workload",
        help="per-table workload shape profile: op mix, batch/value "
             "size distributions, scan selectivity, hot-hashkey share "
             "(one meta call off the config-sync digests)")
    p.add_argument("table")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser(
        "tenants",
        help="per-tenant QoS view: weights, CU budgets and bucket "
             "levels, consumed CU, shed/over-budget counts, brownout "
             "state (one meta call off the config-sync tenant blocks)")
    p.add_argument("--json", action="store_true")
    p = sub.add_parser(
        "placement",
        help="the offload pays/doesn't-pay verdict "
             "(ops/placement.offload_breakdown) + the live cost-model "
             "drift audit, per node")
    p.add_argument("workload", nargs="?", default="rules",
                   help="workload class: ttl|probe|rules|match")
    p.add_argument("--bytes", type=int, default=1 << 20,
                   help="batch size for the breakdown estimate")
    p.add_argument("--windows", type=int, default=0,
                   help="model the compaction block at this many "
                        "filter windows (0 = default pipeline "
                        "geometry)")
    p.add_argument("--node", default=None,
                   help="one node (wire mode); default = first node")
    # cluster/node admin breadth (parity: shell admin commands)
    sub.add_parser("cluster_info")
    p = sub.add_parser("server_info")
    p.add_argument("node", nargs="?", default=None,
                   help="one node, or all when omitted")
    p = sub.add_parser("server_stat")
    p.add_argument("node", nargs="?", default=None)
    p = sub.add_parser("storage_stats")
    p.add_argument("table",
                   help="dump cache/bloom/phash/codec counters per "
                        "partition (block codec, compression ratio, "
                        "decode and encoded-probe counts, resident "
                        "index memory bloom-vs-phash split)")
    p = sub.add_parser("disk_health")
    p.add_argument("node", nargs="?", default=None,
                   help="one node, or all replica nodes when omitted")
    p = sub.add_parser("scrub")
    p.add_argument("table")
    p.add_argument("--status", action="store_true",
                   help="report background-scrub progress/last-result "
                        "only (no trigger)")
    p = sub.add_parser("app_stat")
    p.add_argument("table")
    p = sub.add_parser("app_disk")
    p.add_argument("table")
    sub.add_parser("ddd_diagnose")
    p = sub.add_parser("detect_hotkey")
    p.add_argument("node")
    p.add_argument("action", choices=["start", "query", "stop"])
    p.add_argument("app_id", type=int)
    p.add_argument("pidx", type=int)
    p.add_argument("kind", choices=["read", "write"])
    sub.add_parser("get_meta_level")
    p = sub.add_parser("set_meta_level")
    p.add_argument("level", choices=["freezed", "steady", "lively"])
    p = sub.add_parser("get_replica_count")
    p.add_argument("table")
    p = sub.add_parser("set_replica_count")
    p.add_argument("table")
    p.add_argument("count", type=int)
    p = sub.add_parser("propose")
    p.add_argument("table")
    p.add_argument("pidx", type=int)
    p.add_argument("action",
                   choices=["assign_primary", "add_secondary",
                            "downgrade"])
    p.add_argument("node")
    p.add_argument("--force", action="store_true")
    p = sub.add_parser("recall_app", aliases=["recall"])
    p.add_argument("table")
    p = sub.add_parser("rename")
    p.add_argument("old_name")
    p.add_argument("new_name")
    p = sub.add_parser("del_app_envs")
    p.add_argument("table")
    p.add_argument("keys", nargs="+")
    p = sub.add_parser("clear_app_envs")
    p.add_argument("table")
    p.add_argument("--prefix", default="")
    p = sub.add_parser("add_backup_policy")
    p.add_argument("name")
    p.add_argument("--tables", nargs="+", required=True)
    p.add_argument("--bucket", required=True)
    p.add_argument("--interval", type=int, default=86400)
    p.add_argument("--history", type=int, default=3)
    sub.add_parser("ls_backup_policy")
    p = sub.add_parser("query_backup_policy")
    p.add_argument("name")
    p = sub.add_parser("modify_backup_policy")
    p.add_argument("name")
    p.add_argument("--add_tables", nargs="*", default=None)
    p.add_argument("--remove_tables", nargs="*", default=None)
    p.add_argument("--interval", type=int, default=None)
    p.add_argument("--history", type=int, default=None)
    p = sub.add_parser("enable_backup_policy")
    p.add_argument("name")
    p = sub.add_parser("disable_backup_policy")
    p.add_argument("name")
    p = sub.add_parser("pause_dup")
    p.add_argument("dupid", type=int)
    p = sub.add_parser("start_dup")
    p.add_argument("dupid", type=int)
    p = sub.add_parser("set_dup_fail_mode")
    p.add_argument("dupid", type=int)
    p.add_argument("fail_mode", choices=["slow", "skip"])
    p = sub.add_parser("pause_bulk_load")
    p.add_argument("table")
    p = sub.add_parser("restart_bulk_load")
    p.add_argument("table")
    p = sub.add_parser("cancel_bulk_load")
    p.add_argument("table")
    p = sub.add_parser("clear_bulk_load")
    p.add_argument("table")
    p = sub.add_parser("flush_log")
    p.add_argument("node")
    sub.add_parser("dups")
    p = sub.add_parser("dup_stats",
                       help="cluster-wide duplication health: per-dup "
                            "lag (decrees+ms), inflight decree, "
                            "fail_mode, shipped bytes, last error")
    p.add_argument("table", nargs="?", default="")
    p = sub.add_parser("dup_failover",
                       help="controlled failover drill: fence the "
                            "source table (writes get retryable "
                            "ERR_DUP_FENCED), drain confirmed decrees, "
                            "flip the follower writable")
    p.add_argument("table")
    p.add_argument("--status", action="store_true",
                   help="report the in-flight drill instead of "
                        "starting one")
    sub.add_parser("recover")
    p = sub.add_parser("query_restore_status")
    p.add_argument("table", nargs="?", default="")
    for cmd in ("enable_atomic_idempotent", "disable_atomic_idempotent",
                "get_atomic_idempotent"):
        p = sub.add_parser(cmd)
        p.add_argument("table")

    args = parser.parse_args(argv)
    args.cmd = _CANONICAL.get(args.cmd, args.cmd)

    if args.cmd in ("sst_dump", "mlog_dump", "local_get"):
        return _offline_dump(args, sys.stdout)
    if args.cmd in ("rdb_key_str2hex", "rdb_key_hex2str",
                    "rdb_value_hex2str"):
        return _dispatch(args, None, sys.stdout)  # pure codec tools
    if (args.root is None) == (args.cluster is None):
        print("error: exactly one of --root / --cluster is required",
              file=sys.stderr)
        return 2
    if args.cluster is not None:
        box = _ClusterBox(args.cluster)
    else:
        from pegasus_tpu.tools.onebox import Onebox

        box = Onebox(args.root)
    from pegasus_tpu.utils.errors import (
        PegasusError,
        StorageCorruptionError,
    )

    out = sys.stdout
    try:
        if args.cmd is None:
            if not (args.interactive or sys.stdin.isatty()):
                # a script that lost its verb must fail loudly, not
                # hang on (or EOF out of) an accidental REPL
                print("error: no command given and stdin is not a tty "
                      "(pass -i to force the REPL)", file=sys.stderr)
                return 2
            return _repl(parser, box, out)
        return _dispatch(args, box, out)
    except AttributeError as exc:
        print(f"error: {exc} (this command may need wire mode: "
              f"--cluster)", file=sys.stderr)
        return 1
    except (KeyError, ValueError, NotImplementedError,
            PegasusError, StorageCorruptionError) as exc:
        # StorageCorruptionError: the offline dump tools exist to poke
        # at exactly the corrupt files that raise it — report, don't
        # traceback
        print(f"error: {exc}", file=sys.stderr)
        return 1
    finally:
        box.close()


# data verbs that take the current table as their first argument when a
# `use <table>` is active in the REPL (parity: the shell's use/cc model)
_TABLE_VERBS = frozenset({
    "set", "get", "del", "exist", "ttl", "incr", "multi_set",
    "multi_get", "count", "scan", "check_and_set", "check_and_mutate",
    "multi_del", "multi_del_range", "multi_get_range",
    "multi_get_sortkeys", "hash_scan", "full_scan", "count_data",
    "clear_data", "hash", "set_app_envs", "get_app_envs",
    "manual_compact", "partition_split", "flush", "app_stat",
    "app_disk", "scrub", "get_replica_count", "explain", "workload",
    "enable_atomic_idempotent",
    "disable_atomic_idempotent", "get_atomic_idempotent",
})


def _repl(parser, box, out) -> int:
    """Interactive mode (parity: the shell's linenoise REPL,
    src/shell/main.cpp:874): `use <table>` scopes data commands,
    `help` lists verbs, `exit`/`quit` leaves. Errors never kill the
    session."""
    import shlex

    from pegasus_tpu.utils.errors import (
        PegasusError,
        StorageCorruptionError,
    )

    import pegasus_tpu

    current_table = None
    print(f"pegasus_tpu shell {pegasus_tpu.__version__} — 'help' for "
          f"commands, 'exit' to leave", file=out)
    while True:
        try:
            prompt = f"{current_table or ''}> "
            line = input(prompt)
        except EOFError:
            return 0
        except KeyboardInterrupt:
            print(file=out)
            continue
        try:
            words = shlex.split(line)
        except ValueError as exc:
            print(f"error: {exc}", file=out)
            continue
        if not words:
            continue
        verb = _CANONICAL.get(words[0], words[0])
        words[0] = verb
        if verb in ("exit", "quit"):
            return 0
        if verb == "use":
            if len(words) != 2:
                print("usage: use <table>", file=out)
                continue
            current_table = words[1]
            print(f"OK: using {current_table}", file=out)
            continue
        if verb == "version":
            print(pegasus_tpu.__version__, file=out)
            continue
        if verb == "mycluster":
            print(getattr(box, "root", None) or getattr(box, "path", "?"),
                  file=out)
            continue
        if verb == "cc":
            # switch cluster (parity: shell cc — change cluster): point
            # the session at another onebox catalog / cluster dir
            if len(words) != 2:
                print("usage: cc <onebox-dir>", file=out)
                continue
            try:
                new_box = type(box)(words[1])
            except Exception as exc:  # noqa: BLE001 - operator feedback
                print(f"error: {exc}", file=out)
                continue
            box.close()
            box = new_box
            current_table = None
            print(f"OK: now on {words[1]}", file=out)
            continue
        if verb == "timeout":
            # REPL setting (parity: shell `timeout`): admin RPC deadline
            if len(words) == 1:
                print(f"{getattr(box, 'admin_timeout', 15.0)}s", file=out)
                continue
            try:
                box.admin_timeout = float(words[1])
            except ValueError:
                print("usage: timeout [seconds]", file=out)
                continue
            print("OK", file=out)
            continue
        if verb == "escape_all":
            # REPL setting (parity: shell escape_all): escape every
            # non-printable byte in printed values, not just invalid
            # UTF-8
            global _ESCAPE_ALL
            if len(words) == 2 and words[1] in ("true", "false"):
                _ESCAPE_ALL = words[1] == "true"
            print("escape_all: %s" % str(_ESCAPE_ALL).lower(), file=out)
            continue
        if verb == "help":
            choices = parser._subparsers._group_actions[0].choices
            print("  ".join(sorted(choices)) +
                  "\n  plus: use <table>, cc <dir>, mycluster, timeout, "
                  "escape_all, version, exit", file=out)
            continue
        if verb in _TABLE_VERBS and current_table is not None:
            words = [verb, current_table] + words[1:]
        try:
            cmd_args = parser.parse_args(words)
            cmd_args.cmd = _CANONICAL.get(cmd_args.cmd, cmd_args.cmd)
        except SystemExit:
            continue  # argparse already printed the usage error
        try:
            if verb in ("sst_dump", "mlog_dump", "local_get"):
                _offline_dump(cmd_args, out)
            else:
                _dispatch(cmd_args, box, out)
        except AttributeError as exc:
            print(f"error: {exc} (this command may need wire mode: "
                  f"--cluster)", file=out)
        except (KeyError, ValueError, NotImplementedError,
                PegasusError, StorageCorruptionError) as exc:
            print(f"error: {exc}", file=out)


def _offline_dump(args, out) -> int:
    import os

    from pegasus_tpu.base.key_schema import restore_key
    from pegasus_tpu.base.value_schema import (
        extract_expire_ts,
        extract_user_data,
    )

    with _offline_key_zone(args.path, out):
        return _offline_dump_body(args, out, restore_key,
                                  extract_user_data)


def _offline_key_zone(path, out):
    """Offline forensics on an ENCRYPTED cluster's files: walk up from
    the dump target to the server data root (the dir holding
    .pegasus_data_key), unwrap it with the operator's exported
    PEGASUS_KMS_ROOT_KEY(_FILE), and register a temporary zone so the
    dump reads plaintext. Without the root key the dump fails with the
    actual reason instead of showing ciphertext as an empty log."""
    import contextlib
    import os

    from pegasus_tpu.security.kms import (
        KEY_FILE, KeyProvider, LocalKmsClient, root_key_from_env)
    from pegasus_tpu.storage import efile

    @contextlib.contextmanager
    def zone():
        probe = os.path.abspath(path)
        key_root = None
        while True:
            parent = (probe if os.path.isdir(probe)
                      else os.path.dirname(probe))
            if os.path.exists(os.path.join(parent, KEY_FILE)):
                key_root = parent
                break
            up = os.path.dirname(parent)
            if up == parent:
                break
            probe = up
        if key_root is None:
            yield  # plaintext cluster: nothing to do
            return
        root = root_key_from_env()
        if root is None:
            raise SystemExit(
                f"{key_root} holds encrypted data "
                f"({KEY_FILE} present) — export PEGASUS_KMS_ROOT_KEY "
                "or PEGASUS_KMS_ROOT_KEY_FILE to dump it")
        efile.enable_encryption(
            key_root, KeyProvider(key_root, LocalKmsClient(root)))
        try:
            yield
        finally:
            efile.disable_encryption(key_root)

    return zone()


def _offline_dump_body(args, out, restore_key, extract_user_data) -> int:
    import os

    if args.cmd == "local_get":
        # parity: shell local_get — read one key straight from a replica's
        # sst files, newest first (no running cluster needed)
        from pegasus_tpu.base.key_schema import generate_key
        from pegasus_tpu.storage.sstable import SSTable

        key = generate_key(args.hash_key.encode(),
                           args.sort_key.encode())

        def newest_first(name):
            # files are "l<level>-<seq>.sst": lower level = newer data,
            # higher seq = newer within a level
            level, _, seq = name[:-4].partition("-")
            try:
                return (int(level.lstrip("l")), -int(seq))
            except ValueError:
                return (99, 0)

        paths = [os.path.join(args.path, n)
                 for n in sorted((n for n in os.listdir(args.path)
                                  if n.endswith(".sst")),
                                 key=newest_first)]
        for path in paths:
            t = SSTable(path)
            hit = t.get(key)
            t.close()
            if hit is None:
                continue
            value, ets = hit
            if value is None:
                print("DELETED (tombstone)", file=out)
                return 1
            data = extract_user_data(1, value)
            print(f"{_s(data)} (ets={ets}, "
                  f"from {os.path.basename(path)})", file=out)
            return 0
        print("not found", file=out)
        return 1
    if args.cmd == "sst_dump":
        from pegasus_tpu.storage.sstable import SSTable

        paths = ([args.path] if args.path.endswith(".sst") else sorted(
            os.path.join(args.path, n) for n in os.listdir(args.path)
            if n.endswith(".sst")))
        shown = 0
        for path in paths:
            t = SSTable(path)
            print(f"# {path}: {t.total_count} records, "
                  f"{len(t.blocks)} blocks, meta={t.meta}", file=out)
            for key, value, ets in t.iterate():
                if shown >= args.max:
                    break
                hk, sk = restore_key(key)
                if value is None:
                    print(f"  DEL {hk!r} : {sk!r}", file=out)
                else:
                    data = extract_user_data(1, value)
                    print(f"  {hk!r} : {sk!r} => {data!r} "
                          f"(ets={ets})", file=out)
                shown += 1
            t.close()
            if shown >= args.max:
                break
        return 0
    # mlog_dump
    from pegasus_tpu.replica.mutation_log import MutationLog

    shown = 0
    for mu in MutationLog.replay(args.path):
        if shown >= args.max:
            break
        ops = ", ".join(f"op{wo.op}" for wo in mu.ops)
        print(f"decree={mu.decree} ballot={mu.ballot} "
              f"last_committed={mu.last_committed} "
              f"ts_us={mu.timestamp_us} ops=[{ops}]", file=out)
        shown += 1
    print(f"# {shown} mutation(s) shown", file=out)
    return 0


class _ClusterBox:
    """Adapter: the shell's verbs over the wire clients (parity: the
    reference shell drives ddl_client + client_lib RPCs, never local
    state)."""

    def __init__(self, directory: str) -> None:
        from pegasus_tpu.tools.onebox_cluster import OneboxAdmin

        self.directory = directory
        self.admin = OneboxAdmin(directory)
        self._clients = {}

    def client(self, app_name: str):
        c = self._clients.get(app_name)
        if c is None:
            from pegasus_tpu.tools.onebox_cluster import connect

            c = connect(app_name, self.directory)
            self._clients[app_name] = c
        return c

    def create_table(self, name: str, partition_count: int):
        return self.admin.create_table(name, partition_count)

    def drop_table(self, name: str) -> None:
        self.admin.call("drop_app", app_name=name)

    def list_tables(self):
        return [{"app_id": a["app_id"], "name": a["app_name"],
                 "partition_count": a["partition_count"]}
                for a in self.admin.call("list_apps")]

    def update_app_envs(self, name: str, envs) -> None:
        self.admin.call("update_app_envs", app_name=name, envs=envs)

    def manual_compact_table(self, name: str) -> None:
        """Remote manual compaction: set the one-shot trigger env; every
        replica compacts when config-sync delivers it (parity: the shell
        writing MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY,
        pegasus_manual_compact_service.cpp)."""
        import time as _time

        self.update_app_envs(name, {
            "manual_compact.once.trigger_time": str(int(_time.time()))})

    def remote_command(self, node: str, verb: str, cmd_args):
        """Invoke a registered control verb on one node (parity: shell
        remote_command over RPC_CLI_CLI_CALL) — the poll protocol lives
        on OneboxAdmin (the chaos harness shares it); this surfaces its
        failures in the shell's ValueError error space."""
        from pegasus_tpu.utils.errors import PegasusError

        try:
            return self.admin.remote_command(node, verb, cmd_args)
        except PegasusError as e:
            raise ValueError(str(e))

    def open_table(self, name: str):
        raise NotImplementedError(
            "this command needs local table access — use --root mode, or "
            "the admin verbs in wire mode")

    def split_table(self, name: str):
        raise NotImplementedError(
            "online split over the wire lands with the meta split service")

    def close(self) -> None:
        for c in self._clients.values():
            c.net.close()
        self.admin.close()


_CHECK_TYPES = {
    "no_check": 0, "not_exist": 1, "not_exist_or_empty": 2, "exist": 3,
    "not_empty": 4, "match_anywhere": 5, "match_prefix": 6,
    "match_postfix": 7, "bytes_less": 8, "bytes_less_or_equal": 9,
    "bytes_equal": 10, "bytes_greater_or_equal": 11, "bytes_greater": 12,
    "int_less": 13, "int_less_or_equal": 14, "int_equal": 15,
    "int_greater_or_equal": 16, "int_greater": 17,
}


def _check_type(name: str) -> int:
    try:
        return _CHECK_TYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown check type {name!r}; one of "
            f"{', '.join(_CHECK_TYPES)}") from None


def _full_scan_records(box, table, limit, with_ttl=False):
    """Iterate every record of a table via unordered scanners (parity:
    full_scan's total-order seek across partitions). Yields
    (hk, sk, value) — or (hk, sk, value, expire_ts) with `with_ttl`.
    Open server scan contexts are closed even on early exit."""
    from pegasus_tpu.client import ScanOptions

    c = box.client(table)
    opts = ScanOptions(batch_size=500, return_expire_ts=with_ttl)
    n = 0
    for sc in c.get_unordered_scanners(4, opts):
        try:
            while True:
                try:
                    rec = sc.next_record() if with_ttl else next(sc)
                except StopIteration:
                    break
                yield rec
                n += 1
                if limit and n >= limit:
                    return
        finally:
            sc.close()


def _build_timeline(box, target: str, window_s: float) -> dict:
    """Assemble ONE incident bundle for a node or table: the meta's
    damped status + event ledger, the implicated flight-recorder ring
    slices fetched from the reporting nodes via `timeseries-dump`, and
    the tail-kept slow-trace roots from the config-sync trace reports.
    The time window anchors on the newest evidence (node clocks, not
    the shell's), so it renders correctly over sim and wall clocks."""
    nodes = box.admin.call("list_nodes")
    status = box.admin.call("cluster_health")
    if target in nodes or target in status.get("nodes", {}):
        node, table = target, None
        events = box.admin.call("health_events", node=target, limit=256)
        tstat = status["nodes"].get(target, {}).get("status", "?")
    else:
        apps = {a["app_name"]: str(a["app_id"])
                for a in box.admin.call("list_apps")}
        app_id = apps.get(target)
        if app_id is None:
            raise ValueError(
                f"{target!r} is neither a live node nor a table")
        node, table = None, app_id
        events = box.admin.call("health_events", table=app_id, limit=256)
        tstat = status.get("tables", {}).get(app_id,
                                             {}).get("status", "ok")
    # ring slices: every series the events implicate, fetched from the
    # node that reported it; a node timeline adds the pressure pair so
    # a quiet incident still shows its load context
    wanted = {(ev.get("node"), tuple(ev["entity"]), ev["metric"])
              for ev in events if ev.get("node")}
    if node is not None:
        wanted.add((node, ("rpc", node), "read_shed_count"))
        wanted.add((node, ("rpc", node), "deadline_expired_count"))
    series = []
    for n, (et, ei), metric in sorted(wanted):
        try:
            rows = box.remote_command(
                n, "timeseries-dump", [et, ei, metric, str(window_s)])
        except (ValueError, KeyError):
            rows = None  # node gone mid-incident: render what we have
        for row in rows or []:
            row["node"] = n
            series.append(row)
    # anchor the window on the newest evidence timestamp
    t1 = None
    for ev in events:
        t1 = ev["ts"] if t1 is None else max(t1, ev["ts"])
    for row in series:
        if row["points"]:
            ts = row["points"][-1][0]
            t1 = ts if t1 is None else max(t1, ts)
    bundle = {"target": target, "status": tstat,
              "events": events, "series": series, "traces": []}
    if t1 is not None:
        t0 = t1 - window_s
        bundle["window"] = [t0, t1]
        bundle["events"] = [ev for ev in events if ev["ts"] >= t0]
        for row in series:
            row["points"] = [p for p in row["points"] if p[0] >= t0]
    reports = box.admin.call("slow_traces") or {}
    for rep_node, rep in sorted(reports.items()):
        if node is not None and rep_node != node:
            continue
        for root in rep.get("roots", []):
            if t1 is not None and not (
                    t1 - window_s <= root.get("start", 0.0) <= t1 + 1.0):
                continue
            bundle["traces"].append(root)
    return bundle


def _dispatch(args, box, out) -> int:
    from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX
    from pegasus_tpu.utils.errors import StorageStatus

    if args.cmd == "create_app":
        box.create_table(args.name, args.partition_count)
        print(f"OK: created {args.name} "
              f"({args.partition_count} partitions)", file=out)
    elif args.cmd == "drop_app":
        box.drop_table(args.name)
        print(f"OK: dropped {args.name}", file=out)
    elif args.cmd == "ls":
        for row in box.list_tables():
            print(f"{row['app_id']:>4}  {row['name']:<24} "
                  f"partitions={row['partition_count']}", file=out)
    elif args.cmd == "app":
        t = box.open_table(args.name)
        for p_ in t.all_partitions():
            print(f"  {t.app_id}.{p_.pidx}: decree="
                  f"{p_.engine.last_committed_decree} "
                  f"records~{sum(s.total_count for s in p_.engine.lsm.l0) + sum(s.total_count for s in p_.engine.lsm.l1_runs)}",
                  file=out)
    elif args.cmd == "set":
        c = box.client(args.table)
        err = c.set(_b(args.hash_key), _b(args.sort_key), _b(args.value),
                    ttl_seconds=args.ttl)
        print("OK" if err == 0 else f"error {err}", file=out)
        if err != 0:
            return 1
    elif args.cmd == "get":
        c = box.client(args.table)
        err, value = c.get(_b(args.hash_key), _b(args.sort_key))
        if err == int(StorageStatus.NOT_FOUND):
            print("not found", file=out)
            return 1
        print(_s(value), file=out)
    elif args.cmd == "del":
        c = box.client(args.table)
        err = c.delete(_b(args.hash_key), _b(args.sort_key))
        print("OK" if err == 0 else f"error {err}", file=out)
        if err != 0:
            return 1
    elif args.cmd == "exist":
        c = box.client(args.table)
        print("true" if c.exist(_b(args.hash_key), _b(args.sort_key))
              else "false", file=out)
    elif args.cmd == "ttl":
        c = box.client(args.table)
        err, ttl = c.ttl(_b(args.hash_key), _b(args.sort_key))
        if err != 0:
            print("not found", file=out)
            return 1
        print("no ttl" if ttl < 0 else f"{ttl}s", file=out)
    elif args.cmd == "incr":
        c = box.client(args.table)
        resp = c.incr(_b(args.hash_key), _b(args.sort_key),
                      args.increment)
        if resp.error != 0:
            print(f"error {resp.error}", file=out)
            return 1
        print(resp.new_value, file=out)
    elif args.cmd == "multi_set":
        c = box.client(args.table)
        kvs = dict(kv.split("=", 1) for kv in args.kvs)
        err = c.multi_set(_b(args.hash_key),
                          {_b(k): _b(v) for k, v in kvs.items()})
        print("OK" if err == 0 else f"error {err}", file=out)
        if err != 0:
            return 1
    elif args.cmd == "multi_get":
        c = box.client(args.table)
        err, kvs = c.multi_get(_b(args.hash_key))
        if err != 0:
            print(f"error {err}", file=out)
            return 1
        for k, v in sorted(kvs.items()):
            print(f"{_s(k)} : "
                  f"{_s(v)}", file=out)
        print(f"{len(kvs)} record(s)", file=out)
    elif args.cmd == "count":
        c = box.client(args.table)
        err, n = c.sortkey_count(_b(args.hash_key))
        if err != 0:
            print(f"error {err}", file=out)
            return 1
        print(n, file=out)
    elif args.cmd == "scan":
        from pegasus_tpu.client import ScanOptions
        c = box.client(args.table)
        opts = ScanOptions(batch_size=args.max)
        if args.hash_prefix:
            opts.hash_key_filter_type = FT_MATCH_PREFIX
            opts.hash_key_filter_pattern = _b(args.hash_prefix)
        n = 0
        for sc in c.get_unordered_scanners(1, opts):
            for hk, sk, v in sc:
                print(f"{_s(hk)} : "
                      f"{_s(sk)} => "
                      f"{_s(v)}", file=out)
                n += 1
                if n >= args.max:
                    break
            if n >= args.max:
                break
        print(f"{n} record(s)", file=out)
    elif args.cmd == "check_and_set":
        c = box.client(args.table)
        resp = c.check_and_set(
            _b(args.hash_key), _b(args.check_sort_key),
            _check_type(args.check_type), _b(args.check_operand),
            _b(args.set_sort_key), _b(args.set_value),
            ttl_seconds=args.ttl, return_check_value=True)
        # TRY_AGAIN is ambiguous: a FAILED CHECK carries the check value
        # back (we asked for it); a gate rejection (throttle/deny) is a
        # bare error and must not read as "check failed"
        check_failed = (resp.error == int(StorageStatus.TRY_AGAIN)
                        and resp.check_value_returned)
        if resp.error != 0 and not check_failed:
            print(f"error {resp.error}", file=out)
            return 1
        print("set" if resp.error == 0 else "not set (check failed)",
              file=out)
        if resp.check_value_returned:
            print(f"check value: "
                  f"{_s(resp.check_value)}",
                  file=out)
    elif args.cmd == "check_and_mutate":
        from pegasus_tpu.server.types import Mutate, MutateOperation
        c = box.client(args.table)
        muts = []
        for m in args.mutations:
            if m.startswith("del:"):
                muts.append(Mutate(MutateOperation.MO_DELETE,
                                   _b(m[4:])))
            elif "=" in m:
                sk, _, v = m.partition("=")
                muts.append(Mutate(MutateOperation.MO_PUT, _b(sk),
                                   _b(v)))
            else:
                raise ValueError(
                    f"mutation {m!r}: use sortkey=value (put, empty "
                    "value allowed) or del:sortkey (delete)")
        resp = c.check_and_mutate(
            _b(args.hash_key), _b(args.check_sort_key),
            _check_type(args.check_type), _b(args.check_operand), muts,
            return_check_value=True)
        check_failed = (resp.error == int(StorageStatus.TRY_AGAIN)
                        and resp.check_value_returned)
        if resp.error != 0 and not check_failed:
            print(f"error {resp.error}", file=out)
            return 1
        print("mutated" if resp.error == 0
              else "not mutated (check failed)", file=out)
    elif args.cmd == "multi_del":
        c = box.client(args.table)
        err, n = c.multi_del(_b(args.hash_key),
                             [_b(s) for s in args.sort_keys])
        if err != 0:
            print(f"error {err}", file=out)
            return 1
        print(f"deleted {n} record(s)", file=out)
    elif args.cmd == "multi_del_range":
        c = box.client(args.table)
        # paginate: the server caps one multi_get at its read-limiter
        # budget (INCOMPLETE=7); delete page by page until exhausted
        deleted = 0
        cursor = _b(args.start)
        inclusive = True
        while True:
            err, kvs = c.multi_get(_b(args.hash_key),
                                   start_sortkey=cursor,
                                   stop_sortkey=_b(args.stop),
                                   start_inclusive=inclusive,
                                   no_value=True)
            if err not in (0, int(StorageStatus.INCOMPLETE)):
                print(f"error {err}", file=out)
                return 1
            if kvs:
                derr, n = c.multi_del(_b(args.hash_key), sorted(kvs))
                if derr != 0:
                    print(f"error {derr}", file=out)
                    return 1
                deleted += n
            if err == 0 or not kvs:
                break
            cursor = max(kvs)  # resume past the page's last sort key
            inclusive = False
        print(f"deleted {deleted} record(s)", file=out)
    elif args.cmd == "multi_get_range":
        c = box.client(args.table)
        err, kvs = c.multi_get(_b(args.hash_key),
                               start_sortkey=_b(args.start),
                               stop_sortkey=_b(args.stop),
                               max_kv_count=args.max)
        incomplete = err == int(StorageStatus.INCOMPLETE)
        if err != 0 and not incomplete:
            print(f"error {err}", file=out)
            return 1
        for k, v in sorted(kvs.items()):
            print(f"{_s(k)} : "
                  f"{_s(v)}", file=out)
        print(f"{len(kvs)} record(s)"
              + (" (truncated — narrow the range or raise --max)"
                 if incomplete else ""), file=out)
    elif args.cmd == "multi_get_sortkeys":
        c = box.client(args.table)
        err, sks = c.multi_get_sortkeys(_b(args.hash_key))
        if err != 0:
            print(f"error {err}", file=out)
            return 1
        for sk in sks:
            print(_s(sk), file=out)
        print(f"{len(sks)} sort key(s)", file=out)
    elif args.cmd == "hash_scan":
        c = box.client(args.table)
        sc = c.get_scanner(_b(args.hash_key), _b(args.start),
                           _b(args.stop))
        n = 0
        for hk, sk, v in sc:
            print(f"{_s(sk)} => "
                  f"{_s(v)}", file=out)
            n += 1
            if n >= args.max:
                sc.close()
                break
        print(f"{n} record(s)", file=out)
    elif args.cmd == "full_scan":
        n = 0
        for hk, sk, v in _full_scan_records(box, args.table, args.max):
            print(f"{_s(hk)} : "
                  f"{_s(sk)} => "
                  f"{_s(v)}", file=out)
            n += 1
        print(f"{n} record(s)", file=out)
    elif args.cmd == "count_data":
        n = 0
        for _ in _full_scan_records(box, args.table, 0):
            n += 1
        print(n, file=out)
    elif args.cmd == "copy_data":
        from pegasus_tpu.base.value_schema import epoch_now

        dst = box.client(args.dst_table)
        n = 0
        for hk, sk, v, ets in _full_scan_records(
                box, args.src_table, args.max, with_ttl=True):
            # preserve remaining TTL (the reference's copy_data keeps
            # expire timestamps) — `now` per record, or a long scan
            # would inflate TTLs and resurrect records that expired
            # mid-scan
            if ets > 0:
                ttl = ets - epoch_now()
                if ttl <= 0:
                    continue
            else:
                ttl = 0
            err = dst.set(hk, sk, v, ttl_seconds=ttl)
            if err != 0:
                print(f"error {err} at {hk!r}:{sk!r}", file=out)
                return 1
            n += 1
        print(f"copied {n} record(s)", file=out)
    elif args.cmd == "clear_data":
        if not args.force:
            print("refusing without --force (deletes every record)",
                  file=out)
            return 1
        c = box.client(args.table)
        # stream: records arrive in key order per partition, so one
        # hash key's sort keys are contiguous — flush per hash key
        # instead of materializing the whole table's keys
        n = 0
        cur_hk, cur_sks = None, []

        def flush_hk():
            nonlocal n
            if cur_hk is not None and cur_sks:
                err, deleted = c.multi_del(cur_hk, cur_sks)
                if err != 0:
                    raise ValueError(f"error {err} at {cur_hk!r}")
                n += deleted

        for hk, sk, _v in _full_scan_records(box, args.table, 0):
            if hk != cur_hk:
                flush_hk()
                cur_hk, cur_sks = hk, []
            cur_sks.append(sk)
        flush_hk()
        print(f"deleted {n} record(s)", file=out)
    elif args.cmd == "hash":
        from pegasus_tpu.base.key_schema import (
            generate_key, key_hash_parts)
        h = key_hash_parts(_b(args.hash_key), _b(args.sort_key))
        count = next((row["partition_count"]
                      for row in box.list_tables()
                      if row["name"] == args.table), None)
        key = generate_key(_b(args.hash_key), _b(args.sort_key))
        print(f"key_hash: {h}", file=out)
        print(f"encoded_key: {key.hex()}", file=out)
        if count:
            print(f"partition: {h % count} (of {count})", file=out)
    elif args.cmd == "rdb_key_str2hex":
        from pegasus_tpu.base.key_schema import generate_key
        print(generate_key(_b(args.hash_key), _b(args.sort_key)).hex(),
              file=out)
    elif args.cmd == "rdb_key_hex2str":
        from pegasus_tpu.base.key_schema import restore_key
        hk, sk = restore_key(bytes.fromhex(args.hex_key))
        print(f"hash_key: {_s(hk)}", file=out)
        print(f"sort_key: {_s(sk)}", file=out)
    elif args.cmd == "rdb_value_hex2str":
        from pegasus_tpu.base.value_schema import (
            extract_expire_ts, extract_user_data)
        raw = bytes.fromhex(args.hex_value)
        print(f"expire_ts: {extract_expire_ts(1, raw)}", file=out)
        print(f"user_data: "
              f"{extract_user_data(1, raw).decode(errors='replace')}",
              file=out)
    elif args.cmd == "set_app_envs":
        envs = dict(kv.split("=", 1) for kv in args.envs)
        box.update_app_envs(args.table, envs)
        print("OK", file=out)
    elif args.cmd == "get_app_envs":
        t = box.open_table(args.table)
        print(json.dumps(t.partitions[0].app_envs, indent=1), file=out)
    elif args.cmd == "manual_compact":
        mc = getattr(box, "manual_compact_table", None)
        if mc is not None:  # wire mode: env-triggered remote compaction
            mc(args.table)
        else:
            box.open_table(args.table).manual_compact_all()
        print("OK", file=out)
    elif args.cmd == "partition_split":
        new_count = box.split_table(args.table)
        print(f"OK: partition count now {new_count}", file=out)
    elif args.cmd == "flush":
        box.open_table(args.table).flush_all()
        print("OK", file=out)
    elif args.cmd == "metrics":
        from pegasus_tpu.utils.metrics import METRICS
        print(json.dumps(METRICS.snapshot(args.entity_type), indent=1),
              file=out)
    elif args.cmd == "storage_stats":
        # per-partition filter / cache observability (round-8): block
        # cache + bloom + row cache counters, plus each partition's
        # filter coverage (how many runs actually carry blooms — a
        # mixed old/new-format store shows it here)
        from pegasus_tpu.server.row_cache import ROW_CACHE
        from pegasus_tpu.utils.metrics import METRICS

        t = box.open_table(args.table)
        rows = []
        for p_ in t.all_partitions():
            lsm = p_.engine.lsm
            tables = list(lsm.l0) + list(lsm.l1_runs)
            snap = p_.metrics.snapshot()["metrics"]
            # codec coverage + compression ratio (round-11): a mixed
            # legacy/compressed store shows partial coverage here, and
            # the ratio sums each run's logical-vs-stored byte stats
            codecs = sorted({x.codec or "none" for x in tables}) \
                if tables else []
            raw_b = sum((x.codec_stats or {}).get("raw_bytes", 0)
                        for x in tables)
            stored_b = sum((x.codec_stats or {}).get("stored_bytes", 0)
                           for x in tables)
            rows.append({
                "gpid": [p_.app_id, p_.pidx],
                "generation": lsm.generation,
                "l0_tables": len(lsm.l0),
                "l1_runs": len(lsm.l1_runs),
                "block_codec": codecs,
                "runs_compressed": sum(
                    1 for x in tables if x.codec is not None),
                "compression_ratio": (round(stored_b / raw_b, 4)
                                      if raw_b else None),
                "compressed_bytes": stored_b,
                "logical_bytes": raw_b,
                "runs_with_bloom": sum(
                    1 for x in tables if x.bloom is not None),
                "bloom_bits": sum(
                    x.bloom.m for x in tables if x.bloom is not None),
                # resident index memory, bloom-vs-phash split (round
                # 15): the perfect-hash index's bytes against the
                # filter bytes it retires at probe time, plus how many
                # runs actually carry one (a build-failure or pre-index
                # file shows partial coverage here)
                "runs_with_phash": sum(
                    1 for x in tables if x.phash is not None),
                "index_bloom_bytes": sum(
                    x.index_memory()["bloom"] for x in tables),
                "index_phash_bytes": sum(
                    x.index_memory()["phash"] for x in tables),
                "cached_blocks": sum(len(x._cache) for x in tables),
                "cached_block_bytes": sum(x._cache_bytes
                                          for x in tables),
                "bloom_useful_count": snap.get(
                    "bloom_useful_count", {}).get("value", 0),
                "phash_useful_count": snap.get(
                    "phash_useful_count", {}).get("value", 0),
                "row_cache_hit": snap.get(
                    "row_cache_hit", {}).get("value", 0),
                "row_cache_miss": snap.get(
                    "row_cache_miss", {}).get("value", 0),
            })
        node_wide = [s["metrics"]
                     for s in METRICS.snapshot("storage")] or [{}]
        # round-12: the compaction pipeline's stage counters
        # (compact_{read,filter,write}_stall_ms, queue depths,
        # compaction_bytes_per_s) land in the node-wide `storage`
        # block above; `compaction` is the governor's live throttle /
        # grant state
        from pegasus_tpu.storage.compact_governor import GOVERNOR
        print(json.dumps({
            "partitions": rows,
            "storage": {n: m.get("value", 0)
                        for n, m in node_wide[0].items()},
            "row_cache": ROW_CACHE.stats(),
            "compaction": GOVERNOR.status(),
        }, indent=1), file=out)
    elif args.cmd == "backup":
        from pegasus_tpu.server.backup import BackupEngine
        from pegasus_tpu.storage.block_service import block_service_for
        t = box.open_table(args.table)  # NotImplementedError in wire mode
        be = BackupEngine(block_service_for(args.bucket), args.policy)
        for p_ in t.all_partitions():
            be.backup_partition(args.backup_id, t.app_id, p_.pidx,
                                p_.engine, server=p_)
        be.finish_backup(args.backup_id, t.app_id, args.table,
                         t.partition_count)
        print(f"OK: backup {args.backup_id}", file=out)
    elif args.cmd == "start_backup":
        bid = box.admin.call("start_backup", app_name=args.table,
                             root=args.bucket, policy=args.policy)
        print(f"OK: backup {bid} started", file=out)
    elif args.cmd == "query_backup":
        print(json.dumps(box.admin.call("backup_status",
                                        backup_id=args.backup_id)),
              file=out)
    elif args.cmd == "restore_app":
        app_id = box.admin.call("restore_app", new_name=args.new_name,
                                root=args.bucket, policy=args.policy,
                                backup_id=args.backup_id)
        print(f"OK: restoring into {args.new_name} (app {app_id})",
              file=out)
    elif args.cmd == "start_bulk_load":
        box.admin.call("start_bulk_load", app_name=args.table,
                       root=args.bucket, src_app=args.staged_app)
        print("OK: bulk load started", file=out)
    elif args.cmd == "query_bulk_load":
        print(json.dumps(box.admin.call("bulk_load_status",
                                        app_name=args.table)), file=out)
    elif args.cmd == "add_dup":
        dupid = box.admin.call("add_dup", app_name=args.table,
                               follower_meta=args.follower_meta,
                               follower_app=args.follower_app)
        print(f"OK: dup {dupid}", file=out)
    elif args.cmd == "query_dup":
        print(json.dumps(box.admin.call("query_dup",
                                        app_name=args.table)), file=out)
    elif args.cmd == "remove_dup":
        box.admin.call("remove_dup", dupid=args.dupid)
        print("OK", file=out)
    elif args.cmd == "dups":
        print(json.dumps(box.admin.call("list_dups")), file=out)
    elif args.cmd == "dup_stats":
        # meta-aggregated dup health (the config-sync dup block), plus
        # each node's live session/governor view from the dup.stats verb
        rows = box.admin.call("dup_stats", app_name=args.table)
        print(json.dumps(rows, indent=1), file=out)
        for n in box.admin.call("list_nodes"):
            node_stats = box.remote_command(n, "dup.stats", [])
            if node_stats and node_stats.get("sessions"):
                print(json.dumps(node_stats, indent=1), file=out)
    elif args.cmd == "dup_failover":
        verb = ("dup_failover_status" if args.status
                else "dup_failover")
        print(json.dumps(box.admin.call(verb, app_name=args.table),
                         indent=1), file=out)
    elif args.cmd == "recover":
        print(json.dumps(box.admin.call("recover")), file=out)
    elif args.cmd == "query_restore_status":
        print(json.dumps(box.admin.call("query_restore_status",
                                        app_name=args.table)), file=out)
    elif args.cmd in ("enable_atomic_idempotent",
                      "disable_atomic_idempotent"):
        val = "true" if args.cmd.startswith("enable") else "false"
        box.update_app_envs(args.table,
                            {"replica.atomic_idempotent": val})
        print("OK", file=out)
    elif args.cmd == "get_atomic_idempotent":
        t = box.open_table(args.table)
        envs = t.partitions[0].app_envs
        print(envs.get("replica.atomic_idempotent", "false"), file=out)
    elif args.cmd == "start_split":
        n = box.admin.call("start_partition_split", app_name=args.table)
        print(f"OK: splitting to {n} partitions", file=out)
    elif args.cmd == "query_split":
        print(json.dumps(box.admin.call("split_status",
                                        app_name=args.table)), file=out)
    elif args.cmd == "cluster_info":
        print(json.dumps(box.admin.call("cluster_info"), indent=1),
              file=out)
    elif args.cmd in ("server_info", "server_stat"):
        nodes = ([args.node] if args.node
                 else box.admin.call("list_nodes"))
        verb = ("server.info" if args.cmd == "server_info"
                else "metrics")
        for n in nodes:
            print(json.dumps({n: box.remote_command(n, verb, [])},
                             indent=1), file=out)
    elif args.cmd == "disk_health":
        # per-dir health state + io error counts across the fleet
        # (parity: shell query_disk_info over the fs_manager states)
        nodes = ([args.node] if args.node
                 else box.admin.call("list_nodes"))
        for n in nodes:
            print(json.dumps(
                {n: box.remote_command(n, "fs.health", [])},
                indent=1), file=out)
    elif args.cmd == "scrub":
        # trigger (or query) the storage scrub for one table: every
        # node scrubs its hosted partitions and reports per-partition
        # progress + last result
        app_ids = {row["app_id"] for row in box.list_tables()
                   if row["name"] == args.table}
        if not app_ids:
            raise ValueError(f"no such table {args.table!r}")
        app_id = str(sorted(app_ids)[0])
        verb_args = (["status", app_id] if args.status else [app_id])
        for n in box.admin.call("list_nodes"):
            rows = box.remote_command(n, "replica.scrub", verb_args)
            for row in rows:
                print(json.dumps(dict(row, node=n)), file=out)
    elif args.cmd == "app_stat":
        rows = []
        for n in box.admin.call("list_nodes"):
            for rep in box.remote_command(n, "replica.info", []):
                rows.append(dict(rep, node=n))
        app_ids = {row["app_id"] for row in box.list_tables()
                   if row["name"] == args.table}
        for rep in sorted(rows, key=lambda r: tuple(r["gpid"])):
            if rep["gpid"][0] in app_ids:
                print(json.dumps(rep), file=out)
    elif args.cmd == "app_disk":
        app_ids = {row["app_id"] for row in box.list_tables()
                   if row["name"] == args.table}
        total = 0
        for n in box.admin.call("list_nodes"):
            for rep in box.remote_command(n, "replica.disk", []):
                if rep["gpid"][0] in app_ids:
                    print(json.dumps(dict(rep, node=n)), file=out)
                    total += rep["sst_bytes"] + rep["log_bytes"]
        print(f"total: {total} bytes", file=out)
    elif args.cmd == "ddd_diagnose":
        for d in box.admin.call("ddd_diagnose"):
            print(json.dumps(d), file=out)
    elif args.cmd == "detect_hotkey":
        print(json.dumps(box.remote_command(
            args.node, "hotkey",
            [args.action, str(args.app_id), str(args.pidx),
             args.kind])), file=out)
    elif args.cmd == "get_meta_level":
        print(box.admin.call("get_meta_level"), file=out)
    elif args.cmd == "set_meta_level":
        print(box.admin.call("set_meta_level", level=args.level),
              file=out)
    elif args.cmd == "get_replica_count":
        print(box.admin.call("get_replica_count", app_name=args.table),
              file=out)
    elif args.cmd == "set_replica_count":
        print(box.admin.call("set_replica_count", app_name=args.table,
                             count=args.count), file=out)
    elif args.cmd == "propose":
        box.admin.call("propose", app_name=args.table, pidx=args.pidx,
                       action=args.action, node=args.node,
                       force=args.force)
        print("OK", file=out)
    elif args.cmd == "recall_app":
        app_id = box.admin.call("recall_app", app_name=args.table)
        print(f"OK: recalled {args.table} (app {app_id})", file=out)
    elif args.cmd == "rename":
        box.admin.call("rename_app", old_name=args.old_name,
                       new_name=args.new_name)
        print("OK", file=out)
    elif args.cmd == "del_app_envs":
        n = box.admin.call("del_app_envs", app_name=args.table,
                           keys=args.keys)
        print(f"OK: removed {n}", file=out)
    elif args.cmd == "clear_app_envs":
        n = box.admin.call("clear_app_envs", app_name=args.table,
                           prefix=args.prefix)
        print(f"OK: removed {n}", file=out)
    elif args.cmd == "add_backup_policy":
        box.admin.call("add_backup_policy", name=args.name,
                       app_names=args.tables, root=args.bucket,
                       interval_seconds=args.interval,
                       backup_history_count=args.history)
        print("OK", file=out)
    elif args.cmd == "ls_backup_policy":
        for pol in box.admin.call("ls_backup_policy"):
            print(json.dumps(pol), file=out)
    elif args.cmd == "query_backup_policy":
        print(json.dumps(box.admin.call("query_backup_policy",
                                        name=args.name), indent=1),
              file=out)
    elif args.cmd == "modify_backup_policy":
        pol = box.admin.call(
            "modify_backup_policy", name=args.name,
            add_apps=args.add_tables, remove_apps=args.remove_tables,
            interval_seconds=args.interval,
            backup_history_count=args.history)
        print(json.dumps(pol), file=out)
    elif args.cmd == "enable_backup_policy":
        box.admin.call("enable_backup_policy", name=args.name)
        print("OK", file=out)
    elif args.cmd == "disable_backup_policy":
        box.admin.call("disable_backup_policy", name=args.name)
        print("OK", file=out)
    elif args.cmd == "pause_dup":
        box.admin.call("pause_dup", dupid=args.dupid)
        print("OK", file=out)
    elif args.cmd == "start_dup":
        box.admin.call("start_dup", dupid=args.dupid)
        print("OK", file=out)
    elif args.cmd == "set_dup_fail_mode":
        box.admin.call("set_dup_fail_mode", dupid=args.dupid,
                       fail_mode=args.fail_mode)
        print("OK", file=out)
    elif args.cmd == "pause_bulk_load":
        box.admin.call("pause_bulk_load", app_name=args.table)
        print("OK", file=out)
    elif args.cmd == "restart_bulk_load":
        box.admin.call("restart_bulk_load", app_name=args.table)
        print("OK", file=out)
    elif args.cmd == "cancel_bulk_load":
        box.admin.call("cancel_bulk_load", app_name=args.table)
        print("OK", file=out)
    elif args.cmd == "clear_bulk_load":
        box.admin.call("clear_bulk_load", app_name=args.table)
        print("OK", file=out)
    elif args.cmd == "flush_log":
        print(box.remote_command(args.node, "flush", []), file=out)
    elif args.cmd == "remote_command":
        print(json.dumps(box.remote_command(args.node, args.verb,
                                            args.cmd_args), indent=1),
              file=out)
    elif args.cmd == "slow_queries":
        for rep in box.remote_command(args.node, "slow-query-dump", []):
            print(json.dumps(rep), file=out)
    elif args.cmd == "trace":
        from pegasus_tpu.utils import tracing

        # local rings first (this process's client spans), then fan the
        # trace-dump verb out to every node; stitch dedupes overlaps
        spans = list(tracing.dump_all(args.trace_id))
        if isinstance(box, _ClusterBox):
            for n in box.admin.call("list_nodes"):
                res = box.remote_command(n, "trace-dump",
                                         [args.trace_id])
                if res:
                    spans.extend(res)
        tree = tracing.stitch(spans)
        if tree is None:
            print(f"no spans for trace {args.trace_id}", file=out)
        elif args.json:
            print(json.dumps(tree, indent=1, default=str), file=out)
        else:
            print(tracing.render(tree), file=out)
    elif args.cmd == "traces":
        from pegasus_tpu.utils import tracing

        if isinstance(box, _ClusterBox):
            reports = box.admin.call("slow_traces")
            for rep in reports.values():  # newest last per node
                if isinstance(rep.get("roots"), list):
                    rep["roots"] = rep["roots"][-args.limit:]
            print(json.dumps(reports, indent=1), file=out)
        else:
            print(json.dumps(tracing.slow_roots_all(args.limit),
                             indent=1), file=out)
    elif args.cmd == "health":
        status = box.admin.call("cluster_health")
        if args.json:
            print(json.dumps(status, indent=1), file=out)
        else:
            print(f"cluster: {status['cluster']}", file=out)
            for node, st in sorted(status["nodes"].items()):
                firing = ", ".join(
                    f"{f['rule']}[{f['entity'][0]}/{f['entity'][1]}]"
                    for f in st["firing"]) or "-"
                print(f"  {node:<12} {st['status']:<9} "
                      f"rings={st['ring_bytes']}B "
                      f"events={st['events_total']}  {firing}",
                      file=out)
            for table, st in sorted(status["tables"].items()):
                rules = ", ".join(f"{f['rule']}@{f['node']}"
                                  for f in st["firing"])
                print(f"  table {table:<6} {st['status']:<9} {rules}",
                      file=out)
    elif args.cmd == "timeline":
        from pegasus_tpu.utils.health import parse_window, render_timeline

        bundle = _build_timeline(box, args.target,
                                 parse_window(args.window))
        if args.json:
            print(json.dumps(bundle, indent=1), file=out)
        else:
            print(render_timeline(bundle), file=out)
    elif args.cmd == "explain":
        from pegasus_tpu.server import explain as explain_mod

        if args.from_trace:
            # rebuild the report from a kept slow trace's span perf
            # tags: local rings + (wire mode) every node's trace-dump
            from pegasus_tpu.utils import tracing

            spans = list(tracing.dump_all(args.from_trace))
            if isinstance(box, _ClusterBox):
                for n in box.admin.call("list_nodes"):
                    res = box.remote_command(n, "trace-dump",
                                             [args.from_trace])
                    if res:
                        spans.extend(res)
            report = explain_mod.from_trace(spans, args.from_trace)
            if args.json:
                print(json.dumps(report, indent=1, default=str),
                      file=out)
            else:
                print(explain_mod.render_trace_report(report),
                      file=out)
        else:
            if args.table is None or not args.spec:
                raise ValueError(
                    "usage: explain <table> <op-spec>  |  "
                    "explain --from-trace <trace_id>")
            spec = explain_mod.spec_from_words(args.spec)
            if isinstance(box, _ClusterBox):
                from pegasus_tpu.base.key_schema import key_hash_parts

                ph = key_hash_parts(
                    spec.get("hash_key", "").encode(), b"")
                # one meta call resolves the hosting primary; the
                # probe loop below is only the fallback for a config
                # racing the resolution
                info = box.admin.call("partition_primary",
                                      app_name=args.table,
                                      partition_hash=ph)
                spec["app_id"] = info["app_id"]
                nodes = box.admin.call("list_nodes")
                if info.get("primary"):
                    nodes = [info["primary"]] + [
                        n for n in nodes if n != info["primary"]]
                report = None
                last_err = None
                for n in nodes:
                    # the hosting primary answers; others raise
                    try:
                        res = box.remote_command(n, "perf.explain",
                                                 [json.dumps(spec)])
                    except ValueError as e:
                        last_err = str(e)
                        continue
                    if isinstance(res, dict):
                        report = dict(res, node=n)
                        break
                if report is None:
                    raise ValueError(
                        f"no node could explain: {last_err}")
            else:
                t = box.open_table(args.table)
                op, op_args, ph = explain_mod.op_from_spec(spec)
                if ph is not None:
                    srv = t.partitions[ph % t.partition_count]
                else:
                    srv = t.partitions[0]
                report = explain_mod.explain_op(srv, op, op_args,
                                                partition_hash=ph)
            if args.json:
                print(json.dumps(report, indent=1, default=str),
                      file=out)
            else:
                print(explain_mod.render_report(report), file=out)
    elif args.cmd == "tenants":
        if isinstance(box, _ClusterBox):
            # one meta call off the config-sync tenant blocks
            status = box.admin.call("tenant_stats")
        else:
            from pegasus_tpu.server.tenancy import TENANTS

            status = {"tenants": TENANTS.snapshot(),
                      "nodes_reporting": 1}
        if args.json:
            print(json.dumps(status, indent=1), file=out)
        else:
            print(f"tenants ({status.get('nodes_reporting', 0)} nodes "
                  f"reporting):", file=out)
            for name, st in sorted(
                    (status.get("tenants") or {}).items()):
                brown = "BROWNOUT" if st.get("browned") else "-"
                budget = st.get("cu_budget") or 0
                print(f"  {name:<16} w={st.get('weight')} "
                      f"budget={budget if budget else 'unlimited'} "
                      f"cu={st.get('cu_total', 0)} "
                      f"ratio={st.get('cu_ratio', 0.0)} "
                      f"shed={st.get('shed', 0)} "
                      f"overbudget={st.get('overbudget', 0)}  {brown}",
                      file=out)
    elif args.cmd == "workload":
        if isinstance(box, _ClusterBox):
            # one meta call off the config-sync workload digests
            status = box.admin.call("workload", app_name=args.table)
        else:
            from pegasus_tpu.server.workload import (
                DRIFT,
                fold_summaries,
            )

            t = box.open_table(args.table)
            rows = [dict(p_.workload.summary(),
                         gpid=[p_.app_id, p_.pidx])
                    for p_ in t.all_partitions()]
            status = {args.table: {"partitions": rows,
                                   "table": fold_summaries(rows)},
                      "drift": DRIFT.status()}
        if args.json:
            print(json.dumps(status, indent=1), file=out)
        else:
            for name, tbl in sorted(status.items()):
                if name == "drift":
                    print(f"drift: {json.dumps(tbl)}", file=out)
                    continue
                fold = tbl.get("table", {})
                print(f"table {name}: "
                      f"{fold.get('partitions', 0)} partitions  "
                      f"reads={fold.get('read_ops', 0)} "
                      f"scans={fold.get('scan_ops', 0)} "
                      f"(pushdown {fold.get('pushdown_ops', 0)}, "
                      f"plain {max(0, fold.get('scan_ops', 0) - fold.get('pushdown_ops', 0))}) "
                      f"writes={fold.get('write_ops', 0)}  "
                      f"selectivity_p50="
                      f"{fold.get('scan_selectivity_p50', 0.0)}%  "
                      f"hot_share={fold.get('hot_share', 0.0)}",
                      file=out)
                for row in tbl.get("partitions", []):
                    print(f"  {row.get('gpid')} "
                          f"r/s/w={row.get('read_ops', 0)}/"
                          f"{row.get('scan_ops', 0)}/"
                          f"{row.get('write_ops', 0)} "
                          f"read_batch_p99={row.get('read_batch_p99')} "
                          f"value_p99={row.get('value_bytes_p99')}",
                          file=out)
    elif args.cmd == "placement":
        if isinstance(box, _ClusterBox):
            nodes = box.admin.call("list_nodes")
            targets = [args.node] if args.node else nodes[:1]
            for n in targets:
                print(json.dumps(
                    {n: box.remote_command(
                        n, "placement",
                        [args.workload, str(args.bytes),
                         str(args.windows or "")])},
                    indent=1), file=out)
        else:
            from pegasus_tpu.ops.placement import (
                compact_breakdown,
                offload_breakdown,
            )
            from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
            from pegasus_tpu.server.workload import DRIFT

            bd = offload_breakdown(args.workload, args.bytes)
            if args.windows:
                bd["compact"] = compact_breakdown(
                    args.bytes, n_windows=args.windows)
            print(json.dumps(
                {"breakdown": bd,
                 "drift": DRIFT.status(),
                 "mesh": MESH_SERVING.status()}, indent=1), file=out)
    elif args.cmd == "nodes":
        for n in box.admin.call("list_nodes"):
            print(n, file=out)
    elif args.cmd == "hot_partitions":
        # the elasticity controller's view: per-partition CU rates +
        # hotkey signals, node load, in-flight splits, pressure backoff
        status = box.admin.call("hot_partitions", app_name=args.table)
        for row in status.pop("partitions", []):
            print(json.dumps(row), file=out)
        print(json.dumps(status, indent=1), file=out)
    elif args.cmd == "compact_sched":
        # the cluster background-IO scheduler's meta half: who holds
        # the heavy-compaction grant, who waits, what each node
        # reported (running / waiting / paced bytes_per_s)
        print(json.dumps(box.admin.call("compact_sched"), indent=1),
              file=out)
    elif args.cmd == "rebalance":
        n = box.admin.call("rebalance")
        print(f"OK: {n} proposals", file=out)
    elif args.cmd == "offline_node":
        n = box.admin.call("drain_node", node=args.node)
        print(f"OK: moved {n} primaries off {args.node}", file=out)
    elif args.cmd == "restore":
        if isinstance(box, _ClusterBox):
            raise NotImplementedError(
                "restore needs local table access — use --root mode")
        from pegasus_tpu.server.backup import BackupEngine
        from pegasus_tpu.storage.block_service import block_service_for
        be = BackupEngine(block_service_for(args.bucket), args.policy)
        meta = be.read_backup_metadata(args.backup_id)
        new_name = args.new_name or f"{args.table}_restored"
        t = box.create_table(new_name, meta["partition_count"])
        for p_ in t.all_partitions():
            p_.engine.close()
            p_.install_engine(be.restore_partition(
                args.backup_id, meta["app_id"], p_.pidx,
                p_.engine.data_dir))
        print(f"OK: restored into {new_name}", file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
