"""pegasus_tpu shell — data access + table administration CLI.

Parity: the reference's interactive shell (src/shell/main.cpp:874, 87
commands in commands.h) and the Go admin-cli/pegic split. One binary
serves both roles here:

    python -m pegasus_tpu.tools.shell --root /data/onebox <command> ...

Commands (subset mirroring the reference's most used):
  table mgmt : create_app, drop_app, ls, app
  data       : set, get, del, exist, ttl, incr, multi_set, multi_get,
               count, scan
  admin      : set_app_envs, get_app_envs, manual_compact, flush,
               metrics, backup, restore

Bytes arguments accept UTF-8 strings.
"""

from __future__ import annotations

import argparse
import json
import sys


def _b(s: str) -> bytes:
    return s.encode()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="pegasus-shell",
                                     description=__doc__)
    parser.add_argument("--root", default=None,
                        help="in-process onebox catalog root directory")
    parser.add_argument("--cluster", default=None,
                        help="multi-process onebox directory (wire mode: "
                             "commands go over TCP through meta and the "
                             "replica servers)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("create_app")
    p.add_argument("name")
    p.add_argument("-p", "--partition_count", type=int, default=8)
    p = sub.add_parser("drop_app")
    p.add_argument("name")
    sub.add_parser("ls")
    p = sub.add_parser("app")
    p.add_argument("name")

    for cmd in ("set", "get", "del", "exist", "ttl"):
        p = sub.add_parser(cmd)
        p.add_argument("table")
        p.add_argument("hash_key")
        p.add_argument("sort_key")
        if cmd == "set":
            p.add_argument("value")
            p.add_argument("--ttl", type=int, default=0)
    p = sub.add_parser("incr")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("sort_key")
    p.add_argument("increment", type=int)
    p = sub.add_parser("multi_set")
    p.add_argument("table")
    p.add_argument("hash_key")
    p.add_argument("kvs", nargs="+", help="sortkey=value pairs")
    p = sub.add_parser("multi_get")
    p.add_argument("table")
    p.add_argument("hash_key")
    p = sub.add_parser("count")
    p.add_argument("table")
    p.add_argument("hash_key")
    p = sub.add_parser("scan")
    p.add_argument("table")
    p.add_argument("--hash_prefix", default="")
    p.add_argument("--max", type=int, default=100)

    p = sub.add_parser("set_app_envs")
    p.add_argument("table")
    p.add_argument("envs", nargs="+", help="key=value pairs")
    p = sub.add_parser("get_app_envs")
    p.add_argument("table")
    p = sub.add_parser("manual_compact")
    p.add_argument("table")
    p = sub.add_parser("partition_split")
    p.add_argument("table")
    p = sub.add_parser("flush")
    p.add_argument("table")
    p = sub.add_parser("metrics")
    p.add_argument("--entity_type", default=None)
    p = sub.add_parser("backup")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p.add_argument("--backup_id", type=int, required=True)
    p = sub.add_parser("restore")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p.add_argument("--backup_id", type=int, required=True)
    p.add_argument("--new_name", default=None)

    # meta-orchestrated ops (wire mode; parity: the shell's backup/dup/
    # split/bulk-load admin verbs over ddl_client)
    p = sub.add_parser("start_backup")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p = sub.add_parser("query_backup")
    p.add_argument("backup_id", type=int)
    p = sub.add_parser("restore_app")
    p.add_argument("new_name")
    p.add_argument("--bucket", required=True)
    p.add_argument("--policy", default="manual")
    p.add_argument("--backup_id", type=int, required=True)
    p = sub.add_parser("start_bulk_load")
    p.add_argument("table")
    p.add_argument("--bucket", required=True)
    p.add_argument("--staged_app", default=None)
    p = sub.add_parser("query_bulk_load")
    p.add_argument("table")
    p = sub.add_parser("add_dup")
    p.add_argument("table")
    p.add_argument("follower_app")
    p.add_argument("--follower_meta", default="meta")
    p = sub.add_parser("query_dup")
    p.add_argument("table")
    p = sub.add_parser("remove_dup")
    p.add_argument("dupid", type=int)
    p = sub.add_parser("start_split")
    p.add_argument("table")
    p = sub.add_parser("query_split")
    p.add_argument("table")
    p = sub.add_parser("nodes")
    p = sub.add_parser("rebalance")
    p = sub.add_parser("offline_node")
    p.add_argument("node", help="drain all primaries off this node")
    # offline debugging (parity: shell sst_dump / mlog_dump and
    # src/tools/mutation_log_tool.*) — read files directly, no cluster
    p = sub.add_parser("sst_dump")
    p.add_argument("path", help="one .sst file or a replica sst dir")
    p.add_argument("--max", type=int, default=20)
    p = sub.add_parser("mlog_dump")
    p.add_argument("path", help="a replica's plog file (mlog.bin)")
    p.add_argument("--max", type=int, default=20)
    p = sub.add_parser("remote_command")
    p.add_argument("node", help="node name (meta / node0 / ...)")
    p.add_argument("verb", help="registered verb ('help' lists them)")
    p.add_argument("cmd_args", nargs="*")
    p = sub.add_parser("slow_queries")
    p.add_argument("node")

    args = parser.parse_args(argv)

    if args.cmd in ("sst_dump", "mlog_dump"):
        return _offline_dump(args, sys.stdout)
    if (args.root is None) == (args.cluster is None):
        print("error: exactly one of --root / --cluster is required",
              file=sys.stderr)
        return 2
    if args.cluster is not None:
        box = _ClusterBox(args.cluster)
    else:
        from pegasus_tpu.tools.onebox import Onebox

        box = Onebox(args.root)
    from pegasus_tpu.utils.errors import PegasusError

    out = sys.stdout
    try:
        return _dispatch(args, box, out)
    except (KeyError, ValueError, NotImplementedError,
            PegasusError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    finally:
        box.close()


def _offline_dump(args, out) -> int:
    import os

    from pegasus_tpu.base.key_schema import restore_key
    from pegasus_tpu.base.value_schema import (
        extract_expire_ts,
        extract_user_data,
    )

    with _offline_key_zone(args.path, out):
        return _offline_dump_body(args, out, restore_key,
                                  extract_user_data)


def _offline_key_zone(path, out):
    """Offline forensics on an ENCRYPTED cluster's files: walk up from
    the dump target to the server data root (the dir holding
    .pegasus_data_key), unwrap it with the operator's exported
    PEGASUS_KMS_ROOT_KEY(_FILE), and register a temporary zone so the
    dump reads plaintext. Without the root key the dump fails with the
    actual reason instead of showing ciphertext as an empty log."""
    import contextlib
    import os

    from pegasus_tpu.security.kms import (
        KEY_FILE, KeyProvider, LocalKmsClient, root_key_from_env)
    from pegasus_tpu.storage import efile

    @contextlib.contextmanager
    def zone():
        probe = os.path.abspath(path)
        key_root = None
        while True:
            parent = (probe if os.path.isdir(probe)
                      else os.path.dirname(probe))
            if os.path.exists(os.path.join(parent, KEY_FILE)):
                key_root = parent
                break
            up = os.path.dirname(parent)
            if up == parent:
                break
            probe = up
        if key_root is None:
            yield  # plaintext cluster: nothing to do
            return
        root = root_key_from_env()
        if root is None:
            raise SystemExit(
                f"{key_root} holds encrypted data "
                f"({KEY_FILE} present) — export PEGASUS_KMS_ROOT_KEY "
                "or PEGASUS_KMS_ROOT_KEY_FILE to dump it")
        efile.enable_encryption(
            key_root, KeyProvider(key_root, LocalKmsClient(root)))
        try:
            yield
        finally:
            efile.disable_encryption(key_root)

    return zone()


def _offline_dump_body(args, out, restore_key, extract_user_data) -> int:
    import os

    if args.cmd == "sst_dump":
        from pegasus_tpu.storage.sstable import SSTable

        paths = ([args.path] if args.path.endswith(".sst") else sorted(
            os.path.join(args.path, n) for n in os.listdir(args.path)
            if n.endswith(".sst")))
        shown = 0
        for path in paths:
            t = SSTable(path)
            print(f"# {path}: {t.total_count} records, "
                  f"{len(t.blocks)} blocks, meta={t.meta}", file=out)
            for key, value, ets in t.iterate():
                if shown >= args.max:
                    break
                hk, sk = restore_key(key)
                if value is None:
                    print(f"  DEL {hk!r} : {sk!r}", file=out)
                else:
                    data = extract_user_data(1, value)
                    print(f"  {hk!r} : {sk!r} => {data!r} "
                          f"(ets={ets})", file=out)
                shown += 1
            t.close()
            if shown >= args.max:
                break
        return 0
    # mlog_dump
    from pegasus_tpu.replica.mutation_log import MutationLog

    shown = 0
    for mu in MutationLog.replay(args.path):
        if shown >= args.max:
            break
        ops = ", ".join(f"op{wo.op}" for wo in mu.ops)
        print(f"decree={mu.decree} ballot={mu.ballot} "
              f"last_committed={mu.last_committed} "
              f"ts_us={mu.timestamp_us} ops=[{ops}]", file=out)
        shown += 1
    print(f"# {shown} mutation(s) shown", file=out)
    return 0


class _ClusterBox:
    """Adapter: the shell's verbs over the wire clients (parity: the
    reference shell drives ddl_client + client_lib RPCs, never local
    state)."""

    def __init__(self, directory: str) -> None:
        from pegasus_tpu.tools.onebox_cluster import OneboxAdmin

        self.directory = directory
        self.admin = OneboxAdmin(directory)
        self._clients = {}

    def client(self, app_name: str):
        c = self._clients.get(app_name)
        if c is None:
            from pegasus_tpu.tools.onebox_cluster import connect

            c = connect(app_name, self.directory)
            self._clients[app_name] = c
        return c

    def create_table(self, name: str, partition_count: int):
        return self.admin.create_table(name, partition_count)

    def drop_table(self, name: str) -> None:
        self.admin.call("drop_app", app_name=name)

    def list_tables(self):
        return [{"app_id": a["app_id"], "name": a["app_name"],
                 "partition_count": a["partition_count"]}
                for a in self.admin.call("list_apps")]

    def update_app_envs(self, name: str, envs) -> None:
        self.admin.call("update_app_envs", app_name=name, envs=envs)

    def remote_command(self, node: str, verb: str, cmd_args):
        """Invoke a registered control verb on one node (parity: shell
        remote_command over RPC_CLI_CLI_CALL)."""
        import itertools as _it
        import time as _time

        rid = next(self.admin._rids)
        replies = self.admin._replies
        self.admin.net.register(self.admin.name, self.admin._on_message)

        def on_msg(src, msg_type, payload):
            if msg_type in ("admin_reply", "remote_command_reply"):
                replies[payload["rid"]] = payload

        self.admin.net.register(self.admin.name, on_msg)
        self.admin.net.send(self.admin.name, node, "remote_command",
                            {"rid": rid, "cmd": verb, "args": cmd_args})
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            if rid in replies:
                reply = replies.pop(rid)
                if reply["err"] != 0:
                    raise ValueError(str(reply["result"]))
                return reply["result"]
            _time.sleep(0.01)
        raise ValueError(f"remote_command to {node} timed out")

    def open_table(self, name: str):
        raise NotImplementedError(
            "this command needs local table access — use --root mode, or "
            "the admin verbs in wire mode")

    def split_table(self, name: str):
        raise NotImplementedError(
            "online split over the wire lands with the meta split service")

    def close(self) -> None:
        for c in self._clients.values():
            c.net.close()
        self.admin.close()


def _dispatch(args, box, out) -> int:
    from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX
    from pegasus_tpu.utils.errors import StorageStatus

    if args.cmd == "create_app":
        box.create_table(args.name, args.partition_count)
        print(f"OK: created {args.name} "
              f"({args.partition_count} partitions)", file=out)
    elif args.cmd == "drop_app":
        box.drop_table(args.name)
        print(f"OK: dropped {args.name}", file=out)
    elif args.cmd == "ls":
        for row in box.list_tables():
            print(f"{row['app_id']:>4}  {row['name']:<24} "
                  f"partitions={row['partition_count']}", file=out)
    elif args.cmd == "app":
        t = box.open_table(args.name)
        for p_ in t.all_partitions():
            print(f"  {t.app_id}.{p_.pidx}: decree="
                  f"{p_.engine.last_committed_decree} "
                  f"records~{sum(s.total_count for s in p_.engine.lsm.l0) + sum(s.total_count for s in p_.engine.lsm.l1_runs)}",
                  file=out)
    elif args.cmd == "set":
        c = box.client(args.table)
        err = c.set(_b(args.hash_key), _b(args.sort_key), _b(args.value),
                    ttl_seconds=args.ttl)
        print("OK" if err == 0 else f"error {err}", file=out)
        if err != 0:
            return 1
    elif args.cmd == "get":
        c = box.client(args.table)
        err, value = c.get(_b(args.hash_key), _b(args.sort_key))
        if err == int(StorageStatus.NOT_FOUND):
            print("not found", file=out)
            return 1
        print(value.decode(errors="replace"), file=out)
    elif args.cmd == "del":
        c = box.client(args.table)
        err = c.delete(_b(args.hash_key), _b(args.sort_key))
        print("OK" if err == 0 else f"error {err}", file=out)
        if err != 0:
            return 1
    elif args.cmd == "exist":
        c = box.client(args.table)
        print("true" if c.exist(_b(args.hash_key), _b(args.sort_key))
              else "false", file=out)
    elif args.cmd == "ttl":
        c = box.client(args.table)
        err, ttl = c.ttl(_b(args.hash_key), _b(args.sort_key))
        if err != 0:
            print("not found", file=out)
            return 1
        print("no ttl" if ttl < 0 else f"{ttl}s", file=out)
    elif args.cmd == "incr":
        c = box.client(args.table)
        resp = c.incr(_b(args.hash_key), _b(args.sort_key),
                      args.increment)
        if resp.error != 0:
            print(f"error {resp.error}", file=out)
            return 1
        print(resp.new_value, file=out)
    elif args.cmd == "multi_set":
        c = box.client(args.table)
        kvs = dict(kv.split("=", 1) for kv in args.kvs)
        err = c.multi_set(_b(args.hash_key),
                          {_b(k): _b(v) for k, v in kvs.items()})
        print("OK" if err == 0 else f"error {err}", file=out)
        if err != 0:
            return 1
    elif args.cmd == "multi_get":
        c = box.client(args.table)
        err, kvs = c.multi_get(_b(args.hash_key))
        if err != 0:
            print(f"error {err}", file=out)
            return 1
        for k, v in sorted(kvs.items()):
            print(f"{k.decode(errors='replace')} : "
                  f"{v.decode(errors='replace')}", file=out)
        print(f"{len(kvs)} record(s)", file=out)
    elif args.cmd == "count":
        c = box.client(args.table)
        err, n = c.sortkey_count(_b(args.hash_key))
        if err != 0:
            print(f"error {err}", file=out)
            return 1
        print(n, file=out)
    elif args.cmd == "scan":
        from pegasus_tpu.client import ScanOptions
        c = box.client(args.table)
        opts = ScanOptions(batch_size=args.max)
        if args.hash_prefix:
            opts.hash_key_filter_type = FT_MATCH_PREFIX
            opts.hash_key_filter_pattern = _b(args.hash_prefix)
        n = 0
        for sc in c.get_unordered_scanners(1, opts):
            for hk, sk, v in sc:
                print(f"{hk.decode(errors='replace')} : "
                      f"{sk.decode(errors='replace')} => "
                      f"{v.decode(errors='replace')}", file=out)
                n += 1
                if n >= args.max:
                    break
            if n >= args.max:
                break
        print(f"{n} record(s)", file=out)
    elif args.cmd == "set_app_envs":
        envs = dict(kv.split("=", 1) for kv in args.envs)
        box.update_app_envs(args.table, envs)
        print("OK", file=out)
    elif args.cmd == "get_app_envs":
        t = box.open_table(args.table)
        print(json.dumps(t.partitions[0].app_envs, indent=1), file=out)
    elif args.cmd == "manual_compact":
        box.open_table(args.table).manual_compact_all()
        print("OK", file=out)
    elif args.cmd == "partition_split":
        new_count = box.split_table(args.table)
        print(f"OK: partition count now {new_count}", file=out)
    elif args.cmd == "flush":
        box.open_table(args.table).flush_all()
        print("OK", file=out)
    elif args.cmd == "metrics":
        from pegasus_tpu.utils.metrics import METRICS
        print(json.dumps(METRICS.snapshot(args.entity_type), indent=1),
              file=out)
    elif args.cmd == "backup":
        from pegasus_tpu.server.backup import BackupEngine
        from pegasus_tpu.storage.block_service import LocalBlockService
        t = box.open_table(args.table)  # NotImplementedError in wire mode
        be = BackupEngine(LocalBlockService(args.bucket), args.policy)
        for p_ in t.all_partitions():
            be.backup_partition(args.backup_id, t.app_id, p_.pidx,
                                p_.engine)
        be.finish_backup(args.backup_id, t.app_id, args.table,
                         t.partition_count)
        print(f"OK: backup {args.backup_id}", file=out)
    elif args.cmd == "start_backup":
        bid = box.admin.call("start_backup", app_name=args.table,
                             root=args.bucket, policy=args.policy)
        print(f"OK: backup {bid} started", file=out)
    elif args.cmd == "query_backup":
        print(json.dumps(box.admin.call("backup_status",
                                        backup_id=args.backup_id)),
              file=out)
    elif args.cmd == "restore_app":
        app_id = box.admin.call("restore_app", new_name=args.new_name,
                                root=args.bucket, policy=args.policy,
                                backup_id=args.backup_id)
        print(f"OK: restoring into {args.new_name} (app {app_id})",
              file=out)
    elif args.cmd == "start_bulk_load":
        box.admin.call("start_bulk_load", app_name=args.table,
                       root=args.bucket, src_app=args.staged_app)
        print("OK: bulk load started", file=out)
    elif args.cmd == "query_bulk_load":
        print(json.dumps(box.admin.call("bulk_load_status",
                                        app_name=args.table)), file=out)
    elif args.cmd == "add_dup":
        dupid = box.admin.call("add_dup", app_name=args.table,
                               follower_meta=args.follower_meta,
                               follower_app=args.follower_app)
        print(f"OK: dup {dupid}", file=out)
    elif args.cmd == "query_dup":
        print(json.dumps(box.admin.call("query_dup",
                                        app_name=args.table)), file=out)
    elif args.cmd == "remove_dup":
        box.admin.call("remove_dup", dupid=args.dupid)
        print("OK", file=out)
    elif args.cmd == "start_split":
        n = box.admin.call("start_partition_split", app_name=args.table)
        print(f"OK: splitting to {n} partitions", file=out)
    elif args.cmd == "query_split":
        print(json.dumps(box.admin.call("split_status",
                                        app_name=args.table)), file=out)
    elif args.cmd == "remote_command":
        print(json.dumps(box.remote_command(args.node, args.verb,
                                            args.cmd_args), indent=1),
              file=out)
    elif args.cmd == "slow_queries":
        for rep in box.remote_command(args.node, "slow-query-dump", []):
            print(json.dumps(rep), file=out)
    elif args.cmd == "nodes":
        for n in box.admin.call("list_nodes"):
            print(n, file=out)
    elif args.cmd == "rebalance":
        n = box.admin.call("rebalance")
        print(f"OK: {n} proposals", file=out)
    elif args.cmd == "offline_node":
        n = box.admin.call("drain_node", node=args.node)
        print(f"OK: moved {n} primaries off {args.node}", file=out)
    elif args.cmd == "restore":
        if isinstance(box, _ClusterBox):
            raise NotImplementedError(
                "restore needs local table access — use --root mode")
        from pegasus_tpu.server.backup import BackupEngine
        from pegasus_tpu.storage.block_service import LocalBlockService
        be = BackupEngine(LocalBlockService(args.bucket), args.policy)
        meta = be.read_backup_metadata(args.backup_id)
        new_name = args.new_name or f"{args.table}_restored"
        t = box.create_table(new_name, meta["partition_count"])
        for p_ in t.all_partitions():
            p_.engine.close()
            p_.engine = be.restore_partition(
                args.backup_id, meta["app_id"], p_.pidx,
                p_.engine.data_dir)
            p_.write_service.engine = p_.engine
        print(f"OK: restored into {new_name}", file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
