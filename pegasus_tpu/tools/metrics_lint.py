"""metrics_lint: static drift check over every metric registration.

The Prometheus exposition (utils/metrics.py to_prometheus) groups all
samples of one metric NAME under a single TYPE header — if the same
name is registered as a counter in one file and a gauge in another,
whichever entity renders first silently decides the advertised type
and every scraper mislabels the other. Likewise a name the
``_prom_name`` sanitizer has to rewrite aliases with any other name
that sanitizes to the same string. Both are cross-file drift no unit
test sees, so this linter walks the tree, extracts every
``counter(`` / ``gauge(`` / ``percentile(`` registration with a
string-literal name, and fails on:

- one name registered with conflicting kinds (counter families —
  counter/relaxed/volatile — all count as "counter");
- a name the Prometheus sanitizer would rewrite (or that collides
  with another name after sanitizing).

A tier-1 test runs it over the package so metric-name drift is caught
at PR time, not at the dashboard.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# .counter("name") / .gauge('name') / .percentile("name"), tolerating
# a line break between the call and its name literal
_REG_RE = re.compile(
    r"\.(counter|relaxed_counter|volatile_counter|gauge|percentile)\(\s*"
    r"(?:\n\s*)?([\"'])([^\"'\n]+)\2",
    re.MULTILINE)

# PerfContext field registrations (utils/perf_context.py perf_field):
# context fields ride the same rules — a field named like an existing
# metric of a DIFFERENT kind, or a name the sanitizer would rewrite,
# is the same cross-file drift (slow-log perf dicts and explain
# reports render these names next to real metrics)
_PERF_RE = re.compile(
    r"\bperf_field\(\s*(?:\n\s*)?([\"'])([^\"'\n]+)\1\s*"
    r"(?:,\s*(?:\n\s*)?(?:kind\s*=\s*)?"
    r"([\"'])(counter|gauge|percentile)\3)?",
    re.MULTILINE)

_KIND = {"counter": "counter", "relaxed_counter": "counter",
         "volatile_counter": "counter", "gauge": "gauge",
         "percentile": "percentile"}

# tenant-labeled metric entities: the per-tenant series are BOUNDED
# (server/tenancy.py caps the registry at MAX_TENANTS and folds
# unknown wire tags into "default"). Any other call site minting an
# .entity("tenant", ...) bypasses that bound — a raw request-supplied
# string there is an unbounded-cardinality leak into the metric
# registry and every scrape — so the linter fails it.
_TENANT_ENTITY_RE = re.compile(
    r"\.entity\(\s*(?:\n\s*)?([\"'])tenant\1")
_TENANT_ENTITY_HOME = os.path.join("server", "tenancy.py")


def scan_file(path: str) -> List[Tuple[str, str, int]]:
    """(metric_name, kind, line_number) registrations in one file."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out = []
    for m in _REG_RE.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        out.append((m.group(3), _KIND[m.group(1)], line))
    for m in _PERF_RE.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        out.append((m.group(2), m.group(4) or "counter", line))
    return out


def scan_tree(root: str = _PKG_ROOT) -> Dict[str, Dict[str, List[str]]]:
    """name -> kind -> ["path:line", ...] across every .py in `root`."""
    found: Dict[str, Dict[str, List[str]]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if not fn.endswith(".py") or fn == "metrics_lint.py":
                continue  # this file's own docstring shows the pattern
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            for name, kind, line in scan_file(path):
                found.setdefault(name, {}).setdefault(kind, []).append(
                    f"{rel}:{line}")
    return found


def scan_tenant_entities(root: str = _PKG_ROOT) -> List[str]:
    """\"path:line\" sites minting a tenant-labeled metric entity
    OUTSIDE server/tenancy.py (the bounded registry's home)."""
    sites: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if not fn.endswith(".py") or fn == "metrics_lint.py":
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel == _TENANT_ENTITY_HOME:
                continue
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for m in _TENANT_ENTITY_RE.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                sites.append(f"{rel}:{line}")
    return sites


def lint_tenant_entities(sites: List[str]) -> List[str]:
    return [
        f"tenant metric entity minted outside server/tenancy.py at "
        f"{site} — per-tenant series must come from the bounded "
        f"registry (MAX_TENANTS cap + unknown-tag folding), or a raw "
        f"wire tag becomes unbounded metric cardinality"
        for site in sites]


def lint(root: str = _PKG_ROOT) -> List[str]:
    """Problems found (empty = clean)."""
    return (lint_scan(scan_tree(root))
            + lint_tenant_entities(scan_tenant_entities(root)))


def lint_scan(found: Dict[str, Dict[str, List[str]]]) -> List[str]:
    """Problems in an already-scanned registration map."""
    from pegasus_tpu.utils.metrics import _prom_name

    problems: List[str] = []
    for name, kinds in sorted(found.items()):
        if len(kinds) > 1:
            detail = "; ".join(
                f"{kind} at {', '.join(sites)}"
                for kind, sites in sorted(kinds.items()))
            problems.append(
                f"metric {name!r} registered with conflicting kinds: "
                f"{detail} — the Prometheus TYPE header can only "
                f"advertise one")
    sanitized: Dict[str, str] = {}
    for name in sorted(found):
        clean = _prom_name(name)
        if clean != name:
            sites = [s for kinds in (found[name],)
                     for ss in kinds.values() for s in ss]
            problems.append(
                f"metric {name!r} breaks the Prometheus sanitizer "
                f"(would export as {clean!r}) at {', '.join(sites)}")
        prior = sanitized.get(clean)
        if prior is not None and prior != name:
            problems.append(
                f"metrics {prior!r} and {name!r} collide after "
                f"Prometheus sanitizing (both export as {clean!r})")
        sanitized[clean] = name
    return problems


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    root = args[0] if args else _PKG_ROOT
    found = scan_tree(root)  # ONE walk: lint + the status counts
    problems = (lint_scan(found)
                + lint_tenant_entities(scan_tenant_entities(root)))
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}")
        print(f"metrics-lint: FAILED ({len(problems)} problem(s), "
              f"{len(found)} metric names scanned)")
        return 1
    print(f"metrics-lint: OK ({len(found)} metric names, "
          f"{sum(len(s) for k in found.values() for s in k.values())} "
          f"registration sites)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
