"""Info collector: cluster-wide stat aggregation + availability probing.

Parity: src/server/info_collector.h:48 (per-table stat aggregation
written back into a `stat` table via result_writer) and
src/server/available_detector.h:49 / collector/avail/detector.go (a
periodic set/get probe on a detect table producing an availability
percentage). The Go collector's metric scraping maps to the nodes'
remote "metrics" command (the /metrics JSON surface).

Runs over any deployment exposing the remote-command message and a
client factory: the in-process SimCluster (tests) or the multi-process
onebox (point it at the cluster dir).
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Any, Callable, Dict, List, Optional

_RIDS = itertools.count(9_000_000)

STAT_TABLE = "stat"
DETECT_TABLE = "detect"


class InfoCollector:
    """`nodes`: replica node names; `send`/pump come from the transport;
    `client_factory(table_name)` returns a data client."""

    def __init__(self, net, name: str, nodes: List[str],
                 client_factory: Callable[[str], Any],
                 pump: Callable[[], None]) -> None:
        self.net = net
        self.name = name
        self.nodes = list(nodes)
        self.client_factory = client_factory
        self._pump = pump
        self._replies: Dict[int, dict] = {}
        self._stat_client = None
        self._detect_client = None
        # availability accounting (parity: available_detector partition
        # probe counters)
        self.probe_total = 0
        self.probe_failed = 0
        net.register(name, self._on_message)

    def _on_message(self, src: str, msg_type: str, payload) -> None:
        if msg_type == "remote_command_reply":
            self._replies[payload["rid"]] = payload

    def _command(self, node: str, verb: str,
                 args: Optional[list] = None,
                 rounds: int = 100) -> Optional[Any]:
        rid = next(_RIDS)
        self.net.send(self.name, node, "remote_command",
                      {"rid": rid, "cmd": verb, "args": args or []})
        for _ in range(rounds):
            if rid in self._replies:
                reply = self._replies.pop(rid)
                return reply["result"] if reply["err"] == 0 else None
            self._pump()
        return None

    # ---- stat aggregation (parity: info_collector.h:206-212) -----------

    def collect_round(self) -> Dict[str, dict]:
        """Scrape every node's replica metrics, aggregate per table
        (CU counters + read/write latency p50/p99 off the node
        percentile snapshots), and write one row per table into the
        stat table; node tail-kept slow-trace counts land in a
        `_traces` row so soak/scale runs can assert on them."""
        per_table: Dict[str, dict] = {}
        for node in self.nodes:
            snapshot = self._command(node, "metrics", ["replica"])
            if not snapshot:
                continue
            for entity in snapshot:
                table = entity.get("attributes", {}).get("table")
                if table is None:
                    continue
                agg = per_table.setdefault(table, {
                    "partitions": 0, "read_cu": 0, "write_cu": 0,
                    "abnormal_reads": 0,
                    "read_p50_ms": 0.0, "read_p99_ms": 0.0,
                    "write_p50_ms": 0.0, "write_p99_ms": 0.0,
                    "index_bloom_bytes": 0, "index_phash_bytes": 0})
                agg["partitions"] += 1
                metrics = entity.get("metrics", {})
                # resident index memory (round 15): per-partition
                # bloom-vs-phash gauge split summed per table — the
                # thousands-of-partitions elasticity scenario's
                # memory signal
                for key in ("index_bloom_bytes", "index_phash_bytes"):
                    agg[key] += int(
                        metrics.get(key, {}).get("value", 0))
                agg["read_cu"] += int(
                    metrics.get("recent_read_cu", {}).get("value", 0))
                agg["write_cu"] += int(
                    metrics.get("recent_write_cu", {}).get("value", 0))
                agg["abnormal_reads"] += int(
                    metrics.get("abnormal_read_count", {})
                    .get("value", 0))
                # per-table latency: the WORST partition's percentile
                # (percentiles over partitions cannot merge exactly;
                # max is the honest aggregate for an SLO check)
                for key, metric in (("read_p50_ms", "read_latency_ms"),
                                    ("write_p50_ms",
                                     "write_latency_ms")):
                    snap = metrics.get(metric)
                    if not snap:
                        continue
                    agg[key] = max(agg[key], snap.get("p50", 0.0))
                    p99_key = key.replace("p50", "p99")
                    agg[p99_key] = max(agg[p99_key],
                                       snap.get("p99", 0.0))
        node_traces = self.collect_traces()
        dup_rows = self.collect_dups()
        storage_rows = self.collect_storage()
        health_rows = self.collect_health()
        alert_rows = self.collect_alerts()
        workload_rows = self.collect_workload()
        tenant_rows = self.collect_tenants()
        if per_table:
            if self._stat_client is None:
                self._stat_client = self.client_factory(STAT_TABLE)
            ts = b"%d" % int(time.time())
            for table, agg in per_table.items():
                self._stat_client.set(
                    table.encode(), ts, json.dumps(agg).encode())
            if node_traces:
                self._stat_client.set(b"_traces", ts,
                                      json.dumps(node_traces).encode())
            if dup_rows:
                self._stat_client.set(b"_dups", ts,
                                      json.dumps(dup_rows).encode())
            if storage_rows:
                self._stat_client.set(b"_storage", ts,
                                      json.dumps(storage_rows).encode())
            if health_rows:
                self._stat_client.set(b"_health", ts,
                                      json.dumps(health_rows).encode())
            if alert_rows:
                self._stat_client.set(b"_alerts", ts,
                                      json.dumps(alert_rows).encode())
            if workload_rows:
                self._stat_client.set(
                    b"_workload", ts,
                    json.dumps(workload_rows).encode())
            if tenant_rows:
                self._stat_client.set(
                    b"_tenants", ts,
                    json.dumps(tenant_rows).encode())
        return per_table

    def collect_tenants(self) -> Dict[str, dict]:
        """Per-tenant QoS rows off every node's `qos.tenants` verb,
        folded cluster-wide: counters sum, the burn ratio keeps the
        worst node's value, brownout is true if ANY node holds the
        gate — one `_tenants` stat row per round, so a soak can assert
        'the compliant tenant was never shed' from table history."""
        out: Dict[str, dict] = {}
        for node in self.nodes:
            snap = self._command(node, "qos.tenants")
            if not snap:
                continue
            for name, st in snap.items():
                agg = out.setdefault(name, {
                    "weight": st.get("weight"),
                    "cu_budget": st.get("cu_budget"),
                    "cu_total": 0, "cu_ratio": 0.0,
                    "shed": 0, "overbudget": 0, "browned": False})
                # in-process sims share ONE registry across stubs, so
                # identical snapshots repeat per node: max (not sum)
                # keeps the fold honest in both deployments for the
                # monotonic counters too
                agg["cu_total"] = max(agg["cu_total"],
                                      int(st.get("cu_total") or 0))
                agg["cu_ratio"] = max(agg["cu_ratio"],
                                      float(st.get("cu_ratio") or 0.0))
                agg["shed"] = max(agg["shed"], int(st.get("shed") or 0))
                agg["overbudget"] = max(agg["overbudget"],
                                        int(st.get("overbudget") or 0))
                agg["browned"] = (agg["browned"]
                                  or bool(st.get("browned")))
        return out

    def collect_workload(self) -> Dict[str, dict]:
        """Per-table workload shape rows off the nodes' `workload`
        metric entities (op mix rates ride the flight recorder; this
        is the cumulative roll-up), plus the node cost-model drift
        ratio — one `_workload` stat row per round, so a soak can
        assert shape assumptions from table history alone.

        Entities DEDUPE by id with per-metric max across nodes before
        folding: every replica of a partition carries the same
        `app.pidx` workload entity (secondaries tick write applies
        too, and in-process sims share one registry outright), so a
        naive per-node sum would multiply op counts by ~replica_count
        and report replicas as partitions — disagreeing with the
        primary-only `shell workload` meta fold by 3-8x."""
        # (table, entity_id) -> per-metric maxima
        per_part: Dict[tuple, dict] = {}
        drift = 0.0
        for node in self.nodes:
            snapshot = self._command(node, "metrics", ["workload"])
            if not snapshot:
                continue
            for entity in snapshot:
                metrics = entity.get("metrics", {})
                if entity.get("id") == "node":
                    drift = max(drift, float(
                        metrics.get("cost_model_drift_ratio",
                                    {}).get("value", 0.0)))
                    continue
                table = entity.get("attributes", {}).get("table")
                if table is None:
                    continue
                row = per_part.setdefault(
                    (table, entity.get("id")), {
                        "read_ops": 0, "scan_ops": 0, "write_ops": 0,
                        "read_batch_p99": 0.0, "write_batch_p99": 0.0,
                        "value_bytes_p99": 0.0,
                        "scan_selectivity_p50": 0.0, "hot_share": 0.0})
                for key, metric in (("read_ops", "workload_read_ops"),
                                    ("scan_ops", "workload_scan_ops"),
                                    ("write_ops",
                                     "workload_write_ops")):
                    row[key] = max(row[key], int(
                        metrics.get(metric, {}).get("value", 0)))
                for key, metric, pkey in (
                        ("read_batch_p99", "workload_read_batch",
                         "p99"),
                        ("write_batch_p99", "workload_write_batch",
                         "p99"),
                        ("value_bytes_p99", "workload_value_bytes",
                         "p99"),
                        ("scan_selectivity_p50",
                         "workload_scan_selectivity", "p50")):
                    snap = metrics.get(metric)
                    if snap:
                        row[key] = max(row[key],
                                       float(snap.get(pkey, 0.0)))
                row["hot_share"] = max(row["hot_share"], float(
                    metrics.get("workload_hot_share",
                                {}).get("value", 0.0)))
        # ONE fold rule: the per-table rollup is workload.fold_summaries
        # — the same function meta's `shell workload` uses — so the
        # `_workload` stat row and the shell can never disagree on how
        # partitions aggregate
        from pegasus_tpu.server.workload import fold_summaries

        by_table: Dict[str, list] = {}
        for (table, _eid), row in sorted(per_part.items()):
            by_table.setdefault(table, []).append(row)
        tables: Dict[str, dict] = {
            table: fold_summaries(rows)
            for table, rows in by_table.items()}
        if not tables:
            return {}
        # uniformly-typed persisted shape: table rows under "tables",
        # the node drift scalar beside them (a sentinel key mixed into
        # the table dict made `for t, row in rows.items()` consumers
        # trip over a float)
        return {"tables": tables, "drift_ratio": drift}

    def collect_health(self) -> Dict[str, dict]:
        """Per-node watchdog verdict off the `health.status` verb:
        status, firing rules, and the flight recorder's ring-memory
        cost — one `_health` stat row per round, so soaks/SLO checks
        can assert 'nothing fired' from table history alone."""
        out: Dict[str, dict] = {}
        for node in self.nodes:
            st = self._command(node, "health.status")
            if not st:
                continue
            out[node] = {
                "status": st.get("status", "?"),
                "firing": [f.get("rule") for f in st.get("firing", [])],
                "events_total": st.get("events_total", 0),
                "ring_bytes": st.get("ring_bytes", 0),
            }
        return out

    def collect_alerts(self) -> Dict[str, list]:
        """Recent typed health events per node (the `health.events`
        journal) — the `_alerts` stat row: severity, rule, firing/
        cleared, reason, compacted to the essentials."""
        out: Dict[str, list] = {}
        for node in self.nodes:
            events = self._command(node, "health.events", ["16"])
            if not events:
                continue
            out[node] = [{
                "rule": ev.get("rule"), "severity": ev.get("severity"),
                "firing": ev.get("firing"), "ts": ev.get("ts"),
                "entity": ev.get("entity"), "reason": ev.get("reason"),
            } for ev in events]
        return out

    def collect_storage(self) -> Dict[str, dict]:
        """Per-node point-read index health off the `storage` metric
        entity: perfect-hash usefulness (probes that skipped every
        block touch), located hits, and build failures (runs stamped
        "no phash" — a perf event worth alerting on if it trends), next
        to the bloom twin — one `_storage` stat row per round."""
        wanted = ("phash_useful_count", "phash_hit_count",
                  "phash_build_fail_count", "bloom_useful_count")
        out: Dict[str, dict] = {}
        for node in self.nodes:
            snapshot = self._command(node, "metrics", ["storage"])
            if not snapshot:
                continue
            for entity in snapshot:
                metrics = entity.get("metrics", {})
                row = {k: int(metrics.get(k, {}).get("value", 0))
                       for k in wanted if k in metrics}
                if row:
                    out[node] = row
        return out

    def collect_dups(self) -> Dict[str, dict]:
        """Per-table duplication lag rows off every node's `dup.stats`
        verb: worst lag (decrees + ms) across the table's sessions,
        shipped/error/skip totals — the geo-replication health a soak
        or an operator SLO check reads in one row per app."""
        out: Dict[str, dict] = {}
        for node in self.nodes:
            stats = self._command(node, "dup.stats")
            if not stats:
                continue
            for sess in stats.get("sessions", ()):
                app_id = str(sess.get("gpid", [0, 0])[0])
                agg = out.setdefault(app_id, {
                    "sessions": 0, "max_lag_decrees": 0,
                    "max_lag_ms": 0.0, "shipped_bytes": 0,
                    "error_count": 0, "skip_count": 0})
                agg["sessions"] += 1
                agg["max_lag_decrees"] = max(
                    agg["max_lag_decrees"], sess.get("lag_decrees", 0))
                agg["max_lag_ms"] = max(agg["max_lag_ms"],
                                        sess.get("lag_ms", 0.0))
                agg["shipped_bytes"] += sess.get("shipped_bytes", 0)
                agg["error_count"] += sess.get("error_count", 0)
                agg["skip_count"] += sess.get("skip_count", 0)
        return out

    def collect_traces(self) -> Dict[str, int]:
        """Tail-kept slow-trace count per node (the tracing entity's
        kept_trace_count) — how many slow requests each node pinned."""
        out: Dict[str, int] = {}
        for node in self.nodes:
            snapshot = self._command(node, "metrics", ["tracing"])
            if not snapshot:
                continue
            for entity in snapshot:
                if entity.get("id") != node:
                    continue
                out[node] = int(entity.get("metrics", {}).get(
                    "kept_trace_count", {}).get("value", 0))
        return out

    def table_history(self, app_id_str: str) -> List[dict]:
        if self._stat_client is None:
            self._stat_client = self.client_factory(STAT_TABLE)
        err, kvs = self._stat_client.multi_get(app_id_str.encode())
        if err != 0:
            return []
        return [json.loads(v) for _k, v in sorted(kvs.items())]

    # ---- availability (parity: available_detector.h:49) ----------------

    def probe_round(self, probes: int = 4) -> float:
        """Write+read probes against the detect table; returns the
        availability fraction so far."""
        if self._detect_client is None:
            self._detect_client = self.client_factory(DETECT_TABLE)
        c = self._detect_client
        for i in range(probes):
            self.probe_total += 1
            key = b"probe_%d" % (self.probe_total % 64)
            value = b"%d" % self.probe_total
            try:
                if c.set(key, b"s", value) != 0:
                    self.probe_failed += 1
                    continue
                err, got = c.get(key, b"s")
                if err != 0 or got != value:
                    self.probe_failed += 1
            except Exception:  # noqa: BLE001 - a probe failure IS the data
                self.probe_failed += 1
        return self.availability()

    def availability(self) -> float:
        if self.probe_total == 0:
            return 1.0
        return 1.0 - self.probe_failed / self.probe_total
