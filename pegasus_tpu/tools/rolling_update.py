"""Rolling restart of a onebox cluster's replica nodes.

Parity: admin_tools/pegasus_rolling_update.sh — restart nodes ONE at a
time, waiting between steps until the cluster is healthy again (every
partition back to full replication with a primary), so a binary/config
rollout never drops below quorum.

CLI: python -m pegasus_tpu.tools.rolling_update --dir D
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import List

from pegasus_tpu.utils.errors import PegasusError

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _healthy(admin, apps: List[dict]) -> bool:
    from pegasus_tpu.tools.onebox_cluster import connect

    for app in apps:
        try:
            client = connect(app["app_name"],
                             admin_directory(admin))
            client.refresh_config()
            for pc in client._configs:
                members = ([pc["primary"]] if pc["primary"] else []) \
                    + pc["secondaries"]
                if not pc["primary"] or len(members) < min(
                        app["replica_count"], 3):
                    client.net.close()
                    return False
            client.net.close()
        except PegasusError:
            return False
    return True


def admin_directory(admin) -> str:
    return admin._directory


def rolling_update(directory: str, settle_timeout: float = 120.0) -> None:
    from pegasus_tpu.tools import onebox_cluster as ob

    admin = ob.OneboxAdmin(directory)
    admin._directory = directory
    with open(os.path.join(directory, "cluster.json")) as f:
        cfg = json.load(f)
    replicas = [n for n, c in cfg["nodes"].items()
                if c["role"] == "replica"]
    apps = admin.call("list_apps")
    for node in replicas:
        print(f"[rolling] restarting {node}", flush=True)
        with open(os.path.join(directory, "pids.json")) as f:
            pids = json.load(f)
        try:
            os.kill(pids[node], 15)
        except ProcessLookupError:
            pass
        time.sleep(1.0)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        log = open(os.path.join(directory, "logs",
                                f"{node}.rolling.log"), "ab")
        p = subprocess.Popen(
            [sys.executable, "-m", "pegasus_tpu.server.node_main",
             "--config", os.path.join(directory, "cluster.json"),
             "--name", node],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            cwd=_REPO_ROOT)
        pids[node] = p.pid
        with open(os.path.join(directory, "pids.json"), "w") as f:
            json.dump(pids, f)
        # wait until the cluster is fully healthy before the next node
        deadline = time.monotonic() + settle_timeout
        while time.monotonic() < deadline:
            if _healthy(admin, apps):
                break
            time.sleep(2.0)
        else:
            raise RuntimeError(
                f"cluster did not settle after restarting {node}")
        print(f"[rolling] {node} back, cluster healthy", flush=True)
    admin.close()
    print("[rolling] update complete", flush=True)


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--settle-timeout", type=float, default=120.0)
    args = ap.parse_args()
    rolling_update(args.dir, args.settle_timeout)


if __name__ == "__main__":
    main()
