"""Scale soak: elasticity under multi-tenant load and chaos.

The ROADMAP "million-user scale scenario" proof artifact: a
multi-process onebox hosting HUNDREDS of partitions across several
tenant tables, hammered by a seeded multi-tenant zipfian workload with
per-tenant capacity-unit QoS (throttle envs on the background tenants),
while the DataVerifier invariant — zero acked-write loss — is checked
continuously and chaos (process kills, pauses, disk faults) fires. The
run is DRIVEN THROUGH the two elasticity actions the closed loop
performs: one online partition split of the hottest tenant and one
cluster rebalance, both while the load and the chaos keep running.

Report: per-tenant write/read counts, verifier violations (must be
empty), split + rebalance completion, and the elasticity/fence/
quarantine counters that show each machinery actually engaged.

CLI:
    python -m pegasus_tpu.tools.scale_test --dir D --tenants 4 \
        --partitions 32 --duration 60 [--chaos kill] [--disk-faults]
"""

from __future__ import annotations

import json
import random
import sys
import time
from typing import List, Optional

from pegasus_tpu.tools.kill_test import DataVerifier, Killer
from pegasus_tpu.utils.errors import PegasusError


def zipf_weights(n_keys: int, skew: float) -> List[float]:
    """Rank weights 1/rank^skew — compute once per (n_keys, skew)."""
    return [1.0 / ((rank + 1) ** skew) for rank in range(n_keys)]


def zipf_keys(rng: random.Random, n_keys: int, skew: float,
              count: int, weights: Optional[List[float]] = None
              ) -> List[bytes]:
    """`count` hashkeys drawn zipfian (rank-weighted 1/rank^skew) from a
    tenant's key population — the many-users-few-whales shape."""
    if weights is None:
        weights = zipf_weights(n_keys, skew)
    return [b"user_%06d" % i
            for i in rng.choices(range(n_keys), weights=weights, k=count)]


class TenantWorkload:
    """One tenant: a table, a client, a seeded zipfian stream, and the
    acked-write ledger the final durability check replays."""

    def __init__(self, name: str, client, rng: random.Random,
                 n_keys: int = 2000, skew: float = 1.2) -> None:
        self.name = name
        self.client = client
        self.rng = rng
        self.n_keys = n_keys
        self.skew = skew
        self._weights = zipf_weights(n_keys, skew)
        self.verifier = DataVerifier(client, rng)
        self.reads_ok = 0
        self.read_errors = 0

    def step(self) -> None:
        # sequenced verifier write + history re-read (the invariant)
        self.verifier.step()
        # plus zipfian reads/writes shaping the per-partition heat the
        # elasticity signals are computed from
        for hk in zipf_keys(self.rng, self.n_keys, self.skew, 4,
                            self._weights):
            try:
                if self.rng.random() < 0.5:
                    self.client.set(hk, b"s", b"payload-%s" % hk)
                else:
                    self.client.get(hk, b"s")
                    self.reads_ok += 1
            except PegasusError:
                self.read_errors += 1  # chaos window; durability is
                # checked by the verifier ledger, not this stream


def _wait_split_done(admin, table: str, deadline_s: float) -> bool:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            st = admin.call("split_status", app_name=table)
            if not st.get("splitting"):
                return True
        except PegasusError:
            pass
        time.sleep(0.5)
    return False


def run_scale_test(directory: str, n_tenants: int = 4,
                   partitions: int = 32, duration_s: float = 60.0,
                   n_replica: int = 3, seed: int = 0,
                   chaos_mode: Optional[str] = "kill",
                   kill_every_s: float = 15.0,
                   disk_faults: bool = False,
                   op_timeout_ms: float = 30_000) -> dict:
    """Assumes the onebox in `directory` is NOT yet started; boots it,
    runs the soak, tears it down. Total partitions = n_tenants *
    partitions * 2 after the split of tenant 0 (>= 128 with the
    defaults + split)."""
    from pegasus_tpu.tools import onebox_cluster as ob

    dfp = None
    if disk_faults:
        # light seeded read bit-flips: the PR 5 verify-on-read →
        # quarantine → re-learn loop must repair under the soak load
        dfp = {"seed": seed + 7,
               "points": {"vfs::read": "0.02%return(bit_flip)"}}
    ob.start(directory, n_replica=n_replica, disk_fault_plan=dfp)
    rng = random.Random(seed)
    admin = ob.OneboxAdmin(directory)
    report: dict = {"tenants": {}, "violations": []}
    try:
        # ---- topology: tenant tables, premium first ------------------
        boot_deadline = time.monotonic() + 120
        while time.monotonic() < boot_deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == n_replica:
                    break
            except PegasusError:
                pass
            time.sleep(0.5)
        tenants: List[TenantWorkload] = []
        for t in range(n_tenants):
            table = f"tenant{t}"
            envs = None
            if t >= n_tenants // 2:
                # per-tenant capacity-unit QoS: background tenants get a
                # write throttle so a noisy neighbor cannot starve the
                # premium half's capacity (reject mode -> TryAgain,
                # surfaced in write_rejected, never a violation)
                envs = {"replica.write_throttling": "200*reject*10"}
            create_deadline = time.monotonic() + 90
            while True:
                try:
                    admin.create_table(table, partition_count=partitions,
                                       replica_count=min(3, n_replica),
                                       envs=envs)
                    break
                except PegasusError as e:
                    if "APP_EXIST" in str(e):
                        break
                    if time.monotonic() > create_deadline:
                        raise
                    time.sleep(1)
            client = ob.connect(table, directory,
                                op_timeout_ms=op_timeout_ms)
            tenants.append(TenantWorkload(
                table, client, random.Random(seed * 1000 + t)))
        killer = (Killer(directory, rng, mode=chaos_mode, admin=admin)
                  if chaos_mode else None)

        # ---- the soak: load + chaos + one split + one rebalance ------
        t_end = time.monotonic() + duration_s
        split_at = time.monotonic() + duration_s * 0.25
        rebalance_at = time.monotonic() + duration_s * 0.6
        next_kill = time.monotonic() + kill_every_s
        next_restart = None
        split_started = split_done = False
        rebalance_proposals = None
        while time.monotonic() < t_end:
            for tw in tenants:
                tw.step()
            now = time.monotonic()
            if killer and next_restart is not None and now >= next_restart:
                killer.restart_down()
                next_restart = None
            if killer and now >= next_kill and killer.down is None:
                killer.kill_one()
                next_restart = now + kill_every_s / 2
                next_kill = now + kill_every_s
            if not split_started and now >= split_at:
                # the elasticity act: split tenant0 ONLINE, under load
                # and chaos (retry past a mid-failover meta/primary)
                try:
                    admin.call("start_partition_split",
                               app_name="tenant0")
                    split_started = True
                except PegasusError as e:
                    report.setdefault("split_refusals", []).append(str(e))
                    split_at = now + 3.0  # guarded off; retry shortly
            if split_started and not split_done:
                try:
                    st = admin.call("split_status", app_name="tenant0",
                                    timeout=6)
                    split_done = not st.get("splitting")
                except PegasusError:
                    pass
            if rebalance_proposals is None and now >= rebalance_at:
                try:
                    rebalance_proposals = admin.call("rebalance")
                except PegasusError:
                    rebalance_at = now + 3.0
        if killer:
            killer.restart_down()
        if split_started and not split_done:
            split_done = _wait_split_done(admin, "tenant0", 60.0)

        # ---- the invariant: every acked write of every tenant --------
        for tw in tenants:
            tw.verifier.final_check(deadline_s=120.0)
            report["tenants"][tw.name] = {
                "writes_acked": tw.verifier.write_ok,
                "writes_rejected": tw.verifier.write_rejected,
                "reads_ok": tw.reads_ok,
                "read_errors": tw.read_errors,
            }
            report["violations"].extend(
                f"{tw.name}: {v}" for v in tw.verifier.violations)
        report["split_started"] = split_started
        report["split_done"] = split_done
        report["rebalance_proposals"] = rebalance_proposals
        report["kills"] = killer.kills if killer else 0
        try:
            report["hot_partitions"] = admin.call("hot_partitions",
                                                  timeout=6)
        except PegasusError:
            report["hot_partitions"] = None
        # machinery counters: fences/quarantines prove the guards fired
        fence = quarantine = 0
        for n, c in admin.cfg["nodes"].items():
            if c["role"] != "replica":
                continue
            try:
                for ent in admin.remote_command(n, "metrics",
                                                ["storage"]):
                    m = ent.get("metrics", {})
                    fence += m.get("split_fence_reject_count",
                                   {}).get("value", 0)
                    quarantine += m.get("replica_quarantine_count",
                                        {}).get("value", 0)
            except PegasusError:
                pass
        report["split_fence_rejects"] = fence
        report["quarantines"] = quarantine
        report["partition_total"] = sum(
            a["partition_count"] for a in admin.call("list_apps"))
    finally:
        admin.close()
        ob.stop(directory)
    return report


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--partitions", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chaos", choices=["kill", "pause", "corrupt", "none"],
                    default="kill")
    ap.add_argument("--disk-faults", action="store_true")
    args = ap.parse_args()
    report = run_scale_test(
        args.dir, n_tenants=args.tenants, partitions=args.partitions,
        duration_s=args.duration, n_replica=args.nodes, seed=args.seed,
        chaos_mode=None if args.chaos == "none" else args.chaos,
        disk_faults=args.disk_faults)
    print(json.dumps(report, indent=1, default=str))
    sys.exit(1 if report["violations"] else 0)


if __name__ == "__main__":
    main()
