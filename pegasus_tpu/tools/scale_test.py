"""Scale soak: elasticity under multi-tenant load and chaos.

The ROADMAP "million-user scale scenario" proof artifact: a
multi-process onebox hosting HUNDREDS of partitions across several
tenant tables, hammered by a seeded multi-tenant zipfian workload with
per-tenant capacity-unit QoS (throttle envs on the background tenants),
while the DataVerifier invariant — zero acked-write loss — is checked
continuously and chaos (process kills, pauses, disk faults) fires. The
run is DRIVEN THROUGH the two elasticity actions the closed loop
performs: one online partition split of the hottest tenant and one
cluster rebalance, both while the load and the chaos keep running.

Report: per-tenant write/read counts, verifier violations (must be
empty), split + rebalance completion, and the elasticity/fence/
quarantine counters that show each machinery actually engaged.

`--topology wan` swaps in the geo-replication soak instead: TWO
oneboxes (cluster A duplicating every tenant table to cluster B across
a delayed+lossy FaultPlan link with a mid-run full blackout), kill
chaos alternating across both clusters, ending in the controlled
failover drill — fence A (typed retryable ERR_DUP_FENCED), drain
confirmed decrees, flip B writable — after which the DataVerifier
ledger replays every write A ever acked against B.

CLI:
    python -m pegasus_tpu.tools.scale_test --dir D --tenants 4 \
        --partitions 32 --duration 60 [--chaos kill] [--disk-faults] \
        [--topology wan]
"""

from __future__ import annotations

import json
import os
import random
import sys
import time
from typing import List, Optional

from pegasus_tpu.tools.kill_test import DataVerifier, Killer
from pegasus_tpu.utils.errors import PegasusError


def zipf_weights(n_keys: int, skew: float) -> List[float]:
    """Rank weights 1/rank^skew — compute once per (n_keys, skew)."""
    return [1.0 / ((rank + 1) ** skew) for rank in range(n_keys)]


def zipf_keys(rng: random.Random, n_keys: int, skew: float,
              count: int, weights: Optional[List[float]] = None
              ) -> List[bytes]:
    """`count` hashkeys drawn zipfian (rank-weighted 1/rank^skew) from a
    tenant's key population — the many-users-few-whales shape."""
    if weights is None:
        weights = zipf_weights(n_keys, skew)
    return [b"user_%06d" % i
            for i in rng.choices(range(n_keys), weights=weights, k=count)]


class TenantWorkload:
    """One tenant: a table, a client, a seeded zipfian stream, and the
    acked-write ledger the final durability check replays."""

    def __init__(self, name: str, client, rng: random.Random,
                 n_keys: int = 2000, skew: float = 1.2,
                 monotonic_ledger: bool = False) -> None:
        self.name = name
        self.client = client
        self.rng = rng
        self.n_keys = n_keys
        self.skew = skew
        self._weights = zipf_weights(n_keys, skew)
        read_consistency = None
        if monotonic_ledger:
            # the ledger reads fan out to lease-holding secondaries —
            # the monotonic-reads invariant is checked against follower
            # serving under the same chaos as the durability ledger
            from pegasus_tpu.client.cluster_client import MONOTONIC

            read_consistency = MONOTONIC
        self.verifier = DataVerifier(client, rng,
                                     monotonic_ledger=monotonic_ledger,
                                     read_consistency=read_consistency)
        self.reads_ok = 0
        self.read_errors = 0

    def step(self) -> None:
        # sequenced verifier write + history re-read (the invariant),
        # plus the monotonic-reads ledger when enabled
        self.verifier.step()
        # plus zipfian reads/writes shaping the per-partition heat the
        # elasticity signals are computed from
        for hk in zipf_keys(self.rng, self.n_keys, self.skew, 4,
                            self._weights):
            try:
                if self.rng.random() < 0.5:
                    self.client.set(hk, b"s", b"payload-%s" % hk)
                else:
                    self.client.get(hk, b"s")
                    self.reads_ok += 1
            except PegasusError:
                self.read_errors += 1  # chaos window; durability is
                # checked by the verifier ledger, not this stream


def _wait_split_done(admin, table: str, deadline_s: float) -> bool:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            st = admin.call("split_status", app_name=table)
            if not st.get("splitting"):
                return True
        except PegasusError:
            pass
        time.sleep(0.5)
    return False


def run_scale_test(directory: str, n_tenants: int = 4,
                   partitions: int = 32, duration_s: float = 60.0,
                   n_replica: int = 3, seed: int = 0,
                   chaos_mode: Optional[str] = "kill",
                   kill_every_s: float = 15.0,
                   disk_faults: bool = False,
                   op_timeout_ms: float = 30_000) -> dict:
    """Assumes the onebox in `directory` is NOT yet started; boots it,
    runs the soak, tears it down. Total partitions = n_tenants *
    partitions * 2 after the split of tenant 0 (>= 128 with the
    defaults + split)."""
    from pegasus_tpu.tools import onebox_cluster as ob

    dfp = None
    if disk_faults:
        # light seeded read bit-flips: the PR 5 verify-on-read →
        # quarantine → re-learn loop must repair under the soak load
        dfp = {"seed": seed + 7,
               "points": {"vfs::read": "0.02%return(bit_flip)"}}
    ob.start(directory, n_replica=n_replica, disk_fault_plan=dfp)
    rng = random.Random(seed)
    admin = ob.OneboxAdmin(directory)
    report: dict = {"tenants": {}, "violations": []}
    try:
        # ---- topology: tenant tables, premium first ------------------
        _wait_cluster(admin, n_replica)
        tenants: List[TenantWorkload] = []
        # server-side tenant QoS topology: premium half gets weight 4
        # and an effectively-unmetered CU budget; background half gets
        # weight 1 and a tight CU rate, so the weighted-fair dispatcher
        # and the CU buckets both have something to arbitrate
        qos_decl = ",".join(
            f"tenant{t}:4:1000000" if t < n_tenants // 2
            else f"tenant{t}:1:4000"
            for t in range(n_tenants))
        for t in range(n_tenants):
            table = f"tenant{t}"
            # tenant identity default rides the table envs (clients
            # that don't pass an explicit tag adopt it on config fetch)
            envs = {"qos.tenants": qos_decl,
                    "qos.default_tenant": table}
            if t >= n_tenants // 2:
                # per-tenant capacity-unit QoS: background tenants get a
                # write throttle so a noisy neighbor cannot starve the
                # premium half's capacity (reject mode -> TryAgain,
                # surfaced in write_rejected, never a violation)
                envs["replica.write_throttling"] = "200*reject*10"
            _create_table_retry(admin, table, partitions,
                                min(3, n_replica), envs=envs)
            client = ob.connect(table, directory,
                                op_timeout_ms=op_timeout_ms,
                                tenant=table)
            tenants.append(TenantWorkload(
                table, client, random.Random(seed * 1000 + t),
                monotonic_ledger=True))
        killer = (Killer(directory, rng, mode=chaos_mode, admin=admin)
                  if chaos_mode else None)

        # ---- the soak: load + chaos + one split + one rebalance ------
        t_end = time.monotonic() + duration_s
        split_at = time.monotonic() + duration_s * 0.25
        rebalance_at = time.monotonic() + duration_s * 0.6
        next_kill = time.monotonic() + kill_every_s
        next_restart = None
        split_started = split_done = False
        rebalance_proposals = None
        while time.monotonic() < t_end:
            for tw in tenants:
                tw.step()
            now = time.monotonic()
            if killer and next_restart is not None and now >= next_restart:
                killer.restart_down()
                next_restart = None
            if killer and now >= next_kill and killer.down is None:
                killer.kill_one()
                next_restart = now + kill_every_s / 2
                next_kill = now + kill_every_s
            if not split_started and now >= split_at:
                # the elasticity act: split tenant0 ONLINE, under load
                # and chaos (retry past a mid-failover meta/primary)
                try:
                    admin.call("start_partition_split",
                               app_name="tenant0")
                    split_started = True
                except PegasusError as e:
                    report.setdefault("split_refusals", []).append(str(e))
                    split_at = now + 3.0  # guarded off; retry shortly
            if split_started and not split_done:
                try:
                    st = admin.call("split_status", app_name="tenant0",
                                    timeout=6)
                    split_done = not st.get("splitting")
                except PegasusError:
                    pass
            if rebalance_proposals is None and now >= rebalance_at:
                try:
                    rebalance_proposals = admin.call("rebalance")
                except PegasusError:
                    rebalance_at = now + 3.0
        if killer:
            killer.restart_down()
        if split_started and not split_done:
            split_done = _wait_split_done(admin, "tenant0", 60.0)

        # ---- the invariant: every acked write of every tenant --------
        for tw in tenants:
            tw.verifier.final_check(deadline_s=120.0)
            report["tenants"][tw.name] = {
                "writes_acked": tw.verifier.write_ok,
                "writes_rejected": tw.verifier.write_rejected,
                "reads_ok": tw.reads_ok,
                "read_errors": tw.read_errors,
                "ledger_reads": tw.verifier.ledger_reads,
            }
            report["violations"].extend(
                f"{tw.name}: {v}" for v in tw.verifier.violations)
        report["split_started"] = split_started
        report["split_done"] = split_done
        report["rebalance_proposals"] = rebalance_proposals
        report["kills"] = killer.kills if killer else 0
        try:
            report["hot_partitions"] = admin.call("hot_partitions",
                                                  timeout=6)
        except PegasusError:
            report["hot_partitions"] = None
        # server-side tenant QoS roll-up (meta folds the per-node
        # config_sync tenant reports): CU totals, shed/overbudget
        # counts, and any brownout verdicts from the soak
        try:
            report["tenant_qos"] = admin.call("tenant_stats", timeout=6)
        except PegasusError:
            report["tenant_qos"] = None
        # machinery counters: fences/quarantines prove the guards fired
        fence = quarantine = 0
        for n, c in admin.cfg["nodes"].items():
            if c["role"] != "replica":
                continue
            try:
                for ent in admin.remote_command(n, "metrics",
                                                ["storage"]):
                    m = ent.get("metrics", {})
                    fence += m.get("split_fence_reject_count",
                                   {}).get("value", 0)
                    quarantine += m.get("replica_quarantine_count",
                                        {}).get("value", 0)
            except PegasusError:
                pass
        report["split_fence_rejects"] = fence
        report["quarantines"] = quarantine
        report["partition_total"] = sum(
            a["partition_count"] for a in admin.call("list_apps"))
    finally:
        admin.close()
        ob.stop(directory)
    return report


def _wait_cluster(admin, n_replica: float, deadline_s: float = 120.0
                  ) -> None:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            if len(admin.call("list_nodes", timeout=6)) == n_replica:
                return
        except PegasusError:
            pass
        time.sleep(0.5)
    raise RuntimeError("cluster never came up")


def _create_table_retry(admin, table: str, partitions: int,
                        replica_count: int, deadline_s: float = 90.0,
                        envs=None) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            admin.create_table(table, partition_count=partitions,
                               replica_count=replica_count, envs=envs)
            return
        except PegasusError as e:
            if "APP_EXIST" in str(e):
                return
            if time.monotonic() > deadline:
                raise
            time.sleep(1)


def run_wan_test(directory: str, n_tenants: int = 2,
                 partitions: int = 4, duration_s: float = 60.0,
                 n_replica: int = 2, seed: int = 0,
                 kill_every_s: float = 15.0,
                 wan_delay_s: float = 0.05, wan_drop: float = 0.1,
                 blackout_s: float = 5.0,
                 op_timeout_ms: float = 8_000) -> dict:
    """Two-cluster geo-replication soak ending in the failover drill.

    Cluster A (`a-` prefix, cluster id 1) duplicates every tenant table
    to cluster B (`b-` prefix, cluster id 2). The inter-cluster link is
    WAN-shaped by a seeded FaultPlan on A's nodes (delay + drop on every
    A→B pair) plus a mid-run FULL link blackout toggled live through the
    `fault.set` remote verb; Killer kill chaos fires on BOTH clusters
    the whole run. At ~70% the controlled failover drill runs per table:
    fence A (writes get retryable ERR_DUP_FENCED), drain every
    partition to confirmed == last_committed, flip B writable — then
    the DataVerifier ledger replays EVERY write A ever acked against B.
    Zero violations is the acceptance invariant."""
    from pegasus_tpu.tools import onebox_cluster as ob

    da = os.path.join(directory, "A")
    db = os.path.join(directory, "B")
    rng = random.Random(seed)
    report: dict = {"topology": "wan", "tenants": {}, "violations": []}
    ob.start(db, n_replica=n_replica, name_prefix="b-", cluster_id=2)
    try:
        admin_b = ob.OneboxAdmin(db)
        _wait_cluster(admin_b, n_replica)
        with open(os.path.join(db, "cluster.json")) as f:
            bnodes = {n: (c["host"], c["port"])
                      for n, c in json.load(f)["nodes"].items()}
        # WAN shape: every A→B link pays delay + seeded loss (replies
        # ride the inbound TCP sessions back, so the fault charge is
        # sender-side once per link — the FaultPlan contract)
        a_fault = {
            "seed": seed + 11,
            "delay": [{"extra_s": wan_delay_s, "dst": bn}
                      for bn in bnodes],
            "drop": [{"prob": wan_drop, "dst": bn} for bn in bnodes],
        }
        ob.start(da, n_replica=n_replica, name_prefix="a-",
                 extra_peers=bnodes, fault_plan=a_fault, cluster_id=1)
        try:
            admin_a = ob.OneboxAdmin(da)
            _wait_cluster(admin_a, n_replica)
            rc = min(3, n_replica)
            tenants: List[TenantWorkload] = []
            tables = [f"tenant{t}" for t in range(n_tenants)]
            for t, table in enumerate(tables):
                _create_table_retry(admin_b, table, partitions, rc)
                _create_table_retry(admin_a, table, partitions, rc)
                client = ob.connect(table, da,
                                    op_timeout_ms=op_timeout_ms,
                                    tenant=table)
                tenants.append(TenantWorkload(
                    table, client, random.Random(seed * 1000 + t),
                    n_keys=500))
                admin_a.call("add_dup", app_name=table,
                             follower_meta="b-meta", follower_app=table,
                             timeout=30)
            a_replicas = [n for n, c in admin_a.cfg["nodes"].items()
                          if c["role"] == "replica"]
            killer_a = Killer(da, rng, mode="kill", admin=admin_a)
            killer_b = Killer(db, random.Random(seed + 1), mode="kill",
                              admin=admin_b)

            def set_link_drop(prob: float) -> None:
                for n in a_replicas:
                    for bn in bnodes:
                        try:
                            admin_a.remote_command(
                                n, "fault.set",
                                ["drop", str(prob), "", bn])
                        except PegasusError:
                            pass  # node mid-kill; the link heals with it

            t_end = time.monotonic() + duration_s
            blackout_at = time.monotonic() + duration_s * 0.4
            drill_at = time.monotonic() + duration_s * 0.7
            next_kill = time.monotonic() + kill_every_s
            restarts = {}  # killer -> restart deadline
            blackout_done = False
            side = 0
            while time.monotonic() < min(t_end, drill_at):
                for tw in tenants:
                    tw.step()
                now = time.monotonic()
                for killer, at in list(restarts.items()):
                    if now >= at:
                        killer.restart_down()
                        restarts.pop(killer)
                if now >= next_kill:
                    # alternate chaos between the two clusters
                    killer = (killer_a, killer_b)[side % 2]
                    side += 1
                    if killer.down is None:
                        killer.kill_one()
                        restarts[killer] = now + kill_every_s / 2
                    next_kill = now + kill_every_s
                if not blackout_done and now >= blackout_at:
                    # full inter-cluster partition: shipping must stall
                    # (re-drives only), then converge after the heal
                    set_link_drop(1.0)
                    time.sleep(blackout_s)
                    set_link_drop(0.0)  # heal to delay-only
                    blackout_done = True
            for killer in (killer_a, killer_b):
                killer.restart_down()
            report["blackout_done"] = blackout_done
            report["kills_a"] = killer_a.kills
            report["kills_b"] = killer_b.kills

            # ---- the drill: fence → drain → flip, per table ----------
            drill_status: dict = {}
            for table in tables:
                deadline = time.monotonic() + 60
                while True:
                    try:
                        admin_a.call("dup_failover", app_name=table,
                                     timeout=15)
                        break
                    except PegasusError as e:
                        if time.monotonic() > deadline:
                            raise
                        drill_status.setdefault(
                            "start_retries", []).append(str(e))
                        time.sleep(1)
            # writes during the drain must surface the typed fence,
            # never an ack that could be stranded on A
            fence_seen = 0
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                done = 0
                for table in tables:
                    try:
                        st = admin_a.call("dup_failover_status",
                                          app_name=table, timeout=10)
                        drill_status[table] = st
                        done += st["phase"] == "done"
                    except PegasusError:
                        pass
                if done == len(tables):
                    break
                time.sleep(1)
            report["drill"] = drill_status
            report["drill_done"] = all(
                drill_status.get(t, {}).get("phase") == "done"
                for t in tables)
            try:
                report["dup_stats"] = admin_a.call("dup_stats",
                                                   timeout=10)
            except PegasusError:
                report["dup_stats"] = None
            for n in a_replicas:
                try:
                    for ent in admin_a.remote_command(n, "metrics",
                                                      ["storage"]):
                        fence_seen += ent.get("metrics", {}).get(
                            "dup_fence_reject_count", {}).get("value", 0)
                except PegasusError:
                    pass
            report["fence_rejects"] = fence_seen

            # ---- the invariant: every write A acked reads back on B --
            for tw in tenants:
                b_client = ob.connect(tw.name, db,
                                      op_timeout_ms=op_timeout_ms,
                                      tenant=tw.name)
                tw.verifier.client = b_client
                tw.verifier.final_check(deadline_s=180.0)
                report["tenants"][tw.name] = {
                    "writes_acked": tw.verifier.write_ok,
                    "writes_rejected": tw.verifier.write_rejected,
                    "reads_ok": tw.reads_ok,
                    "read_errors": tw.read_errors,
                }
                report["violations"].extend(
                    f"{tw.name}: {v}" for v in tw.verifier.violations)
        finally:
            try:
                admin_a.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            ob.stop(da)
    finally:
        try:
            admin_b.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        ob.stop(db)
    return report


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--topology", choices=["single", "wan"],
                    default="single",
                    help="wan: two clusters, A geo-replicating to B "
                         "across a faulted link, ending in the "
                         "controlled failover drill")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--partitions", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chaos", choices=["kill", "pause", "corrupt", "none"],
                    default="kill")
    ap.add_argument("--disk-faults", action="store_true")
    args = ap.parse_args()
    if args.topology == "wan":
        report = run_wan_test(
            args.dir, n_tenants=args.tenants,
            partitions=args.partitions, duration_s=args.duration,
            n_replica=args.nodes, seed=args.seed)
    else:
        report = run_scale_test(
            args.dir, n_tenants=args.tenants, partitions=args.partitions,
            duration_s=args.duration, n_replica=args.nodes, seed=args.seed,
            chaos_mode=None if args.chaos == "none" else args.chaos,
            disk_faults=args.disk_faults)
    print(json.dumps(report, indent=1, default=str))
    sys.exit(1 if report["violations"] else 0)


if __name__ == "__main__":
    main()
