"""Chaos harness: random process kills under continuous verification.

Parity: src/test/kill_test/ — process killers plus data_verifier.cpp's
continuous write/read consistency checking, driven as a script
(admin_tools/pegasus_kill_test.sh). Runs against the multi-process
onebox: a verifier loop writes sequenced records and re-reads a random
sample of everything previously acked; a killer loop kill -9s a random
replica node, waits, and restarts it.

Modes: kill (kill -9 + restart), pause (SIGSTOP/SIGCONT hung-node),
corrupt (seeded bit-flips inside a live replica's SST blocks — the
victim stays up; detection must come from verify-on-read / the
background scrubber, then quarantine + guardian re-learn repair the
replica while the DataVerifier invariant holds).

CLI:
    python -m pegasus_tpu.tools.kill_test --dir D --duration 120
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time
from typing import Dict, List, Optional

from pegasus_tpu.utils.errors import PegasusError

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class DataVerifier:
    """Continuous write->read verification (data_verifier.cpp parity):
    every acked write must remain readable with its exact value.

    `monotonic_ledger` adds the follower-read invariant: a small set of
    REPEATEDLY-OVERWRITTEN ledger keys carries a strictly increasing
    counter, and every ledger read (issued at `read_consistency`, e.g.
    MONOTONIC so it fans out to lease-holding secondaries) must never
    observe a counter below what this session already saw for that key
    — and never NotFound after a value was observed. The write-once
    `kt` keys can't catch a time-travelling follower read; the ledger
    keys exist to."""

    LEDGER_KEYS = 8

    def __init__(self, client, rng: random.Random,
                 monotonic_ledger: bool = False,
                 read_consistency=None) -> None:
        self.client = client
        self.rng = rng
        self.acked: Dict[bytes, bytes] = {}
        self.seq = 0
        self.write_ok = 0
        self.write_rejected = 0
        self.violations: List[str] = []
        self.monotonic_ledger = monotonic_ledger
        self.read_consistency = read_consistency
        self.ledger_next: Dict[bytes, int] = {}   # next counter to write
        self.ledger_seen: Dict[bytes, int] = {}   # session read floor
        self.ledger_reads = 0

    def step(self) -> None:
        # one write
        self.seq += 1
        hk = b"kt%06d" % self.seq
        value = b"v%d" % self.seq
        try:
            if self.client.set(hk, b"s", value) == 0:
                self.acked[hk] = value
                self.write_ok += 1
            else:
                self.write_rejected += 1
        except PegasusError:
            self.write_rejected += 1
        # verify a sample of history
        if self.acked:
            for hk in self.rng.sample(sorted(self.acked),
                                      min(4, len(self.acked))):
                want = self.acked[hk]
                try:
                    err, got = self.client.get(hk, b"s")
                except PegasusError:
                    continue  # unavailable now; durability checked later
                if err == 0 and got != want:
                    self.violations.append(
                        f"{hk!r}: read {got!r}, acked {want!r}")
                elif err == 1:  # NotFound: an acked write vanished
                    self.violations.append(f"{hk!r}: acked write lost")
        if self.monotonic_ledger:
            self._ledger_step()

    @staticmethod
    def _ledger_counter(value: bytes) -> Optional[int]:
        if value[:1] == b"c" and value[1:].isdigit():
            return int(value[1:])
        return None

    def _ledger_step(self) -> None:
        # bump one ledger key. An unacked write may still have
        # committed — harmless: the floor only ratchets on READS, and
        # a committed-but-unacked counter that becomes visible simply
        # raises the floor when first observed.
        hk = b"ml%02d" % self.rng.randrange(self.LEDGER_KEYS)
        nxt = self.ledger_next.get(hk, 0) + 1
        self.ledger_next[hk] = nxt
        try:
            self.client.set(hk, b"c", b"c%08d" % nxt)
        except PegasusError:
            pass
        # read a sample back at the session's consistency level: the
        # observed counter must never regress below this session's floor
        for hk in self.rng.sample(sorted(self.ledger_next),
                                  min(2, len(self.ledger_next))):
            try:
                if self.read_consistency is not None:
                    err, got = self.client.get(
                        hk, b"c", consistency=self.read_consistency)
                else:  # plain clients lack the kwarg entirely
                    err, got = self.client.get(hk, b"c")
            except PegasusError:
                continue  # unavailable now; not a monotonicity breach
            self.ledger_reads += 1
            floor = self.ledger_seen.get(hk, 0)
            if err == 1:
                if floor:
                    self.violations.append(
                        f"ledger {hk!r}: NotFound after observing "
                        f"counter {floor} (monotonic-reads breach)")
                continue
            if err != 0:
                continue
            cur = self._ledger_counter(got)
            if cur is None:
                self.violations.append(
                    f"ledger {hk!r}: unparseable value {got!r}")
            elif cur < floor:
                self.violations.append(
                    f"ledger {hk!r}: read counter {cur} below session "
                    f"floor {floor} (monotonic-reads breach)")
            else:
                self.ledger_seen[hk] = cur

    def final_check(self, deadline_s: float = 120.0) -> None:
        """After chaos ends: EVERY acked write must read back."""
        deadline = time.monotonic() + deadline_s
        pending = dict(self.acked)
        while pending and time.monotonic() < deadline:
            for hk in list(pending):
                try:
                    err, got = self.client.get(hk, b"s")
                except PegasusError:
                    break
                if err == 0 and got == pending[hk]:
                    del pending[hk]
                elif err == 1:
                    self.violations.append(
                        f"final: {hk!r} acked write lost")
                    del pending[hk]
            if pending:
                time.sleep(1)
        for hk in pending:
            self.violations.append(f"final: {hk!r} unreadable at deadline")


def corrupt_sst_file(path: str, rng: random.Random) -> bool:
    """Flip one seeded bit inside a random DATA BLOCK of a live SST —
    the at-rest single-event-upset. The flip targets block bytes
    specifically (never the index/footer/bloom section) so detection
    exercises the per-block crc32, exactly the protection a real
    flipped sector relies on. Returns False when the file has no
    blocks to corrupt."""
    import struct  # noqa: F401 - FOOTER below is a struct.Struct

    from pegasus_tpu.storage.sstable import FOOTER

    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size < FOOTER.size + 4:
            return False
        f.seek(size - FOOTER.size)
        index_offset, index_size, _crc, _magic = FOOTER.unpack(
            f.read(FOOTER.size))
        f.seek(index_offset)
        index = json.loads(f.read(index_size))
        blocks = index.get("blocks") or []
        if not blocks:
            return False
        b = blocks[rng.randrange(len(blocks))]
        pos = b["off"] + rng.randrange(b["size"])
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ (1 << rng.randrange(8))]))
        f.flush()
        os.fsync(f.fileno())
    return True


class Killer:
    """Random chaos strikes against replica processes.

    mode='kill': kill -9 + cold restart (crash recovery).
    mode='pause': SIGSTOP + later SIGCONT (the hung-node shape — GC
    pause, disk stall — that must trip failure-detector lease expiry,
    and whose victim wakes up believing it still serves).
    mode='corrupt': flip seeded bits in a live replica's SST files (the
    process stays up and trusts its disk; the block-crc verify-on-read
    path or the background scrubber must detect, quarantine, and
    re-learn — `admin` forces flushes so SSTs exist to corrupt)."""

    def __init__(self, directory: str, rng: random.Random,
                 mode: str = "kill", admin=None) -> None:
        if mode not in ("kill", "pause", "corrupt"):
            raise ValueError(f"unknown chaos mode {mode!r}")
        self.directory = directory
        self.rng = rng
        self.mode = mode
        self.admin = admin
        with open(os.path.join(directory, "cluster.json")) as f:
            self.cfg = json.load(f)
        self.replica_nodes = [n for n, c in self.cfg["nodes"].items()
                              if c["role"] == "replica"]
        self.down: Optional[str] = None
        self.kills = 0

    def corrupt_one(self) -> Optional[str]:
        """Flip a bit in one SST of a random node; returns the victim
        (None when no SST was available to corrupt yet)."""
        victim = self.rng.choice(self.replica_nodes)
        if self.admin is not None:
            try:
                # memtables flush so there are on-disk blocks to flip
                self.admin.remote_command(victim, "flush", [])
            except PegasusError:
                return None  # node busy/unreachable; try next strike
        import glob

        ssts = sorted(glob.glob(os.path.join(
            self.cfg["data_root"], victim, "*", "app", "sst", "*.sst")))
        if not ssts:
            return None
        try:
            hit = corrupt_sst_file(self.rng.choice(ssts), self.rng)
        except (OSError, ValueError, KeyError):
            # the live node's compaction unlinked (or was mid-rewriting)
            # the chosen file between the glob and the open: skip this
            # strike, the next one picks from the current file set
            return None
        if hit:
            self.kills += 1
            return victim
        return None

    def kill_one(self) -> Optional[str]:
        from pegasus_tpu.tools.onebox_cluster import kill_node, pause_node

        if self.mode == "corrupt":
            return self.corrupt_one()
        victim = self.rng.choice([n for n in self.replica_nodes
                                  if n != self.down])
        if self.mode == "pause":
            pause_node(victim, self.directory)
        else:
            kill_node(victim, self.directory)
        self.down = victim
        self.kills += 1
        return victim

    def restart_down(self) -> Optional[str]:
        if self.down is None:
            return None
        if self.mode == "pause":
            from pegasus_tpu.tools.onebox_cluster import resume_node

            name = self.down
            resume_node(name, self.directory)
            self.down = None
            return name
        name = self.down
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        log = open(os.path.join(self.directory, "logs",
                                f"{name}.restart.log"), "ab")
        p = subprocess.Popen(
            [sys.executable, "-m", "pegasus_tpu.server.node_main",
             "--config", os.path.join(self.directory, "cluster.json"),
             "--name", name],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            cwd=_REPO_ROOT)
        # track the fresh pid so stop()/later kills target the live one
        pids_path = os.path.join(self.directory, "pids.json")
        with open(pids_path) as f:
            pids = json.load(f)
        pids[name] = p.pid
        with open(pids_path, "w") as f:
            json.dump(pids, f)
        self.down = None
        return name


def run_kill_test(directory: str, duration_s: float = 60.0,
                  kill_every_s: float = 12.0, seed: int = 0,
                  table: str = "killtest", mode: str = "kill",
                  op_timeout_ms: Optional[float] = None,
                  monotonic_ledger: bool = False) -> dict:
    """`op_timeout_ms`: verifier-client end-to-end op deadline — under
    chaos every op must either succeed or raise a typed PegasusError
    within it (no hangs); None keeps the flag default.
    `monotonic_ledger`: also run the follower-read monotonic-reads
    ledger, with the ledger reads issued at MONOTONIC consistency so
    they fan out to secondaries under the read lease while nodes die."""
    from pegasus_tpu.tools import onebox_cluster as ob

    rng = random.Random(seed)
    admin = ob.OneboxAdmin(directory)
    deadline = time.monotonic() + 90
    n_nodes = len([1 for c in admin.cfg["nodes"].values()
                   if c["role"] == "replica"])
    while time.monotonic() < deadline:
        try:
            if len(admin.call("list_nodes", timeout=6)) == n_nodes:
                break
        except PegasusError:
            pass  # meta still booting/electing (slow loaded machines)
        time.sleep(0.5)
    create_deadline = time.monotonic() + 60
    while True:
        try:
            admin.create_table(table, partition_count=4, replica_count=3)
            break
        except PegasusError as e:
            if "APP_EXIST" in str(e):
                break
            if time.monotonic() > create_deadline:
                raise
            time.sleep(1)
    client = ob.connect(table, directory, op_timeout_ms=op_timeout_ms)
    if monotonic_ledger:
        from pegasus_tpu.client.cluster_client import MONOTONIC

        verifier = DataVerifier(client, rng, monotonic_ledger=True,
                                read_consistency=MONOTONIC)
    else:
        verifier = DataVerifier(client, rng)
    killer = Killer(directory, rng, mode=mode,
                    admin=admin if mode == "corrupt" else None)

    t_end = time.monotonic() + duration_s
    next_kill = time.monotonic() + kill_every_s
    next_restart = None
    while time.monotonic() < t_end:
        verifier.step()
        now = time.monotonic()
        if next_restart is not None and now >= next_restart:
            killer.restart_down()
            next_restart = None
        if now >= next_kill and killer.down is None:
            killer.kill_one()
            next_restart = now + kill_every_s / 2
            next_kill = now + kill_every_s
        time.sleep(0.05)
    killer.restart_down()
    verifier.final_check()
    report = {
        "mode": mode,
        "kills": killer.kills,
        "writes_acked": verifier.write_ok,
        "writes_rejected": verifier.write_rejected,
        "violations": verifier.violations,
    }
    if monotonic_ledger:
        report["ledger_reads"] = verifier.ledger_reads
    if mode == "corrupt":
        # the integrity loop's observability: every planted flip must
        # have been detected (read path or scrub), quarantined, and
        # repaired — the storage-entity counters record each stage
        quarantines = scrub_hits = 0
        for n in killer.replica_nodes:
            try:
                for ent in admin.remote_command(n, "metrics",
                                                ["storage"]):
                    m = ent.get("metrics", {})
                    quarantines += m.get("replica_quarantine_count",
                                         {}).get("value", 0)
                    scrub_hits += m.get("scrub_corrupt_blocks",
                                        {}).get("value", 0)
            except PegasusError:
                pass  # node mid-restart; counters are best-effort
        report["quarantines"] = quarantines
        report["scrub_corrupt_blocks"] = scrub_hits
    admin.close()
    return report


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--kill-every", type=float, default=12.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mode", choices=["kill", "pause", "corrupt"],
                    default="kill",
                    help="kill: kill -9 + restart (crash recovery); "
                         "pause: SIGSTOP/SIGCONT (hung-node detection); "
                         "corrupt: seeded SST bit-flips (block-crc "
                         "detection -> quarantine -> re-learn)")
    ap.add_argument("--monotonic-ledger", action="store_true",
                    help="also run the follower-read monotonic-reads "
                         "ledger (MONOTONIC-consistency reads against "
                         "secondaries under chaos)")
    args = ap.parse_args()
    report = run_kill_test(args.dir, args.duration, args.kill_every,
                           args.seed, mode=args.mode,
                           monotonic_ledger=args.monotonic_ledger)
    print(json.dumps(report, indent=1))
    sys.exit(1 if report["violations"] else 0)


if __name__ == "__main__":
    main()
