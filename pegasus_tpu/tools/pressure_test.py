"""Pressure tier: sustained mixed load with online data verification.

Parity: src/test/pressure_test/ (sustained load generator with per-case
qps control) + src/test/kill_test/data_verifier.cpp (every acked write
must stay readable with its exact value) — run for MINUTES against the
multi-process onebox, not seconds, reporting ops/s over time.

Workload mix per loop iteration (YCSB-A-flavoured, configurable):
    set / get / del / multi_get / scan over a growing sequenced keyspace
with continuous verification: reads check the exact last-acked value,
scans check ordering + membership of the sampled hashkey. Any
divergence is a consistency VIOLATION and fails the run.

CLI:
    python -m pegasus_tpu.tools.pressure_test --dir D --duration 300 \
        [--qps 0 (unthrottled)] [--report-every 10]

Output: one JSON line per report interval
    {"t": s, "ops": n, "ops_per_s": r, "violations": 0, ...}
and a final summary line. Exit code 1 on any violation.

The CI smoke (tests/test_pressure.py) runs the same loop for a few
seconds in-process; this module is the minutes-long operator tier.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from typing import Dict, List

from pegasus_tpu.utils.errors import PegasusError


class PressureWorkload:
    """One client's mixed-op loop with online verification.

    Keeps an acked-model: hashkey -> {sortkey: value} mirroring every
    acknowledged mutation; every read verifies against it. The model IS
    the verifier (data_verifier.cpp's expectation table)."""

    def __init__(self, client, seed: int = 0,
                 mix=(("set", 40), ("get", 35), ("multi_get", 10),
                     ("scan", 10), ("del", 5))) -> None:
        self.client = client
        self.rng = random.Random(seed)
        self.model: Dict[bytes, Dict[bytes, bytes]] = {}
        # O(1) random sampling over a growing/shrinking keyspace:
        # parallel list + index map, swap-remove on delete (list(model)
        # per op would make the LOAD GENERATOR quadratic over a long
        # run and read as a server throughput regression)
        self._hk_list: List[bytes] = []
        self._hk_idx: Dict[bytes, int] = {}
        self.seq = 0
        self.ops = 0
        self.rejected = 0
        self.violations: List[str] = []
        self._ops, weights = zip(*mix)
        self._weights = list(weights)

    # ---- model maintenance --------------------------------------------

    def _track(self, hk: bytes) -> None:
        if hk not in self._hk_idx:
            self._hk_idx[hk] = len(self._hk_list)
            self._hk_list.append(hk)

    def _untrack(self, hk: bytes) -> None:
        i = self._hk_idx.pop(hk, None)
        if i is None:
            return
        last = self._hk_list.pop()
        if last != hk:
            self._hk_list[i] = last
            self._hk_idx[last] = i

    def _adopt(self, hk: bytes, sk: bytes) -> None:
        """A write/delete raised (e.g. timeout): the outcome is
        AMBIGUOUS — it may have committed. Re-read and adopt the
        store's answer as the expectation, so a committed-but-unacked
        mutation is not later reported as a false corruption
        (kill_test's verifier sidesteps this by never overwriting;
        this mixed workload overwrites constantly)."""
        self.rejected += 1
        try:
            err, got = self.client.get(hk, sk)
        except PegasusError:
            # still unreachable: stop verifying this sort key
            sks = self.model.get(hk)
            if sks is not None:
                sks.pop(sk, None)
                if not sks:
                    self.model.pop(hk, None)
                    self._untrack(hk)
            return
        if err == 0:
            self.model.setdefault(hk, {})[sk] = got
            self._track(hk)
        else:
            sks = self.model.get(hk)
            if sks is not None:
                sks.pop(sk, None)
                if not sks:
                    self.model.pop(hk, None)
                    self._untrack(hk)

    # ---- op implementations -------------------------------------------

    def _hk(self, existing: bool) -> bytes:
        if existing and self._hk_list:
            return self._hk_list[self.rng.randrange(len(self._hk_list))]
        self.seq += 1
        return b"pt%07d" % self.seq

    def _op_set(self) -> None:
        hk = self._hk(self.rng.random() < 0.5)
        sk = b"s%02d" % self.rng.randrange(8)
        value = b"v%d.%d" % (self.seq, self.rng.randrange(1 << 20))
        try:
            if self.client.set(hk, sk, value) == 0:
                self.model.setdefault(hk, {})[sk] = value
                self._track(hk)
            else:
                self.rejected += 1
        except PegasusError:
            self._adopt(hk, sk)

    def _op_del(self) -> None:
        if not self.model:
            return
        hk = self._hk(True)
        sks = self.model.get(hk)
        if not sks:
            return
        sk = next(iter(sks))
        try:
            if self.client.delete(hk, sk) == 0:
                sks.pop(sk, None)
                if not sks:
                    self.model.pop(hk, None)
                    self._untrack(hk)
            else:
                self.rejected += 1
        except PegasusError:
            self._adopt(hk, sk)

    def _op_get(self) -> None:
        if not self.model:
            return
        hk = self._hk(True)
        sks = self.model.get(hk)
        if not sks:
            return
        sk = self.rng.choice(list(sks))
        want = sks[sk]
        try:
            err, got = self.client.get(hk, sk)
        except PegasusError:
            self.rejected += 1
            return
        if err != 0 or got != want:
            self.violations.append(
                f"get {hk!r}/{sk!r}: want {want!r}, got err={err} "
                f"{got!r}")

    def _op_multi_get(self) -> None:
        if not self.model:
            return
        hk = self._hk(True)
        want = self.model.get(hk)
        if not want:
            return
        try:
            err, got = self.client.multi_get(hk)
        except PegasusError:
            self.rejected += 1
            return
        if err != 0 or got != want:
            self.violations.append(
                f"multi_get {hk!r}: want {len(want)} kvs, got err={err} "
                f"{len(got)} kvs")

    def _op_scan(self) -> None:
        if not self.model:
            return
        hk = self._hk(True)
        want = self.model.get(hk)
        if not want:
            return
        try:
            scanner = self.client.get_scanner(hk)
            got = {sk: v for _hk, sk, v in scanner}
        except (PegasusError, RuntimeError):
            self.rejected += 1
            return
        if got != want:
            self.violations.append(
                f"scan {hk!r}: want {len(want)} rows, got {len(got)}")

    # ---- loop ----------------------------------------------------------

    def step(self) -> None:
        op = self.rng.choices(self._ops, weights=self._weights)[0]
        getattr(self, f"_op_{op}")()
        self.ops += 1


def run(client, duration_s: float, qps: float = 0.0,
        report_every: float = 10.0, seed: int = 0,
        out=sys.stdout) -> dict:
    """Drive the workload for `duration_s`; returns the summary dict."""
    w = PressureWorkload(client, seed=seed)
    t0 = time.monotonic()
    next_report = t0 + report_every
    last_ops = 0
    last_t = t0
    series = []
    while True:
        now = time.monotonic()
        if now - t0 >= duration_s:
            break
        w.step()
        if qps > 0:
            # crude rate limit: sleep off any lead over the target rate
            lead = w.ops / qps - (now - t0)
            if lead > 0.002:
                time.sleep(lead)
        if now >= next_report:
            rate = (w.ops - last_ops) / max(now - last_t, 1e-9)
            rec = {"t": round(now - t0, 1), "ops": w.ops,
                   "ops_per_s": round(rate, 1),
                   "rejected": w.rejected,
                   "violations": len(w.violations),
                   "keys": len(w.model)}
            print(json.dumps(rec), file=out, flush=True)
            series.append(rec)
            last_ops, last_t = w.ops, now
            next_report = now + report_every
    elapsed = time.monotonic() - t0
    summary = {
        "summary": True,
        "duration_s": round(elapsed, 1),
        "ops": w.ops,
        "ops_per_s": round(w.ops / max(elapsed, 1e-9), 1),
        "rejected": w.rejected,
        "violations": len(w.violations),
        "violation_samples": w.violations[:5],
        "keys": len(w.model),
        "series": series,
    }
    print(json.dumps(summary), file=out, flush=True)
    return summary


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--dir", default=None,
                    help="onebox directory (tools/onebox_cluster)")
    ap.add_argument("--app", default="pressure")
    ap.add_argument("--duration", type=float, default=300.0)
    ap.add_argument("--qps", type=float, default=0.0,
                    help="target ops/s (0 = unthrottled)")
    ap.add_argument("--report-every", type=float, default=10.0)
    ap.add_argument("--partitions", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from pegasus_tpu.tools import onebox_cluster as ob

    d = args.dir or ob.DEFAULT_DIR
    admin = ob.OneboxAdmin(d)
    try:
        admin.create_table(args.app, partition_count=args.partitions)
    except PegasusError:
        pass  # already exists: keep pressing the same table
    admin.close()
    client = ob.connect(args.app, d)
    summary = run(client, args.duration, qps=args.qps,
                  report_every=args.report_every, seed=args.seed)
    sys.exit(1 if summary["violations"] else 0)


if __name__ == "__main__":
    main()
