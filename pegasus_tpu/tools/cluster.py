"""SimCluster: an in-process replicated cluster (meta + N replica nodes).

The replicated onebox: one MetaService and N ReplicaStubs wired over the
deterministic SimNetwork (parity: the reference's onebox, run.sh:60-66 —
N meta + M replica processes on one machine — collapsed into one process
with simulated transport; the multi-process deployment swaps SimNetwork
for the TCP transport without touching this wiring).

`step()` advances the cluster exactly like the real timers would: worker
beacons, meta FD check + guardian pass, message delivery. It doubles as
the ClusterClient's pump, so a client blocked on a reply keeps failure
detection and cures moving — a mid-workload failover resolves while the
client retries.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from pegasus_tpu.client.cluster_client import ClusterClient
from pegasus_tpu.meta.meta_service import MetaService
from pegasus_tpu.replica.stub import ReplicaStub
from pegasus_tpu.runtime.sim import SimLoop, SimNetwork


class SimCluster:
    def __init__(self, data_dir: str, n_nodes: int = 3, seed: int = 0,
                 beacon_interval: float = 3.0, n_meta: int = 1,
                 auth_secret: Optional[str] = None,
                 name_prefix: str = "", loop: Optional[SimLoop] = None,
                 net: Optional[SimNetwork] = None,
                 cluster_id: int = 1) -> None:
        """`name_prefix`/`loop`/`net`/`cluster_id`: the two-cluster
        geo-replication shape — build BOTH clusters over ONE shared
        loop+network (prefixes keep their node names apart, distinct
        cluster ids keep their timetags and the duplication
        origin-echo filter honest), then fault the inter-cluster links
        like a WAN. Step the second cluster with `advance=False` so a
        pair of steps advances shared time once, not twice."""
        self.data_dir = data_dir
        self.name_prefix = name_prefix
        self.cluster_id = cluster_id
        self.loop = loop if loop is not None else SimLoop(seed=seed)
        self.net = net if net is not None else SimNetwork(self.loop)
        self.beacon_interval = beacon_interval
        clock = lambda: self.loop.now  # noqa: E731
        if n_meta <= 1:
            self.metas = [MetaService(
                f"{name_prefix}meta",
                os.path.join(data_dir, f"{name_prefix}meta"),
                self.net, clock)]
        else:
            group = [f"{name_prefix}meta{i}" for i in range(n_meta)]
            self.metas = [MetaService(
                name, os.path.join(data_dir, name), self.net, clock,
                peers=group) for name in group]
            # deterministic initial leader: meta0 wins the first election
            self.metas[0].election._start_election()
            self.loop.run_until_idle()
        self.auth_secret = auth_secret
        self.stubs: Dict[str, ReplicaStub] = {}
        self._dead: set = set()
        self._last_step_time = 0.0
        # wall-anchored clock so value timetags / TTL math are realistic
        # while FD timing stays on deterministic sim time
        self._epoch = 1_700_000_000
        # distributed-tracing rings live on the SIM clock: span
        # timelines (and the slow-trace threshold) must see injected
        # virtual delays, not the microseconds of wall time a sim
        # schedule actually burns
        from pegasus_tpu.utils import tracing

        self._trace_clock = lambda: self._epoch + self.loop.now
        self._trace_rings: List[str] = []
        for m in self.metas:
            tracing.ring_for(m.name, clock=self._trace_clock)
            self._trace_rings.append(m.name)
        for i in range(n_nodes):
            self.add_node(f"{name_prefix}node{i}")
        # settle: everyone beacons, FD learns the membership
        self.step(rounds=2)

    # ---- membership ----------------------------------------------------

    def add_node(self, name: str) -> ReplicaStub:
        from pegasus_tpu.utils import tracing

        tracing.ring_for(name, clock=self._trace_clock)
        self._trace_rings.append(name)
        stub = ReplicaStub(
            name, os.path.join(self.data_dir, name), self.net,
            clock=lambda: self._epoch + self.loop.now,
            sim_clock=lambda: self.loop.now,
            cluster_id=self.cluster_id)
        stub.meta_addrs = [m.name for m in self.metas]
        stub.meta_addr = self.metas[0].name
        stub.auth_secret = self.auth_secret
        self.stubs[name] = stub
        return stub

    def kill(self, name: str) -> None:
        """Crash a node: partition it and stop its beacons (parity:
        kill -9 in the kill_test harness)."""
        self._dead.add(name)
        self.net.partition(name)

    def revive(self, name: str) -> None:
        self._dead.discard(name)
        self.net.heal(name)

    # ---- time ----------------------------------------------------------

    def step(self, rounds: int = 1, advance: bool = True) -> None:
        """One beacon interval per round: beacons from alive nodes, message
        delivery, meta FD + guardian tick. `advance=False` fires this
        cluster's timers and drains delivery WITHOUT advancing the
        shared loop a beacon interval — the second cluster of a
        two-cluster topology steps this way so paired steps move shared
        time once."""
        from pegasus_tpu.replica.replica import PartitionStatus

        for _ in range(rounds):
            for name, stub in self.stubs.items():
                if name not in self._dead:
                    stub.send_beacon()
                    # group-check timer: advances secondaries' commit
                    # points (piggy-backed last_committed) and re-sends
                    # lost prepares (parity: replica_check.cpp:212)
                    for r in stub.replicas.values():
                        if r.status == PartitionStatus.PRIMARY:
                            r.broadcast_group_check()
                    # config-sync timer (parity: replica_stub.cpp:944
                    # query_configuration_by_node): pull reconciliation
                    # re-delivers config changes whose one-shot proposal
                    # was LOST — without it a dropped promotion wedges
                    # the partition until manual intervention
                    stub.config_sync()
                    stub.dup_tick()
                    stub.split_tick()
                    stub.transfer_tick()
                    # background scrub timer: latent at-rest corruption
                    # on non-serving replicas is detected here
                    stub.scrub_tick()
                    # flight-recorder timer: drain metrics into the
                    # node's rings + one watchdog pass (coalesced to
                    # the recorder cadence internally)
                    stub.health_tick()
            if advance:
                self.loop.run_for(self.beacon_interval)
            else:
                self.loop.run_until_idle()
            for m in self.metas:
                if m.name not in self._dead:
                    m.tick()
        self._last_step_time = self.loop.now
        self.loop.run_until_idle()

    def pump(self) -> None:
        """ClusterClient wait-callback: drain messages; if the client is
        still blocked (caller loops), advance a beacon interval so FD/
        guardian progress can unblock it. Heavy traffic ALSO advances sim
        time (per-message delays), so the timer round must fire whenever
        a beacon interval of sim time has passed — otherwise a long write
        burst starves beacons and every worker's lease lapses."""
        if (self.loop.run_until_idle() == 0
                or self.loop.now - self._last_step_time
                > self.beacon_interval):
            self.step()

    # ---- DDL + clients -------------------------------------------------

    @property
    def meta(self) -> MetaService:
        """The current leader meta (single-meta: the only one)."""
        for m in self.metas:
            if m.election.is_leader and m.name not in self._dead:
                return m
        alive = [m for m in self.metas if m.name not in self._dead]
        if not alive:
            raise RuntimeError("no live meta")
        # no elected leader yet: return a live member so callers get a
        # VISIBLE not-enough-members/forwarded behavior, never a dead one
        return alive[0]

    def create_table(self, app_name: str, partition_count: int = 8,
                     replica_count: int = 3,
                     envs: Optional[Dict[str, str]] = None) -> int:
        app_id = self.meta.create_app(app_name, partition_count,
                                      replica_count, envs)
        self.loop.run_until_idle()
        return app_id

    def client(self, app_name: str, name: Optional[str] = None,
               user: str = "admin",
               tenant: Optional[str] = None) -> ClusterClient:
        auth = None
        if self.auth_secret:
            from pegasus_tpu.security.auth import make_credentials

            auth = make_credentials(user, self.auth_secret)
        # deadline timebase = the stubs' wall-anchored clock; backoff
        # "sleep" advances VIRTUAL time (delivering due messages), so
        # retry pacing shapes the schedule without wall-clock cost
        import zlib

        # per-client FIXED backoff seed (name-derived, not hash() —
        # that's salted per interpreter): sim schedules replay exactly,
        # while two sim clients still draw distinct jitter streams
        # (real clients default to per-process entropy instead)
        cname = name or f"{self.name_prefix}client-{app_name}"
        from pegasus_tpu.utils import tracing

        tracing.ring_for(cname, clock=self._trace_clock)
        self._trace_rings.append(cname)
        c = ClusterClient(self.net, cname,
                          [m.name for m in self.metas],
                          app_name, pump=self.pump, auth=auth,
                          clock=lambda: self._epoch + self.loop.now,
                          sleep=lambda s: self.loop.run_for(s),
                          backoff_seed=zlib.crc32(cname.encode()),
                          tenant=tenant)
        return c

    def primaries(self, app_id: int) -> List[str]:
        app = self.meta.state.apps[app_id]
        return [self.meta.state.get_partition(app_id, p).primary
                for p in range(app.partition_count)]

    def close(self) -> None:
        from pegasus_tpu.utils import tracing

        for stub in self.stubs.values():
            stub.close()
        # drop the rings this cluster registered: their clock closures
        # pin the whole dead cluster, and stale spans must not leak
        # into a later cluster reusing the same node names
        for name in self._trace_rings:
            tracing.drop_ring(name)
