"""Operational tools: onebox cluster, interactive shell (reference:
src/shell/, run.sh onebox, admin-cli/)."""

from pegasus_tpu.tools.onebox import Onebox
