"""Onebox: a whole cluster in one process rooted at a directory.

Parity: the reference's onebox mode (run.sh:60-66 start_onebox — N meta +
M replica processes on one machine) as used by every function test. Here
the catalog (table name -> app_id/partition_count) persists in a JSON
file and tables open lazily; the shell and function-style tests drive it.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from pegasus_tpu.client import PegasusClient, Table


class Onebox:
    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._catalog_path = os.path.join(root, "catalog.json")
        self._catalog: Dict[str, dict] = {}
        self._tables: Dict[str, Table] = {}
        if os.path.exists(self._catalog_path):
            with open(self._catalog_path) as f:
                self._catalog = json.load(f)

    def _persist(self) -> None:
        with open(self._catalog_path, "w") as f:
            json.dump(self._catalog, f, indent=1)

    def create_table(self, name: str, partition_count: int = 8) -> Table:
        if name in self._catalog:
            raise ValueError(f"table {name} exists")
        app_id = max((t["app_id"] for t in self._catalog.values()),
                     default=0) + 1
        self._catalog[name] = {"app_id": app_id,
                               "partition_count": partition_count}
        self._persist()
        return self.open_table(name)

    def open_table(self, name: str) -> Table:
        if name not in self._catalog:
            raise KeyError(f"no such table: {name}")
        t = self._tables.get(name)
        if t is None:
            info = self._catalog[name]
            t = Table(os.path.join(self.root, name),
                      app_id=info["app_id"], app_name=name,
                      partition_count=info["partition_count"])
            if info.get("envs"):
                t.update_app_envs(info["envs"])
            self._tables[name] = t
        return t

    def split_table(self, name: str) -> int:
        """2x partition split, persisted in the catalog. Returns the new
        partition count."""
        t = self.open_table(name)
        t.split()
        self._catalog[name]["partition_count"] = t.partition_count
        self._persist()
        return t.partition_count

    def update_app_envs(self, name: str, envs: Dict[str, str]) -> None:
        """Persisted env update (parity: envs live in meta state and are
        re-delivered through config-sync after restarts)."""
        t = self.open_table(name)
        t.update_app_envs(envs)  # validates before we persist
        self._catalog[name].setdefault("envs", {}).update(envs)
        self._persist()

    def drop_table(self, name: str) -> None:
        if name not in self._catalog:
            raise KeyError(f"no such table: {name}")
        t = self._tables.pop(name, None)
        if t is not None:
            t.close()
        import shutil
        shutil.rmtree(os.path.join(self.root, name), ignore_errors=True)
        del self._catalog[name]
        self._persist()

    def list_tables(self) -> List[dict]:
        return [{"name": name, **info}
                for name, info in sorted(self._catalog.items())]

    def client(self, name: str) -> PegasusClient:
        return PegasusClient(self.open_table(name))

    def close(self) -> None:
        for t in self._tables.values():
            t.close()
        self._tables.clear()
