from pegasus_tpu.geo.cells import cell_id, covering_cells, haversine_m
from pegasus_tpu.geo.geo_client import GeoClient, GeoSearchResult, LatLngCodec

__all__ = ["GeoClient", "GeoSearchResult", "LatLngCodec", "cell_id",
           "covering_cells", "haversine_m"]
