"""GeoClient: location-aware KV over the dual-table design.

Parity: src/geo/lib/geo_client.h:96 — two tables:
- the RAW table: the user's (hashkey, sortkey) -> value, unchanged;
- the GEO index table: hashkey = cell id at `index_level` (the S2
  min_level analogue), sortkey = remaining cell digits + the raw keys,
  value = the raw value. Radius search covers the circle with index
  cells (geo_client.h:295-335), scans each cell in parallel-ready
  fashion, and filters candidates by exact distance — here as ONE
  batched device predicate (ops/geo.py) instead of a scalar loop.

Values carry their coordinates; the codec extracts (lat, lng) from a
'|'-separated value by field index (parity: latlng_codec with
configurable latitude_index/longitude_index).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from pegasus_tpu.geo.cells import cell_id, covering_cells, haversine_m
from pegasus_tpu.ops.geo import radius_filter
from pegasus_tpu.utils.errors import StorageStatus

SORT_SEP = b"|"


@dataclass
class LatLngCodec:
    """Extract/encode coordinates from a record value (parity:
    base/latlng_codec)."""

    latitude_index: int = 0
    longitude_index: int = 1

    def decode(self, value: bytes) -> Optional[Tuple[float, float]]:
        parts = value.split(b"|")
        hi = max(self.latitude_index, self.longitude_index)
        if len(parts) <= hi:
            return None
        try:
            return (float(parts[self.latitude_index]),
                    float(parts[self.longitude_index]))
        except ValueError:
            return None


@dataclass
class GeoSearchResult:
    hash_key: bytes
    sort_key: bytes
    value: bytes
    distance_m: float


class GeoClient:
    """`raw` and `index` are any client exposing the PegasusClient API
    (in-process or cluster)."""

    def __init__(self, raw_client, index_client,
                 codec: Optional[LatLngCodec] = None,
                 index_level: int = 12, max_level: int = 16) -> None:
        self.raw = raw_client
        self.index = index_client
        self.codec = codec or LatLngCodec()
        self.index_level = index_level
        self.max_level = max_level

    # ---- index key layout ---------------------------------------------

    def _index_keys(self, hash_key: bytes, sort_key: bytes,
                    lat: float, lng: float) -> Tuple[bytes, bytes]:
        cell = cell_id(lat, lng, self.max_level)
        idx_hash = cell[:self.index_level].encode()
        idx_sort = (cell[self.index_level:].encode() + SORT_SEP
                    + hash_key + SORT_SEP + sort_key)
        return idx_hash, idx_sort

    @staticmethod
    def _restore_raw_keys(idx_sort: bytes) -> Tuple[bytes, bytes]:
        _cell_rest, hk, sk = idx_sort.split(SORT_SEP, 2)
        return hk, sk

    # ---- data ops (parity: geo_client set/get/del keep both tables) ---

    def set(self, hash_key: bytes, sort_key: bytes, value: bytes,
            ttl_seconds: int = 0) -> int:
        coord = self.codec.decode(value)
        if coord is None:
            return int(StorageStatus.INVALID_ARGUMENT)
        # stale index entries for a moved point are removed first (the
        # reference reads the old value and deletes its old cell entry)
        err, old = self.raw.get(hash_key, sort_key)
        if err == int(StorageStatus.OK):
            old_coord = self.codec.decode(old)
            if old_coord is not None and old_coord != coord:
                oh, os_ = self._index_keys(hash_key, sort_key, *old_coord)
                self.index.delete(oh, os_)
        err = self.raw.set(hash_key, sort_key, value, ttl_seconds)
        if err != int(StorageStatus.OK):
            return err
        ih, isk = self._index_keys(hash_key, sort_key, *coord)
        return self.index.set(ih, isk, value, ttl_seconds)

    def get(self, hash_key: bytes, sort_key: bytes) -> Tuple[int, bytes]:
        return self.raw.get(hash_key, sort_key)

    def delete(self, hash_key: bytes, sort_key: bytes) -> int:
        err, value = self.raw.get(hash_key, sort_key)
        if err == int(StorageStatus.OK):
            coord = self.codec.decode(value)
            if coord is not None:
                ih, isk = self._index_keys(hash_key, sort_key, *coord)
                self.index.delete(ih, isk)
        return self.raw.delete(hash_key, sort_key)

    # ---- radius search (parity: async_search_radial :295-335) ----------

    def _cover_level(self, radius_m: float) -> int:
        """Covering level whose cell edge is comparable to the radius
        (parity: S2RegionCoverer's adaptive cells between min and max
        level, geo_client.h:374). Covering a small circle with
        index_level cells scans the whole coarse cell — orders of
        magnitude more candidates than the circle needs; the index
        sortkey carries the cell digits down to max_level, so finer
        covering cells narrow each scan to a SORTKEY RANGE."""
        import math

        # cell edge at level L is ~(180 deg * 111km/deg) / 2^L
        edge0_m = 180.0 * 111_000.0
        level = int(math.log2(edge0_m / max(radius_m, 1.0)))
        return max(self.index_level, min(self.max_level, level))

    def search_radial(self, lat: float, lng: float, radius_m: float,
                      count: int = -1,
                      sort_by_distance: bool = True
                      ) -> List[GeoSearchResult]:
        # near the poles the longitude span scales by 1/cos(lat), so the
        # radius-based level can overflow the covering budget — coarsen
        # until it fits (index_level always fits or raises legitimately)
        level = self._cover_level(radius_m)
        while True:
            try:
                cells = covering_cells(lat, lng, radius_m, level)
                break
            except ValueError:
                if level <= self.index_level:
                    raise
                level -= 1
        cand_keys: List[Tuple[bytes, bytes, bytes]] = []
        cand_lat: List[float] = []
        cand_lng: List[float] = []
        for _ih, isk, value in self._scan_cells(cells):
            coord = self.codec.decode(value)
            if coord is None:
                continue
            hk, sk = self._restore_raw_keys(isk)
            cand_keys.append((hk, sk, value))
            cand_lat.append(coord[0])
            cand_lng.append(coord[1])
        if not cand_keys:
            return []
        # exact-distance filtering: ONE device dispatch for the batch
        keep, dist = radius_filter(cand_lat, cand_lng, lat, lng, radius_m)
        out = [GeoSearchResult(hk, sk, value, float(d))
               for (hk, sk, value), k, d in zip(cand_keys, keep, dist)
               if k]
        if sort_by_distance:
            out.sort(key=lambda r: r.distance_m)
        if count >= 0:
            out = out[:count]
        return out

    @staticmethod
    def _sub_stop(sub: bytes) -> bytes:
        """Exclusive sortkey stop bound for a cell-digit prefix (digits
        are '0'-'3', so bumping the last byte covers every deeper cell
        and the SORT_SEP continuation)."""
        return sub[:-1] + bytes([sub[-1] + 1]) if sub else b""

    def _scan_cells(self, cells):
        """All covering cells' index rows. A covering cell FINER than
        index_level becomes a sortkey-range scan inside its coarse
        hashkey cell (the cell digits continue into the sortkey). When
        the index client batches (scan_multi), every cell's FIRST page
        rides one coalesced request wave — one stacked device evaluation
        per node — with per-cell paging only for overflowing cells;
        otherwise one scanner per cell (the reference's parallel
        fan-out shape)."""
        specs = []  # (hashkey cell, sortkey sub-cell prefix)
        for cell in cells:
            specs.append((cell[:self.index_level].encode(),
                          cell[self.index_level:].encode()))
        scan_multi = getattr(self.index, "scan_multi", None)
        if scan_multi is None:
            for hk, sub in specs:
                for row in self.index.get_scanner(
                        hk, start_sortkey=sub,
                        stop_sortkey=self._sub_stop(sub)):
                    yield row
            return
        from pegasus_tpu.base.key_schema import key_hash_parts, restore_key
        from pegasus_tpu.client.client import make_hashkey_scan_request

        pcount = getattr(self.index, "partition_count", None)
        if not pcount:
            self.index.refresh_config()
            pcount = self.index.partition_count
        groups: dict = {}
        for hk, sub in specs:
            req = make_hashkey_scan_request(
                hk, batch_size=1000, start_sortkey=sub,
                stop_sortkey=self._sub_stop(sub))
            groups.setdefault(key_hash_parts(hk) % pcount,
                              []).append((hk, req))
        results = scan_multi({p: [r for _hk, r in reqs]
                              for p, reqs in groups.items()})
        for pidx, reqs in groups.items():
            for (hk, _req), resp in zip(reqs, results[pidx]):
                if resp.error != int(StorageStatus.OK):
                    # a denied/throttled partition must not read as
                    # "no nearby points" — match the scanner path
                    raise RuntimeError(
                        f"geo cell scan failed: error {resp.error}")
                for kv in resp.kvs:
                    rhk, rsk = restore_key(kv.key)
                    yield rhk, rsk, kv.value
                # overflowing cells RESUME the server-held context (no
                # re-scan of served rows, no positional skipping, no
                # leaked context)
                cid = resp.context_id
                while cid >= 0:
                    page = self.index.scan_page(pidx, cid)
                    if page.error != int(StorageStatus.OK):
                        raise RuntimeError(
                            f"geo cell scan failed: error {page.error}")
                    for kv in page.kvs:
                        rhk, rsk = restore_key(kv.key)
                        yield rhk, rsk, kv.value
                    cid = page.context_id

    def search_radial_by_key(self, hash_key: bytes, sort_key: bytes,
                             radius_m: float, count: int = -1
                             ) -> List[GeoSearchResult]:
        """Radius search centered on an existing record (parity:
        the hashkey/sortkey overload of async_search_radial)."""
        err, value = self.raw.get(hash_key, sort_key)
        if err != int(StorageStatus.OK):
            return []
        coord = self.codec.decode(value)
        if coord is None:
            return []
        return self.search_radial(coord[0], coord[1], radius_m, count)

    def distance(self, hk1: bytes, sk1: bytes, hk2: bytes, sk2: bytes
                 ) -> Optional[float]:
        """Parity: geo_client::distance."""
        err1, v1 = self.raw.get(hk1, sk1)
        err2, v2 = self.raw.get(hk2, sk2)
        if err1 != int(StorageStatus.OK) or err2 != int(StorageStatus.OK):
            return None
        c1 = self.codec.decode(v1)
        c2 = self.codec.decode(v2)
        if c1 is None or c2 is None:
            return None
        return haversine_m(c1[0], c1[1], c2[0], c2[1])
