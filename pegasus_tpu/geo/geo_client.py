"""GeoClient: location-aware KV over the dual-table design.

Parity: src/geo/lib/geo_client.h:96 — two tables:
- the RAW table: the user's (hashkey, sortkey) -> value, unchanged;
- the GEO index table: hashkey = cell id at `index_level` (the S2
  min_level analogue), sortkey = remaining cell digits + the raw keys,
  value = the raw value. Radius search covers the circle with index
  cells (geo_client.h:295-335), scans each cell in parallel-ready
  fashion, and filters candidates by exact distance — here as ONE
  batched device predicate (ops/geo.py) instead of a scalar loop.

Values carry their coordinates; the codec extracts (lat, lng) from a
'|'-separated value by field index (parity: latlng_codec with
configurable latitude_index/longitude_index). The RAW table stores the
user's value untouched; INDEX rows prefix it with a versioned packed
coordinate header (see _MAGIC/_COORD) so radius searches lift
candidate coordinates vectorized; headerless index rows written by
older builds still decode through the text codec.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple

from pegasus_tpu.geo.cells import cell_id, covering_cells, haversine_m
from pegasus_tpu.ops.geo import radius_filter
from pegasus_tpu.utils.errors import StorageStatus

SORT_SEP = b"|"

# Index-table value layout: 2-byte version magic, 16-byte packed
# (lat, lng) doubles, then the raw value verbatim. The RAW table keeps
# the user's value untouched (text codec, latlng_codec parity); the
# INDEX table is internal to GeoClient, and the fixed binary header is
# what lets a radius search lift every candidate's coordinates out of
# a columnar scan page with ONE vectorized gather instead of a
# per-record text parse. The magic distinguishes headered rows from
# index rows written by builds that stored the raw value directly —
# those fall back to the per-record text codec.
_MAGIC = b"G\x01"
_COORD = struct.Struct("<dd")
_HDR = len(_MAGIC) + _COORD.size


def _coord_in_range(lat: float, lng: float) -> bool:
    """Sanity gate on header-sniffed coordinates: the 2-byte magic is
    weak evidence, and a legacy headerless value that happens to start
    with it would otherwise inject garbage coordinates into the radius
    filter (and silently lose its first 18 bytes). Out-of-range or
    non-finite doubles mean "not really a packed header" — the row
    falls back to the text codec. NaN fails both comparisons."""
    return -90.0 <= lat <= 90.0 and -180.0 <= lng <= 180.0


@dataclass
class LatLngCodec:
    """Extract/encode coordinates from a record value (parity:
    base/latlng_codec)."""

    latitude_index: int = 0
    longitude_index: int = 1

    def decode(self, value: bytes) -> Optional[Tuple[float, float]]:
        parts = value.split(b"|")
        hi = max(self.latitude_index, self.longitude_index)
        if len(parts) <= hi:
            return None
        try:
            return (float(parts[self.latitude_index]),
                    float(parts[self.longitude_index]))
        except ValueError:
            return None


@dataclass
class GeoSearchResult:
    hash_key: bytes
    sort_key: bytes
    value: bytes
    distance_m: float


def _page_coords(kvs, codec, value_of, n_rows):
    """(coords float64[n, 2], row indices int64[n], packed bool[n]) of
    the decodable rows of one response page.

    Rows carrying the versioned packed header decode VECTORIZED on the
    columnar ScanPage shape (one gather over the value blob); rows
    without it — index entries written by a build predating the header
    — fall back to the per-record text codec (`packed`=False marks
    them so the caller keeps their value unstripped)."""
    import numpy as np

    m0, m1 = _MAGIC
    if not hasattr(kvs, "val_offs"):  # KeyValue list / raw rows
        rows, coords, packed = [], [], []
        for i in range(n_rows):
            v = value_of(i)
            c = None
            if len(v) >= _HDR and v[0] == m0 and v[1] == m1:
                lat, lng = _COORD.unpack_from(v, len(_MAGIC))
                if _coord_in_range(lat, lng):
                    rows.append(i)
                    coords.append((lat, lng))
                    packed.append(True)
                    continue
            c = codec.decode(v)
            if c is not None:
                rows.append(i)
                coords.append(c)
                packed.append(False)
        if not rows:
            return None, (), ()
        return (np.asarray(coords, dtype=np.float64),
                np.asarray(rows, dtype=np.int64),
                np.asarray(packed, dtype=bool))
    vo = np.frombuffer(kvs.val_offs, dtype="<u4").astype(np.int64)
    if len(vo) <= 1:
        return None, (), ()
    starts = vo[:-1]
    blob = np.frombuffer(kvs.val_blob, dtype=np.uint8)
    fits = (vo[1:] - starts) >= _HDR
    has_magic = fits.copy()
    idx = np.flatnonzero(fits)
    if len(idx):
        has_magic[idx] &= (blob[starts[idx]] == m0) \
            & (blob[starts[idx] + 1] == m1)
    prows = np.flatnonzero(has_magic)
    pcoords = np.zeros((0, 2))
    if len(prows):
        win = (starts[prows][:, None] + len(_MAGIC)
               + np.arange(_COORD.size))
        pcoords = blob[win].reshape(-1).view("<f8").reshape(-1, 2)
        # range-validate the sniffed headers (vectorized): impossible
        # lat/lng means a legacy value that merely starts with the
        # magic — demote those rows to the text-codec path
        with np.errstate(invalid="ignore"):
            sane = (np.isfinite(pcoords).all(axis=1)
                    & (np.abs(pcoords[:, 0]) <= 90.0)
                    & (np.abs(pcoords[:, 1]) <= 180.0))
        if not sane.all():
            has_magic[prows[~sane]] = False
            prows = prows[sane]
            pcoords = pcoords[sane]
    # legacy headerless rows: per-record text decode
    lrows, lcoords = [], []
    for i in np.flatnonzero(~has_magic):
        c = codec.decode(value_of(int(i)))
        if c is not None:
            lrows.append(int(i))
            lcoords.append(c)
    if not len(prows) and not lrows:
        return None, (), ()
    coords = np.concatenate(
        [pcoords, np.asarray(lcoords, dtype=np.float64).reshape(-1, 2)])
    rows = np.concatenate(
        [prows.astype(np.int64),
         np.asarray(lrows, dtype=np.int64)])
    packed = np.concatenate(
        [np.ones(len(prows), dtype=bool),
         np.zeros(len(lrows), dtype=bool)])
    return coords, rows, packed


class GeoClient:
    """`raw` and `index` are any client exposing the PegasusClient API
    (in-process or cluster)."""

    def __init__(self, raw_client, index_client,
                 codec: Optional[LatLngCodec] = None,
                 index_level: int = 12, max_level: int = 16) -> None:
        self.raw = raw_client
        self.index = index_client
        self.codec = codec or LatLngCodec()
        self.index_level = index_level
        self.max_level = max_level

    # ---- index key layout ---------------------------------------------

    def _index_keys(self, hash_key: bytes, sort_key: bytes,
                    lat: float, lng: float) -> Tuple[bytes, bytes]:
        cell = cell_id(lat, lng, self.max_level)
        idx_hash = cell[:self.index_level].encode()
        idx_sort = (cell[self.index_level:].encode() + SORT_SEP
                    + hash_key + SORT_SEP + sort_key)
        return idx_hash, idx_sort

    @staticmethod
    def _restore_raw_keys(idx_sort: bytes) -> Tuple[bytes, bytes]:
        _cell_rest, hk, sk = idx_sort.split(SORT_SEP, 2)
        return hk, sk

    # ---- data ops (parity: geo_client set/get/del keep both tables) ---

    def set(self, hash_key: bytes, sort_key: bytes, value: bytes,
            ttl_seconds: int = 0) -> int:
        coord = self.codec.decode(value)
        if coord is None:
            return int(StorageStatus.INVALID_ARGUMENT)
        # stale index entries for a moved point are removed first (the
        # reference reads the old value and deletes its old cell entry)
        err, old = self.raw.get(hash_key, sort_key)
        if err == int(StorageStatus.OK):
            old_coord = self.codec.decode(old)
            if old_coord is not None and old_coord != coord:
                oh, os_ = self._index_keys(hash_key, sort_key, *old_coord)
                self.index.delete(oh, os_)
        err = self.raw.set(hash_key, sort_key, value, ttl_seconds)
        if err != int(StorageStatus.OK):
            return err
        ih, isk = self._index_keys(hash_key, sort_key, *coord)
        return self.index.set(
            ih, isk, _MAGIC + _COORD.pack(*coord) + value, ttl_seconds)

    def get(self, hash_key: bytes, sort_key: bytes) -> Tuple[int, bytes]:
        return self.raw.get(hash_key, sort_key)

    def delete(self, hash_key: bytes, sort_key: bytes) -> int:
        err, value = self.raw.get(hash_key, sort_key)
        if err == int(StorageStatus.OK):
            coord = self.codec.decode(value)
            if coord is not None:
                ih, isk = self._index_keys(hash_key, sort_key, *coord)
                self.index.delete(ih, isk)
        return self.raw.delete(hash_key, sort_key)

    # ---- radius search (parity: async_search_radial :295-335) ----------

    def _cover_level(self, radius_m: float) -> int:
        """Covering level whose cell edge is comparable to the radius
        (parity: S2RegionCoverer's adaptive cells between min and max
        level, geo_client.h:374). Covering a small circle with
        index_level cells scans the whole coarse cell — orders of
        magnitude more candidates than the circle needs; the index
        sortkey carries the cell digits down to max_level, so finer
        covering cells narrow each scan to a SORTKEY RANGE."""
        import math

        # cell edge at level L is ~(180 deg * 111km/deg) / 2^L
        edge0_m = 180.0 * 111_000.0
        level = int(math.log2(edge0_m / max(radius_m, 1.0)))
        return max(self.index_level, min(self.max_level, level))

    def search_radial(self, lat: float, lng: float, radius_m: float,
                      count: int = -1,
                      sort_by_distance: bool = True
                      ) -> List[GeoSearchResult]:
        # near the poles the longitude span scales by 1/cos(lat), so the
        # radius-based level can overflow the covering budget — coarsen
        # until it fits (index_level always fits or raises legitimately)
        level = self._cover_level(radius_m)
        while True:
            try:
                cells = covering_cells(lat, lng, radius_m, level)
                break
            except ValueError:
                if level <= self.index_level:
                    raise
                level -= 1
        import numpy as np

        from pegasus_tpu.base.key_schema import restore_key

        # Candidate coordinates are lifted PAGE-at-a-time: columnar
        # scan pages give every packed (lat, lng) header in one numpy
        # gather; keys/values materialize per record only for the
        # SURVIVORS of the distance filter (typically a small fraction
        # of the candidate set). A page is a columnar ScanPage, a
        # KeyValue list, or the fallback scanner's raw
        # ("raw", [(index_sortkey, value), ...]) batch.
        pages: list = []  # (page, row_indices, packed_flags)
        lat_parts: list = []
        lng_parts: list = []
        for page in self._scan_cell_pages(cells):
            if isinstance(page, tuple):  # raw fallback batch
                kvs = page[1]
                value_of = lambda i, kvs=kvs: kvs[i][1]  # noqa: E731
                n = len(kvs)
            elif isinstance(page, list):
                kvs = page
                value_of = lambda i, kvs=kvs: kvs[i].value  # noqa: E731
                n = len(kvs)
            else:
                kvs = page
                value_of = kvs.value_at
                n = len(kvs)
            coords, rows, packed = _page_coords(kvs, self.codec,
                                                value_of, n)
            if coords is None or not len(rows):
                continue
            pages.append((page, rows, packed))
            lat_parts.append(coords[:, 0])
            lng_parts.append(coords[:, 1])
        if not pages:
            return []
        cand_lat = np.concatenate(lat_parts)
        cand_lng = np.concatenate(lng_parts)
        # exact-distance filtering: ONE device dispatch for the batch
        keep, dist = radius_filter(cand_lat, cand_lng, lat, lng, radius_m)
        out: List[GeoSearchResult] = []
        base = 0
        for page, rows, packed in pages:
            n = len(rows)
            for j in np.flatnonzero(keep[base:base + n]):
                i = int(rows[int(j)])
                if isinstance(page, tuple):
                    isk, value = page[1][i]
                elif isinstance(page, list):
                    _ih, isk = restore_key(page[i].key)
                    value = page[i].value
                else:
                    _ih, isk = restore_key(page.key_at(i))
                    value = page.value_at(i)
                hk, sk = self._restore_raw_keys(isk)
                if packed[int(j)]:
                    value = bytes(value[_HDR:])
                out.append(GeoSearchResult(
                    hk, sk, value, float(dist[base + int(j)])))
            base += n
        if sort_by_distance:
            out.sort(key=lambda r: r.distance_m)
        if count >= 0:
            out = out[:count]
        return out

    @staticmethod
    def _sub_stop(sub: bytes) -> bytes:
        """Exclusive sortkey stop bound for a cell-digit prefix (digits
        are '0'-'3', so bumping the last byte covers every deeper cell
        and the SORT_SEP continuation)."""
        return sub[:-1] + bytes([sub[-1] + 1]) if sub else b""

    def _scan_cell_pages(self, cells):
        """All covering cells' index rows, yielded as whole response
        PAGES (columnar ScanPage or KeyValue list) so the caller can
        lift coordinates vectorized. A covering cell FINER than
        index_level becomes a sortkey-range scan inside its coarse
        hashkey cell (the cell digits continue into the sortkey). When
        the index client batches (scan_multi), every cell's FIRST page
        rides one coalesced request wave — one stacked device evaluation
        per node — with per-cell paging only for overflowing cells;
        otherwise one scanner per cell (the reference's parallel
        fan-out shape)."""
        specs = []  # (hashkey cell, sortkey sub-cell prefix)
        for cell in cells:
            specs.append((cell[:self.index_level].encode(),
                          cell[self.index_level:].encode()))
        scan_multi = getattr(self.index, "scan_multi", None)
        if scan_multi is None:
            # streaming fallback for clients without batched scans:
            # bounded ("raw", [(index_sortkey, value), ...]) batches —
            # no key encode/restore round-trip, no whole-cell buffering
            for hk, sub in specs:
                batch: list = []
                for _rhk, rsk, value in self.index.get_scanner(
                        hk, start_sortkey=sub,
                        stop_sortkey=self._sub_stop(sub)):
                    batch.append((rsk, value))
                    if len(batch) >= 1024:
                        yield ("raw", batch)
                        batch = []
                if batch:
                    yield ("raw", batch)
            return
        from pegasus_tpu.base.key_schema import key_hash_parts
        from pegasus_tpu.client.client import make_hashkey_scan_request

        pcount = getattr(self.index, "partition_count", None)
        if not pcount:
            self.index.refresh_config()
            pcount = self.index.partition_count
        groups: dict = {}
        for hk, sub in specs:
            req = make_hashkey_scan_request(
                hk, batch_size=1000, start_sortkey=sub,
                stop_sortkey=self._sub_stop(sub))
            groups.setdefault(key_hash_parts(hk) % pcount,
                              []).append((hk, req))
        results = scan_multi({p: [r for _hk, r in reqs]
                              for p, reqs in groups.items()})
        for pidx, reqs in groups.items():
            for (hk, _req), resp in zip(reqs, results[pidx]):
                if resp.error != int(StorageStatus.OK):
                    # a denied/throttled partition must not read as
                    # "no nearby points" — match the scanner path
                    raise RuntimeError(
                        f"geo cell scan failed: error {resp.error}")
                yield resp.kvs
                # overflowing cells RESUME the server-held context (no
                # re-scan of served rows, no positional skipping, no
                # leaked context)
                cid = resp.context_id
                while cid >= 0:
                    page = self.index.scan_page(pidx, cid)
                    if page.error != int(StorageStatus.OK):
                        raise RuntimeError(
                            f"geo cell scan failed: error {page.error}")
                    yield page.kvs
                    cid = page.context_id

    def search_radial_by_key(self, hash_key: bytes, sort_key: bytes,
                             radius_m: float, count: int = -1
                             ) -> List[GeoSearchResult]:
        """Radius search centered on an existing record (parity:
        the hashkey/sortkey overload of async_search_radial)."""
        err, value = self.raw.get(hash_key, sort_key)
        if err != int(StorageStatus.OK):
            return []
        coord = self.codec.decode(value)
        if coord is None:
            return []
        return self.search_radial(coord[0], coord[1], radius_m, count)

    def distance(self, hk1: bytes, sk1: bytes, hk2: bytes, sk2: bytes
                 ) -> Optional[float]:
        """Parity: geo_client::distance."""
        err1, v1 = self.raw.get(hk1, sk1)
        err2, v2 = self.raw.get(hk2, sk2)
        if err1 != int(StorageStatus.OK) or err2 != int(StorageStatus.OK):
            return None
        c1 = self.codec.decode(v1)
        c2 = self.codec.decode(v2)
        if c1 is None or c2 is None:
            return None
        return haversine_m(c1[0], c1[1], c2[0], c2[1])
