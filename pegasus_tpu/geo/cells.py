"""Hierarchical geo cells: quadtree Morton codes over (lat, lng).

Parity role: the S2 cell ids the reference's geo client keys its index
table with (src/geo/lib/geo_client.h:96 — hashkey = cell id at
min_level, sortkey continues to max_level). S2's exact cell geometry is
library-specific; what the design needs from it is (a) a hierarchical
id whose string prefix identifies every ancestor cell and (b) a way to
cover a circle with cells at a fixed level. A base-4 Morton code over
the equirectangular grid provides both: digit k subdivides the parent
cell into quadrants, so a level-L cell is exactly a length-L prefix.

Cells are strings of digits '0'-'3' (level = len). Level L cell size:
180/2^L degrees of latitude by 360/2^L degrees of longitude.
"""

from __future__ import annotations

import math
from typing import List, Tuple

EARTH_RADIUS_M = 6_371_000.0


def cell_id(lat: float, lng: float, level: int) -> str:
    """The level-`level` cell containing (lat, lng)."""
    if not (-90.0 <= lat <= 90.0 and -180.0 <= lng <= 180.0):
        raise ValueError(f"bad coordinate ({lat}, {lng})")
    # normalize to [0, 1); clamp the closed upper edge into the last cell
    y = min((lat + 90.0) / 180.0, 1.0 - 1e-12)
    x = min((lng + 180.0) / 360.0, 1.0 - 1e-12)
    digits = []
    for _ in range(level):
        y *= 2
        x *= 2
        yb = int(y)
        xb = int(x)
        digits.append(str((yb << 1) | xb))
        y -= yb
        x -= xb
    return "".join(digits)


def cell_bounds(cell: str) -> Tuple[float, float, float, float]:
    """(lat_min, lat_max, lng_min, lng_max) of a cell."""
    y0, y1 = 0.0, 1.0
    x0, x1 = 0.0, 1.0
    for d in cell:
        v = int(d)
        ym = (y0 + y1) / 2
        xm = (x0 + x1) / 2
        if v & 2:
            y0 = ym
        else:
            y1 = ym
        if v & 1:
            x0 = xm
        else:
            x1 = xm
    return (y0 * 180.0 - 90.0, y1 * 180.0 - 90.0,
            x0 * 360.0 - 180.0, x1 * 360.0 - 180.0)


def covering_cells(lat: float, lng: float, radius_m: float,
                   level: int, max_cells: int = 256) -> List[str]:
    """Cells at `level` intersecting the circle's bounding box (parity:
    S2RegionCoverer over the search cap, geo_client.h:295-335)."""
    dlat = math.degrees(radius_m / EARTH_RADIUS_M)
    cos_lat = max(math.cos(math.radians(lat)), 1e-6)
    dlng = math.degrees(radius_m / (EARTH_RADIUS_M * cos_lat))
    lat_lo = max(lat - dlat, -90.0)
    lat_hi = min(lat + dlat, 90.0)
    lng_lo = max(lng - dlng, -180.0)
    lng_hi = min(lng + dlng, 180.0)
    step_lat = 180.0 / (1 << level)
    step_lng = 360.0 / (1 << level)
    cells = []
    seen = set()
    la = lat_lo
    while True:
        ln = lng_lo
        while True:
            c = cell_id(min(la, 90.0), min(ln, 180.0), level)
            if c not in seen:
                seen.add(c)
                cells.append(c)
                if len(cells) > max_cells:
                    raise ValueError(
                        f"radius {radius_m}m needs >{max_cells} cells at "
                        f"level {level}; use a coarser index level")
            if ln >= lng_hi:
                break
            ln += step_lng
        if la >= lat_hi:
            break
        la += step_lat
    return cells


def haversine_m(lat1: float, lng1: float, lat2: float, lng2: float) -> float:
    """Great-circle distance in meters (host-side scalar; the batched
    candidate filter runs on device — ops/geo.py)."""
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lng2 - lng1)
    a = (math.sin(dp / 2) ** 2
         + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
    return 2 * EARTH_RADIUS_M * math.asin(min(1.0, math.sqrt(a)))
