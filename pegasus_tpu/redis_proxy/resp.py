"""RESP2 wire protocol: parse client commands, serialize replies.

Parity: the reference's redis parser (src/redis_protocol/proxy_lib/
redis_parser.cpp) — inline and multibulk request forms in, the five
RESP2 reply types out. Incremental: feed() consumes bytes and yields
complete command argv lists.
"""

from __future__ import annotations

from typing import List, Optional

CRLF = b"\r\n"


class RespParser:
    """Incremental request parser (multibulk *N\\r\\n$len\\r\\n... and
    inline commands)."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[List[bytes]]:
        self._buf.extend(data)
        out = []
        while True:
            cmd = self._try_parse()
            if cmd is None:
                return out
            if cmd:
                out.append(cmd)

    def _try_parse(self) -> Optional[List[bytes]]:
        buf = self._buf
        if not buf:
            return None
        if buf[0:1] != b"*":
            # inline command: a plain line of words
            nl = buf.find(b"\r\n")
            if nl < 0:
                return None
            line = bytes(buf[:nl])
            del buf[:nl + 2]
            return line.split()
        # multibulk
        nl = buf.find(b"\r\n")
        if nl < 0:
            return None
        try:
            n = int(buf[1:nl])
        except ValueError:
            raise ValueError(f"bad multibulk header {bytes(buf[:nl])!r}")
        pos = nl + 2
        args = []
        for _ in range(n):
            if len(buf) < pos + 1 or buf[pos:pos + 1] != b"$":
                return None if len(buf) <= pos else self._bad(pos)
            nl2 = buf.find(b"\r\n", pos)
            if nl2 < 0:
                return None
            size = int(buf[pos + 1:nl2])
            if size < 0:
                # a negative bulk length in a REQUEST is a protocol error
                # (accepting it would desynchronize the buffer)
                raise ValueError(f"negative bulk length {size}")
            start = nl2 + 2
            if len(buf) < start + size + 2:
                return None
            args.append(bytes(buf[start:start + size]))
            pos = start + size + 2
        del buf[:pos]
        return args

    def _bad(self, pos: int):
        raise ValueError(f"bad bulk header at {pos}: "
                         f"{bytes(self._buf[pos:pos + 8])!r}")


# ---- reply serializers --------------------------------------------------


def simple(s: str) -> bytes:
    return b"+" + s.encode() + CRLF


def error(msg: str) -> bytes:
    return b"-ERR " + msg.encode() + CRLF


def integer(n: int) -> bytes:
    return b":" + str(n).encode() + CRLF


def bulk(data: Optional[bytes]) -> bytes:
    if data is None:
        return b"$-1" + CRLF  # nil
    return b"$" + str(len(data)).encode() + CRLF + data + CRLF


def array(items) -> bytes:
    if items is None:
        return b"*-1" + CRLF
    out = [b"*" + str(len(items)).encode() + CRLF]
    for item in items:
        if isinstance(item, bytes) or item is None:
            out.append(bulk(item))
        elif isinstance(item, int):
            out.append(integer(item))
        elif isinstance(item, (list, tuple)):
            out.append(array(item))
        else:
            out.append(bulk(str(item).encode()))
    return b"".join(out)
