"""Redis proxy: a RESP front end over the Pegasus client API.

Parity: src/redis_protocol/ — the proxy maps Redis commands onto the KV
API (redis_parser.cpp:60-74: SET/GET/DEL/SETEX/TTL/PTTL/INCR(BY)/
DECR(BY) + GEO*): a Redis key becomes (hash_key=key, sort_key="");
GEO* commands ride a GeoClient over a dedicated index table.

Thread-per-connection TCP server (the proxy is stateless; each command
is one client call). Works over any object exposing the PegasusClient
API — the in-process Table client or the wire ClusterClient.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional

from pegasus_tpu.redis_proxy import resp
from pegasus_tpu.utils.errors import PegasusError, StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)
_EMPTY_SK = b""


class RedisHandler:
    """Command dispatch, transport-independent (testable without
    sockets)."""

    def __init__(self, client, geo=None) -> None:
        self.client = client
        self.geo = geo  # optional GeoClient for GEO* verbs

    def handle(self, argv: List[bytes]) -> bytes:
        if not argv:
            return resp.error("empty command")
        cmd = argv[0].upper().decode(errors="replace")
        fn = getattr(self, "cmd_" + cmd, None)
        if fn is None:
            return resp.error(f"unknown command '{cmd}'")
        try:
            return fn(argv[1:])
        except (ValueError, IndexError) as e:
            return resp.error(str(e) or "wrong number of arguments")
        except PegasusError as e:
            # cluster-side failures (failover retries exhausted, timeouts)
            # become -ERR replies, never dropped connections
            return resp.error(f"cluster error: {e}")

    # ---- connection & introspection ------------------------------------

    def cmd_PING(self, args):
        return resp.bulk(args[0]) if args else resp.simple("PONG")

    def cmd_COMMAND(self, _args):
        return resp.array([])  # redis-cli handshake compatibility

    def cmd_ECHO(self, args):
        return resp.bulk(args[0])

    # ---- strings -------------------------------------------------------

    def cmd_SET(self, args):
        if len(args) < 2:
            raise ValueError("wrong number of arguments for 'set'")
        key, value = args[0], args[1]
        ttl = 0
        i = 2
        while i < len(args):
            opt = args[i].upper()
            if opt == b"EX":
                ttl = int(args[i + 1])
                i += 2
            elif opt == b"PX":
                ttl = max(1, int(args[i + 1]) // 1000)
                i += 2
            else:
                raise ValueError(f"unsupported SET option {opt!r}")
        err = self.client.set(key, _EMPTY_SK, value, ttl_seconds=ttl)
        return resp.simple("OK") if err == OK else resp.error(
            f"storage error {err}")

    def cmd_SETEX(self, args):
        key, seconds, value = args[0], int(args[1]), args[2]
        err = self.client.set(key, _EMPTY_SK, value, ttl_seconds=seconds)
        return resp.simple("OK") if err == OK else resp.error(
            f"storage error {err}")

    def cmd_GET(self, args):
        err, value = self.client.get(args[0], _EMPTY_SK)
        if err == NOT_FOUND:
            return resp.bulk(None)
        if err != OK:
            return resp.error(f"storage error {err}")
        return resp.bulk(value)

    def cmd_DEL(self, args):
        n = 0
        for key in args:
            if self.client.exist(key, _EMPTY_SK):
                if self.client.delete(key, _EMPTY_SK) == OK:
                    n += 1
        return resp.integer(n)

    def cmd_EXISTS(self, args):
        return resp.integer(sum(
            1 for key in args if self.client.exist(key, _EMPTY_SK)))

    def cmd_TTL(self, args):
        err, ttl = self.client.ttl(args[0], _EMPTY_SK)
        if err == NOT_FOUND:
            return resp.integer(-2)
        if err != OK:
            return resp.error(f"storage error {err}")
        return resp.integer(-1 if ttl < 0 else ttl)

    def cmd_PTTL(self, args):
        err, ttl = self.client.ttl(args[0], _EMPTY_SK)
        if err == NOT_FOUND:
            return resp.integer(-2)
        if err != OK:
            return resp.error(f"storage error {err}")
        return resp.integer(-1 if ttl < 0 else ttl * 1000)

    # ---- counters ------------------------------------------------------

    def _incr(self, key: bytes, delta: int) -> bytes:
        r = self.client.incr(key, _EMPTY_SK, delta)
        if r.error != OK:
            return resp.error("value is not an integer or out of range")
        return resp.integer(r.new_value)

    def cmd_INCR(self, args):
        return self._incr(args[0], 1)

    def cmd_INCRBY(self, args):
        return self._incr(args[0], int(args[1]))

    def cmd_DECR(self, args):
        return self._incr(args[0], -1)

    def cmd_DECRBY(self, args):
        return self._incr(args[0], -int(args[1]))

    # ---- GEO (parity: the proxy's GEO* verbs over geo_client) ----------

    def _need_geo(self):
        if self.geo is None:
            raise ValueError("GEO commands need a geo-enabled proxy")
        return self.geo

    @staticmethod
    def _geo_unit_scale(unit: bytes) -> float:
        scale = {b"m": 1.0, b"km": 1000.0}.get(unit.lower())
        if scale is None:
            raise ValueError("unsupported unit")
        return scale

    @staticmethod
    def _geo_count(args, start: int) -> int:
        rest = [a.upper() for a in args[start:]]
        if b"COUNT" in rest:
            return int(args[start + rest.index(b"COUNT") + 1])
        return -1

    def cmd_GEOADD(self, args):
        geo = self._need_geo()
        key = args[0]
        added = 0
        for i in range(1, len(args), 3):
            lng, lat, member = (float(args[i]), float(args[i + 1]),
                                args[i + 2])
            value = b"%f|%f|" % (lat, lng)
            if geo.set(key, member, value) == OK:
                added += 1
        return resp.integer(added)

    def cmd_GEODIST(self, args):
        geo = self._need_geo()
        key, m1, m2 = args[0], args[1], args[2]
        d = geo.distance(key, m1, key, m2)
        if d is None:
            return resp.bulk(None)
        scale = self._geo_unit_scale(args[3] if len(args) > 3 else b"m")
        return resp.bulk(b"%.4f" % (d / scale))

    def cmd_GEORADIUS(self, args):
        """GEORADIUS key lng lat radius m|km [COUNT n] — member names
        within the radius (the reference proxy's search_radial front)."""
        geo = self._need_geo()
        _key = args[0]
        lng, lat, radius = float(args[1]), float(args[2]), float(args[3])
        scale = self._geo_unit_scale(args[4])
        count = self._geo_count(args, 5)
        hits = geo.search_radial(lat, lng, radius * scale, count=count)
        return resp.array([h.sort_key for h in hits])

    def cmd_GEOPOS(self, args):
        """GEOPOS key member [member ...] — (lng, lat) per member, a
        NIL ARRAY (*-1, the Redis wire shape) for absent ones
        (redis_parser g_geo_pos parity). Storage faults other than
        NOT_FOUND surface as -ERR, never as a silent nil."""
        from pegasus_tpu.utils.errors import StorageStatus

        geo = self._need_geo()
        key = args[0]
        parts = [b"*%d\r\n" % (len(args) - 1)]
        for member in args[1:]:
            err, value = geo.get(key, member)
            if err == int(StorageStatus.NOT_FOUND):
                parts.append(b"*-1\r\n")
                continue
            if err != OK:
                raise ValueError(f"storage error {err}")
            coords = geo.codec.decode(value)
            if coords is None:
                parts.append(b"*-1\r\n")
                continue
            lat, lng = coords
            parts.append(resp.array([b"%.17g" % lng, b"%.17g" % lat]))
        return b"".join(parts)

    def cmd_GEORADIUSBYMEMBER(self, args):
        """GEORADIUSBYMEMBER key member radius m|km [COUNT n] — like
        GEORADIUS but centered on an EXISTING member
        (g_geo_radius_by_member parity). A missing / undecodable center
        is an ERROR, as in Redis ("could not decode requested zset
        member") — an empty array must mean 'nobody in radius', never
        'the center lookup failed'."""
        geo = self._need_geo()
        key, member = args[0], args[1]
        radius = float(args[2])
        scale = self._geo_unit_scale(args[3])
        count = self._geo_count(args, 4)
        err, value = geo.get(key, member)
        if err != OK or geo.codec.decode(value) is None:
            raise ValueError("could not decode requested member")
        lat, lng = geo.codec.decode(value)
        hits = geo.search_radial(lat, lng, radius * scale, count=count)
        return resp.array([h.sort_key for h in hits])


class RedisProxy:
    """TCP front (parity: proxy/main.cpp) — bind port 0 for ephemeral."""

    def __init__(self, client, host: str = "127.0.0.1", port: int = 0,
                 geo=None) -> None:
        self.handler = RedisHandler(client, geo=geo)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._closing = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RedisProxy":
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        parser = resp.RespParser()
        try:
            while True:
                data = conn.recv(1 << 16)
                if not data:
                    return
                try:
                    commands = parser.feed(data)
                except ValueError as e:
                    conn.sendall(resp.error(f"protocol error: {e}"))
                    return
                for argv in commands:
                    conn.sendall(self.handler.handle(argv))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass


def main() -> None:
    """python -m pegasus_tpu.redis_proxy.proxy --cluster DIR --table T
    [--port P] [--geo-index TABLE]"""
    import argparse
    import time

    from pegasus_tpu.tools import onebox_cluster as ob

    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster", required=True)
    ap.add_argument("--table", required=True)
    ap.add_argument("--port", type=int, default=6379)
    ap.add_argument("--geo-index", default=None,
                    help="geo index table name enabling GEO* verbs")
    args = ap.parse_args()
    client = ob.connect(args.table, args.cluster)
    geo = None
    if args.geo_index:
        from pegasus_tpu.geo import GeoClient

        geo = GeoClient(client, ob.connect(args.geo_index, args.cluster))
    proxy = RedisProxy(client, port=args.port, geo=geo).start()
    print(f"redis proxy serving {args.table} on port {proxy.port}",
          flush=True)
    while True:
        time.sleep(1)


if __name__ == "__main__":
    main()
