from pegasus_tpu.redis_proxy.proxy import RedisHandler, RedisProxy

__all__ = ["RedisHandler", "RedisProxy"]
