from pegasus_tpu.redis_proxy.proxy import main

main()
