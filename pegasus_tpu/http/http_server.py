"""Built-in HTTP endpoints: /metrics, /version, /config, /command.

Parity: src/http/http_server.h:91 (registry-based endpoints) with the
builtin calls (src/http/builtin_http_calls.cpp:80-103 /version /config;
:280-288 /metrics via metrics_http_service, JSON with entity/metric
filters — the surface the Go collector scrapes) plus remote-command
verbs over HTTP (/command?verb=...&args=a,b — command_manager.h:52).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import pegasus_tpu
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.metrics import METRICS


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def _reply(self, code: int, payload) -> None:
        body = json.dumps(payload, indent=1).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str,
                    content_type: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        url = urlparse(self.path)
        query = parse_qs(url.query)
        routes = getattr(self.server, "routes", None) or {}
        if url.path in routes:
            try:
                self._reply(200, routes[url.path](
                    {k: v[0] for k, v in query.items()}))
            except (KeyError, ValueError) as e:
                self._reply(400, {"error": str(e)})
            return
        if url.path == "/version":
            self._reply(200, {"version": pegasus_tpu.__version__,
                              "framework": "pegasus_tpu"})
        elif url.path == "/config":
            self._reply(200, FLAGS.snapshot())
        elif url.path == "/command":
            mgr = getattr(self.server, "commands", None)
            if mgr is None:
                self._reply(404, {"error": "no command manager attached"})
                return
            verb = query.get("verb", ["help"])[0]
            args = [a for a in query.get("args", [""])[0].split(",") if a]
            try:
                self._reply(200, {"verb": verb,
                                  "result": mgr.call(verb, args)})
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": str(e)})
        elif url.path == "/pprof/heap":
            # parity: pprof_http_service heap endpoint — Python-native:
            # tracemalloc top allocations when tracing, else rss only
            import resource
            import tracemalloc

            out = {"max_rss_kb": resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss,
                "tracing": tracemalloc.is_tracing()}
            if tracemalloc.is_tracing():
                snap = tracemalloc.take_snapshot()
                out["top"] = [
                    {"site": str(stat.traceback[0]),
                     "size_kb": stat.size // 1024,
                     "count": stat.count}
                    for stat in snap.statistics("lineno")[:25]]
            self._reply(200, out)
        elif url.path == "/pprof/profile":
            # parity: pprof cpu profile — sampled Python stacks over a
            # short window; collapsed-stack counts, biggest first
            import collections
            import sys
            import time as _time

            try:
                seconds = min(10.0, float(
                    query.get("seconds", ["1"])[0]))
            except ValueError:
                self._reply(400, {"error": "seconds must be a number"})
                return
            hz = 50
            me = threading.get_ident()
            counts: collections.Counter = collections.Counter()
            end = _time.monotonic() + seconds
            while _time.monotonic() < end:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue
                    stack = []
                    f = frame
                    while f is not None and len(stack) < 24:
                        stack.append(
                            f"{f.f_code.co_filename.rsplit('/', 1)[-1]}"
                            f":{f.f_code.co_name}")
                        f = f.f_back
                    counts[";".join(reversed(stack))] += 1
                _time.sleep(1.0 / hz)
            self._reply(200, {
                "seconds": seconds, "hz": hz,
                "samples": sum(counts.values()),
                "stacks": [{"stack": k, "count": v}
                           for k, v in counts.most_common(40)]})
        elif url.path == "/metrics":
            entity_type = query.get("with_metric_entity_type",
                                    query.get("entity_type", [None]))[0]
            names = query.get("with_metrics", query.get("metrics", [None]))[0]
            metric_names = names.split(",") if names else None
            snap = METRICS.snapshot(entity_type, metric_names)
            if query.get("format", [None])[0] == "prom":
                # Prometheus text exposition (standard scrapers; the
                # collector->Prometheus sink path). JSON stays default.
                from pegasus_tpu.utils.metrics import to_prometheus

                self._reply_text(
                    200, to_prometheus(snap),
                    "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._reply(200, snap)
        else:
            self._reply(404, {"error": f"unknown path {url.path}"})


class MetricsHttpServer:
    """Threaded HTTP server; bind port 0 for an ephemeral port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 commands=None, routes=None) -> None:
        self._server = ThreadingHTTPServer((host, port), _Handler)
        # the /command endpoint serves this registry (None = 404)
        self._server.commands = commands
        # extra GET routes: path -> callable(query_dict) -> payload
        # (the meta REST surface rides here, meta_http_service parity)
        self._server.routes = routes
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MetricsHttpServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
