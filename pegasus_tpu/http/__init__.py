"""HTTP service (reference: src/http/)."""

from pegasus_tpu.http.http_server import MetricsHttpServer
