"""Device compaction filter: TTL + default-TTL rewrite + stale-split drop.

Parity: KeyWithTTLCompactionFilter::Filter
(src/server/key_ttl_compaction_filter.h:55-121):
1. default_ttl != 0 and record has no TTL -> rewrite expire_ts to
   now + default_ttl (value_changed).
2. user-specified compaction operations may delete / update TTL (the rule
   kernels live in ops/compaction_rules.py).
3. drop iff expired(now) after the rewrite, OR the key is stale post-split
   data: validate_hash and partition_version >= 0 and
   pidx <= partition_version and crc64-hash doesn't map here
   (check_if_stale_split_data, :114-121 — note: partition_version < 0 means
   KEEP here, the opposite of the scan path's reject).

Evaluated for a whole columnar batch in one XLA program, vs the reference's
per-record scalar Filter() callback during RocksDB compaction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from pegasus_tpu.ops.device_crc import key_hash_device
from pegasus_tpu.ops.predicates import ttl_expired
from pegasus_tpu.ops.record_block import next_bucket


@functools.partial(jax.jit, static_argnames=("validate_hash",))
def compaction_filter_block(keys, key_len, hashkey_len, expire_ts, valid,
                            now, default_ttl, pidx, partition_version,
                            validate_hash: bool):
    """Returns (drop: bool[B], new_expire_ts: uint32[B]).

    `partition_version` must be >= 0 when validate_hash is set (callers gate
    the pv<0 / pidx>pv cases to keep, mirroring check_if_stale_split_data).
    """
    now = jnp.asarray(now, jnp.uint32)
    default_ttl = jnp.asarray(default_ttl, jnp.uint32)

    new_ets = jnp.where((default_ttl != 0) & (expire_ts == 0),
                        now + default_ttl, expire_ts)
    expired = ttl_expired(new_ets, now)

    if validate_hash:
        _, lo = key_hash_device(keys, key_len, hashkey_len)
        pv = jnp.asarray(partition_version, jnp.uint32)
        stale = (lo & pv) != jnp.asarray(pidx, jnp.uint32)
    else:
        stale = jnp.zeros_like(valid)

    drop = (expired | stale) & valid
    return drop, new_ets


# ---- bulk block-level compaction (the GB/s path) -----------------------
#
# The merge-based compactor streams per-record Python; the bulk path
# below evaluates WHOLE device-resident columnar blocks — stacked across
# blocks (and partitions) into a handful of programs — and rewrites
# surviving rows with vectorized numpy gathers. One fused program per
# ruleset covers the reference's full Filter() ordering
# (key_ttl_compaction_filter.h:55-121): default-TTL rewrite -> user
# rules -> expiry + stale-split drop.

from collections import OrderedDict

_EVAL_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_EVAL_CACHE_CAP = 32


def _ops_key(operations) -> tuple:
    """Content identity of a parsed ruleset: recompiling the same JSON
    (config-sync re-delivers app-envs periodically) must reuse the same
    jitted program instead of leaking one compiled executable per
    delivery."""
    if not operations:
        return ()
    out = []
    for op in operations:
        rules = []
        for r in op.rules:
            if r.kind == "ttl_range":
                rules.append((r.kind, r.start_ttl, r.stop_ttl))
            else:
                rules.append((r.kind, r.filter.filter_type, r.filter.raw))
        out.append((op.op, getattr(op, "utot", None),
                    getattr(op, "value", None), tuple(rules)))
    return tuple(out)


def make_compaction_eval(operations=None):
    """Jitted (drop, new_ets) program for one (optional) parsed ruleset.

    `operations` is the tuple from compile_rules(...).operations (static
    ruleset structure -> its own XLA program, cached by CONTENT and
    bounded)."""
    key = _ops_key(operations)
    cached = _EVAL_CACHE.get(key)
    if cached is not None:
        _EVAL_CACHE.move_to_end(key)
        return cached

    @functools.partial(jax.jit, static_argnames=("validate_hash",
                                                 "use_hash_lo",
                                                 "want_ets", "pack"))
    def eval_block(keys, key_len, hashkey_len, expire_ts, valid, hash_lo,
                   now, default_ttl, pidx, partition_version,
                   validate_hash: bool, use_hash_lo: bool,
                   want_ets: bool = True, pack: bool = False):
        from pegasus_tpu.ops.compaction_rules import apply_rules_ops

        now = jnp.asarray(now, jnp.uint32)
        default_ttl = jnp.asarray(default_ttl, jnp.uint32)
        ets1 = jnp.where((default_ttl != 0) & (expire_ts == 0),
                         now + default_ttl, expire_ts)
        if operations:
            rule_drop, ets2 = apply_rules_ops(
                operations, keys, key_len, hashkey_len, ets1, valid, now)
        else:
            rule_drop = jnp.zeros_like(valid)
            ets2 = ets1
        expired = ttl_expired(ets2, now)
        if validate_hash:
            if use_hash_lo:
                lo = hash_lo  # precomputed at SST write time
            else:
                _, lo = key_hash_device(keys, key_len, hashkey_len)
            pv = jnp.asarray(partition_version, jnp.uint32)
            stale = (lo & pv) != jnp.asarray(pidx, jnp.uint32)
        else:
            stale = jnp.zeros_like(valid)
        drop = ((expired | stale) & valid) | rule_drop
        # pack: bit-pack the drop mask on device (the tunnel's
        # device->host link is the scarce resource); want_ets=False skips
        # returning the rewritten-TTL column entirely when no rule or
        # default-TTL can change it (the caller never reads it)
        if pack:
            drop = jnp.packbits(drop)
        return (drop, ets2) if want_ets else (drop,)

    _EVAL_CACHE[key] = eval_block
    while len(_EVAL_CACHE) > _EVAL_CACHE_CAP:
        _EVAL_CACHE.popitem(last=False)
    return eval_block


def encoded_drop_mask(enc, now: int, default_ttl: int, pidx: int,
                      partition_version: int, validate_hash: bool,
                      want_ets: bool = True):
    """(drop bool[n], new_ets|None) for one ENCODED block — the
    direct-compute twin of the jitted eval_block for rulesets that
    touch no key bytes (no user rules): the TTL + default-TTL rewrite
    reads the raw `expire_ts` column and the stale-split check reads
    the raw `hash_lo` column, so a compressed block's drop mask costs
    zero key decode, zero value-heap inflate, and zero device
    dispatch. Semantics match eval_block exactly (valid is all-True
    for SST-origin blocks, as compaction_eval_submit stamps it)."""
    ets = np.asarray(enc.expire_ts)
    if default_ttl:
        new_ets = np.where(ets == 0,
                           np.uint32((now + default_ttl) & 0xFFFFFFFF),
                           ets)
    else:
        new_ets = ets
    now32 = np.uint32(now & 0xFFFFFFFF)
    drop = (new_ets > 0) & (new_ets <= now32)
    if validate_hash:
        pv = np.uint32(max(partition_version, 0) & 0xFFFFFFFF)
        drop = drop | ((np.asarray(enc.hash_lo) & pv)
                       != np.uint32(pidx & 0xFFFFFFFF))
    return drop, (new_ets if want_ets else None)


def mesh_compact_step(keys, key_len, hashkey_len, expire_ts, present,
                      hash_lo, pidx, allowed, now, default_ttl,
                      partition_version, *, operations=None,
                      validate_hash: bool = False,
                      want_ets: bool = True):
    """Whole-table [P, B] twin of eval_block over the RESIDENT image
    (parallel/mesh_resident.py): one SPMD dispatch computes every
    compacting partition's drop masks instead of per-window host/XLA
    programs — the LUDA shape.

    Filter ordering is byte-for-byte eval_block's (default-TTL rewrite
    -> user rules -> expiry + stale-split), flattened [P, B] -> [P*B]
    with a per-row pidx vector exactly like mesh_resident._mesh_step so
    the paths cannot drift. `present` plays eval_block's `valid`: the
    host submit path stamps valid=True for every real SST row
    (tombstones included — the write stage's flags check drops them
    either way), and the stack's present mask is exactly that. The
    stale-split term is additionally gated per-slot by `allowed`
    (pidx <= partition_version — check_if_stale_split_data's KEEP for
    mid-split children above the version), so one dispatch serves a
    table whose partitions straddle a split. Returns
    (packed_drop uint8[P, B/8], ets2 uint32[P, B] if want_ets)."""
    from pegasus_tpu.ops.compaction_rules import apply_rules_ops

    p, b = expire_ts.shape
    k = keys.shape[-1]
    now = jnp.asarray(now, jnp.uint32)
    default_ttl = jnp.asarray(default_ttl, jnp.uint32)
    ets = expire_ts.reshape(p * b)
    present_f = present.reshape(p * b)
    ets1 = jnp.where((default_ttl != 0) & (ets == 0),
                     now + default_ttl, ets)
    if operations:
        rule_drop, ets2 = apply_rules_ops(
            operations, keys.reshape(p * b, k), key_len.reshape(p * b),
            hashkey_len.reshape(p * b), ets1, present_f, now)
    else:
        rule_drop = jnp.zeros_like(present_f)
        ets2 = ets1
    expired = ttl_expired(ets2, now)
    if validate_hash:
        pv = jnp.asarray(partition_version, jnp.uint32)
        stale = ((hash_lo.reshape(p * b) & pv) != jnp.repeat(pidx, b)) \
            & jnp.repeat(allowed, b)
    else:
        stale = jnp.zeros_like(present_f)
    drop = ((expired | stale) & present_f) | rule_drop
    packed = jnp.packbits(drop.reshape(p, b), axis=1)
    if want_ets:
        return packed, ets2.reshape(p, b)
    return (packed,)


COMPACT_CHUNK_ROWS = 1 << 18  # 256k records per stacked program


def _row_bucket(n: int) -> int:
    """Power-of-two row capacity for a stacked program (bounds distinct
    XLA compilations). Unlike record_block.next_bucket this is a ROW
    count, not a key width — no 64k ceiling (chunking already bounds it
    at COMPACT_CHUNK_ROWS plus one block)."""
    w = 4096
    while w < n:
        w <<= 1
    return w


# compaction must move every key byte host->device and the masks back,
# so eval placement is decided by the shared link probe
from pegasus_tpu.ops.placement import choose_eval_device  # noqa: F401 (re-export)


def rules_workload(operations) -> str:
    """Placement class for a parsed ruleset (ops/placement.py).

    The accelerator's upload cost (~32 key bytes/record at ~0.5 GB/s)
    buys ALL rules' compute at once, while the host pays per pattern —
    measured break-even on this image is around two substring
    (MATCH_ANYWHERE) patterns or a handful of cheaper prefix/postfix
    ones. Rulesets below that stay compute-trivial ("ttl" class)."""
    if not operations:
        return "ttl"
    anywhere = 0
    patterns = 0
    for op in operations:
        for r in op.rules:
            if r.kind == "ttl_range":
                continue
            patterns += 1
            ft = getattr(r.filter, "filter_type", None)
            if ft == 1:  # FT_MATCH_ANYWHERE
                anywhere += 1
    return "rules" if (anywhere >= 2 or patterns >= 4) else "ttl"


def compaction_eval_submit(blocks, now, default_ttl, partition_version,
                           validate_hash: bool, operations=None,
                           eval_device=None, want_ets: bool = True):
    """Phase 1: dispatch compaction-filter programs WITHOUT waiting.

    `blocks`: [(tag, host_block, pidx)] — host_block is a columnar SST
    Block (storage/sstable.py), `pidx` the owning partition (one wave
    can span a whole table). Blocks are concatenated host-side into
    ~COMPACT_CHUNK_ROWS-record programs per key width (ONE transfer set
    per chunk, not per block). Returns an opaque list for
    compaction_eval_drain. Drop masks come back bit-packed; the
    rewritten-TTL column transfers only when `want_ets` (a pass with no
    default-TTL and no update_ttl rule never reads it).

    `eval_device`: jax device to run on ("auto" via choose_eval_device
    when None is resolved by the caller)."""
    import contextlib

    import jax as _jax

    eval_block = make_compaction_eval(operations)
    ctx = (contextlib.nullcontext() if eval_device is None
           else _jax.default_device(eval_device))

    buckets: dict = {}
    for tag, blk, pidx in blocks:
        buckets.setdefault(int(blk.keys.shape[1]), []).append(
            (tag, blk, pidx))

    submitted = []
    with ctx:
        for _w, group in buckets.items():
            off = 0
            while off < len(group):
                chunk = []
                rows = 0
                while off < len(group):
                    n_blk = group[off][1].count
                    if chunk and rows + n_blk > COMPACT_CHUNK_ROWS:
                        break  # close the chunk at the row target
                    chunk.append(group[off])
                    rows += n_blk
                    off += 1
                cap = _row_bucket(rows)
                keys = np.zeros((cap, _w), dtype=np.uint8)
                key_len = np.zeros(cap, dtype=np.int32)
                ets = np.zeros(cap, dtype=np.uint32)
                valid = np.zeros(cap, dtype=bool)
                pidx_col = np.zeros(cap, dtype=np.uint32)
                use_lo = validate_hash and all(
                    b.hash_lo is not None for _t, b, _p in chunk)
                hash_lo = (np.zeros(cap, dtype=np.uint32) if use_lo
                           else np.zeros(1, dtype=np.uint32))
                pos = 0
                spans = []
                for tag, blk, pidx in chunk:
                    n = blk.count
                    keys[pos:pos + n, :blk.keys.shape[1]] = blk.keys
                    key_len[pos:pos + n] = blk.key_len
                    ets[pos:pos + n] = blk.expire_ts
                    valid[pos:pos + n] = True
                    pidx_col[pos:pos + n] = pidx
                    if use_lo:
                        hash_lo[pos:pos + n] = blk.hash_lo
                    spans.append((tag, pos, n))
                    pos += n
                # hashkey_len from the big-endian u16 key prefix
                hkl = ((key_len > 0)
                       * ((keys[:, 0].astype(np.int32) << 8)
                          | keys[:, 1].astype(np.int32)))
                out = eval_block(
                    keys, key_len, hkl, ets, valid, hash_lo,
                    np.uint32(now), np.uint32(default_ttl), pidx_col,
                    np.uint32(max(partition_version, 0) & 0xFFFFFFFF),
                    validate_hash, use_lo, want_ets=want_ets, pack=True)
                drop = out[0]
                new_ets = out[1] if want_ets else None
                submitted.append((spans, cap, drop, new_ets))
    return submitted


def compaction_eval_drain(submitted, want_ets: bool = True):
    """Phase 2: fetch EVERY submitted result in one transfer round (the
    tunnel charges ~69 ms per synchronous fetch regardless of size) and
    yield (tag, drop[:n], new_ets[:n]|None) per block."""
    import jax as _jax

    arrays = [d for _s, _c, d, _e in submitted]
    if want_ets:
        arrays += [e for _s, _c, _d, e in submitted]
    try:
        fetched = _jax.device_get(arrays)
    except Exception:  # noqa: BLE001 - fall back to per-array fetch
        fetched = [np.asarray(a) for a in arrays]
    n_chunks = len(submitted)
    for i, (spans, cap, _d, _e) in enumerate(submitted):
        drop_all = np.unpackbits(fetched[i], count=cap).astype(bool)
        ets_all = fetched[n_chunks + i] if want_ets else None
        for tag, pos, n in spans:
            yield (tag, drop_all[pos:pos + n],
                   ets_all[pos:pos + n] if want_ets else None)


def compaction_eval_stacked(blocks, now, default_ttl, partition_version,
                            validate_hash: bool, operations=None,
                            eval_device=None, want_ets: bool = True):
    """Submit + drain in one call (the non-pipelined form; the engine's
    windowed compactor overlaps a window's drain/rewrite with the next
    window's submit)."""
    yield from compaction_eval_drain(
        compaction_eval_submit(blocks, now, default_ttl,
                               partition_version, validate_hash,
                               operations=operations,
                               eval_device=eval_device,
                               want_ets=want_ets),
        want_ets=want_ets)
