"""Device compaction filter: TTL + default-TTL rewrite + stale-split drop.

Parity: KeyWithTTLCompactionFilter::Filter
(src/server/key_ttl_compaction_filter.h:55-121):
1. default_ttl != 0 and record has no TTL -> rewrite expire_ts to
   now + default_ttl (value_changed).
2. user-specified compaction operations may delete / update TTL (the rule
   kernels live in ops/compaction_rules.py).
3. drop iff expired(now) after the rewrite, OR the key is stale post-split
   data: validate_hash and partition_version >= 0 and
   pidx <= partition_version and crc64-hash doesn't map here
   (check_if_stale_split_data, :114-121 — note: partition_version < 0 means
   KEEP here, the opposite of the scan path's reject).

Evaluated for a whole columnar batch in one XLA program, vs the reference's
per-record scalar Filter() callback during RocksDB compaction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pegasus_tpu.ops.device_crc import key_hash_device
from pegasus_tpu.ops.predicates import ttl_expired


@functools.partial(jax.jit, static_argnames=("validate_hash",))
def compaction_filter_block(keys, key_len, hashkey_len, expire_ts, valid,
                            now, default_ttl, pidx, partition_version,
                            validate_hash: bool):
    """Returns (drop: bool[B], new_expire_ts: uint32[B]).

    `partition_version` must be >= 0 when validate_hash is set (callers gate
    the pv<0 / pidx>pv cases to keep, mirroring check_if_stale_split_data).
    """
    now = jnp.asarray(now, jnp.uint32)
    default_ttl = jnp.asarray(default_ttl, jnp.uint32)

    new_ets = jnp.where((default_ttl != 0) & (expire_ts == 0),
                        now + default_ttl, expire_ts)
    expired = ttl_expired(new_ets, now)

    if validate_hash:
        _, lo = key_hash_device(keys, key_len, hashkey_len)
        pv = jnp.asarray(partition_version, jnp.uint32)
        stale = (lo & pv) != jnp.asarray(pidx, jnp.uint32)
    else:
        stale = jnp.zeros_like(valid)

    drop = (expired | stale) & valid
    return drop, new_ets
