"""Adaptive device placement for data-movement-bound programs.

Serving scans keep their inputs DEVICE-RESIDENT (uploaded once, masks
cached), so accelerator latency never sits on the steady-state path.
But some programs must move their whole input per call — compaction
filters (every key byte), geo distance batches (fresh candidates per
search). Placement is decided per WORKLOAD SHAPE from one measured link
probe, because the tunnel's cost model (measured on this image:
~70 ms fixed per program round, ~0.5 GB/s host->device, ~37 MB/s
device->host marginal) splits these programs into two classes:

- "ttl" / "probe" — compute-trivial per byte (a compare against `now`;
  a crc/bisect over short key regions for the point-read batch gate).
  The host XLA backend streams these at memory speed with zero
  movement; the accelerator can never win unless it is co-located
  (sub-ms RTT).
- "rules" / "match" — compute-dense per byte (multi-pattern substring
  matching over wide key rows, K-flavor batches). Upload cost buys K
  patterns of compute, results return bit-packed; the accelerator wins
  once the link RTT is amortizable (deep pipelining), so these stay on
  the ambient accelerator even over a moderate-latency link, and fall
  back to host only when the link is pathological (probe failure).

The SAME jitted code runs either way (jax.default_device does the
placement; nothing is duplicated).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_PROBE_RTT: object = ...       # ... = unprobed; None = no accelerator
_PROBE_DEFAULT = None          # the probed non-cpu device (if any)

# a round-trip under this means effectively co-located: even
# compute-trivial movement-bound programs can ride the accelerator
LINK_RTT_COLOCATED_S = 0.005

# a round-trip above this means the link is pathological: nothing
# movement-bound belongs on the accelerator, however compute-dense
LINK_RTT_BROKEN_S = 2.0


def _probe_rtt():
    """One tiny measured round-trip to the ambient accelerator; cached
    per process. Returns (rtt_seconds, device) or (None, None) when the
    ambient default is the CPU already (or the probe fails)."""
    global _PROBE_RTT, _PROBE_DEFAULT
    if _PROBE_RTT is not ...:
        return _PROBE_RTT, _PROBE_DEFAULT
    import time

    import jax
    import jax.numpy as jnp

    rtt = None
    dev = None
    try:
        default = jnp.zeros(1).devices().pop()
        if default.platform != "cpu":
            x = np.zeros(1024, dtype=np.uint8)
            jax.device_put(x, default)  # warm any lazy session setup
            t0 = time.perf_counter()
            np.asarray(jax.device_put(x, default))
            rtt = time.perf_counter() - t0
            dev = default
    except Exception:  # noqa: BLE001 - probe failure = no accelerator
        rtt = None
        dev = None
    _PROBE_RTT, _PROBE_DEFAULT = rtt, dev
    return rtt, dev


def choose_eval_device(workload: str = "rules"):
    """jax.Device to place a movement-bound program on, or None to keep
    the ambient default.

    workload: "ttl"/"probe"/"scan_pushdown" (compute-trivial per byte —
    scan-pushdown value filters and aggregate folds stream the value
    heap once, host-side, because value heaps are never
    device-resident) or "rules"/"match" (compute-dense). See the module
    docstring for the policy.
    """
    import jax

    rtt, _dev = _probe_rtt()
    if rtt is None:
        return None  # ambient default is already the host
    if workload in ("ttl", "probe", "scan_pushdown"):
        route_host = rtt > LINK_RTT_COLOCATED_S
    else:
        route_host = rtt > LINK_RTT_BROKEN_S
    if route_host:
        try:
            cpus = jax.local_devices(backend="cpu")
        except Exception:  # noqa: BLE001 - no cpu backend registered
            return None
        return cpus[0] if cpus else None
    return None


def reset_probe() -> None:
    """Forget the cached probe (tests / backend swaps)."""
    global _PROBE_RTT, _PROBE_DEFAULT
    _PROBE_RTT = ...
    _PROBE_DEFAULT = None


# modeled link constants (measured once on this image, see module
# docstring): used only for the offload BREAKDOWN — the routing
# decision itself stays the probed-RTT thresholds above, which hold
# across link models
H2D_GBPS_EST = 0.5      # host->device marginal bandwidth
ROUND_FIXED_S_EST = 0.070  # fixed cost per program round over the tunnel
HOST_FILTER_GBPS_EST = 2.0  # host-side TTL/hash compare streams near
#                             memory speed (no movement at all)
HOST_DISPATCH_S_EST = 0.002  # fixed per-program dispatch cost on the
#                              host backend (jit call + mask fetch) —
#                              part of the PREDICTION so the drift
#                              gauge compares model vs measurement on
#                              the same footing for small batches


# mesh topology constants (the third placement class): a resident-mesh
# round needs no H2D movement at all — the blocks already live sharded
# on the mesh — so its cost is the dispatch floor, the cross-device
# collectives (packbits gather + psum counts travel ICI-neighbor hops,
# not the tunnel), and the sharded predicate stream
ICI_NEIGHBOR_S_EST = 0.0002   # per-hop collective cost on the mesh
MESH_ICI_HOPS_EST = 8         # nominal ring hops per whole-table round
MESH_EVAL_GBPS_EST = 8.0      # aggregate predicate stream across shards
D2H_GBPS_EST = 0.037          # device->host marginal bandwidth — the
#                               tunnel's downlink (module docstring);
#                               what a mesh COMPACTION pays to bring
#                               the packed drop masks + rewritten-TTL
#                               column home (scans only fetch masks;
#                               compaction fetches the ets column too)

# a compaction row's resident predicate bytes: the same accounting the
# slab/stack builders use (key matrix ~32 B + 9 B of len/expiry
# columns) — offload_breakdown models window counts from it
MESH_COMPACT_ROW_BYTES_EST = 41


def mesh_round_fixed_s() -> float:
    """Fixed cost of one whole-table mesh dispatch. Colocated devices
    (CPU fallback mesh, sub-ms link) pay the same jit-call floor a host
    program pays; a tunneled mesh pays the full tunnel round."""
    rtt, _dev = _probe_rtt()
    if rtt is not None and rtt > LINK_RTT_COLOCATED_S:
        return ROUND_FIXED_S_EST
    return HOST_DISPATCH_S_EST


def _mask_download_s(mask_bytes: int) -> float:
    """Device->host return cost for a mesh result of `mask_bytes`. A
    colocated mesh (CPU fallback devices, sub-ms link) hands results
    back at memory speed; a tunneled mesh pays the ~37 MB/s downlink."""
    rtt, _dev = _probe_rtt()
    if rtt is not None and rtt > LINK_RTT_COLOCATED_S:
        return mask_bytes / (D2H_GBPS_EST * 1e9)
    return mask_bytes / (HOST_FILTER_GBPS_EST * 1e9)


def predict_mesh_compact_seconds(batch_bytes: int,
                                 mask_bytes: Optional[int] = None) -> float:
    """The model's claim for ONE whole-table mesh compaction-filter
    dispatch: the mesh round floor + ICI collectives + the sharded
    predicate stream over the resident bytes + downloading the packed
    drop masks (and rewritten-TTL column) back to the write stage.

    Unlike the scan shape, compaction's result is not just a bitmask:
    the rewritten expire_ts column rides home too when TTLs can
    change, so the downlink term is first-class here. `mask_bytes`
    defaults to the modeled 1 bit/row + 4 B/row from the row-bytes
    estimate."""
    if mask_bytes is None:
        rows = batch_bytes / MESH_COMPACT_ROW_BYTES_EST
        mask_bytes = int(rows / 8 + 4 * rows)
    return (mesh_round_fixed_s()
            + ICI_NEIGHBOR_S_EST * MESH_ICI_HOPS_EST
            + batch_bytes / (MESH_EVAL_GBPS_EST * 1e9)
            + _mask_download_s(int(mask_bytes)))


def mesh_compact_pays(n_windows: int, batch_bytes: int,
                      mask_bytes: Optional[int] = None) -> bool:
    """Does ONE resident-mesh compaction-filter round beat the host
    filter stage's `n_windows` per-window dispatches over the same
    bytes? The compaction twin of mesh_wave_pays: a solo small
    compaction (one window, one partition) has nothing to amortize the
    mesh round + mask download against and honestly stays on
    encoded_drop_mask / the host kernels; a table-wide bulk compaction
    collapses every partition's windows into one dispatch and wins."""
    host_s = (HOST_DISPATCH_S_EST * max(1, int(n_windows))
              + batch_bytes / (HOST_FILTER_GBPS_EST * 1e9))
    return predict_mesh_compact_seconds(batch_bytes, mask_bytes) < host_s


def placement_verdict(workload: str = "rules") -> str:
    """The compute class the policy routes `workload` to, as the
    PerfContext `placement` string: "device" (ambient accelerator),
    "host-XLA" (host backend — either because the ambient default IS
    the host or because the policy re-routed there), or "mesh" (the
    resident whole-table SPMD program)."""
    if workload == "mesh":
        return "mesh"
    rtt, _dev = _probe_rtt()
    if rtt is None or choose_eval_device(workload) is not None:
        return "host-XLA"
    return "device"


def predict_kernel_seconds(workload: str, batch_bytes: int) -> float:
    """The cost model's prediction for one mask-evaluation batch on the
    device the policy actually routes it to — what the workload
    profiler's drift gauge compares the measured wall time against.
    Mirrors offload_breakdown's estimates plus the fixed host dispatch
    cost (a prediction of 3µs for a 6KB batch would make every
    measurement look like 1000x drift; the model's claim includes the
    per-call floor)."""
    if workload == "mesh":
        return (mesh_round_fixed_s()
                + ICI_NEIGHBOR_S_EST * MESH_ICI_HOPS_EST
                + batch_bytes / (MESH_EVAL_GBPS_EST * 1e9))
    if workload == "mesh_compact":
        return predict_mesh_compact_seconds(batch_bytes)
    if placement_verdict(workload) == "device":
        return ROUND_FIXED_S_EST + batch_bytes / (H2D_GBPS_EST * 1e9)
    return (HOST_DISPATCH_S_EST
            + batch_bytes / (HOST_FILTER_GBPS_EST * 1e9))


def mesh_wave_pays(n_programs: int, batch_bytes: int) -> bool:
    """Does ONE resident-mesh round beat the host path's `n_programs`
    per-chunk dispatches over the same bytes? The mesh routing gate:
    single-chunk waves stay on the host (same dispatch floor, nothing to
    amortize); multi-chunk / multi-partition waves collapse to one
    round and win."""
    host_s = (HOST_DISPATCH_S_EST * max(1, int(n_programs))
              + batch_bytes / (HOST_FILTER_GBPS_EST * 1e9))
    return predict_kernel_seconds("mesh", batch_bytes) < host_s


def offload_breakdown(workload: str, batch_bytes: int) -> dict:
    """Quantified pays/doesn't-pay verdict for one movement-bound
    filter batch — the compaction pipeline's filter stage logs this,
    and the bench publishes it (PERF round-12's offload table). The
    verdict mirrors choose_eval_device exactly; the cost estimates are
    the modeled link constants scaled by the probed RTT."""
    rtt, dev = _probe_rtt()
    routed_host = choose_eval_device(workload) is not None
    out = {
        "workload": workload,
        "batch_bytes": int(batch_bytes),
        "accelerator_present": rtt is not None,
        "link_rtt_s": round(rtt, 6) if rtt is not None else None,
        "offload_pays": rtt is not None and not routed_host,
        "routed": ("host" if (rtt is None or routed_host)
                   else str(dev)),
    }
    if rtt is not None:
        # scale the fixed-round estimate by how the probed RTT compares
        # to the co-located threshold (a colocated link has ~no fixed
        # round cost; the wedged tunnel's is ~70ms)
        fixed = (ROUND_FIXED_S_EST if rtt > LINK_RTT_COLOCATED_S
                 else rtt)
        out["accel_batch_s_est"] = round(
            fixed + batch_bytes / (H2D_GBPS_EST * 1e9), 6)
        out["host_batch_s_est"] = round(
            batch_bytes / (HOST_FILTER_GBPS_EST * 1e9), 6)
    out["compact"] = compact_breakdown(batch_bytes)
    return out


def compact_breakdown(batch_bytes: int,
                      n_windows: Optional[int] = None,
                      mask_bytes: Optional[int] = None) -> dict:
    """Quantified verdict for the compaction FILTER stage over
    `batch_bytes` of resident predicate columns — the mesh-vs-host twin
    of the scan-wave breakdown, so `shell placement` (and the drift
    auditor reading the `mesh_compact` class) cover the compaction
    dispatch site exactly like the wave one. Window count defaults to
    the modeled pipeline geometry (compact_pipeline_window blocks of
    BLOCK_CAPACITY rows at ~MESH_COMPACT_ROW_BYTES_EST per row)."""
    rows = batch_bytes / MESH_COMPACT_ROW_BYTES_EST
    if n_windows is None:
        window_rows = 128 * 1024  # pipeline window x block capacity
        n_windows = max(1, int(-(-rows // window_rows)))
    if mask_bytes is None:
        mask_bytes = int(rows / 8 + 4 * rows)
    host_s = (HOST_DISPATCH_S_EST * max(1, int(n_windows))
              + batch_bytes / (HOST_FILTER_GBPS_EST * 1e9))
    mesh_s = predict_mesh_compact_seconds(batch_bytes, mask_bytes)
    return {
        "workload": "mesh_compact",
        "batch_bytes": int(batch_bytes),
        "n_windows": int(n_windows),
        "mask_bytes": int(mask_bytes),
        "mesh_pays": bool(mesh_s < host_s),
        "mesh_batch_s_est": round(mesh_s, 6),
        "host_batch_s_est": round(host_s, 6),
    }
