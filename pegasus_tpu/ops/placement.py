"""Adaptive device placement for data-movement-bound programs.

Serving scans keep their inputs DEVICE-RESIDENT (uploaded once, masks
cached), so accelerator latency never sits on the steady-state path.
But some programs must move their whole input per call — compaction
filters (every key byte), geo distance batches (fresh candidates per
search). On a co-located accelerator that movement is nearly free; on a
high-latency tunnel it dwarfs the compute. These programs therefore ask
`choose_eval_device()` once per process: a measured round-trip probe
decides whether they run on the ambient accelerator or on the host XLA
backend — the SAME jitted code either way (jax.default_device does the
placement; nothing is duplicated).
"""

from __future__ import annotations

import numpy as np

_EVAL_DEVICE_CHOICE: object = ...  # ... = unprobed (None is a real answer)

# round-trips slower than this mean the link, not the compute, would
# dominate any per-call data-movement-bound program
LINK_RTT_BUDGET_S = 0.005


def choose_eval_device():
    """jax.Device to place movement-bound programs on, or None to keep
    the ambient default. Probes the accelerator link once per process
    with one tiny measured round-trip."""
    global _EVAL_DEVICE_CHOICE
    if _EVAL_DEVICE_CHOICE is not ...:
        return _EVAL_DEVICE_CHOICE
    import time

    import jax
    import jax.numpy as jnp

    choice = None
    try:
        default = jnp.zeros(1).devices().pop()
        if default.platform != "cpu":
            x = np.zeros(1024, dtype=np.uint8)
            jax.device_put(x, default)  # warm any lazy session setup
            t0 = time.perf_counter()
            np.asarray(jax.device_put(x, default))
            rtt = time.perf_counter() - t0
            if rtt > LINK_RTT_BUDGET_S:
                cpus = jax.local_devices(backend="cpu")
                choice = cpus[0] if cpus else None
    except Exception:  # noqa: BLE001 - probe failure = keep default
        choice = None
    _EVAL_DEVICE_CHOICE = choice
    return choice


def reset_probe() -> None:
    """Forget the cached probe (tests / backend swaps)."""
    global _EVAL_DEVICE_CHOICE
    _EVAL_DEVICE_CHOICE = ...
