"""Device-side geo predicates: batched haversine distance filtering.

The radius-search hot loop (geo_client.h:295-335 filters every candidate
record by exact distance after the cell cover narrows the set) is a
classic per-record predicate — exactly the shape this framework
dispatches to the accelerator: one fused kernel evaluates the distance
mask for a whole candidate batch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

EARTH_RADIUS_M = 6_371_000.0


@partial(jax.jit, static_argnames=())
def _haversine_mask(lats, lngs, valid, center_lat, center_lng, radius_m):
    lat1 = jnp.radians(center_lat)
    lat2 = jnp.radians(lats)
    dp = lat2 - lat1
    dl = jnp.radians(lngs) - jnp.radians(center_lng)
    a = (jnp.sin(dp / 2.0) ** 2
         + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dl / 2.0) ** 2)
    dist = 2.0 * EARTH_RADIUS_M * jnp.arcsin(
        jnp.minimum(1.0, jnp.sqrt(a)))
    return valid & (dist <= radius_m), dist


def radius_filter(lats: np.ndarray, lngs: np.ndarray,
                  center_lat: float, center_lng: float,
                  radius_m: float, valid=None):
    """(keep_mask, distances_m) for a candidate batch. Arrays are padded
    to a power-of-two bucket so repeated searches reuse one compiled
    program (the same static-shape discipline as the scan kernels).

    Every search moves its whole candidate batch to the eval device and
    the mask back, so placement follows the shared link probe
    (ops/placement.py): co-located accelerators run it on-chip; behind a
    high-latency tunnel the same program runs on the host XLA backend
    instead of paying two link round-trips per query."""
    import contextlib

    from pegasus_tpu.ops.placement import choose_eval_device

    n = len(lats)
    if n == 0:
        return np.zeros(0, dtype=bool), np.zeros(0, dtype=np.float64)
    cap = 1 << max(6, (n - 1).bit_length())
    la = np.zeros(cap, dtype=np.float32)
    lo = np.zeros(cap, dtype=np.float32)
    va = np.zeros(cap, dtype=bool)
    la[:n] = lats
    lo[:n] = lngs
    va[:n] = True if valid is None else valid
    # per-query latency-bound movement (two link round-trips per search):
    # "ttl"-class placement — host XLA unless the accelerator is
    # co-located
    dev = choose_eval_device(workload="ttl")
    ctx = contextlib.nullcontext()
    if dev is not None:
        ctx = jax.default_device(dev)
    with ctx:
        keep, dist = _haversine_mask(
            jnp.asarray(la), jnp.asarray(lo), jnp.asarray(va),
            jnp.float32(center_lat), jnp.float32(center_lng),
            jnp.float32(radius_m))
        return np.asarray(keep)[:n], np.asarray(dist)[:n]
