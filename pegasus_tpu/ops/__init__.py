"""Device data plane: columnar record blocks + vectorized predicate kernels.

This package is the TPU-native replacement for the reference's scalar
per-record C++ hot loops:
- scan/multi_get record validation (src/server/pegasus_server_impl.cpp:2350
  validate_filter, :2382 validate_key_value_for_scan)
- TTL compaction filtering (src/server/key_ttl_compaction_filter.h:55)
- user-specified compaction rules (src/server/compaction_filter_rule.h,
  compaction_operation.h)

Records are laid out as fixed-shape uint8 tensors (keys padded to a bucket
width, expire_ts decoded into a u32 column) so that an entire block of
records is evaluated in one XLA program: filter matching, TTL expiry, and
partition-hash validation all become masked elementwise/window ops.
"""

from pegasus_tpu.ops.record_block import RecordBlock, build_record_block, next_bucket
from pegasus_tpu.ops.predicates import (
    FT_NO_FILTER,
    FT_MATCH_ANYWHERE,
    FT_MATCH_PREFIX,
    FT_MATCH_POSTFIX,
    FilterSpec,
    match_filter,
    ttl_expired,
    scan_block_predicate,
)
from pegasus_tpu.ops.device_crc import crc64_device, key_hash_device
