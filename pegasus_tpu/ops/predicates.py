"""Vectorized record predicates — the scan/multi_get hot path on device.

Parity with the reference's per-record scalar loop:
- validate_filter (src/server/pegasus_server_impl.cpp:2350): empty pattern
  matches everything; a region shorter than the pattern never matches;
  FT_MATCH_ANYWHERE/PREFIX/POSTFIX substring semantics.
- validate_key_value_for_scan (:2382): precedence is
  expired → hash_invalid → filtered → normal.
- check_if_ts_expired (src/base/pegasus_value_schema.h:113):
  expired iff 0 < expire_ts <= now.

Filter types are *static* arguments: each of the four types compiles to its
own XLA program (4 variants max per shape bucket), so FT_NO_FILTER costs
nothing and PREFIX doesn't pay for the ANYWHERE sliding window.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pegasus_tpu.ops.device_crc import key_hash_device
from pegasus_tpu.ops.record_block import RecordBlock, next_bucket

# rrdb filter_type values (idl/rrdb.thrift:27-33)
FT_NO_FILTER = 0
FT_MATCH_ANYWHERE = 1
FT_MATCH_PREFIX = 2
FT_MATCH_POSTFIX = 3


def host_match_filter(data: bytes, filter_type: int,
                      pattern: bytes) -> bool:
    """Scalar twin of match_filter for host-side paths (overlay rows,
    tests). Empty pattern matches everything, like the device kernel."""
    if filter_type == FT_NO_FILTER or not pattern:
        return True
    if filter_type == FT_MATCH_ANYWHERE:
        return pattern in data
    if filter_type == FT_MATCH_PREFIX:
        return data.startswith(pattern)
    if filter_type == FT_MATCH_POSTFIX:
        return data.endswith(pattern)
    raise ValueError(f"unknown filter type {filter_type}")

_PATTERN_MIN_WIDTH = 32


class FilterSpec(NamedTuple):
    """A filter pattern padded for device dispatch. `filter_type` stays a
    Python int (static); pattern bytes + length are device operands.
    `raw` keeps the original pattern bytes host-side so cache keys never
    need a device->host fetch of `pattern`."""

    filter_type: int
    pattern: jax.Array      # uint8[P] padded
    pattern_len: jax.Array  # int32 scalar
    raw: bytes = b""

    @staticmethod
    def make(filter_type: int, pattern: bytes = b"") -> "FilterSpec":
        return _make_cached(int(filter_type), bytes(pattern),
                            jax.config.jax_default_device)

    @staticmethod
    def none() -> "FilterSpec":
        return _make_cached(FT_NO_FILTER, b"",
                            jax.config.jax_default_device)

    @property
    def key(self) -> tuple:
        """Hashable host-side identity (for mask cache keys)."""
        return (self.filter_type, self.raw)


@functools.lru_cache(maxsize=256)
def _make_cached(filter_type: int, pattern: bytes, _device) -> FilterSpec:
    """FilterSpec fields are immutable (jax arrays), so identical
    filters share one device copy — on a remote accelerator each
    cache hit saves two host->device transfers per scan batch.
    Keyed by the ambient default device so a multi-backend process
    (e.g. bench.py's accel phase vs cpu-baseline phase) never leaks
    one backend's arrays into the other's dispatches."""
    width = next_bucket(len(pattern))
    buf = np.zeros(width, dtype=np.uint8)
    if pattern:
        buf[:len(pattern)] = np.frombuffer(pattern, dtype=np.uint8)
    return FilterSpec(filter_type, jnp.asarray(buf),
                      jnp.asarray(len(pattern), jnp.int32), pattern)


def match_filter(keys: jax.Array, region_start: jax.Array,
                 region_len: jax.Array, pattern: jax.Array,
                 pattern_len: jax.Array, filter_type: int) -> jax.Array:
    """bool[B]: does each record's byte region match the pattern?

    keys uint8[B, K]; region_start/region_len int32[B] (region within the
    padded key row); pattern uint8[P]; pattern_len int32; filter_type static.
    """
    b, k = keys.shape
    if filter_type == FT_NO_FILTER:
        return jnp.ones((b,), dtype=bool)

    p = pattern.shape[0]
    jp = jnp.arange(p, dtype=jnp.int32)
    pat_mask = jp < pattern_len                      # bool[P]
    empty = pattern_len == 0
    fits = region_len >= pattern_len                 # bool[B]

    if filter_type in (FT_MATCH_PREFIX, FT_MATCH_POSTFIX):
        if filter_type == FT_MATCH_PREFIX:
            offs = region_start
        else:
            offs = region_start + region_len - pattern_len
        idx = jnp.clip(offs[:, None] + jp[None, :], 0, k - 1)
        window = jnp.take_along_axis(keys, idx, axis=1)        # uint8[B, P]
        eq = (window == pattern[None, :]) | ~pat_mask[None, :]
        return (eq.all(axis=1) & fits) | empty

    # FT_MATCH_ANYWHERE: AND-accumulate shifted byte compares — O(B*K)
    # memory per step instead of materializing B*K*P windows. `t` indexes
    # absolute window-start positions within the padded row; a window is a
    # real candidate iff it lies inside [region_start, region_start +
    # region_len - pattern_len].
    padded = jnp.pad(keys, ((0, 0), (0, p)))
    window_ok = jnp.ones((b, k), dtype=bool)
    for j in range(p):  # static unroll over the pattern buffer; XLA fuses
        cmp = (padded[:, j:j + k] == pattern[j]) | (j >= pattern_len)
        window_ok = window_ok & cmp
    t = jnp.arange(k, dtype=jnp.int32)
    t_ok = ((t[None, :] >= region_start[:, None]) &
            (t[None, :] <= (region_start + region_len - pattern_len)[:, None]))
    return (jnp.any(window_ok & t_ok, axis=1) & fits) | empty


def ttl_expired(expire_ts: jax.Array, now: jax.Array) -> jax.Array:
    """bool[B]: expired iff 0 < expire_ts <= now (value_schema.h:113)."""
    now = jnp.asarray(now, jnp.uint32)
    return (expire_ts > 0) & (expire_ts <= now)


class ScanMasks(NamedTuple):
    """Per-record outcome masks, mutually exclusive, reference precedence
    (pegasus_server_impl.cpp:2382): expired → hash_invalid → filtered."""

    keep: jax.Array
    expired: jax.Array
    hash_invalid: jax.Array
    filtered: jax.Array


@functools.partial(jax.jit, static_argnames=("hash_filter_type",
                                             "sort_filter_type",
                                             "validate_hash",
                                             "use_hash_lo"))
def _scan_block_predicate(keys, key_len, hashkey_len, expire_ts, valid,
                          now, hash_pattern, hash_pattern_len,
                          sort_pattern, sort_pattern_len,
                          pidx, partition_version,
                          hash_filter_type: int, sort_filter_type: int,
                          validate_hash: bool, hash_lo=None,
                          use_hash_lo: bool = False) -> ScanMasks:
    expired = ttl_expired(expire_ts, now) & valid

    if validate_hash:
        if use_hash_lo:
            lo = hash_lo  # precomputed at SST write time
        else:
            _, lo = key_hash_device(keys, key_len, hashkey_len)
        pv = jnp.asarray(partition_version, jnp.uint32)
        hash_ok = (lo & pv) == jnp.asarray(pidx, jnp.uint32)
    else:
        hash_ok = jnp.ones_like(valid)
    hash_invalid = ~hash_ok & valid & ~expired

    hk_ok = match_filter(keys, jnp.full_like(key_len, 2), hashkey_len,
                         hash_pattern, hash_pattern_len, hash_filter_type)
    sort_start = 2 + hashkey_len
    sort_len = key_len - sort_start
    sk_ok = match_filter(keys, sort_start, sort_len,
                         sort_pattern, sort_pattern_len, sort_filter_type)
    filtered = ~(hk_ok & sk_ok) & valid & ~expired & ~hash_invalid

    keep = valid & ~expired & ~hash_invalid & ~filtered
    return ScanMasks(keep, expired, hash_invalid, filtered)


@functools.partial(jax.jit, static_argnames=("hash_filter_type",
                                             "sort_filter_type",
                                             "validate_hash",
                                             "use_hash_lo", "pack"))
def _static_block_predicate(keys, key_len, hashkey_len, valid,
                            hash_pattern, hash_pattern_len,
                            sort_pattern, sort_pattern_len,
                            pidx, partition_version,
                            hash_filter_type: int, sort_filter_type: int,
                            validate_hash: bool, hash_lo=None,
                            use_hash_lo: bool = False,
                            pack: bool = False) -> jax.Array:
    """The `now`-independent part of the scan predicate.

    For an IMMUTABLE columnar block, filter matching and partition-hash
    validation never change; only TTL expiry depends on the current
    second — and `expire_ts` is already host-resident, so the host can
    apply expiry with one vectorized AND at assembly time. Splitting the
    predicate this way means each (block, filter, partition_version)
    needs exactly ONE device evaluation for the block's whole lifetime:
    steady-state serving performs zero device round-trips (the decisive
    property on a high-latency accelerator link).
    """
    if validate_hash:
        if use_hash_lo:
            lo = hash_lo  # precomputed at SST write time
        else:
            _, lo = key_hash_device(keys, key_len, hashkey_len)
        pv = jnp.asarray(partition_version, jnp.uint32)
        hash_ok = (lo & pv) == jnp.asarray(pidx, jnp.uint32)
    else:
        hash_ok = jnp.ones_like(valid)
    hk_ok = match_filter(keys, jnp.full_like(key_len, 2), hashkey_len,
                         hash_pattern, hash_pattern_len, hash_filter_type)
    sort_start = 2 + hashkey_len
    sort_len = key_len - sort_start
    sk_ok = match_filter(keys, sort_start, sort_len,
                         sort_pattern, sort_pattern_len, sort_filter_type)
    keep = valid & hash_ok & hk_ok & sk_ok
    # pack=True: bit-pack the mask ON DEVICE — the device->host link is
    # the scarce resource on a tunneled accelerator (~25 MB/s measured);
    # 8x fewer mask bytes per program
    return jnp.packbits(keep) if pack else keep


def static_block_predicate(block: RecordBlock,
                           hash_filter: Optional[FilterSpec] = None,
                           sort_filter: Optional[FilterSpec] = None,
                           validate_hash: bool = False,
                           pidx=0,
                           partition_version: int = -1,
                           pack: bool = False) -> jax.Array:
    """bool[B]: records passing every `now`-independent predicate.

    keep(now) == static_keep & ~expired(now), applied host-side from the
    block's expire_ts column. Same reject-all split-safety gate as
    scan_block_predicate (pegasus_server_impl.cpp:2392-2401)."""
    hash_filter = hash_filter or FilterSpec.none()
    sort_filter = sort_filter or FilterSpec.none()
    pidx_is_array = not isinstance(pidx, int)
    if (validate_hash and not pidx_is_array
            and (partition_version < 0 or pidx > partition_version)):
        if pack:
            return jnp.zeros((block.capacity // 8,), dtype=jnp.uint8)
        return jnp.zeros((block.capacity,), dtype=bool)
    use_hash_lo = validate_hash and block.hash_lo is not None
    return _static_block_predicate(
        jnp.asarray(block.keys), jnp.asarray(block.key_len),
        jnp.asarray(block.hashkey_len), jnp.asarray(block.valid),
        hash_filter.pattern, hash_filter.pattern_len,
        sort_filter.pattern, sort_filter.pattern_len,
        jnp.asarray(pidx, jnp.uint32)
        if not pidx_is_array else jnp.asarray(pidx),
        jnp.asarray(partition_version & 0xFFFFFFFF, jnp.uint32),
        hash_filter.filter_type, sort_filter.filter_type, validate_hash,
        hash_lo=(jnp.asarray(block.hash_lo) if use_hash_lo
                 else jnp.zeros((1,), jnp.uint32)),
        use_hash_lo=use_hash_lo, pack=pack)


def host_alive_mask(expire_ts: np.ndarray, now: int) -> np.ndarray:
    """bool[B] numpy twin of ~ttl_expired: rows NOT expired at `now`."""
    ets = np.asarray(expire_ts)
    return ~((ets > 0) & (ets <= np.uint32(now)))


# direct compute on compressed blocks: probes answered from the encoded
# representation, with zero key-matrix rebuild and zero device dispatch
from pegasus_tpu.utils.metrics import METRICS as _METRICS  # noqa: E402

_ENCODED_PROBE = _METRICS.entity("storage", "node").relaxed_counter(
    "encoded_probe_count")


def _region_filter_host(heap: np.ndarray, offs: np.ndarray,
                        filter_type: int, pattern: bytes) -> np.ndarray:
    """bool[n] pattern match over ragged byte regions
    heap[offs[i]:offs[i+1]] — native kernel when available, scalar
    host_match_filter loop otherwise. Device-kernel semantics: empty
    pattern matches everything; region shorter than pattern never
    matches."""
    from pegasus_tpu import native

    n = len(offs) - 1
    if filter_type == FT_NO_FILTER or not pattern:
        return np.ones(n, dtype=bool)
    fn = native.region_filter_fn()
    if fn is not None:
        out = np.empty(n, dtype=np.uint8)
        fn(np.ascontiguousarray(heap),
           np.ascontiguousarray(offs, dtype=np.int64), n, pattern,
           filter_type, out)
        return out.astype(bool)
    hv = np.asarray(heap)
    return np.fromiter(
        (host_match_filter(hv[offs[i]:offs[i + 1]].tobytes(),
                           filter_type, pattern) for i in range(n)),
        dtype=bool, count=n)


def encoded_static_keep(enc, validate_hash: bool, pidx: int,
                        partition_version: int,
                        filter_key) -> Optional[np.ndarray]:
    """bool[n] static keep mask of an EncodedBlock
    (storage/block_codec.py), bit-identical to
    `static_block_predicate` over the decoded block — evaluated
    entirely on the HOST against the encoded representation:

    - partition-hash validation reads the raw `hash_lo` column;
    - the hashkey filter evaluates once per DICTIONARY entry (D unique
      hashkeys, not n rows) and gathers per-row through the index
      column;
    - the sortkey filter runs over the packed sortkey heap (no padded
      key matrix, no zero-byte scanning).

    Returns None when the block cannot take this path (malformed rows
    present — the device kernel's hashkey_len semantics differ there).
    TTL stays the caller's per-second host mask, exactly as on the
    device path (static masks are `now`-independent).
    """
    if enc.has_malformed:
        return None
    n = enc.n
    hft, hfp, sft, sfp = filter_key
    if validate_hash and (partition_version < 0
                          or pidx > partition_version):
        # split-safety reject-all gate, mirroring static_block_predicate
        _ENCODED_PROBE.increment()
        return np.zeros(n, dtype=bool)
    keep = np.asarray(enc.key_len) >= 2
    if validate_hash:
        pv = np.uint32(partition_version & 0xFFFFFFFF)
        keep = keep & ((np.asarray(enc.hash_lo) & pv)
                       == np.uint32(pidx))
    if hft != FT_NO_FILTER and hfp:
        do = np.asarray(enc.dict_offs, dtype=np.int64)
        per_dict = _region_filter_host(enc.dict_heap, do, hft, hfp)
        keep = keep & per_dict[enc.hk_idx]
    if sft != FT_NO_FILTER and sfp:
        keep = keep & _region_filter_host(enc.sk_heap, enc.sk_offs,
                                          sft, sfp)
    _ENCODED_PROBE.increment()
    return keep


def pad_probe_keys(probe_keys, width: int):
    """(uint8[P, width] padded rows, int64[P] lengths) for a batch of
    exact-match probe keys. Keys longer than `width` cannot exist in a
    block of that key width; their rows are zeroed and flagged by
    length so point_probe_rows reports them absent."""
    p = len(probe_keys)
    lens = np.fromiter((len(k) for k in probe_keys), dtype=np.int64,
                       count=p)
    buf = bytearray(p * width)
    for i, k in enumerate(probe_keys):
        if len(k) <= width:
            off = i * width
            buf[off:off + len(k)] = k
    return (np.frombuffer(bytes(buf), dtype=np.uint8).reshape(p, width),
            lens)


def point_probe_rows(keys_matrix: np.ndarray, key_len: np.ndarray,
                     probe_keys, block_void=None) -> np.ndarray:
    """Vectorized exact-key probe into ONE sorted columnar block.

    keys_matrix: uint8[N, W] zero-padded sorted rows (SST block order);
    key_len: int[N]; probe_keys: list[bytes]; block_void: optional
    precomputed memcmp-ordered void view of keys_matrix (cached per
    block by page.probe_nat). Returns int64[P] row indices (-1 =
    absent). One np.searchsorted over the void view locates every probe
    at once — the batched replacement for per-key Python bisects on the
    point-get hot path; no key materialization, so cold blocks probe as
    fast as hot ones.

    Zero padding makes two keys differing only in TRAILING zero bytes
    pad to identical rows; such twins are adjacent and sorted by true
    length, so the rare collision resolves with a short forward scan.
    """
    n, w = keys_matrix.shape
    p = len(probe_keys)
    if p == 0 or n == 0:
        return np.full(p, -1, dtype=np.int64)
    vt = np.dtype((np.void, w))
    if block_void is None:
        block_void = np.ascontiguousarray(keys_matrix).view(vt).ravel()
    if p <= 4:
        # scalar fast path: the common flush shape scatters 1-2 keys
        # per block, where the batch verify's array setup costs more
        # than the probes
        rows = np.full(p, -1, dtype=np.int64)
        for i, k in enumerate(probe_keys):
            lk = len(k)
            if lk > w:
                continue
            padded = k.ljust(w, b"\x00")
            pos = int(np.searchsorted(
                block_void, np.frombuffer(padded, dtype=vt))[0])
            while pos < n and block_void[pos].tobytes() == padded:
                if int(key_len[pos]) == lk:
                    rows[i] = pos
                    break
                pos += 1  # trailing-zero twin: true match is ahead
        return rows
    pm, lens = pad_probe_keys(probe_keys, w)
    probe_v = pm.view(vt).ravel()
    pos = np.searchsorted(block_void, probe_v)
    rows = np.full(p, -1, dtype=np.int64)
    in_range = (pos < n) & (lens <= w)
    cand = np.flatnonzero(in_range)
    if cand.size:
        cpos = pos[cand]
        same = (keys_matrix[cpos] == pm[cand]).all(axis=1)
        exact = same & (np.asarray(key_len)[cpos] == lens[cand])
        rows[cand[exact]] = cpos[exact]
        # padded-equal but length-mismatched: trailing-zero twins ahead
        for i in cand[same & ~exact]:
            j = int(pos[i]) + 1
            want = int(lens[i])
            while j < n and block_void[j] == probe_v[i]:
                if int(key_len[j]) == want:
                    rows[i] = j
                    break
                j += 1
    return rows


def phash_verify_rows(keys_matrix: np.ndarray, key_len: np.ndarray,
                      rows: np.ndarray, probe_keys) -> np.ndarray:
    """bool[P]: does block row rows[i] hold EXACTLY probe_keys[i]?

    The perfect-hash probe's fingerprint-collision rejector: the index
    (storage/phash.py) maps a batched flush straight to (block, slot)
    rows, and this one vectorized compare per touched block confirms
    each located row before it serves — a collision (~0.08% of absent
    keys) must read as "absent", never as another row's value. Scalar
    fast path below the same threshold as point_probe_rows (the 1-4
    key flush shape)."""
    p = len(probe_keys)
    if p == 0:
        return np.zeros(0, dtype=bool)
    n, w = keys_matrix.shape
    kl = np.asarray(key_len)
    if p <= 4:
        out = np.zeros(p, dtype=bool)
        for i, k in enumerate(probe_keys):
            r = int(rows[i])
            lk = len(k)
            out[i] = (lk <= w and int(kl[r]) == lk
                      and keys_matrix[r, :lk].tobytes() == k)
        return out
    pm, lens = pad_probe_keys(probe_keys, w)
    fits = lens <= w
    rows = np.asarray(rows, dtype=np.int64)
    same = (keys_matrix[rows] == pm).all(axis=1)
    return same & fits & (kl[rows] == lens)


def bloom_key_hashes(keys) -> np.ndarray:
    """uint64[B] full-key crc64 for a batch of probe keys — the hash
    input EVERY sidecar structure shares (bloom filters and the
    perfect-hash index probe the same column), evaluated once per read
    flush and consumed by every table/run the flush's candidates
    touch.

    Placement: compute-trivial per byte (the "probe" workload class in
    ops/placement.py — a table lookup per byte), so this always runs on
    the host: small batches take the scalar C crc64 (one call per key
    beats the batch call's array setup), larger flushes take ONE
    `crc64_rows` pass over the padded key matrix.
    """
    n = len(keys)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    from pegasus_tpu.base.crc import crc64, crc64_rows

    if n < 16:
        return np.fromiter((crc64(k) for k in keys), dtype=np.uint64,
                           count=n)
    width = max(1, max(len(k) for k in keys))
    mat, lens = pad_probe_keys(keys, width)
    return crc64_rows(mat, lens)


def bloom_probe_rows(bloom, hashes: np.ndarray) -> np.ndarray:
    """bool[B]: may each hashed probe key be present in `bloom`
    (storage.bloom.BloomFilter)? False is definitive — the caller skips
    that run/table without decoding a block. One vectorized pass
    answers the whole flush; a filterless table answers all-True.

    This is the batch-evaluation form the coalesced read flush feeds
    (LSM-OPD's direct-on-format idea: membership for N keys is k
    vectorized gathers over the bit array, not N scalar walks).
    """
    if bloom is None:
        return np.ones(len(hashes), dtype=bool)
    return bloom.may_contain_hashes(hashes)


def host_key_hash_lo(hash_keys, sort_keys=None) -> np.ndarray:
    """uint32[B] low lane of pegasus_key_hash for a key batch, evaluated
    with ONE vectorized crc64 pass (base.crc.crc64_batch) instead of a
    per-key scalar crc loop — the batched probe-eval form of
    key_hash_parts used by the point-read coordinator's split-staleness
    gate. Empty hash keys hash by their sort key (pegasus_key_schema
    .h:150); placement note: this is compute-trivial per byte (the
    "probe" workload class in ops/placement.py), so it always runs on
    the host."""
    from pegasus_tpu.base.crc import crc64_batch

    regions = list(hash_keys)
    if sort_keys is not None:
        regions = [hk if hk else sk
                   for hk, sk in zip(hash_keys, sort_keys)]
    b = len(regions)
    if b == 0:
        return np.zeros(0, dtype=np.uint32)
    width = max(1, max(len(r) for r in regions))
    mat, lens = pad_probe_keys(regions, width)
    return (crc64_batch(mat, lens, start=0)
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


@functools.partial(jax.jit, static_argnames=("hash_filter_type",
                                             "sort_filter_type",
                                             "validate_hash",
                                             "use_hash_lo"))
def _multi_static_block_predicate(keys, key_len, hashkey_len, valid,
                                  hash_patterns, hash_plens,
                                  sort_patterns, sort_plens,
                                  pidx, partition_version,
                                  hash_filter_type: int,
                                  sort_filter_type: int,
                                  validate_hash: bool, hash_lo=None,
                                  use_hash_lo: bool = False) -> jax.Array:
    """K filter flavors × one stacked block in ONE program, bit-packed.

    The tunnel-accelerator design point (SURVEY §2.6 dispatch model,
    measured here: ~70 ms fixed cost per dispatched program and
    ~25 MB/s device->host): batching the FLAVOR axis multiplies
    compute-per-byte K-fold over the already-resident key matrix, and
    `packbits` shrinks the returned masks 8x. hash validation is
    flavor-independent, so it is evaluated once and broadcast.

    hash_patterns/sort_patterns: uint8[K, P]; *_plens: int32[K].
    Returns uint8[K, B//8] packed masks (B is a multiple of 8 — block
    capacities are power-of-two bucketed).
    """
    if validate_hash:
        if use_hash_lo:
            lo = hash_lo
        else:
            _, lo = key_hash_device(keys, key_len, hashkey_len)
        pv = jnp.asarray(partition_version, jnp.uint32)
        hash_ok = (lo & pv) == jnp.asarray(pidx, jnp.uint32)
    else:
        hash_ok = jnp.ones_like(valid)
    base = valid & hash_ok
    sort_start = 2 + hashkey_len
    sort_len = key_len - sort_start
    hk_start = jnp.full_like(key_len, 2)

    def one_flavor(hp, hl, sp, sl):
        hk_ok = match_filter(keys, hk_start, hashkey_len, hp, hl,
                             hash_filter_type)
        sk_ok = match_filter(keys, sort_start, sort_len, sp, sl,
                             sort_filter_type)
        return base & hk_ok & sk_ok

    ok = jax.vmap(one_flavor)(hash_patterns, hash_plens,
                              sort_patterns, sort_plens)     # [K, B]
    return jnp.packbits(ok, axis=1)


def multi_static_block_predicate_submit(block: RecordBlock, filters,
                                        validate_hash: bool, pidx,
                                        partition_version: int):
    """Dispatch K same-type filter flavors over one (stacked) block
    WITHOUT waiting; returns the device uint8[K, B//8] packed-mask
    array (callers overlap many submissions, then unpack with
    `unpack_masks`).

    `filters`: [(hash_FilterSpec, sort_FilterSpec)] — every entry must
    share (hash_filter_type, sort_filter_type) and pattern pad widths
    (callers group by exactly that). The split-safety reject-all gate
    matches static_block_predicate.
    """
    pidx_is_array = not isinstance(pidx, int)
    cap = block.capacity
    if (validate_hash and not pidx_is_array
            and (partition_version < 0 or pidx > partition_version)):
        return jnp.zeros((len(filters), cap // 8), dtype=jnp.uint8)
    hf0, sf0 = filters[0]
    hash_patterns = jnp.stack([hf.pattern for hf, _sf in filters])
    hash_plens = jnp.stack([hf.pattern_len for hf, _sf in filters])
    sort_patterns = jnp.stack([sf.pattern for _hf, sf in filters])
    sort_plens = jnp.stack([sf.pattern_len for _hf, sf in filters])
    use_hash_lo = validate_hash and block.hash_lo is not None
    return _multi_static_block_predicate(
        jnp.asarray(block.keys), jnp.asarray(block.key_len),
        jnp.asarray(block.hashkey_len), jnp.asarray(block.valid),
        hash_patterns, hash_plens, sort_patterns, sort_plens,
        jnp.asarray(pidx, jnp.uint32)
        if not pidx_is_array else jnp.asarray(pidx),
        jnp.asarray(partition_version & 0xFFFFFFFF, jnp.uint32),
        hf0.filter_type, sf0.filter_type, validate_hash,
        hash_lo=(jnp.asarray(block.hash_lo) if use_hash_lo
                 else jnp.zeros((1,), jnp.uint32)),
        use_hash_lo=use_hash_lo)


def unpack_masks(packed, count: int) -> np.ndarray:
    """uint8[..., B//8] packed device/host masks -> bool[..., count]."""
    arr = np.asarray(packed)
    return np.unpackbits(arr, axis=-1, count=count).astype(bool)


def multi_static_block_predicate(block: RecordBlock, filters,
                                 validate_hash: bool, pidx,
                                 partition_version: int) -> np.ndarray:
    """Synchronous form of multi_static_block_predicate_submit:
    bool[K, B] host masks."""
    packed = multi_static_block_predicate_submit(
        block, filters, validate_hash, pidx, partition_version)
    return unpack_masks(packed, block.capacity)


def scan_block_predicate(block: RecordBlock, now,
                         hash_filter: Optional[FilterSpec] = None,
                         sort_filter: Optional[FilterSpec] = None,
                         validate_hash: bool = False,
                         pidx=0,
                         partition_version: int = -1) -> ScanMasks:
    """Evaluate the full scan validation for a record block on device.

    Mirrors validate_key_value_for_scan for a whole block at once. When
    `validate_hash` and partition_version < 0 or pidx > partition_version,
    every non-expired record is hash-invalid (the reference checks expiry
    first, then rejects with kHashInvalid; pegasus_server_impl.cpp:2392-2401).
    """
    hash_filter = hash_filter or FilterSpec.none()
    sort_filter = sort_filter or FilterSpec.none()
    # `pidx` may be a PER-RECORD array: stacked cross-partition batches
    # (SURVEY §2.6 — partitions as the batch dimension of one dispatch)
    # pass each record its owning partition index; scalar callers keep
    # the reject-all split-safety gate below
    pidx_is_array = not isinstance(pidx, int)
    if (validate_hash and not pidx_is_array
            and (partition_version < 0 or pidx > partition_version)):
        valid = jnp.asarray(block.valid)
        expired = ttl_expired(jnp.asarray(block.expire_ts),
                              jnp.asarray(now, jnp.uint32)) & valid
        zeros = jnp.zeros((block.capacity,), dtype=bool)
        return ScanMasks(zeros, expired, valid & ~expired, zeros)
    use_hash_lo = validate_hash and block.hash_lo is not None
    return _scan_block_predicate(
        jnp.asarray(block.keys), jnp.asarray(block.key_len),
        jnp.asarray(block.hashkey_len), jnp.asarray(block.expire_ts),
        jnp.asarray(block.valid), jnp.asarray(now, jnp.uint32),
        hash_filter.pattern, hash_filter.pattern_len,
        sort_filter.pattern, sort_filter.pattern_len,
        jnp.asarray(pidx, jnp.uint32)
        if not pidx_is_array else jnp.asarray(pidx),
        jnp.asarray(partition_version & 0xFFFFFFFF, jnp.uint32),
        hash_filter.filter_type, sort_filter.filter_type, validate_hash,
        hash_lo=(jnp.asarray(block.hash_lo) if use_hash_lo
                 else jnp.zeros((1,), jnp.uint32)),
        use_hash_lo=use_hash_lo)
