"""User-specified compaction: declarative retention rules on device.

Parity: src/server/compaction_filter_rule.{h,cpp} +
compaction_operation.{h,cpp} (design doc
rfcs/2021-05-27-user-specified-compaction.md):

- rules: hashkey_pattern / sortkey_pattern (SMT match anywhere/prefix/
  postfix) and ttl_range (matches records whose expire_ts lies in
  [now+start_ttl, now+stop_ttl]; start==stop==0 matches no-TTL records,
  compaction_filter_rule.cpp:75-90).
- operations AND their rules (compaction_operation.h:77):
  delete_key drops matching records; update_ttl rewrites expire_ts with
  op types FROM_NOW (now+value), FROM_CURRENT (current expire_ts+value,
  no-op on no-TTL records), TIMESTAMP (expire at unix ts `value`)
  (compaction_operation.cpp:77-103).
- evaluation order: operations run in sequence; the first matching
  delete wins; updates apply where matched and not deleted.

The reference evaluates these per record in scalar C++ inside RocksDB's
compaction callback; here one jitted program evaluates an entire columnar
batch per ruleset. Rulesets are parsed from the same kind of JSON the
reference stores in the `user_specified_compaction` table env.
"""

from __future__ import annotations

import json
from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pegasus_tpu.base.value_schema import PEGASUS_EPOCH_BEGIN
from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    FilterSpec,
    match_filter,
)
from pegasus_tpu.ops.record_block import build_record_block

_MATCH_TYPES = {
    "anywhere": FT_MATCH_ANYWHERE,
    "prefix": FT_MATCH_PREFIX,
    "postfix": FT_MATCH_POSTFIX,
    # reference enum spellings (SMT_MATCH_*) accepted too
    "SMT_MATCH_ANYWHERE": FT_MATCH_ANYWHERE,
    "SMT_MATCH_PREFIX": FT_MATCH_PREFIX,
    "SMT_MATCH_POSTFIX": FT_MATCH_POSTFIX,
}

UTOT_FROM_NOW = "from_now"
UTOT_FROM_CURRENT = "from_current"
UTOT_TIMESTAMP = "timestamp"
_UTOT_ALIASES = {
    "from_now": UTOT_FROM_NOW, "UTOT_FROM_NOW": UTOT_FROM_NOW,
    "from_current": UTOT_FROM_CURRENT, "UTOT_FROM_CURRENT": UTOT_FROM_CURRENT,
    "timestamp": UTOT_TIMESTAMP, "UTOT_TIMESTAMP": UTOT_TIMESTAMP,
}


class Rule:
    """One predicate; device-evaluated over a whole block."""

    def __init__(self, spec: dict) -> None:
        self.kind = spec["type"]
        if self.kind in ("hashkey_pattern", "FRT_HASHKEY_PATTERN",
                         "sortkey_pattern", "FRT_SORTKEY_PATTERN"):
            self.kind = ("hashkey_pattern" if "hash" in self.kind.lower()
                         else "sortkey_pattern")
            pattern = spec["pattern"]
            if isinstance(pattern, str):
                pattern = pattern.encode()
            self.filter = FilterSpec.make(_MATCH_TYPES[spec["match"]],
                                          pattern)
        elif self.kind in ("ttl_range", "FRT_TTL_RANGE"):
            self.kind = "ttl_range"
            self.start_ttl = int(spec["start_ttl"])
            self.stop_ttl = int(spec["stop_ttl"])
        else:
            raise ValueError(f"unknown rule type {spec['type']!r}")

    def evaluate(self, keys, key_len, hashkey_len, expire_ts, now):
        if self.kind in ("hashkey_pattern", "sortkey_pattern"):
            # an empty pattern matches NOTHING here — the reference's
            # string_pattern_match returns false for empty patterns
            # (compaction_filter_rule.cpp:35), the OPPOSITE of the scan
            # path's validate_filter; without this, an empty-pattern
            # delete_key rule would wipe the table
            if int(self.filter.pattern_len) == 0:
                return jnp.zeros(keys.shape[0], dtype=bool)
        if self.kind == "hashkey_pattern":
            return match_filter(keys, jnp.full_like(key_len, 2), hashkey_len,
                                self.filter.pattern, self.filter.pattern_len,
                                self.filter.filter_type)
        if self.kind == "sortkey_pattern":
            start = 2 + hashkey_len
            return match_filter(keys, start, key_len - start,
                                self.filter.pattern, self.filter.pattern_len,
                                self.filter.filter_type)
        # ttl_range (compaction_filter_rule.cpp:75-90)
        no_ttl_match = ((expire_ts == 0)
                        & (self.start_ttl == 0) & (self.stop_ttl == 0))
        in_range = ((expire_ts >= now + jnp.uint32(self.start_ttl))
                    & (expire_ts <= now + jnp.uint32(self.stop_ttl)))
        return no_ttl_match | (in_range & (expire_ts != 0))


class Operation:
    def __init__(self, spec: dict) -> None:
        op = spec["op"] if "op" in spec else spec["type"]
        if op in ("delete_key", "COT_DELETE"):
            self.op = "delete_key"
        elif op in ("update_ttl", "COT_UPDATE_TTL"):
            self.op = "update_ttl"
            self.utot = _UTOT_ALIASES[spec["update_ttl_type"]]
            self.value = int(spec["value"])
        else:
            raise ValueError(f"unknown compaction op {op!r}")
        self.rules = [Rule(r) for r in spec["rules"]]
        if not self.rules:
            raise ValueError("compaction operation requires >= 1 rule")


def parse_rules(spec) -> List[Operation]:
    """Accepts a JSON string or a parsed list of operation dicts."""
    if isinstance(spec, (str, bytes)):
        spec = json.loads(spec)
    return [Operation(s) for s in spec]


def apply_rules_ops(operations, keys, key_len, hashkey_len, expire_ts,
                    valid, now):
    """Apply a parsed ruleset inside a jit: (drop, new_ets).

    Every operation evaluates against the ORIGINAL (pre-rules)
    expire_ts — the reference fixes existing_value before its op loop
    (key_ttl_compaction_filter.h:94-108); only the output ets
    accumulates updates. Shared by the per-batch wrapper below and the
    fused bulk-compaction program (ops/compaction.py)."""
    drop = jnp.zeros_like(valid)
    ets = expire_ts
    for op in operations:  # static unroll: ruleset structure is fixed
        matched = valid & ~drop
        for rule in op.rules:
            matched = matched & rule.evaluate(keys, key_len, hashkey_len,
                                              expire_ts, now)
        if op.op == "delete_key":
            drop = drop | matched
        else:
            if op.utot == UTOT_FROM_NOW:
                new_ts = now + jnp.uint32(op.value)
            elif op.utot == UTOT_FROM_CURRENT:
                # no-op for records without a TTL, judged on the
                # original value (compaction_operation.cpp:93-96)
                matched = matched & (expire_ts != 0)
                new_ts = expire_ts + jnp.uint32(op.value)
            else:  # UTOT_TIMESTAMP: expire at unix ts `value`
                new_ts = jnp.uint32(max(0, op.value - PEGASUS_EPOCH_BEGIN))
            ets = jnp.where(matched, new_ts, ets)
    return drop, ets


def compile_rules(spec) -> Callable:
    """Returns `rules_filter(keys, expire_ts, now) -> (drop, new_ets)`
    matching StorageEngine.manual_compact's hook signature; the predicate
    pipeline for the whole ruleset is one jitted device program. The
    parsed ruleset is exposed as `rules_filter.operations` so the bulk
    block-level compactor can fuse it into its own program."""
    operations = parse_rules(spec)

    @jax.jit
    def _eval(keys, key_len, hashkey_len, expire_ts, valid, now):
        return apply_rules_ops(operations, keys, key_len, hashkey_len,
                               expire_ts, valid, now)

    def rules_filter(keys: Sequence[bytes], expire_ts, now: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
        n = len(keys)
        # power-of-two capacity bucket: every distinct batch size would
        # otherwise compile its own XLA program — 64 partitions with 64
        # different record counts meant 64 compiles (observed 35x slower
        # than the TTL-only path on identical data)
        cap = 1024
        while cap < n:
            cap <<= 1
        block = build_record_block(list(keys), list(np.asarray(expire_ts)),
                                   capacity=cap)
        drop, ets = _eval(jnp.asarray(block.keys), jnp.asarray(block.key_len),
                          jnp.asarray(block.hashkey_len),
                          jnp.asarray(block.expire_ts),
                          jnp.asarray(block.valid), jnp.uint32(now))
        return np.asarray(drop)[:n], np.asarray(ets)[:n]

    rules_filter.operations = tuple(operations)
    return rules_filter
