"""Fused Pallas TPU kernel for the scan predicate hot path.

One VMEM-resident program fuses everything the scan loop needs per record
block: TTL expiry, partition-ownership check (against the precomputed
crc64 lo column — no byte loop on device), and sortkey filter matching —
the fully-fused form of ops.predicates._scan_block_predicate for the
no-hash-filter fast path the YCSB-E workload takes.

Layout: keys are TRANSPOSED to uint8[K + P, B] so the record dimension
(B = block capacity, a multiple of 128) rides the TPU lane dimension and
the byte-position dimension rides sublanes — pattern matching becomes P
shifted row-compares on the VPU, with zero gathers. Per-record scalar
columns travel as [1, B] rows. The dynamic per-record sortkey offset is
resolved with iota masks (position == offset) instead of gathers, which
TPUs hate.

Falls back to interpret mode off-TPU (tests run it on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    FT_NO_FILTER,
    FilterSpec,
)
from pegasus_tpu.ops.record_block import RecordBlock

_PATTERN_WIDTH = 32  # pattern buffer rows appended below the key rows


def _kernel(pattern_ref, scalar_ref, keys_ref, klen_ref, hklen_ref,
            ets_ref, valid_ref, hashlo_ref, keep_ref, expired_ref, *,
            key_rows: int, sort_filter_type: int, validate_hash: bool):
    now = scalar_ref[0]
    plen = scalar_ref[1]
    pidx = scalar_ref[2]
    pv = scalar_ref[3]

    valid = valid_ref[...] != 0                       # [1, B]
    ets = ets_ref[...]
    expired = (ets > 0) & (ets <= now.astype(jnp.uint32)) & valid

    if validate_hash:
        hash_ok = ((hashlo_ref[...] & pv.astype(jnp.uint32))
                   == pidx.astype(jnp.uint32))
    else:
        hash_ok = jnp.ones_like(valid)

    if sort_filter_type == FT_NO_FILTER:
        sk_ok = jnp.ones_like(valid)
    else:
        b = valid.shape[1]
        # window_ok[t, b] = pattern matches starting at byte t of record b
        window_ok = jnp.ones((key_rows, b), dtype=jnp.bool_)
        for j in range(_PATTERN_WIDTH):  # static unroll on the VPU
            pat_j = pattern_ref[j]
            cmp = (keys_ref[j:j + key_rows, :].astype(jnp.int32)
                   == pat_j) | (j >= plen)
            window_ok = window_ok & cmp
        iota_t = jax.lax.broadcasted_iota(jnp.int32, (key_rows, b), 0)
        sort_start = 2 + hklen_ref[...]               # [1, B]
        sort_len = klen_ref[...] - sort_start
        if sort_filter_type == FT_MATCH_PREFIX:
            t_sel = iota_t == sort_start
        elif sort_filter_type == FT_MATCH_POSTFIX:
            t_sel = iota_t == sort_start + sort_len - plen
        else:  # FT_MATCH_ANYWHERE
            t_sel = ((iota_t >= sort_start)
                     & (iota_t <= sort_start + sort_len - plen))
        matched = jnp.any(window_ok & t_sel, axis=0, keepdims=True)
        fits = sort_len >= plen
        sk_ok = (matched & fits) | (plen == 0)

    keep = valid & ~expired & hash_ok & sk_ok
    keep_ref[...] = keep.astype(jnp.int32)
    expired_ref[...] = expired.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("key_rows", "sort_filter_type",
                                             "validate_hash", "interpret"))
def _fused_call(pattern, scalars, keys_t, klen, hklen, ets, valid, hashlo,
                key_rows: int, sort_filter_type: int, validate_hash: bool,
                interpret: bool):
    b = keys_t.shape[1]
    kernel = functools.partial(_kernel, key_rows=key_rows,
                               sort_filter_type=sort_filter_type,
                               validate_hash=validate_hash)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((1, b), jnp.int32),
                   jax.ShapeDtypeStruct((1, b), jnp.int32)),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # pattern int32[P]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # scalars int32[4]
            pl.BlockSpec(memory_space=pltpu.VMEM),   # keys_t uint8[K+P, B]
            pl.BlockSpec(memory_space=pltpu.VMEM),   # key_len int32[1, B]
            pl.BlockSpec(memory_space=pltpu.VMEM),   # hashkey_len int32[1, B]
            pl.BlockSpec(memory_space=pltpu.VMEM),   # expire_ts uint32[1, B]
            pl.BlockSpec(memory_space=pltpu.VMEM),   # valid int32[1, B]
            pl.BlockSpec(memory_space=pltpu.VMEM),   # hash_lo uint32[1, B]
        ],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(pattern, scalars, keys_t, klen, hklen, ets, valid, hashlo)


def prepare_transposed(block: RecordBlock) -> Tuple[jax.Array, ...]:
    """Host-side one-time prep: transpose keys to [K+P, B] and lift scalar
    columns to [1, B] rows (cacheable alongside the device block cache)."""
    keys = np.asarray(block.keys)
    b, k = keys.shape
    keys_t = np.zeros((k + _PATTERN_WIDTH, b), dtype=np.uint8)
    keys_t[:k, :] = keys.T
    hash_lo = (np.zeros(b, dtype=np.uint32) if block.hash_lo is None
               else np.asarray(block.hash_lo))
    return (jnp.asarray(keys_t),
            jnp.asarray(np.asarray(block.key_len,
                                   dtype=np.int32).reshape(1, b)),
            jnp.asarray(np.asarray(block.hashkey_len,
                                   dtype=np.int32).reshape(1, b)),
            jnp.asarray(np.asarray(block.expire_ts,
                                   dtype=np.uint32).reshape(1, b)),
            jnp.asarray(np.asarray(block.valid,
                                   dtype=np.int32).reshape(1, b)),
            jnp.asarray(hash_lo.reshape(1, b)))


def fused_scan_block(block: RecordBlock, now: int,
                     sort_filter: Optional[FilterSpec] = None,
                     pidx: int = 0, partition_version: int = -1,
                     validate_hash: bool = False,
                     interpret: Optional[bool] = None,
                     prepared: Optional[Tuple] = None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (keep, expired) bool arrays for the block.

    Requires block.hash_lo when validate_hash (the fused path exists
    because the hash column is precomputed). `prepared` short-circuits
    the transpose for cached blocks.
    """
    sort_filter = sort_filter or FilterSpec.none()
    if validate_hash and block.hash_lo is None:
        raise ValueError("fused kernel needs a precomputed hash_lo column")
    if validate_hash and (partition_version < 0 or pidx > partition_version):
        # invalid ownership state: keep nothing, report expiry only — the
        # same reject-all gate as scan_block_predicate (split safety)
        valid = np.asarray(block.valid)
        ets = np.asarray(block.expire_ts)
        expired = (ets > 0) & (ets <= np.uint32(now)) & valid
        return np.zeros_like(valid), expired
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if prepared is None:
        prepared = prepare_transposed(block)
    keys_t, klen, hklen, ets, valid, hashlo = prepared
    pattern = np.zeros(_PATTERN_WIDTH, dtype=np.int32)
    pat_np = np.asarray(sort_filter.pattern)[:_PATTERN_WIDTH]
    pattern[:pat_np.shape[0]] = pat_np
    plen = int(sort_filter.pattern_len)
    if plen > _PATTERN_WIDTH:
        raise ValueError(f"pattern longer than {_PATTERN_WIDTH} bytes")
    scalars = np.asarray([now, plen, pidx,
                          max(partition_version, 0) & 0xFFFFFFFF],
                         dtype=np.int32)
    key_rows = keys_t.shape[0] - _PATTERN_WIDTH
    keep, expired = _fused_call(
        jnp.asarray(pattern), jnp.asarray(scalars), keys_t, klen, hklen,
        ets, valid, hashlo, key_rows=key_rows,
        sort_filter_type=sort_filter.filter_type,
        validate_hash=validate_hash, interpret=bool(interpret))
    return (np.asarray(keep[0]).astype(bool),
            np.asarray(expired[0]).astype(bool))
