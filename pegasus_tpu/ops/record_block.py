"""Columnar record blocks — the unit of device dispatch.

The reference iterates records one at a time through RocksDB and evaluates
predicates in scalar C++ (src/server/pegasus_server_impl.cpp:643 hot loop).
We instead batch records into fixed-shape columnar blocks:

    keys        uint8[capacity, key_width]   encoded keys, zero-padded
    key_len     int32[capacity]
    hashkey_len int32[capacity]              decoded from the 2-byte header
    expire_ts   uint32[capacity]             decoded from the value header
    valid       bool[capacity]               padding mask

Key widths are bucketed to powers of two (min 32) so the number of distinct
XLA compilations stays small; `capacity` is chosen by the caller (storage
blocks use a fixed record count). Values stay host-side — the device only
needs key bytes and the expiry column for the predicate work, which is the
TPU-first version of the reference's key/value schema split
(src/base/pegasus_key_schema.h, pegasus_value_schema.h).
"""

from __future__ import annotations

import struct
from typing import NamedTuple, Sequence

import numpy as np

_MIN_WIDTH = 32
_MAX_WIDTH = 1 << 16


class RecordBlock(NamedTuple):
    """Host (numpy) or device (jax) columnar record block; NamedTuple makes
    it a pytree so it can flow through jit boundaries unchanged.

    `hash_lo` (crc64 lo lane of pegasus_key_hash) is optional: SST blocks
    carry it precomputed; ad-hoc blocks leave it None and the predicate
    kernel computes the hash on device when needed."""

    keys: np.ndarray        # uint8[B, K]
    key_len: np.ndarray     # int32[B]
    hashkey_len: np.ndarray  # int32[B]
    expire_ts: np.ndarray   # uint32[B]
    valid: np.ndarray       # bool[B]
    hash_lo: np.ndarray | None = None  # uint32[B] or None

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    @property
    def key_width(self) -> int:
        return self.keys.shape[1]

    def count(self) -> int:
        return int(np.asarray(self.valid).sum())


def next_bucket(n: int) -> int:
    """Smallest power-of-two width >= n (>= 32), bounding recompilations."""
    w = _MIN_WIDTH
    while w < n:
        w <<= 1
    if w > _MAX_WIDTH:
        raise ValueError(f"key width {n} exceeds maximum {_MAX_WIDTH}")
    return w


def build_record_block(
    keys: Sequence[bytes],
    expire_ts: Sequence[int],
    capacity: int | None = None,
    key_width: int | None = None,
) -> RecordBlock:
    """Pack encoded keys + decoded expire_ts into a padded columnar block.

    Uses the native C++ packer when available (one call packs the key
    matrix + length/hashkey-length/crc64 columns — the host hot loop of
    the non-columnar scan path); falls back to the Python loop otherwise.
    Blocks produced by the native packer carry hash_lo for free.
    """
    n = len(keys)
    if capacity is None:
        capacity = n
    if n > capacity:
        raise ValueError(f"{n} records exceed block capacity {capacity}")
    max_len = max((len(k) for k in keys), default=2)
    if key_width is None:
        key_width = next_bucket(max_len)
    elif max_len > key_width:
        raise ValueError(f"key of {max_len} bytes exceeds key_width {key_width}")

    ets = np.zeros(capacity, dtype=np.uint32)
    ets[:n] = np.asarray(list(expire_ts), dtype=np.uint32)

    if n > 0:
        from pegasus_tpu import native

        packed = native.pack_records(list(keys), key_width) \
            if native.available() else None
        if packed is not None:
            nk, nlen, nhkl, nhash, nvalid = packed
            if capacity == n:
                return RecordBlock(nk, nlen, nhkl, ets, nvalid, nhash)
            arr = np.zeros((capacity, key_width), dtype=np.uint8)
            arr[:n] = nk
            key_len = np.zeros(capacity, dtype=np.int32)
            key_len[:n] = nlen
            hashkey_len = np.zeros(capacity, dtype=np.int32)
            hashkey_len[:n] = nhkl
            hash_lo = np.zeros(capacity, dtype=np.uint32)
            hash_lo[:n] = nhash
            valid = np.zeros(capacity, dtype=bool)
            valid[:n] = nvalid
            return RecordBlock(arr, key_len, hashkey_len, ets, valid,
                               hash_lo)

    arr = np.zeros((capacity, key_width), dtype=np.uint8)
    key_len = np.zeros(capacity, dtype=np.int32)
    hashkey_len = np.zeros(capacity, dtype=np.int32)
    valid = np.zeros(capacity, dtype=bool)
    for i, k in enumerate(keys):
        arr[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
        key_len[i] = len(k)
        # malformed rows (short key / header longer than the body) are
        # marked invalid, matching the native packer's contract
        if len(k) >= 2:
            (hkl,) = struct.unpack_from(">H", k)
            if hkl <= len(k) - 2:
                hashkey_len[i] = hkl
                valid[i] = True
    return RecordBlock(arr, key_len, hashkey_len, ets, valid)


def block_from_columns(keys: np.ndarray, key_len: np.ndarray,
                       expire_ts: np.ndarray,
                       valid: np.ndarray | None = None,
                       hash_lo: np.ndarray | None = None) -> RecordBlock:
    """Build a block from already-columnar storage (SST blocks are stored in
    this layout — no per-record host work on the read path)."""
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    key_len = np.asarray(key_len, dtype=np.int32)
    hashkey_len = (keys[:, 0].astype(np.int32) << 8) | keys[:, 1].astype(np.int32)
    hashkey_len = np.where(key_len >= 2, hashkey_len, 0)
    if valid is None:
        valid = key_len >= 2
    return RecordBlock(keys, key_len, hashkey_len,
                       np.asarray(expire_ts, dtype=np.uint32),
                       np.asarray(valid, dtype=bool),
                       None if hash_lo is None
                       else np.asarray(hash_lo, dtype=np.uint32))
