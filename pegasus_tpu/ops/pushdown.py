"""Scan pushdown: value-region predicates + fused mask->aggregate folds.

The scan path evaluates key-side predicates (hashkey/sortkey filters,
partition hash, TTL) with cached vectorized masks, but every surviving
row still ships to the client — for filter-heavy or aggregate queries
most of those bytes are discarded there. This module is the server-side
half of the Taurus-style near-data pushdown (PAPERS.md): a
``PushdownSpec`` rides the scan request, a VALUE-region filter leg joins
the existing mask algebra, and the mask feeds a fused aggregate fold
(count / sum(value_as_u64) / top-k by sortkey / reservoir sample) so an
aggregate-mode scan returns ONE partial per partition instead of pages
of rows.

Kernel notes:

- The value-region filter is host-side by construction: value heaps are
  NOT device-resident (RecordBlock carries keys/expire_ts only), and the
  match is compute-trivial per byte — the "scan_pushdown" workload class
  in ops/placement.py routes it to the host like "ttl"/"probe".
- Value regions skip the stored value header (``hdr`` =
  value_schema.header_length), so they do NOT tile the heap contiguously
  and the native ``region_filter_fn`` (which assumes ``offs[i] ==`` end
  of region i-1) cannot be reused directly; ``region_filter_ranges``
  below is the vectorized numpy twin over arbitrary (start, end) pairs —
  one AND-of-shifted-compares pass over the heap, then per-region
  prefix-sum / endpoint gathers. ``hdr == 0`` still takes the native
  kernel.
- Aggregates fold off raw columns without row materialization where
  possible: count/sum never build a row; top-k materializes at most k
  rows per block (blocks are key-sorted, so a block's top-k is its last
  k survivors); sample materializes at most k candidate rows per block
  (bottom-k by deterministic per-ordinal priority — a mergeable
  reservoir: uniform because the priorities behave randomly, and two
  partials merge by keeping the k smallest priorities).

Sum semantics: ``value_as_u64`` is the little-endian u64 of the first
min(8, len) USER bytes of the value, zero-padded; sums are modulo 2^64.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    FT_NO_FILTER,
    _region_filter_host,
    host_match_filter,
)

_MASK64 = (1 << 64) - 1

# aggregate kinds ("" = filter-mode: rows come back, just fewer)
AGG_KINDS = ("", "count", "sum", "top_k", "sample")

_KNOWN_FILTER_TYPES = (FT_NO_FILTER, FT_MATCH_ANYWHERE, FT_MATCH_PREFIX,
                       FT_MATCH_POSTFIX)


@dataclasses.dataclass(frozen=True)
class PushdownSpec:
    """What the server should evaluate INSIDE the scan-page path.

    ``value_filter_*`` reuses the FilterSpec match types
    (ops/predicates.FT_*) against the USER bytes of each value; sortkey
    predicates already exist on the request itself
    (sort_key_filter_type/pattern) and compose with this. ``aggregate``
    turns the scan into one-partial-per-partition mode; ``k`` sizes
    top_k/sample; ``seed`` makes sample deterministic.
    """

    value_filter_type: int = FT_NO_FILTER
    value_filter_pattern: bytes = b""
    aggregate: str = ""
    k: int = 0
    seed: int = 0

    @property
    def value_filter(self) -> Optional[Tuple[int, bytes]]:
        """(type, pattern) normal form, or None when match-all (same
        collapse rule as _normalize_filter_key: empty pattern and
        FT_NO_FILTER both match everything)."""
        vft, vfp = self.value_filter_type, self.value_filter_pattern
        if vft == FT_NO_FILTER or not vfp:
            return None
        return (int(vft), bytes(vfp))

    @property
    def key(self) -> tuple:
        """Hashable normal-form identity (batch grouping / mask keys)."""
        vf = self.value_filter or (FT_NO_FILTER, b"")
        return vf + (self.aggregate, int(self.k), int(self.seed))

    def check(self) -> None:
        """Raise ValueError on a malformed spec (the stub maps that to
        ERR_INVALID_PARAMETERS, like any bad request field)."""
        if self.aggregate not in AGG_KINDS:
            raise ValueError(f"unknown pushdown aggregate "
                             f"{self.aggregate!r} (want one of "
                             f"{AGG_KINDS[1:]})")
        if self.aggregate in ("top_k", "sample") and self.k <= 0:
            raise ValueError(f"pushdown aggregate {self.aggregate!r} "
                             f"requires k > 0 (got {self.k})")
        if self.value_filter_type not in _KNOWN_FILTER_TYPES:
            raise ValueError(f"unknown value filter type "
                             f"{self.value_filter_type}")


# -- value-region filtering ------------------------------------------------

def _as_u8(heap) -> np.ndarray:
    arr = (np.frombuffer(heap, dtype=np.uint8)
           if isinstance(heap, (bytes, bytearray, memoryview))
           else np.asarray(heap))
    if arr.dtype != np.uint8:
        arr = arr.view(np.uint8)
    return arr


def region_filter_ranges(heap, starts: np.ndarray, ends: np.ndarray,
                         filter_type: int, pattern: bytes) -> np.ndarray:
    """bool[n] pattern match over byte ranges ``heap[starts[i]:ends[i]]``.

    The ragged-region twin of predicates._region_filter_host for regions
    that do NOT tile the heap contiguously (value regions skip the
    stored header). One vectorized AND-of-shifted-compares pass marks
    every heap position where the pattern starts (the numpy analogue of
    match_filter's ANYWHERE accumulation), then each region answers from
    endpoint gathers (PREFIX/POSTFIX) or a hit-count prefix sum
    (ANYWHERE). Device-kernel semantics: empty pattern matches
    everything; a region shorter than the pattern never matches.
    """
    starts = np.asarray(starts, dtype=np.int64)
    ends = np.asarray(ends, dtype=np.int64)
    n = len(starts)
    if filter_type == FT_NO_FILTER or not pattern:
        return np.ones(n, dtype=bool)
    p = len(pattern)
    lens = ends - starts
    fits = lens >= p
    hv = np.ascontiguousarray(_as_u8(heap))
    length = hv.size
    if length < p or not n:
        return np.zeros(n, dtype=bool)
    pat = np.frombuffer(bytes(pattern), dtype=np.uint8)
    hit = np.ones(length - p + 1, dtype=bool)
    for j in range(p):
        hit &= hv[j:length - p + 1 + j] == pat[j]
    top = length - p  # last valid window start
    if filter_type == FT_MATCH_PREFIX:
        pos = np.clip(starts, 0, top)
        return fits & (starts <= top) & hit[pos]
    if filter_type == FT_MATCH_POSTFIX:
        tail = ends - p
        pos = np.clip(tail, 0, top)
        return fits & (tail >= 0) & (tail <= top) & hit[pos]
    if filter_type == FT_MATCH_ANYWHERE:
        csum = np.concatenate(([0], np.cumsum(hit, dtype=np.int64)))
        lo = np.clip(starts, 0, top + 1)
        hi = np.maximum(np.clip(ends - p + 1, 0, top + 1), lo)
        return fits & ((csum[hi] - csum[lo]) > 0)
    raise ValueError(f"unknown filter type {filter_type}")


def value_filter_mask(heap, value_offs, hdr: int, filter_type: int,
                      pattern: bytes) -> np.ndarray:
    """bool[n] value-region keep mask for one columnar block.

    User region of row i is ``heap[value_offs[i]+hdr : value_offs[i+1]]``
    (``hdr`` = the stored expire/timetag header the scan strips before
    returning values). Like the static key masks, this is
    ``now``-independent and pure over the immutable block, so callers
    cache it per (block, filter).
    """
    offs = np.asarray(value_offs, dtype=np.int64)
    n = len(offs) - 1
    if filter_type == FT_NO_FILTER or not pattern:
        return np.ones(n, dtype=bool)
    hv = _as_u8(heap)
    if hdr == 0:
        # regions tile the heap contiguously: the native kernel applies
        return _region_filter_host(hv, offs, filter_type, pattern)
    starts = np.minimum(offs[:-1] + hdr, offs[1:])
    return region_filter_ranges(hv, starts, offs[1:], filter_type,
                                pattern)


# -- value_as_u64 ----------------------------------------------------------

def value_as_u64(user_data: bytes) -> int:
    """Scalar twin of values_as_u64 (overlay rows, client fallback)."""
    return int.from_bytes(bytes(user_data[:8]), "little")


def values_as_u64(heap, value_offs, hdr: int, rows) -> np.ndarray:
    """uint64[len(rows)]: little-endian u64 of the first min(8, len)
    USER bytes of each selected value, zero-padded — one vectorized
    gather, no per-row bytes objects."""
    rows = np.asarray(rows, dtype=np.int64)
    if rows.size == 0:
        return np.zeros(0, dtype=np.uint64)
    offs = np.asarray(value_offs, dtype=np.int64)
    hv = _as_u8(heap)
    starts = np.minimum(offs[rows] + hdr, offs[rows + 1])
    lens = np.minimum(offs[rows + 1] - starts, 8)
    lane = np.arange(8, dtype=np.int64)
    idx = starts[:, None] + lane[None, :]
    valid = lane[None, :] < lens[:, None]
    idx = np.clip(idx, 0, max(0, hv.size - 1))
    data = hv[idx] if hv.size else np.zeros_like(idx, dtype=np.uint8)
    lanes = np.where(valid, data, 0).astype(np.uint64)
    shifts = np.uint64(8) * np.arange(8, dtype=np.uint64)
    return (lanes << shifts[None, :]).sum(axis=1, dtype=np.uint64)


# -- reservoir priorities --------------------------------------------------

def _splitmix64(x) -> np.ndarray:
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        z = x + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _sample_priorities(seed: int, first_ordinal: int, m: int) -> np.ndarray:
    """uint64[m] deterministic per-row reservoir priorities: the sample
    is the k survivors with the SMALLEST priorities, which makes
    partials mergeable (union, keep k smallest) and the whole sample a
    pure function of (seed, survivor order)."""
    base = np.uint64((seed * 0x9E3779B97F4A7C15 + 0x2545F4914F6CDD1D)
                     & _MASK64)
    with np.errstate(over="ignore"):
        ordinals = base + np.arange(first_ordinal, first_ordinal + m,
                                    dtype=np.uint64)
    return _splitmix64(ordinals)


# -- the partial-aggregate accumulator -------------------------------------

class AggState:
    """One partition's partial aggregate, folded incrementally as scan
    pages evaluate. The wire form (``to_wire``) is a plain dict so it
    rides the in-process RPC payloads without new codec surface;
    ``merge_partials``/``finalize`` combine per-partition partials
    client- or coordinator-side.

    items layout: top_k -> [(key, value)] ascending by key (the k
    largest survive, trimmed from the front); sample -> [(pri, key,
    value)] ascending by priority (k smallest survive)."""

    __slots__ = ("kind", "k", "seed", "count", "total", "items", "seen")

    def __init__(self, spec: PushdownSpec) -> None:
        self.kind = spec.aggregate
        self.k = int(spec.k)
        self.seed = int(spec.seed)
        self.count = 0   # matching rows folded
        self.total = 0   # sum(value_as_u64) mod 2^64
        self.items: List[tuple] = []
        self.seen = 0    # reservoir ordinal cursor

    # ---- columnar fold (the scan-page fast path) ----------------------

    def fold_columnar(self, rows, heap=None, value_offs=None,
                      hdr: int = 0, key_at=None) -> None:
        """Fold one block's surviving row indices (``rows`` ascending —
        block key order). count/sum touch no row; top_k/sample
        materialize at most k rows each."""
        m = int(len(rows))
        if m == 0:
            return
        self.count += m
        if self.kind == "sum":
            vals = values_as_u64(heap, value_offs, hdr, rows)
            self.total = (self.total
                          + int(vals.sum(dtype=np.uint64))) & _MASK64
        elif self.kind == "top_k":
            rows = np.asarray(rows, dtype=np.int64)
            offs = np.asarray(value_offs, dtype=np.int64)
            hv = _as_u8(heap)
            for i in rows[-self.k:]:
                i = int(i)
                lo = min(int(offs[i]) + hdr, int(offs[i + 1]))
                self.items.append((key_at(i),
                                   hv[lo:int(offs[i + 1])].tobytes()))
            self.items.sort(key=lambda kv: kv[0])
            del self.items[:-self.k]
        elif self.kind == "sample":
            pris = _sample_priorities(self.seed, self.seen, m)
            self.seen += m
            if m > self.k:
                cand = np.sort(np.argpartition(pris, self.k - 1)[:self.k])
            else:
                cand = np.arange(m)
            rows = np.asarray(rows, dtype=np.int64)
            offs = np.asarray(value_offs, dtype=np.int64)
            hv = _as_u8(heap)
            for j in cand:
                i = int(rows[int(j)])
                lo = min(int(offs[i]) + hdr, int(offs[i + 1]))
                self.items.append((int(pris[int(j)]), key_at(i),
                                   hv[lo:int(offs[i + 1])].tobytes()))
            self.items.sort(key=lambda t: (t[0], t[1]))
            del self.items[self.k:]

    # ---- scalar fold (overlay rows, iterator fallback, client-side) ---

    def fold_row(self, key: bytes, user_data: bytes) -> None:
        self.count += 1
        if self.kind == "sum":
            self.total = (self.total + value_as_u64(user_data)) & _MASK64
        elif self.kind == "top_k":
            bisect.insort(self.items, (key, user_data))
            if len(self.items) > self.k:
                del self.items[0]
        elif self.kind == "sample":
            pri = int(_sample_priorities(self.seed, self.seen, 1)[0])
            self.seen += 1
            if len(self.items) < self.k or pri < self.items[-1][0]:
                bisect.insort(self.items, (pri, key, user_data))
                del self.items[self.k:]

    def to_wire(self) -> Dict[str, Any]:
        return {"kind": self.kind, "k": self.k, "seed": self.seed,
                "count": self.count, "total": self.total,
                "items": list(self.items), "seen": self.seen}


def merge_partials(spec: PushdownSpec,
                   parts: Iterable[Optional[Dict[str, Any]]]
                   ) -> Dict[str, Any]:
    """Fold per-partition wire partials into one combined wire dict
    (counts/sums add; top_k keeps the k largest keys of the union;
    sample keeps the k smallest priorities of the union)."""
    st = AggState(spec)
    for part in parts:
        if not part:
            continue
        st.count += int(part.get("count", 0))
        st.total = (st.total + int(part.get("total", 0))) & _MASK64
        st.seen += int(part.get("seen", 0))
        st.items.extend(tuple(it) for it in part.get("items") or ())
    if spec.aggregate == "top_k":
        st.items.sort(key=lambda kv: kv[0])
        del st.items[:-spec.k]
    elif spec.aggregate == "sample":
        st.items.sort(key=lambda t: (t[0], t[1]))
        del st.items[spec.k:]
    return st.to_wire()


def finalize(spec: PushdownSpec, wire: Dict[str, Any]):
    """Merged wire partial -> the user-facing aggregate value."""
    if spec.aggregate == "count":
        return int(wire["count"])
    if spec.aggregate == "sum":
        return int(wire["total"])
    if spec.aggregate == "top_k":
        # "top" first: descending by key
        return [(k, v) for k, v in reversed(wire["items"])]
    if spec.aggregate == "sample":
        return [(key, v) for _pri, key, v in wire["items"]]
    raise ValueError(f"not an aggregate spec: {spec.aggregate!r}")


def aggregate_rows(spec: PushdownSpec,
                   rows: Iterable[Tuple[bytes, bytes]]):
    """Client-side fallback: evaluate the whole spec (value filter +
    aggregate) over materialized (key, user_value) rows — what a client
    does when the server ignored the pushdown spec (pre-pushdown
    server), and what the bench's client-side arm measures."""
    vf = spec.value_filter
    st = AggState(spec)
    for key, value in rows:
        if vf is not None and not host_match_filter(value, vf[0], vf[1]):
            continue
        st.fold_row(key, value)
    return finalize(spec, st.to_wire())
