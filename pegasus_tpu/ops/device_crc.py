"""crc64 on device, in two uint32 lanes.

Bit-identical to pegasus_tpu.base.crc (and therefore to the reference's
dsn::utils::crc64_calc, src/utils/crc.cpp:464). JAX disables uint64 by
default, so the 64-bit CRC state is carried as (hi, lo) uint32 lanes:

    crc' = table[(crc ^ byte) & 0xff] ^ (crc >> 8)

with crc >> 8 computed as lo' = (lo >> 8) | (hi << 24), hi' = hi >> 8, and
the 256-entry table split into hi/lo halves. The byte loop runs over the
padded key width, vectorized across the whole record block — the same
loop order as the numpy batch implementation.

Used for on-device partition-hash validation during scans
(reference: check_pegasus_key_hash, src/base/pegasus_key_schema.h:176 —
`crc64(hashkey) & partition_version == partition_index`). Since real
partition counts fit in 32 bits, the `&`-check needs only the lo lane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pegasus_tpu.base.crc import TABLE64_HI_NP, TABLE64_LO_NP


def crc64_device(data: jax.Array, lengths: jax.Array,
                 start: jax.Array | int = 0) -> tuple[jax.Array, jax.Array]:
    """crc64 over per-row byte regions of a padded block.

    data:    uint8[B, K]
    lengths: int32[B] — region byte count
    start:   int32[B] or scalar — region start offset
    Returns (hi, lo): uint32[B] lanes of the 64-bit CRC.
    """
    # materialized per call, NOT at module scope: importing the library
    # must never initialize a jax backend (an admin CLI on a TPU-tunnel
    # image would dial the chip just by importing). Under jit these
    # become compile-time constants; the rare un-jitted call pays a
    # 64KB transfer.
    table_hi = jnp.asarray(TABLE64_HI_NP)
    table_lo = jnp.asarray(TABLE64_LO_NP)
    b, k = data.shape
    data32 = data.astype(jnp.uint32)
    starts = jnp.broadcast_to(jnp.asarray(start, jnp.int32), (b,))
    hi0 = jnp.full((b,), 0xFFFFFFFF, jnp.uint32)  # ~init with init=0
    lo0 = jnp.full((b,), 0xFFFFFFFF, jnp.uint32)

    def body(j, carry):
        hi, lo = carry
        pos = jnp.clip(starts + j, 0, k - 1)
        byte = jnp.take_along_axis(data32, pos[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        idx = ((lo ^ byte) & jnp.uint32(0xFF)).astype(jnp.int32)
        nhi = (hi >> 8) ^ table_hi[idx]
        nlo = ((lo >> 8) | (hi << 24)) ^ table_lo[idx]
        active = j < lengths
        return jnp.where(active, nhi, hi), jnp.where(active, nlo, lo)

    hi, lo = jax.lax.fori_loop(0, k, body, (hi0, lo0))
    return ~hi, ~lo


def key_hash_device(keys: jax.Array, key_len: jax.Array,
                    hashkey_len: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-record pegasus_key_hash (src/base/pegasus_key_schema.h:150):
    crc64 of the hashkey region, falling back to the sortkey region when the
    hashkey is empty. Returns (hi, lo) uint32 lanes."""
    region_len = jnp.where(hashkey_len > 0, hashkey_len, key_len - 2)
    return crc64_device(keys, region_len, start=2)


def check_partition_hash_device(keys: jax.Array, key_len: jax.Array,
                                hashkey_len: jax.Array, pidx,
                                partition_version) -> jax.Array:
    """bool[B]: does this partition serve each record (post-split check)?
    partition_version < 0 or pidx > partition_version must be handled by the
    caller (reference treats those as invalid, pegasus_server_impl.cpp:2399)."""
    _, lo = key_hash_device(keys, key_len, hashkey_len)
    pv = jnp.asarray(partition_version, jnp.uint32)
    return (lo & pv) == jnp.asarray(pidx, jnp.uint32)
