"""pegasus_tpu — a TPU-native distributed key-value store framework.

A from-scratch rebuild of the capabilities of Apache Pegasus
(reference: /root/reference, apache/incubator-pegasus) designed TPU-first:

- Host control plane (Python/C++): partitioned tables, PacificA-style
  replication, meta service, clients — the distributed-systems layers.
- Device data plane (JAX/XLA/Pallas): the per-record predicate hot path
  (hashkey/sortkey filter matching, TTL-expiry evaluation, partition-hash
  validation, user-specified compaction rules) evaluated as vectorized
  kernels over columnar record blocks, instead of the reference's scalar
  per-record C++ loops (reference: src/server/pegasus_server_impl.cpp:2350,
  src/server/key_ttl_compaction_filter.h:55).

Subpackages:
  base     — key/value schemas, crc64 (reference: src/base/)
  utils    — errors, flags, metrics, fail points (reference: src/utils/)
  ops      — device record blocks + predicate kernels (the TPU data plane)
  storage  — LSM storage engine with columnar, device-friendly SST blocks
  server   — rrdb request handlers (reference: src/server/)
  client   — client API + partition resolver (reference: src/client/)
  parallel — device-mesh sharding of multi-partition batch work
"""

__version__ = "0.1.0"
