"""Wire codec for whole cluster messages.

Parity role: the reference's message_header + thrift-struct body
(src/rpc/rpc_message.h:81-126: lengths, crc32, rpc_name, gpid routing
fields; thrift payloads generated from idl/*.thrift). We use one compact
self-describing binary format instead of codegen: a tagged value grammar
plus a registry of message dataclasses (the IDL-equivalent single source
of truth is `server/types.py`).

Frame:
    [4s magic "PGT1"] [u32 body_len] [u32 crc32(body)]
    body := str(src) str(dst) str(msg_type) value(payload)

Value grammar (little-endian):
    N       none            T/F     bool
    i       i64             d       f64
    b       u32-len bytes   s       u32-len utf-8 str
    l/t     u32-count list/tuple of value
    m       u32-count dict of (value value)
    D       str(registry-name) u32-count fields (in dataclass field order)

Every registered dataclass is flat (primitives / lists / nested
registered dataclasses), so the grammar closes. Unknown tags or registry
names raise — a version-skewed peer fails loudly, not silently. The one
sanctioned evolution is appending defaulted fields: a decoder accepts a
SHORTER field list when every omitted trailing field has a default
(thrift optional-field semantics), so older clients — including the
compiled native one — keep working across additive changes.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Callable, Dict, Tuple

from pegasus_tpu.base.crc import crc32

MAGIC = b"PGT1"
_U32 = struct.Struct("<I")
_HDR = struct.Struct("<4sII")

# ---- dataclass registry ------------------------------------------------

_REGISTRY: Dict[str, type] = {}
_FIELDS: Dict[str, Tuple[str, ...]] = {}


def register_message_type(cls: type) -> type:
    name = cls.__name__
    _REGISTRY[name] = cls
    _FIELDS[name] = tuple(f.name for f in dataclasses.fields(cls))
    return cls


def _register_defaults() -> None:
    from pegasus_tpu.meta.server_state import PartitionConfig
    from pegasus_tpu.ops.pushdown import PushdownSpec
    from pegasus_tpu.server import types as t

    for cls in (t.KeyValue, t.MultiPutRequest, t.MultiRemoveRequest,
                t.MultiGetRequest, t.MultiGetResponse, t.FullKey,
                t.FullData, t.BatchGetRequest, t.BatchGetResponse,
                t.IncrRequest, t.IncrResponse, t.CheckAndSetRequest,
                t.CheckAndSetResponse, t.Mutate, t.CheckAndMutateRequest,
                t.CheckAndMutateResponse, t.GetScannerRequest,
                t.ScanRequest, t.ScanResponse, t.ScanPage, PushdownSpec,
                PartitionConfig):
        register_message_type(cls)


# ---- value codec -------------------------------------------------------


def _enc_value(out: list, v: Any) -> None:
    if v is None:
        out.append(b"N")
    elif v is True:
        out.append(b"T")
    elif v is False:
        out.append(b"F")
    elif isinstance(v, int):  # bool handled above (is-checks)
        if -(1 << 63) <= v < (1 << 63):
            out.append(b"i" + struct.pack("<q", v))
        elif 0 <= v < (1 << 64):
            # crc64 partition hashes live here
            out.append(b"u" + struct.pack("<Q", v))
        else:
            raw = v.to_bytes((v.bit_length() + 8) // 8, "little",
                             signed=True)
            out.append(b"I" + _U32.pack(len(raw)))
            out.append(raw)
    elif isinstance(v, float):
        out.append(b"d" + struct.pack("<d", v))
    elif isinstance(v, (bytes, bytearray)):
        out.append(b"b" + _U32.pack(len(v)))
        out.append(bytes(v))
    elif isinstance(v, str):
        raw = v.encode()
        out.append(b"s" + _U32.pack(len(raw)))
        out.append(raw)
    elif isinstance(v, list):
        out.append(b"l" + _U32.pack(len(v)))
        for item in v:
            _enc_value(out, item)
    elif isinstance(v, tuple):
        out.append(b"t" + _U32.pack(len(v)))
        for item in v:
            _enc_value(out, item)
    elif isinstance(v, dict):
        out.append(b"m" + _U32.pack(len(v)))
        for k, val in v.items():
            _enc_value(out, k)
            _enc_value(out, val)
    elif dataclasses.is_dataclass(v):
        name = type(v).__name__
        fields = _FIELDS.get(name)
        if fields is None:
            raise TypeError(f"unregistered message dataclass {name}")
        raw = name.encode()
        out.append(b"D" + _U32.pack(len(raw)))
        out.append(raw)
        out.append(_U32.pack(len(fields)))
        for f in fields:
            _enc_value(out, getattr(v, f))
    else:
        raise TypeError(f"unencodable value type {type(v).__name__}")


class _Dec:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = data
        self.pos = pos

    def _u32(self) -> int:
        (n,) = _U32.unpack_from(self.data, self.pos)
        self.pos += 4
        return n

    def _take(self, n: int) -> bytes:
        out = self.data[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("truncated message")
        self.pos += n
        return out

    def value(self) -> Any:
        tag = self.data[self.pos:self.pos + 1]
        self.pos += 1
        if tag == b"N":
            return None
        if tag == b"T":
            return True
        if tag == b"F":
            return False
        if tag == b"i":
            (v,) = struct.unpack_from("<q", self.data, self.pos)
            self.pos += 8
            return v
        if tag == b"u":
            (v,) = struct.unpack_from("<Q", self.data, self.pos)
            self.pos += 8
            return v
        if tag == b"I":
            return int.from_bytes(self._take(self._u32()), "little",
                                  signed=True)
        if tag == b"d":
            (v,) = struct.unpack_from("<d", self.data, self.pos)
            self.pos += 8
            return v
        if tag == b"b":
            return self._take(self._u32())
        if tag == b"s":
            return self._take(self._u32()).decode()
        if tag == b"l":
            return [self.value() for _ in range(self._u32())]
        if tag == b"t":
            return tuple(self.value() for _ in range(self._u32()))
        if tag == b"m":
            return {self.value(): self.value()
                    for _ in range(self._u32())}
        if tag == b"D":
            name = self._take(self._u32()).decode()
            cls = _REGISTRY.get(name)
            if cls is None:
                raise ValueError(f"unknown message dataclass {name!r}")
            nf = self._u32()
            fields = _FIELDS[name]
            if nf > len(fields):
                raise ValueError(
                    f"{name}: field count mismatch ({nf} != {len(fields)})")
            vals = [self.value() for _ in range(nf)]
            if nf < len(fields):
                # thrift-style added-field skew: a peer built before a
                # trailing field was added sends the shorter layout.
                # Tolerate iff every omitted field has a default (it
                # was ADDED with one); anything else fails loudly.
                for fobj in dataclasses.fields(cls)[nf:]:
                    if (fobj.default is dataclasses.MISSING and
                            fobj.default_factory is dataclasses.MISSING):
                        raise ValueError(
                            f"{name}: field count mismatch "
                            f"({nf} != {len(fields)})")
            return cls(**dict(zip(fields, vals)))
        raise ValueError(f"unknown value tag {tag!r} at {self.pos - 1}")


# ---- frame codec -------------------------------------------------------


def encode_message(src: str, dst: str, msg_type: str, payload: Any) -> bytes:
    if not _REGISTRY:
        _register_defaults()
    out: list = []
    _enc_value(out, src)
    _enc_value(out, dst)
    _enc_value(out, msg_type)
    _enc_value(out, payload)
    body = b"".join(out)
    return _HDR.pack(MAGIC, len(body), crc32(body)) + body


def decode_message(frame_body: bytes) -> Tuple[str, str, str, Any]:
    """Decodes a body (header already consumed/validated by the reader).
    Returns (src, dst, msg_type, payload)."""
    if not _REGISTRY:
        _register_defaults()
    d = _Dec(frame_body)
    src = d.value()
    dst = d.value()
    msg_type = d.value()
    payload = d.value()
    return src, dst, msg_type, payload


def read_frames(buf: bytearray) -> "list[bytes]":
    """Extract complete frame bodies from a receive buffer (in place)."""
    bodies = []
    while True:
        if len(buf) < _HDR.size:
            return bodies
        magic, blen, want = _HDR.unpack_from(buf, 0)
        if magic != MAGIC:
            raise ValueError(f"bad frame magic {magic!r}")
        if len(buf) < _HDR.size + blen:
            return bodies
        body = bytes(buf[_HDR.size:_HDR.size + blen])
        if crc32(body) != want:
            raise ValueError("frame crc mismatch")
        del buf[:_HDR.size + blen]
        bodies.append(body)
