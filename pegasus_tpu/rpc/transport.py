"""TCP transport: the real inter-node network layer.

Parity: the reference's network provider (src/rpc/asio_net_provider.*,
rpc_engine.h:146) — every node listens on one port, outbound connections
are cached per peer, replies to non-listening peers (clients) ride the
inbound connection they arrived on, and messages are framed binary
(rpc/message.py, the rpc_message.h analogue). Same interface as the
deterministic SimNetwork (`register`/`send`), so MetaService /
ReplicaStub / ClusterClient run unchanged over either.

Threading model (replaces rDSN's task engine for this path):
- one accept thread; one reader thread per inbound connection;
- ONE dispatcher thread delivers every inbound message serially under
  `self.lock` — preserving the single-threaded access the replica state
  machine asserts (the reference pins a replica's work to one thread by
  gpid thread-hash, task_engine.h:53);
- timer callbacks (beacons, group checks, config-sync) must take the
  same lock; `run_timer` does.

Loss semantics match SimNetwork: a send to an unreachable peer is
dropped (the 2PC/FD/learning protocols already tolerate loss and the
client retries) — no backpressure, no delivery guarantee beyond TCP's
per-connection FIFO.
"""

from __future__ import annotations

import logging
import queue
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from pegasus_tpu.rpc.message import decode_message, encode_message, read_frames
from pegasus_tpu.utils import tracing
from pegasus_tpu.utils.flags import FLAGS, define_flag

Addr = Tuple[str, int]

_LOG = logging.getLogger("pegasus.rpc")


class _RateLimitedLog:
    """Structured transport-failure logging with per-site rate limiting:
    a dead peer's reconnect loop must produce one countable line per
    interval, not a stdout traceback per queued frame."""

    def __init__(self, interval_s: float = 1.0) -> None:
        self._interval = interval_s
        self._last: Dict[str, float] = {}
        self._suppressed: Dict[str, int] = {}
        self._lock = threading.Lock()

    def log(self, site: str, exc: BaseException) -> None:
        with self._lock:
            now = time.monotonic()
            self._suppressed[site] = self._suppressed.get(site, 0) + 1
            if now - self._last.get(site, float("-inf")) < self._interval:
                return
            n = self._suppressed.pop(site, 1)
            self._last[site] = now
        _LOG.error("transport site=%s err=%s.%s msg=%r count=%d",
                   site, type(exc).__module__, type(exc).__name__,
                   str(exc), n,
                   exc_info=not isinstance(exc, OSError))


_RL_LOG = _RateLimitedLog()

import itertools as _itertools
_SESSION_IDS = _itertools.count(1)

define_flag("pegasus.rpc", "connect_timeout_ms", 2000,
            "outbound TCP dial timeout", mutable=True)
define_flag("pegasus.rpc", "reconnect_backoff_base_ms", 50,
            "first pause after a failed peer dial/write (doubles per "
            "consecutive failure)", mutable=True)
define_flag("pegasus.rpc", "reconnect_backoff_max_ms", 2000,
            "cap on the reconnect pause", mutable=True)
define_flag("pegasus.rpc", "read_shed_queue_depth", 2000,
            "inbox depth beyond which NEW client reads are shed with "
            "ERR_BUSY (writes/replication exempt)", mutable=True)
define_flag("pegasus.rpc", "read_shed_queue_age_ms", 5000,
            "queueing age beyond which a client read is shed with "
            "ERR_BUSY", mutable=True)

# client request types the dispatcher may fast-fail without consulting
# the handler: reply envelope (type, result field, empty value). Writes
# get deadline fast-fail only — shedding exempts them (and every
# replication/meta message) so a read storm cannot reject mutations.
_CLIENT_REQS: Dict[str, Tuple[str, str, Any]] = {
    "client_read": ("client_read_reply", "result", None),
    "client_read_batch": ("client_read_reply", "result", None),
    "client_scan_multi": ("client_read_reply", "result", None),
    "client_write": ("client_write_reply", "results", []),
    "client_write_batch": ("client_write_reply", "result", None),
}

# mutation-path requests: exempt from overload shedding (availability
# of writes degrades last) and from chaos duplication (no rid dedup —
# a duplicated atomic write would double-apply)
WRITE_REQS = ("client_write", "client_write_batch")


class TcpTransport:
    def __init__(self, listen: Optional[Addr],
                 address_book: Dict[str, Addr]) -> None:
        """`listen`: (host, port) to serve on, or None for a client-only
        transport. `address_book`: name -> (host, port) for every peer
        this node may dial (the static onebox topology; a dns_resolver
        analogue can replace it later). Peers NOT in the book (clients)
        are reachable once they have dialed us — replies use the learned
        inbound route."""
        self.address_book = dict(address_book)
        self.lock = threading.RLock()  # node-wide handler serialization
        self._handlers: Dict[str, Callable[[str, str, Any], None]] = {}
        # (dst, msg_type) -> handler([(src, payload)]): flush-window
        # coalescing — the dispatcher drains CONSECUTIVE queued messages
        # of the same type into one delivery (see _dispatch_loop). The
        # replica stub registers its point-read batch here so a burst of
        # independent client gets serves as one coordinator flush.
        self._batch_handlers: Dict[tuple, Callable] = {}
        self._current_session: str = ""
        self._session_closed_cbs: list = []
        # name -> (socket, write-lock); outbound dials and learned inbound
        # routes share this table (latest wins — a reconnecting peer's new
        # connection replaces the dead one)
        self._routes: Dict[str, Tuple[socket.socket, threading.Lock]] = {}
        self._routes_lock = threading.Lock()
        self._inbox: "queue.Queue[Optional[tuple]]" = queue.Queue()
        # outbound frames are written by PER-PEER sender threads: the
        # senders (dispatcher, timers) hold the node lock, and a blocking
        # dial/write there would stall every handler and timer; per-peer
        # queues additionally stop one blackholed peer from head-of-line
        # blocking beacons/prepares to healthy peers
        self._peer_outboxes: Dict[str, "queue.Queue[Optional[bytes]]"] = {}
        self._outboxes_lock = threading.Lock()
        self._closing = False
        # weighted-fair admission (dispatch thread ONLY — no locking):
        # shed-eligible client requests are re-queued per tenant and
        # drained by deficit-weighted round-robin, so one hot tenant's
        # backlog cannot head-of-line block everyone else's reads.
        # Writes/replication/meta take the strict-priority system queue
        # (the mutation path degrades last, exactly the old shed
        # exemption — and system traffic was never fair-queue fodder).
        self._tenant_queues: Dict[str, deque] = {}
        self._tenant_rr: list = []  # registration-ordered rotation
        self._rr_i = 0
        self._rr_fresh = True  # next rotation stop earns its quantum
        self._deficits: Dict[str, float] = {}
        self._system_queue: deque = deque()
        self._last_tenant: Optional[str] = None  # set by _sched_get
        self._last_queue: Optional[deque] = None
        self._tenancy = None  # lazily bound server/tenancy registry
        # chaos hook (rpc/fault.py): None = zero-overhead hot path; an
        # installed plan only acts while FAIL_POINTS is enabled
        self.fault_plan = None
        self._threads: list = []
        # transport failure observability (node rpc entity): failures
        # are countable instead of stdout traceback noise
        from pegasus_tpu.utils.metrics import METRICS

        _rpc_ent = METRICS.entity("rpc", "dispatch", {})
        self._dispatch_errors = _rpc_ent.counter("dispatch_error_count")
        self._sender_errors = _rpc_ent.counter("sender_error_count")
        self._listener: Optional[socket.socket] = None
        self.listen_addr: Optional[Addr] = None
        if listen is not None:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(listen)
            srv.listen(64)
            self._listener = srv
            self.listen_addr = srv.getsockname()
            self._spawn(self._accept_loop)
        self._spawn(self._dispatch_loop)

    def _spawn(self, fn, *args) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True)
        t.start()
        self._threads.append(t)

    # ---- public interface (SimNetwork-compatible) ----------------------

    def current_session(self) -> str:
        """The connection id of the message being dispatched (empty
        outside a dispatch). Security state keys on THIS, not on the
        frame's self-reported src."""
        return self._current_session

    def on_session_closed(self, cb) -> None:
        """Subscribe to connection teardown (sess id) — negotiated
        identities die with their connection."""
        self._session_closed_cbs.append(cb)

    def register(self, addr: str,
                 handler: Callable[[str, str, Any], None]) -> None:
        self._handlers[addr] = handler

    # messages drained into one batch delivery; bounds the latency a
    # deep queue can add to the first message of the window
    BATCH_DRAIN_MAX = 64

    def register_batch(self, addr: str, msg_type: str,
                       handler: Callable[[list], None]) -> None:
        """Register a flush-window batch handler: when the dispatcher
        pops a (addr, msg_type) message, it drains every CONSECUTIVE
        queued message with the same address and type (up to
        BATCH_DRAIN_MAX) and delivers them as handler([(src, payload)])
        in one call under the node lock. Only consecutive runs coalesce,
        so cross-type ordering is preserved exactly; a lone message
        costs one extra non-blocking queue poll."""
        self._batch_handlers[(addr, msg_type)] = handler

    def install_fault_plan(self, plan) -> None:
        """Arm chaos injection (rpc/fault.py FaultPlan). Also enables the
        fail-point registry — the plan's global gate — so a single
        FAIL_POINTS.teardown() later disarms every transport at once."""
        from pegasus_tpu.utils.fail_point import FAIL_POINTS

        self.fault_plan = plan
        if plan is not None:
            FAIL_POINTS.setup()

    def send(self, src: str, dst: str, msg_type: str, payload: Any) -> None:
        plan = self.fault_plan
        verdict = (0.0, 1)
        if plan is not None and plan.active:
            verdict = plan.outbound(src, dst, msg_type)
            if verdict is None:
                return  # injected loss (same contract as real loss)
        if isinstance(payload, dict) and "trace" not in payload:
            # distributed-tracing context rides the payload envelope:
            # a send issued under an active span is causally part of it
            # (replies inherit the serving span, whose ctx() carries the
            # tail-keep bit upstream). One thread-local read when
            # untraced; an explicit payload["trace"] wins.
            ctx = tracing.current_ctx()
            if ctx is not None:
                payload["trace"] = ctx
        if dst in self._handlers:
            # loopback: still through the inbox so delivery stays serial
            for _ in range(verdict[1]):
                self._inbox.put((time.perf_counter(), src, dst, msg_type,
                                 payload, "loopback"))
            return
        # encode HERE so an unencodable payload raises at the caller (a
        # programming error, not network loss); network IO happens on the
        # peer's sender thread so a dead peer never stalls handlers/timers
        frame = encode_message(src, dst, msg_type, payload)
        with self._outboxes_lock:
            if self._closing:
                return  # late send: spawning a sender now would leak it
            box = self._peer_outboxes.get(dst)
            if box is None:
                box = queue.Queue()
                self._peer_outboxes[dst] = box
                self._spawn(self._send_loop, dst, box)
        box.put((verdict[0], frame))
        if verdict[1] > 1:
            box.put((0.0, frame))  # injected duplicate

    def _send_loop(self, dst: str, box: "queue.Queue") -> None:
        from pegasus_tpu.utils.backoff import Backoff

        def nap(d: float) -> None:
            # closing-aware sleep: a pause must not delay shutdown
            t_end = time.monotonic() + d
            while not self._closing and time.monotonic() < t_end:
                time.sleep(min(0.05, max(0.0, t_end - time.monotonic())))

        # capped exponential full-jitter pause between reconnect
        # attempts — a dead peer must not be re-dialed at full speed
        # once per queued frame (each dial burns connect_timeout and
        # hammers the peer's accept queue as it restarts), and every
        # sender backing off the same dead peer must NOT wake in
        # lockstep (per-process jitter entropy from Backoff's default)
        backoff = Backoff(
            base_ms=FLAGS.get("pegasus.rpc", "reconnect_backoff_base_ms"),
            max_ms=FLAGS.get("pegasus.rpc", "reconnect_backoff_max_ms"),
            sleep=nap)
        fail_streak = 0
        while True:
            item = box.get()
            if item is None:
                return
            delay, frame = item
            if delay > 0:
                time.sleep(delay)  # injected link latency (fault plan)
            if fail_streak:
                backoff.sleep(fail_streak)
            try:
                sock, wlock = self._route(dst)
                with wlock:
                    sock.sendall(frame)
                fail_streak = 0
            except OSError as e:
                self._drop_route(dst)  # loss; protocols retry
                fail_streak += 1
                self._sender_errors.increment()
                _RL_LOG.log(f"sender.{dst}", e)

    def close(self) -> None:
        with self._outboxes_lock:
            # flag set under the lock: send() cannot race a new sender
            # thread into existence after the sentinels go out
            self._closing = True
            for box in self._peer_outboxes.values():
                box.put(None)
        self._inbox.put(None)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._routes_lock:
            for sock, _ in self._routes.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._routes.clear()

    def offload(self, fn: Callable[[], None]) -> None:
        """Run slow IO (block-service uploads/downloads) off the
        dispatcher: handlers run under the node lock, and a long upload
        there would stall beacons, prepares, and client traffic —
        demoting the node's primaries mid-backup (the reference runs
        these on THREAD_POOL_REPLICATION_LONG)."""

        def run() -> None:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - background op must
                # not kill silently (countable, rate-limited)
                self._dispatch_errors.increment()
                _RL_LOG.log("offload", e)

        self._spawn(run)

    # ---- timers --------------------------------------------------------

    def run_timer(self, interval: float, fn: Callable[[], None]) -> None:
        """Periodic callback under the node lock (parity: timer tasks)."""

        def loop() -> None:
            while not self._closing:
                time.sleep(interval)
                if self._closing:
                    return
                try:
                    with self.lock:
                        fn()
                except Exception as e:  # noqa: BLE001 - timers survive
                    self._dispatch_errors.increment()
                    _RL_LOG.log("timer", e)

        self._spawn(loop)

    # ---- internals -----------------------------------------------------

    def _route(self, dst: str) -> Tuple[socket.socket, threading.Lock]:
        with self._routes_lock:
            entry = self._routes.get(dst)
            if entry is not None:
                return entry
        addr = self.address_book.get(dst)
        if addr is None:
            raise OSError(f"no route to peer {dst!r}")
        sock = socket.create_connection(
            addr,
            timeout=FLAGS.get("pegasus.rpc", "connect_timeout_ms") / 1000.0)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # our own reader on the outbound connection too: RPC replies come
        # back on the connection the request went out on
        self._spawn(self._read_loop, sock)
        with self._routes_lock:
            existing = self._routes.get(dst)
            if existing is not None:
                try:
                    sock.close()
                except OSError:
                    pass
                return existing
            entry = (sock, threading.Lock())
            self._routes[dst] = entry
            return entry

    def _drop_route(self, dst: str) -> None:
        with self._routes_lock:
            entry = self._routes.pop(dst, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:
                pass

    def _learn_route(self, src: str, conn: socket.socket) -> None:
        with self._routes_lock:
            existing = self._routes.get(src)
            if existing is None or existing[0] is not conn:
                self._routes[src] = (conn, threading.Lock())

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closing:
            try:
                conn, _peer_addr = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._spawn(self._read_loop, conn)

    def _read_loop(self, conn: socket.socket) -> None:
        # connection-scoped session id: security state (negotiated
        # identities) must bind to the CONNECTION, never to the
        # forgeable self-reported `src` name in the frame
        sess = f"conn-{id(conn)}-{_SESSION_IDS.__next__()}"
        buf = bytearray()
        while not self._closing:
            try:
                chunk = conn.recv(1 << 16)
            except OSError:
                break
            if not chunk:
                break
            buf.extend(chunk)
            try:
                bodies = read_frames(buf)
            except ValueError as e:
                # corrupt stream: drop the connection — countable, not
                # silent (a flapping peer shows up in the counter)
                self._dispatch_errors.increment()
                _RL_LOG.log("reader", e)
                break
            for body in bodies:
                try:
                    src, dst, msg_type, payload = decode_message(body)
                except (ValueError, TypeError):
                    continue
                self._learn_route(src, conn)
                self._inbox.put((time.perf_counter(), src, dst, msg_type,
                                 payload, sess))
        try:
            conn.close()
        except OSError:
            pass
        for cb in list(self._session_closed_cbs):
            try:
                cb(sess)
            except Exception:  # noqa: BLE001 - observer must not kill IO
                pass

    # ---- weighted-fair admission (dispatch thread only) ----------------

    def _registry(self):
        """The process-global tenant registry, bound lazily: importing
        server/tenancy at module scope would drag the server package
        into every transport user (and risk an import cycle through
        server/__init__); at first dispatch everything is loaded."""
        if self._tenancy is None:
            from pegasus_tpu.server.tenancy import TENANTS

            self._tenancy = TENANTS
        return self._tenancy

    def _classify(self, item: Optional[tuple]) -> None:
        """File one inbox item into the fair-queue structure.
        Shed-eligible client work (non-write _CLIENT_REQS) queues per
        tenant — the tag resolves through the bounded registry, so
        unknown/forged tags fold into the default queue instead of
        minting queues; everything else (writes, replication, meta,
        the shutdown sentinel) takes the strict-priority system queue."""
        if item is None:
            self._system_queue.append(item)
            return
        msg_type, payload = item[3], item[4]
        if (msg_type in _CLIENT_REQS and msg_type not in WRITE_REQS
                and isinstance(payload, dict)):
            tenant = self._registry().resolve(payload.get("tenant")).name
            q = self._tenant_queues.get(tenant)
            if q is None:
                q = self._tenant_queues[tenant] = deque()
                self._tenant_rr.append(tenant)
                self._deficits.setdefault(tenant, 0.0)
            q.append(item)
        else:
            self._system_queue.append(item)

    def _queued_depth(self) -> int:
        return len(self._system_queue) + sum(
            len(q) for q in self._tenant_queues.values())

    def _drr_pick(self) -> tuple:
        """Deficit-weighted round-robin over the non-empty tenant
        queues (caller guarantees at least one). Each rotation stop
        earns the tenant ONE quantum (its clamped weight in message
        units); it then serves until the deficit runs dry, so relative
        drain rates converge on the weight ratios while every tenant
        keeps making progress. An observed-empty queue forfeits its
        banked credit — idle tenants cannot hoard a burst allowance."""
        reg = self._registry()
        rr = self._tenant_rr
        while True:
            name = rr[self._rr_i % len(rr)]
            q = self._tenant_queues[name]
            if not q:
                self._deficits[name] = 0.0
                self._rr_i += 1
                self._rr_fresh = True
                continue
            if self._rr_fresh:
                self._deficits[name] += reg.weight(name)
                self._rr_fresh = False
            if self._deficits[name] >= 1.0:
                self._deficits[name] -= 1.0
                self._last_tenant = name
                self._last_queue = q
                return q.popleft()
            # quantum spent: the next stop (possibly this same queue,
            # next rotation) earns a fresh one. min_weight > 0 bounds
            # the rotations before SOME queue accrues a full unit.
            self._rr_i += 1
            self._rr_fresh = True

    def _sched_get(self) -> Optional[tuple]:
        """The dispatcher's next item: drain whatever the reader
        threads queued, then serve system work first and tenant work
        by DRR. Blocks on the raw inbox only when everything is empty
        (single consumer, so emptiness cannot race)."""
        while True:
            try:
                self._classify(self._inbox.get_nowait())
            except queue.Empty:
                break
        while True:
            if self._system_queue:
                self._last_tenant = None
                self._last_queue = self._system_queue
                return self._system_queue.popleft()
            if self._tenant_queues and any(
                    self._tenant_queues.values()):
                return self._drr_pick()
            self._classify(self._inbox.get())
            while True:
                try:
                    self._classify(self._inbox.get_nowait())
                except queue.Empty:
                    break

    def _dispatch_loop(self) -> None:
        from pegasus_tpu.utils.errors import ErrorCode
        from pegasus_tpu.utils.metrics import METRICS

        # profiler toollet (parity: runtime/profiler.cpp:90-198 —
        # per-task-code execute latency/counts from engine join points;
        # here the join point is handler dispatch, keyed by message type)
        from pegasus_tpu.utils.profiler import PROFILER

        prof = METRICS.entity("rpc", "dispatch", {})
        expired_cnt = prof.counter("deadline_expired_count")
        shed_cnt = prof.counter("read_shed_count")
        lat: Dict[str, Any] = {}
        cnt: Dict[str, Any] = {}
        while True:
            item = self._sched_get()
            if item is None:
                return
            t_enq, src, dst, msg_type, payload, sess = item
            handler = self._handlers.get(dst)
            if handler is None:
                continue
            plan = self.fault_plan
            if plan is not None and plan.active and (
                    plan.is_partitioned(src) or plan.is_partitioned(dst)):
                continue  # inbound half of an injected partition
            env = _CLIENT_REQS.get(msg_type) if isinstance(payload, dict) \
                else None
            if env is not None:
                # (1) end-to-end deadline: work whose deadline lapsed in
                # the queue (or on the wire) is abandoned — the client
                # stopped waiting, so serving it only adds load exactly
                # when the node is least able to afford it
                dl = payload.get("deadline")
                if dl is not None and time.time() > dl:
                    expired_cnt.increment()
                    self.send(dst, src, env[0], {
                        "rid": payload.get("rid"),
                        "err": int(ErrorCode.ERR_TIMEOUT), env[1]: env[2]})
                    continue
                # (2) overload shedding, reads only: the single
                # dispatcher thread drains an unbounded inbox, so under
                # a read storm queue depth (and thus latency) grows
                # without bound; shed NEW reads with ERR_BUSY while the
                # queue is deep or this message aged in it. Writes and
                # replication traffic are exempt — availability of the
                # mutation path degrades last.
                if msg_type not in WRITE_REQS:
                    depth = self._inbox.qsize() + self._queued_depth()
                    age_ms = (time.perf_counter() - t_enq) * 1000.0
                    tname = self._last_tenant
                    if tname is not None:
                        # per-tenant queueing-delay series: the signal
                        # `shell tenants` (and the QoS isolation gate)
                        # read to prove a victim stayed fast
                        self._registry().note_queue_age(tname, age_ms)
                    if (depth > FLAGS.get("pegasus.rpc",
                                          "read_shed_queue_depth")
                            or age_ms > FLAGS.get(
                                "pegasus.rpc", "read_shed_queue_age_ms")):
                        shed_cnt.increment()
                        if tname is not None:
                            # DRR already drained the victims first, so
                            # whoever queued deep enough to shed IS the
                            # aggressor — bill the shed to its tenant
                            self._registry().note_shed(tname)
                        self.send(dst, src, env[0], {
                            "rid": payload.get("rid"),
                            "err": int(ErrorCode.ERR_BUSY),
                            env[1]: env[2]})
                        continue
            batch = None
            bh = self._batch_handlers.get((dst, msg_type))
            if bh is not None:
                # flush-window coalescing: drain the CONSECUTIVE run of
                # same-typed queued messages from the SAME connection
                # into one delivery (the read coordinator's dispatch
                # unit; session-scoped so negotiated identities keep
                # binding to the right connection). The run comes off
                # the SAME scheduler queue the head item came from —
                # for a tenant queue that means one tenant's burst
                # coalesces, and fairness holds because every extra
                # item bills the tenant's deficit (it may go negative;
                # the debt is repaid before the next quantum serves).
                srcq = self._last_queue
                tname = self._last_tenant
                batch = [(src, payload)]
                while srcq and len(batch) < self.BATCH_DRAIN_MAX:
                    nxt = srcq[0]
                    if (nxt is None or nxt[2] != dst
                            or nxt[3] != msg_type or nxt[5] != sess):
                        break
                    srcq.popleft()
                    if tname is not None:
                        self._deficits[tname] -= 1.0
                    batch.append((nxt[1], nxt[4]))
            # distributed-tracing join point: an inbound request
            # carrying a sampled context opens a dispatch span (replies
            # and acks only pin tail-keep). Batch deliveries (bh) open
            # per-item spans at the stub seam instead — one item per
            # trace, never one carrier per item.
            span = None
            if isinstance(payload, dict):
                t_ctx = payload.get("trace")
                if t_ctx is not None and batch is None:
                    name = msg_type
                    if msg_type == "replica":
                        name = f"replica.{payload.get('type')}"
                    if tracing.is_reply_type(name):
                        tracing.on_inbound_ctx(dst, t_ctx)
                    else:
                        span = tracing.start_server_span(dst, name, t_ctx)
                        if span is not None:
                            span.tags["queue_ms"] = round(
                                (time.perf_counter() - t_enq) * 1000.0, 3)
            t0 = time.perf_counter()
            try:
                # the dispatcher is the node's single handler thread, so
                # a plain attribute safely exposes the CONNECTION the
                # in-flight message arrived on (see current_session())
                self._current_session = sess
                with self.lock, tracing.activate(span):
                    if batch is not None:
                        bh(batch)
                    else:
                        handler(src, msg_type, payload)
            except Exception as e:  # noqa: BLE001 - a bad message must
                # not kill the dispatcher (countable, rate-limited)
                self._dispatch_errors.increment()
                _RL_LOG.log("dispatch", e)
            finally:
                if span is not None:
                    span.finish()
                t1 = time.perf_counter()
                p_lat = lat.get(msg_type)
                if p_lat is None:
                    p_lat = lat[msg_type] = prof.percentile(
                        f"{msg_type}_exec_ms")
                    cnt[msg_type] = prof.counter(f"{msg_type}_count")
                p_lat.set((t1 - t0) * 1000.0)
                cnt[msg_type].increment(1 if batch is None
                                        else len(batch))
                if PROFILER.enabled:
                    # toollet join point: queue delay + exec latency
                    # per task code (profiler.cpp:90-198)
                    PROFILER.observe(msg_type, (t0 - t_enq) * 1000.0,
                                     (t1 - t0) * 1000.0)
