"""Binary codec for rrdb write requests.

Role parity: the thrift-serialized rrdb structs that travel on the wire
and inside mutations (idl/rrdb.thrift; the reference checks in generated
C++ and logs raw request blobs into mutations,
src/replica/mutation.cpp). We use a compact length-prefixed binary
format — one byte op code, then op-specific fields — shared by the
mutation log and (later) the network layer.

Grammar (little-endian):
    blob     := [u32 len][bytes]
    put      := OP_PUT blob(key) blob(value) u32(expire_ts)
    remove   := OP_REMOVE blob(key)
    multi_put:= OP_MULTI_PUT blob(hash_key) u32(expire) u32(n) {blob blob}*
    multi_rm := OP_MULTI_REMOVE blob(hash_key) u32(n) {blob}*
    incr     := OP_INCR blob(key) i64(increment) i32(expire)
    cas      := OP_CAS blob(hk) blob(check_sk) u8(type) blob(operand)
                u8(diff) blob(set_sk) blob(set_value) i32(expire) u8(ret)
    cam      := OP_CAM blob(hk) blob(check_sk) u8(type) blob(operand)
                u8(ret) u32(n) {u8(op) blob(sk) blob(value) i32(expire)}*
"""

from __future__ import annotations

import struct
from typing import Any, Tuple

from pegasus_tpu.server.types import (
    CheckAndMutateRequest,
    CheckAndSetRequest,
    IncrRequest,
    KeyValue,
    MultiPutRequest,
    MultiRemoveRequest,
    Mutate,
)

OP_PUT = 1
OP_REMOVE = 2
OP_MULTI_PUT = 3
OP_MULTI_REMOVE = 4
OP_INCR = 5
OP_CAS = 6
OP_CAM = 7
# bulk-load SST ingestion rides the 2PC pipeline as its own mutation
# (parity: RPC_RRDB_RRDB_BULK_LOAD through init_prepare,
# replica_2pc.cpp:211-230): request = (block_root, staged_app_name)
OP_INGEST = 8
# duplication-shipped writes (parity: duplicate-tagged update_request,
# idl/rrdb.thrift dup fields): carry the SOURCE timetag so the follower
# resolves conflicts; applied through the follower's own 2PC
# dup_put: (key, user_data, expire_ts, timetag); dup_remove: (key, timetag)
OP_DUP_PUT = 9
OP_DUP_REMOVE = 10


def _blob(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


class _Reader:
    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = data
        self.pos = pos

    def blob(self) -> bytes:
        (n,) = struct.unpack_from("<I", self.data, self.pos)
        self.pos += 4
        out = self.data[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("truncated blob")
        self.pos += n
        return out

    def u8(self) -> int:
        v = self.data[self.pos]
        self.pos += 1
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.data, self.pos)
        self.pos += 4
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.data, self.pos)
        self.pos += 4
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from("<q", self.data, self.pos)
        self.pos += 8
        return v


def encode_write(op: int, req: Any) -> bytes:
    if op == OP_PUT:
        key, value, expire_ts = req
        return bytes([OP_PUT]) + _blob(key) + _blob(value) + struct.pack(
            "<I", expire_ts)
    if op == OP_REMOVE:
        (key,) = req if isinstance(req, tuple) else (req,)
        return bytes([OP_REMOVE]) + _blob(key)
    if op == OP_MULTI_PUT:
        assert isinstance(req, MultiPutRequest)
        out = [bytes([OP_MULTI_PUT]), _blob(req.hash_key),
               struct.pack("<iI", req.expire_ts_seconds, len(req.kvs))]
        for kv in req.kvs:
            out.append(_blob(kv.key))
            out.append(_blob(kv.value))
        return b"".join(out)
    if op == OP_MULTI_REMOVE:
        assert isinstance(req, MultiRemoveRequest)
        out = [bytes([OP_MULTI_REMOVE]), _blob(req.hash_key),
               struct.pack("<I", len(req.sort_keys))]
        out.extend(_blob(sk) for sk in req.sort_keys)
        return b"".join(out)
    if op == OP_INCR:
        assert isinstance(req, IncrRequest)
        return (bytes([OP_INCR]) + _blob(req.key)
                + struct.pack("<qi", req.increment, req.expire_ts_seconds))
    if op == OP_CAS:
        assert isinstance(req, CheckAndSetRequest)
        return (bytes([OP_CAS]) + _blob(req.hash_key)
                + _blob(req.check_sort_key)
                + bytes([int(req.check_type)]) + _blob(req.check_operand)
                + bytes([int(req.set_diff_sort_key)])
                + _blob(req.set_sort_key) + _blob(req.set_value)
                + struct.pack("<i", req.set_expire_ts_seconds)
                + bytes([int(req.return_check_value)]))
    if op == OP_INGEST:
        root, src_app, load_id = req
        return (bytes([OP_INGEST]) + _blob(root.encode())
                + _blob(src_app.encode()) + struct.pack("<Q", load_id))
    if op == OP_DUP_PUT:
        key, user_data, expire_ts, timetag = req
        return (bytes([OP_DUP_PUT]) + _blob(key) + _blob(user_data)
                + struct.pack("<IQ", expire_ts, timetag))
    if op == OP_DUP_REMOVE:
        key, timetag = req
        return bytes([OP_DUP_REMOVE]) + _blob(key) + struct.pack(
            "<Q", timetag)
    if op == OP_CAM:
        assert isinstance(req, CheckAndMutateRequest)
        out = [bytes([OP_CAM]), _blob(req.hash_key),
               _blob(req.check_sort_key), bytes([int(req.check_type)]),
               _blob(req.check_operand),
               bytes([int(req.return_check_value)]),
               struct.pack("<I", len(req.mutate_list))]
        for m in req.mutate_list:
            out.append(bytes([int(m.operation)]))
            out.append(_blob(m.sort_key))
            out.append(_blob(m.value))
            out.append(struct.pack("<i", m.set_expire_ts_seconds))
        return b"".join(out)
    raise ValueError(f"unknown write op {op}")


def decode_write(data: bytes, pos: int = 0) -> Tuple[int, Any, int]:
    """Returns (op, request, next_pos)."""
    r = _Reader(data, pos)
    op = r.u8()
    if op == OP_PUT:
        key = r.blob()
        value = r.blob()
        expire = r.u32()
        return op, (key, value, expire), r.pos
    if op == OP_REMOVE:
        return op, (r.blob(),), r.pos
    if op == OP_MULTI_PUT:
        hk = r.blob()
        expire = r.i32()
        n = r.u32()
        kvs = []
        for _ in range(n):
            k = r.blob()
            v = r.blob()
            kvs.append(KeyValue(k, v))
        return op, MultiPutRequest(hk, kvs, expire), r.pos
    if op == OP_MULTI_REMOVE:
        hk = r.blob()
        n = r.u32()
        sks = [r.blob() for _ in range(n)]
        return op, MultiRemoveRequest(hk, sks), r.pos
    if op == OP_INCR:
        key = r.blob()
        inc = r.i64()
        expire = r.i32()
        return op, IncrRequest(key, inc, expire), r.pos
    if op == OP_CAS:
        hk = r.blob()
        csk = r.blob()
        ctype = r.u8()
        operand = r.blob()
        diff = bool(r.u8())
        ssk = r.blob()
        sval = r.blob()
        expire = r.i32()
        ret = bool(r.u8())
        return op, CheckAndSetRequest(hk, csk, ctype, operand, diff, ssk,
                                      sval, expire, ret), r.pos
    if op == OP_INGEST:
        root = r.blob().decode()
        src_app = r.blob().decode()
        load_id = r.i64() & 0xFFFFFFFFFFFFFFFF
        return op, (root, src_app, load_id), r.pos
    if op == OP_DUP_PUT:
        key = r.blob()
        user_data = r.blob()
        (expire, timetag) = struct.unpack_from("<IQ", r.data, r.pos)
        r.pos += 12
        return op, (key, user_data, expire, timetag), r.pos
    if op == OP_DUP_REMOVE:
        key = r.blob()
        (timetag,) = struct.unpack_from("<Q", r.data, r.pos)
        r.pos += 8
        return op, (key, timetag), r.pos
    if op == OP_CAM:
        hk = r.blob()
        csk = r.blob()
        ctype = r.u8()
        operand = r.blob()
        ret = bool(r.u8())
        n = r.u32()
        muts = []
        for _ in range(n):
            mop = r.u8()
            sk = r.blob()
            v = r.blob()
            expire = r.i32()
            muts.append(Mutate(mop, sk, v, expire))
        return op, CheckAndMutateRequest(hk, csk, ctype, operand, muts,
                                         ret), r.pos
    raise ValueError(f"unknown write op {op}")


