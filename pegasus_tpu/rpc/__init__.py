"""Wire/wal codecs and (later) the DCN RPC stack (reference: src/rpc/)."""

from pegasus_tpu.rpc.codec import (
    OP_CAM,
    OP_CAS,
    OP_INCR,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
    decode_write,
    encode_write,
)
