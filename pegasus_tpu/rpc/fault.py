"""FaultPlan: chaos injection for the REAL TcpTransport.

Parity: the toollet fault_injector (src/runtime/fault_injector.cpp:62-118)
applied to the asio network path — the same per-link drop / delay /
duplicate / partition surface the deterministic SimNetwork exposes
(runtime/sim.py), so a chaos schedule written against the simulator runs
unchanged against real multi-process oneboxes.

Gating: a transport with no plan installed pays one attribute check per
send; an installed plan only acts while the fail-point registry is
enabled (utils/fail_point.py setup/teardown is the cluster-wide chaos
kill-switch), so `FAIL_POINTS.teardown()` ends an injection run without
un-wiring every node. All probabilistic decisions draw from one seeded
RNG per plan — reproducible per process.

Semantics (matching SimNetwork where the wire allows):
- drop: the frame is lost at the SENDER, before the socket — the peer
  sees silence, exactly like simulated loss;
- delay: the sender thread for that peer holds the frame for the extra
  latency; per-link FIFO order is preserved (delays on a link are
  cumulative under sustained load — a bandwidth-shaped pipe, slightly
  harsher than the simulator's pipelined latency);
- duplicate: the frame is written twice back-to-back (TCP cannot
  duplicate on its own; protocols must tolerate redelivery);
- partition: a named node sends nothing and — on its own transport —
  delivers nothing, isolating it in both directions even when only a
  subset of processes installed the plan.

Loopback (self-addressed) messages honor drop/duplicate/partition but
not delay: the in-process inbox has no timing wheel, and a node's
self-messages are control-plane steps the simulator also delivers
promptly.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Tuple

Link = Tuple[Optional[str], Optional[str]]


def link_rule_lookup(table: Dict, src: str, dst: str) -> float:
    """Most-specific link rule wins: (src,dst) > (src,*) > (*,dst) >
    global. Shared by FaultPlan and SimNetwork so the two chaos
    surfaces can never diverge on precedence. Partial wildcards let a
    schedule fault 'everything one node sends' without enumerating
    peers."""
    for key in ((src, dst), (src, None), (None, dst), None):
        v = table.get(key)
        if v is not None:
            return v
    return 0.0


class FaultPlan:
    """Per-link fault schedule for TcpTransport. Keys are (src, dst)
    node names; `None` keys configure the global default, like
    SimNetwork.set_drop/set_delay with no link arguments."""

    def __init__(self, seed: int = 0) -> None:
        self._drop: Dict[Optional[Link], float] = {}
        self._delay: Dict[Optional[Link], float] = {}
        self._dup: Dict[Optional[Link], float] = {}
        self._partitioned: set = set()
        self._rng = random.Random(seed)
        self._lock = threading.Lock()  # send() runs on many threads
        self.dropped = 0
        self.duplicated = 0

    # ---- configuration (SimNetwork-compatible surface) -----------------

    def set_drop(self, prob: float, src: Optional[str] = None,
                 dst: Optional[str] = None) -> None:
        key = None if src is None and dst is None else (src, dst)
        with self._lock:
            if prob <= 0:
                self._drop.pop(key, None)
            else:
                self._drop[key] = prob

    def set_delay(self, extra_s: float, src: Optional[str] = None,
                  dst: Optional[str] = None) -> None:
        key = None if src is None and dst is None else (src, dst)
        with self._lock:
            if extra_s <= 0:
                self._delay.pop(key, None)
            else:
                self._delay[key] = extra_s

    def set_duplicate(self, prob: float, src: Optional[str] = None,
                      dst: Optional[str] = None) -> None:
        key = None if src is None and dst is None else (src, dst)
        with self._lock:
            if prob <= 0:
                self._dup.pop(key, None)
            else:
                self._dup[key] = prob

    def partition(self, addr: str) -> None:
        with self._lock:
            self._partitioned.add(addr)

    def heal(self, addr: str) -> None:
        with self._lock:
            self._partitioned.discard(addr)

    @classmethod
    def from_config(cls, cfg: dict) -> "FaultPlan":
        """Build from a cluster.json-style dict:
        {"seed": 7, "drop": [{"prob": .1, "src": "node0", "dst": null}],
         "delay": [{"extra_s": .02}], "duplicate": [{"prob": .05}],
         "partition": ["node2"]} — how node_main wires chaos into real
        onebox processes without any in-process test hook."""
        plan = cls(seed=int(cfg.get("seed", 0)))
        for d in cfg.get("drop", ()):
            plan.set_drop(float(d["prob"]), d.get("src"), d.get("dst"))
        for d in cfg.get("delay", ()):
            plan.set_delay(float(d["extra_s"]), d.get("src"), d.get("dst"))
        for d in cfg.get("duplicate", ()):
            plan.set_duplicate(float(d["prob"]), d.get("src"),
                               d.get("dst"))
        for name in cfg.get("partition", ()):
            plan.partition(name)
        return plan

    # ---- decisions -----------------------------------------------------

    @property
    def active(self) -> bool:
        from pegasus_tpu.utils.fail_point import FAIL_POINTS

        return FAIL_POINTS.enabled

    def is_partitioned(self, addr: str) -> bool:
        return addr in self._partitioned

    def outbound(self, src: str, dst: str, msg_type: Optional[str] = None
                 ) -> Optional[Tuple[float, int]]:
        """Sender-side verdict for one message: None = drop it;
        otherwise (extra_delay_seconds, copies). Faults apply at the
        sender only, so a plan installed cluster-wide charges each link
        once, not once per endpoint. client_write is exempt from
        DUPLICATION (only): neither the stub nor the 2PC dedups by rid,
        so a duplicated atomic write (incr/cas/cam) would double-apply —
        the exact hazard the client's own lost-reply handling refuses to
        create. Loss and delay stay fair game for writes."""
        with self._lock:
            if src in self._partitioned or dst in self._partitioned:
                self.dropped += 1
                return None
            prob = link_rule_lookup(self._drop, src, dst)
            if prob > 0 and self._rng.random() < prob:
                self.dropped += 1
                return None
            copies = 1
            from pegasus_tpu.rpc.transport import WRITE_REQS

            dup = link_rule_lookup(self._dup, src, dst)
            if dup > 0 and msg_type not in WRITE_REQS \
                    and self._rng.random() < dup:
                copies = 2
                self.duplicated += 1
            return link_rule_lookup(self._delay, src, dst), copies
