// Native host runtime: record-batch packing + crc64 columns.
//
// Role parity: the reference's hot host-side loops are C++
// (src/server/pegasus_server_impl.cpp record iteration, src/base codecs);
// our device kernels consume columnar batches, and building those batches
// from a record stream is the host hot loop — this library packs a batch
// of encoded keys into the padded key matrix + length/hashkey-length/crc64
// columns in one call instead of a per-record Python loop.
//
// crc64 is reimplemented from the polynomial bit-spec (reflected,
// ~init/~final — see pegasus_tpu/base/crc.py for the spec and golden
// vectors); nothing here is copied from the reference.
//
// Build: g++ -O3 -shared -fPIC packer.cpp -o libpegasus_native.so
// ABI: plain C, consumed via ctypes.

#include <cstdint>
#include <cstring>

namespace {

constexpr int kPolyBits[] = {63, 61, 59, 58, 56, 55, 52, 49, 48, 47, 46, 44,
                             41, 37, 36, 34, 32, 31, 28, 26, 23, 22, 19, 16,
                             13, 12, 10, 9,  6,  4,  3,  0};

struct Crc64Table {
  uint64_t entries[256];
  Crc64Table() {
    uint64_t poly = 0;
    for (int bit : kPolyBits) poly |= 1ULL << (63 - bit);
    for (uint32_t i = 0; i < 256; ++i) {
      uint64_t k = i;
      for (int j = 0; j < 8; ++j) k = (k & 1) ? (k >> 1) ^ poly : k >> 1;
      entries[i] = k;
    }
  }
};

// C++11 guarantees thread-safe once-initialization of local statics —
// concurrent first calls from several partition threads are safe
const Crc64Table& table() {
  static const Crc64Table t;
  return t;
}

inline uint64_t crc64(const uint8_t* data, int64_t len, uint64_t init) {
  const Crc64Table& t = table();
  uint64_t crc = ~init;
  for (int64_t i = 0; i < len; ++i)
    crc = t.entries[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct Crc32cTable {
  uint32_t entries[256];
  Crc32cTable() {
    // CRC-32C (Castagnoli), reflected poly 0x82F63B78 — derived from
    // the polynomial spec, same construction as the Python twin
    // (pegasus_tpu/base/crc.py); golden vectors pin equivalence.
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t k = i;
      for (int j = 0; j < 8; ++j)
        k = (k & 1) ? (k >> 1) ^ 0x82F63B78u : k >> 1;
      entries[i] = k;
    }
  }
};

const Crc32cTable& table32() {
  static const Crc32cTable t;
  return t;
}

inline uint32_t crc32c(const uint8_t* data, int64_t len, uint32_t init) {
  const Crc32cTable& t = table32();
  uint32_t crc = ~init;
  for (int64_t i = 0; i < len; ++i)
    crc = t.entries[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // namespace

extern "C" {

// Scalar crc64 (compatibility checks / tests).
uint64_t pegasus_crc64(const uint8_t* data, int64_t len) {
  return crc64(data, len, 0);
}

// CRC-32C over a buffer — the WAL/SST/wire framing checksum hot loop
// (the Python table loop runs ~2 MB/s; this runs at memory speed).
uint32_t pegasus_crc32(const uint8_t* data, int64_t len, uint32_t init) {
  return crc32c(data, len, init);
}

// Batched crc64 over n zero-padded byte rows (uint8[n, width], row i
// holding lens[i] valid bytes) — one ctypes call hashes a whole
// point-read flush's probe keys for the bloom-filter pass, where the
// numpy per-byte loop pays ~10us of dispatch per byte POSITION and a
// scalar call pays ~1us of ctypes overhead per KEY.
void pegasus_crc64_rows(const uint8_t* rows, const int64_t* lens, int64_t n,
                        int64_t width, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i)
    out[i] = crc64(rows + i * width, lens[i], 0);
}

// Multi-filter bloom probe: out[i * n_filters + t] = 1 iff hash i may
// be present in filter t. Filters are the power-of-two double-hashed
// blooms of storage/bloom.py (g_j = (h + j*delta) & mask, delta =
// ((h>>17)|1) & mask). One call answers a whole point-read flush
// against EVERY L0 table and L1 run of a partition — the per-key
// python probe walk costs ~1.4us per (key, filter) pair, which at
// deep-L0 rivals the block probes the filter exists to skip.
// bits_addrs: n_filters raw pointers to each filter's bit bytes.
void pegasus_bloom_probe_multi(const uint64_t* bits_addrs,
                               const uint64_t* masks, const int32_t* ks,
                               int64_t n_filters, const uint64_t* hashes,
                               int64_t n_keys, uint8_t* out) {
  for (int64_t i = 0; i < n_keys; ++i) {
    const uint64_t h = hashes[i];
    uint8_t* row = out + i * n_filters;
    for (int64_t t = 0; t < n_filters; ++t) {
      const uint8_t* bits =
          reinterpret_cast<const uint8_t*>(static_cast<uintptr_t>(bits_addrs[t]));
      const uint64_t mask = masks[t];
      uint64_t idx = h & mask;
      const uint64_t delta = ((h >> 17) | 1) & mask;
      uint8_t ok = 1;
      for (int32_t j = 0; j < ks[t]; ++j) {
        if (!((bits[idx >> 3] >> (idx & 7)) & 1)) {
          ok = 0;
          break;
        }
        idx = (idx + delta) & mask;
      }
      row[t] = ok;
    }
  }
}

// Pack n encoded keys (concatenated in `heap`, row i spanning
// [offsets[i], offsets[i+1])) into:
//   keys_out     uint8[n, key_width]   zero-padded rows
//   key_len_out  int32[n]
//   hkl_out      int32[n]              big-endian u16 header
//   hash_lo_out  uint32[n]             crc64 lo lane of pegasus_key_hash
//   valid_out    uint8[n]      0 for malformed rows (len < 2, or a
//                              hashkey_len header exceeding the body)
// Returns 0 on success, -1 if any key exceeds key_width.
int32_t pegasus_pack_records(const uint8_t* heap, const int64_t* offsets,
                             int64_t n, int64_t key_width, uint8_t* keys_out,
                             int32_t* key_len_out, int32_t* hkl_out,
                             uint32_t* hash_lo_out, uint8_t* valid_out) {
  std::memset(keys_out, 0, static_cast<size_t>(n) * key_width);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t start = offsets[i];
    const int64_t len = offsets[i + 1] - start;
    if (len > key_width) return -1;
    const uint8_t* key = heap + start;
    std::memcpy(keys_out + i * key_width, key, len);
    key_len_out[i] = static_cast<int32_t>(len);
    int32_t hkl = 0;
    uint64_t hash = 0;
    bool valid = len >= 2;
    if (valid) {
      hkl = (static_cast<int32_t>(key[0]) << 8) | key[1];
      if (hkl > len - 2) {
        // header claims more hashkey bytes than the key holds: malformed
        // (the Python codec rejects such keys); never read past the row
        valid = false;
        hkl = 0;
      } else {
        // pegasus_key_hash: crc64 of the hashkey region, or of the
        // sortkey region when the hashkey is empty
        const int64_t region_len = hkl > 0 ? hkl : len - 2;
        hash = crc64(key + 2, region_len, 0);
      }
    }
    hkl_out[i] = hkl;
    hash_lo_out[i] = static_cast<uint32_t>(hash & 0xFFFFFFFFu);
    valid_out[i] = valid ? 1 : 0;
  }
  return 0;
}

// Gather `m` selected rows of a columnar block into a packed response
// page: keys concatenated into key_blob, user-data (value minus `hdr`
// header bytes) into val_blob, with running offset columns.
//
// Role parity: the reference's response-assembly loop
// (src/server/pegasus_server_impl.cpp append_key_value_for_multi_get /
// validate_key_value_for_scan) copies each surviving record into the
// response one at a time in C++; our survivors are already columnar, so
// one call packs the whole page.
//
//   keys        uint8[.., key_width]  padded key rows
//   key_len     int32[..]
//   value_offs  uint32[..+1]          row i's value = heap[offs[i],offs[i+1])
//   take        int64[m]              row indices to gather (ascending)
//   hdr         value-header bytes to strip (user data starts after it)
//   key_offs    uint32[m+1]; [0] preset by the caller (chaining base)
//   val_offs    uint32[m+1]; [0] preset; pass val_blob=NULL to skip
//                            values (no_value mode) — offsets still run
// The caller sizes key_blob/val_blob exactly (numpy sums of the same
// columns); this routine only copies.
void pegasus_gather_page(const uint8_t* keys, int64_t key_width,
                         const int32_t* key_len, const uint32_t* value_offs,
                         const uint8_t* heap, const int64_t* take, int64_t m,
                         int32_t hdr, uint8_t* key_blob, uint32_t* key_offs,
                         uint8_t* val_blob, uint32_t* val_offs) {
  uint32_t kpos = key_offs[0];
  uint32_t vpos = val_offs[0];
  for (int64_t i = 0; i < m; ++i) {
    const int64_t row = take[i];
    const int32_t kl = key_len[row];
    std::memcpy(key_blob + kpos, keys + row * key_width, kl);
    kpos += static_cast<uint32_t>(kl);
    key_offs[i + 1] = kpos;
    const uint32_t v0 = value_offs[row];
    const uint32_t v1 = value_offs[row + 1];
    const uint32_t vl = v1 - v0 > static_cast<uint32_t>(hdr)
                            ? v1 - v0 - static_cast<uint32_t>(hdr)
                            : 0;
    if (val_blob != nullptr && vl > 0)
      std::memcpy(val_blob + vpos, heap + v0 + hdr, vl);
    vpos += val_blob != nullptr ? vl : 0;
    val_offs[i + 1] = vpos;
  }
}

// Serve a whole BATCH of scan requests' base-path assembly in one
// call. The caller passes a table of the batch's unique blocks
// (pointer columns) and each request's plan as CSR rows into that
// table; rows are packed into shared key/value arenas with running
// offset columns, one offsets window per request
// ([row_base[r], row_base[r] + count_r]).
//
// Per request r, rows are taken in plan order until wants[r] rows or
// `byte_budget` response bytes (keys + stripped values; keys only when
// no_values[r]). The FIRST row of a request is taken even when it
// alone exceeds the budget (forward-progress guarantee) as long as it
// fits the arenas.
//
// out_state[r]: 0 = plan exhausted, 1 = stopped at wants[r],
//               2 = stopped by the byte budget (truncated),
//               3 = arena capacity hit (caller re-serves r in Python).
void pegasus_scan_serve_batch(
    const uint64_t* keys_ptrs, const int64_t* widths,
    const uint64_t* keylen_ptrs,
    const uint64_t* entry_mask_ptrs,  // PER-ENTRY: flavors sharing a
                                      // block carry different masks
    const uint64_t* voffs_ptrs, const uint64_t* heap_ptrs,
    const uint64_t* ets_ptrs, int64_t n_reqs, const int64_t* entry_start,
    const int64_t* entry_block, const int64_t* entry_lo,
    const int64_t* entry_hi, const int64_t* wants,
    const uint8_t* no_values, int64_t byte_budget, int32_t hdr,
    uint8_t* key_blob, int64_t key_cap, uint8_t* val_blob,
    int64_t val_cap, uint32_t* key_offs, uint32_t* val_offs,
    const int64_t* row_base, uint32_t* ets_arena, int64_t* out_count,
    int64_t* out_bytes, int32_t* out_state) {
  uint32_t kpos = 0;
  uint32_t vpos = 0;
  for (int64_t r = 0; r < n_reqs; ++r) {
    const int64_t base = row_base[r];
    const int64_t want = wants[r];
    const int32_t no_value = no_values[r];
    int64_t count = 0;
    int64_t bytes = 0;
    int32_t state = 0;
    key_offs[base] = kpos;
    val_offs[base] = vpos;
    for (int64_t e = entry_start[r];
         e < entry_start[r + 1] && count < want && state == 0; ++e) {
      const int64_t b = entry_block[e];
      const uint8_t* keys = reinterpret_cast<const uint8_t*>(keys_ptrs[b]);
      const int64_t width = widths[b];
      const int32_t* key_len =
          reinterpret_cast<const int32_t*>(keylen_ptrs[b]);
      const uint8_t* mask =
          reinterpret_cast<const uint8_t*>(entry_mask_ptrs[e]);
      const uint32_t* voffs =
          reinterpret_cast<const uint32_t*>(voffs_ptrs[b]);
      const uint8_t* heap = reinterpret_cast<const uint8_t*>(heap_ptrs[b]);
      const uint32_t* ets = reinterpret_cast<const uint32_t*>(ets_ptrs[b]);
      const int64_t hi = entry_hi[e];
      for (int64_t row = entry_lo[e]; row < hi; ++row) {
        if (!mask[row]) continue;
        const int32_t kl = key_len[row];
        const uint32_t v0 = voffs[row];
        const uint32_t v1 = voffs[row + 1];
        const uint32_t vl = (!no_value && v1 - v0 > (uint32_t)hdr)
                                ? v1 - v0 - (uint32_t)hdr
                                : 0;
        const int64_t row_bytes = kl + (int64_t)vl;
        if ((uint64_t)kpos + (uint64_t)kl > (uint64_t)key_cap ||
            (uint64_t)vpos + (uint64_t)vl > (uint64_t)val_cap) {
          state = 3;  // arena full: this request re-serves in Python
          break;
        }
        if (count > 0 && bytes + row_bytes > byte_budget) {
          state = 2;
          break;
        }
        std::memcpy(key_blob + kpos, keys + row * width, kl);
        kpos += (uint32_t)kl;
        key_offs[base + count + 1] = kpos;
        if (vl > 0) std::memcpy(val_blob + vpos, heap + v0 + hdr, vl);
        vpos += vl;
        val_offs[base + count + 1] = vpos;
        if (ets_arena) ets_arena[base - r + count] = ets[row];
        bytes += row_bytes;
        ++count;
        if (count >= want) {
          state = 1;
          break;
        }
      }
    }
    out_count[r] = count;
    out_bytes[r] = bytes;
    out_state[r] = state;
  }
}

}  // extern "C"
