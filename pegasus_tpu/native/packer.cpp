// Native host runtime: record-batch packing + crc64 columns.
//
// Role parity: the reference's hot host-side loops are C++
// (src/server/pegasus_server_impl.cpp record iteration, src/base codecs);
// our device kernels consume columnar batches, and building those batches
// from a record stream is the host hot loop — this library packs a batch
// of encoded keys into the padded key matrix + length/hashkey-length/crc64
// columns in one call instead of a per-record Python loop.
//
// crc64 is reimplemented from the polynomial bit-spec (reflected,
// ~init/~final — see pegasus_tpu/base/crc.py for the spec and golden
// vectors); nothing here is copied from the reference.
//
// Build: g++ -O3 -shared -fPIC packer.cpp -o libpegasus_native.so
// ABI: plain C, consumed via ctypes.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <dlfcn.h>

namespace {

constexpr int kPolyBits[] = {63, 61, 59, 58, 56, 55, 52, 49, 48, 47, 46, 44,
                             41, 37, 36, 34, 32, 31, 28, 26, 23, 22, 19, 16,
                             13, 12, 10, 9,  6,  4,  3,  0};

struct Crc64Table {
  uint64_t entries[256];
  Crc64Table() {
    uint64_t poly = 0;
    for (int bit : kPolyBits) poly |= 1ULL << (63 - bit);
    for (uint32_t i = 0; i < 256; ++i) {
      uint64_t k = i;
      for (int j = 0; j < 8; ++j) k = (k & 1) ? (k >> 1) ^ poly : k >> 1;
      entries[i] = k;
    }
  }
};

// C++11 guarantees thread-safe once-initialization of local statics —
// concurrent first calls from several partition threads are safe
const Crc64Table& table() {
  static const Crc64Table t;
  return t;
}

inline uint64_t crc64(const uint8_t* data, int64_t len, uint64_t init) {
  const Crc64Table& t = table();
  uint64_t crc = ~init;
  for (int64_t i = 0; i < len; ++i)
    crc = t.entries[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct Crc32cTable {
  uint32_t entries[256];
  Crc32cTable() {
    // CRC-32C (Castagnoli), reflected poly 0x82F63B78 — derived from
    // the polynomial spec, same construction as the Python twin
    // (pegasus_tpu/base/crc.py); golden vectors pin equivalence.
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t k = i;
      for (int j = 0; j < 8; ++j)
        k = (k & 1) ? (k >> 1) ^ 0x82F63B78u : k >> 1;
      entries[i] = k;
    }
  }
};

const Crc32cTable& table32() {
  static const Crc32cTable t;
  return t;
}

inline uint32_t crc32c(const uint8_t* data, int64_t len, uint32_t init) {
  const Crc32cTable& t = table32();
  uint32_t crc = ~init;
  for (int64_t i = 0; i < len; ++i)
    crc = t.entries[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // namespace

extern "C" {

// Scalar crc64 (compatibility checks / tests).
uint64_t pegasus_crc64(const uint8_t* data, int64_t len) {
  return crc64(data, len, 0);
}

// CRC-32C over a buffer — the WAL/SST/wire framing checksum hot loop
// (the Python table loop runs ~2 MB/s; this runs at memory speed).
uint32_t pegasus_crc32(const uint8_t* data, int64_t len, uint32_t init) {
  return crc32c(data, len, init);
}

// Batched crc64 over n zero-padded byte rows (uint8[n, width], row i
// holding lens[i] valid bytes) — one ctypes call hashes a whole
// point-read flush's probe keys for the bloom-filter pass, where the
// numpy per-byte loop pays ~10us of dispatch per byte POSITION and a
// scalar call pays ~1us of ctypes overhead per KEY.
void pegasus_crc64_rows(const uint8_t* rows, const int64_t* lens, int64_t n,
                        int64_t width, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i)
    out[i] = crc64(rows + i * width, lens[i], 0);
}

// Multi-filter bloom probe: out[i * n_filters + t] = 1 iff hash i may
// be present in filter t. Filters are the power-of-two double-hashed
// blooms of storage/bloom.py (g_j = (h + j*delta) & mask, delta =
// ((h>>17)|1) & mask). One call answers a whole point-read flush
// against EVERY L0 table and L1 run of a partition — the per-key
// python probe walk costs ~1.4us per (key, filter) pair, which at
// deep-L0 rivals the block probes the filter exists to skip.
// bits_addrs: n_filters raw pointers to each filter's bit bytes.
void pegasus_bloom_probe_multi(const uint64_t* bits_addrs,
                               const uint64_t* masks, const int32_t* ks,
                               int64_t n_filters, const uint64_t* hashes,
                               int64_t n_keys, uint8_t* out) {
  for (int64_t i = 0; i < n_keys; ++i) {
    const uint64_t h = hashes[i];
    uint8_t* row = out + i * n_filters;
    for (int64_t t = 0; t < n_filters; ++t) {
      const uint8_t* bits =
          reinterpret_cast<const uint8_t*>(static_cast<uintptr_t>(bits_addrs[t]));
      const uint64_t mask = masks[t];
      uint64_t idx = h & mask;
      const uint64_t delta = ((h >> 17) | 1) & mask;
      uint8_t ok = 1;
      for (int32_t j = 0; j < ks[t]; ++j) {
        if (!((bits[idx >> 3] >> (idx & 7)) & 1)) {
          ok = 0;
          break;
        }
        idx = (idx + delta) & mask;
      }
      row[t] = ok;
    }
  }
}

// ---- perfect-hash (CHD) two-level SST index -------------------------
//
// The build/probe twins of storage/phash.py (which documents the
// layout: mix -> bucket/p0/delta, entry = fp(10) | loc(22), EMPTY =
// 0xFFFFFFFF). Both sides MUST stay bit-identical to the Python
// fallback — the mixer, geometry, bucket order and displacement search
// are part of the on-disk format (the seed is stored in the index
// header and a file built by either path must probe identically under
// the other).

namespace {

constexpr uint64_t kPhashGolden = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t kPhashMixK = 0xFF51AFD7ED558CCDULL;
constexpr uint32_t kPhashEmpty = 0xFFFFFFFFu;
constexpr int kPhashFpBits = 10;
constexpr int kPhashLocBits = 22;

inline uint64_t phash_mix(uint64_t h, uint64_t seed) {
  uint64_t x = h ^ (kPhashGolden * (seed + 1));
  x ^= x >> 33;
  x *= kPhashMixK;
  return x ^ (x >> 29);
}

// Lemire multiply-shift reduction of a 32-bit value onto [0, range):
// one multiply where a `%` costs a 20-40-cycle divide — the probe
// pays three of these per (key, table) pair, so divisions were the
// measured kernel bottleneck. range < 2^32, v32 < 2^32: exact in u64.
inline uint64_t phash_r32(uint64_t v32, uint64_t range) {
  return (v32 * range) >> 32;
}

// the (bucket, base position, step) triple of one mixed hash — shared
// verbatim by build and probe (and mirrored bit-for-bit by the Python
// fallback in storage/phash.py: these formulas are FORMAT, the stored
// seed/ts/nb only mean anything under them). With a PRIME ts every
// delta in [1, ts-1] is coprime, so (p0 + d*delta) % ts walks the
// whole table — the one remaining division is the modular step the
// displacement search exploits.
inline void phash_bpd(uint64_t x, uint64_t ts, uint64_t nb,
                      uint64_t* bucket, uint64_t* p0, uint64_t* delta) {
  *bucket = phash_r32(x >> 32, nb);
  *p0 = phash_r32(x & 0xFFFFFFFFull, ts);
  *delta = 1 + phash_r32((x >> 17) & 0xFFFFFFFFull, ts - 1);
}

}  // namespace

// CHD construction: bucket the n (hash, loc) pairs, place buckets in
// decreasing-size order (ties by bucket id), and for each bucket find
// the smallest displacement d (uint16) whose positions are distinct
// and empty. Returns 0 on success (-1: some bucket unplaceable or an
// entry collided with the empty sentinel — the caller reseeds, then
// stamps the run "no phash"). One call builds a whole run's index —
// the writer-side "one vectorized pass" contract (the Python loop
// form pays ~n/4 interpreter iterations; this pays none).
int32_t pegasus_phash_build(const uint64_t* hashes, const uint32_t* locs,
                            int64_t n, uint64_t seed, int64_t ts,
                            int64_t nb, uint16_t* disp_out,
                            uint32_t* slots_out) {
  if (n <= 0 || ts < 3 || nb < 1) return -1;
  int64_t* bucket = static_cast<int64_t*>(malloc(sizeof(int64_t) * n));
  int64_t* p0 = static_cast<int64_t*>(malloc(sizeof(int64_t) * n));
  int64_t* delta = static_cast<int64_t*>(malloc(sizeof(int64_t) * n));
  uint32_t* entry = static_cast<uint32_t*>(malloc(sizeof(uint32_t) * n));
  int64_t* counts = static_cast<int64_t*>(calloc(nb + 1, sizeof(int64_t)));
  int64_t* starts = static_cast<int64_t*>(malloc(sizeof(int64_t) * (nb + 1)));
  int64_t* order = static_cast<int64_t*>(malloc(sizeof(int64_t) * n));
  int64_t* border = static_cast<int64_t*>(malloc(sizeof(int64_t) * nb));
  bool ok = bucket && p0 && delta && entry && counts && starts && order &&
            border;
  int32_t rc = -1;
  if (ok) {
    ok = true;
    for (int64_t i = 0; i < n; ++i) {
      const uint64_t x = phash_mix(hashes[i], seed);
      uint64_t b_, p_, d_;
      phash_bpd(x, static_cast<uint64_t>(ts), static_cast<uint64_t>(nb),
                &b_, &p_, &d_);
      bucket[i] = static_cast<int64_t>(b_);
      p0[i] = static_cast<int64_t>(p_);
      delta[i] = static_cast<int64_t>(d_);
      const uint32_t fp =
          static_cast<uint32_t>(x >> (64 - kPhashFpBits));
      entry[i] = (fp << kPhashLocBits) | locs[i];
      if (entry[i] == kPhashEmpty) ok = false;  // sentinel clash: reseed
      counts[bucket[i]]++;
    }
    if (ok) {
      // counting sort: keys grouped by bucket, stable in file order
      starts[0] = 0;
      for (int64_t b = 0; b < nb; ++b) starts[b + 1] = starts[b] + counts[b];
      {
        int64_t* cur = static_cast<int64_t*>(
            malloc(sizeof(int64_t) * nb));
        if (cur == nullptr) {
          ok = false;
        } else {
          std::memcpy(cur, starts, sizeof(int64_t) * nb);
          for (int64_t i = 0; i < n; ++i) order[cur[bucket[i]]++] = i;
          free(cur);
        }
      }
    }
    if (ok) {
      for (int64_t b = 0; b < nb; ++b) border[b] = b;
      std::sort(border, border + nb, [&](int64_t a, int64_t b2) {
        if (counts[a] != counts[b2]) return counts[a] > counts[b2];
        return a < b2;
      });
      for (int64_t s = 0; s < ts; ++s) slots_out[s] = kPhashEmpty;
      std::memset(disp_out, 0, sizeof(uint16_t) * nb);
      int64_t pos[64];  // bucket sizes are ~4 at the default geometry
      for (int64_t bi = 0; bi < nb && ok; ++bi) {
        const int64_t b = border[bi];
        const int64_t c = counts[b];
        if (c == 0) continue;
        if (c > 64) {
          ok = false;  // pathological bucket: reseed / fall back
          break;
        }
        const int64_t* ks = order + starts[b];
        bool placed = false;
        for (int64_t d = 0; d < 65536 && !placed; ++d) {
          bool fits = true;
          for (int64_t j = 0; j < c && fits; ++j) {
            const int64_t k = ks[j];
            pos[j] = (p0[k] + d * delta[k]) % ts;
            if (slots_out[pos[j]] != kPhashEmpty) fits = false;
            for (int64_t j2 = 0; j2 < j && fits; ++j2)
              if (pos[j2] == pos[j]) fits = false;
          }
          if (!fits) continue;
          for (int64_t j = 0; j < c; ++j)
            slots_out[pos[j]] = entry[ks[j]];
          disp_out[b] = static_cast<uint16_t>(d);
          placed = true;
        }
        if (!placed) ok = false;
      }
      if (ok) rc = 0;
    }
  }
  free(bucket);
  free(p0);
  free(delta);
  free(entry);
  free(counts);
  free(starts);
  free(order);
  free(border);
  return rc;
}

// Multi-index perfect-hash probe: out[i * n_tables + t] is the packed
// loc of hash i in index t, or 0xFFFFFFFF for a definitive absent.
// The sibling of pegasus_bloom_probe_multi: one call answers a whole
// point-read flush's candidacy AND location matrix against every
// indexed run of a partition — ONE slot gather per (key, run) pair
// where the bloom pays up to k=7 bit probes and still leaves the
// block bisect to do.
// `hit_out` (uint8[n_keys * n_tables]) carries the candidacy verdict
// separately from the loc matrix: the planner's per-cell consumption
// indexes it as python BYTES (the exact C-speed read shape the bloom
// matrix uses — numpy scalar boxing or memoryview unpacking per cell
// measurably lost to it at L0 depth 16), touching the loc matrix only
// for the rare located cells.
void pegasus_phash_probe_multi(const uint64_t* slots_addrs,
                               const uint64_t* disp_addrs,
                               const uint64_t* ts_arr,
                               const uint64_t* nb_arr,
                               const uint64_t* seeds, int64_t n_tables,
                               const uint64_t* hashes, int64_t n_keys,
                               uint32_t* out, uint8_t* hit_out) {
  for (int64_t i = 0; i < n_keys; ++i) {
    const uint64_t h = hashes[i];
    uint32_t* row = out + i * n_tables;
    uint8_t* hrow = hit_out + i * n_tables;
    for (int64_t t = 0; t < n_tables; ++t) {
      const uint32_t* slots = reinterpret_cast<const uint32_t*>(
          static_cast<uintptr_t>(slots_addrs[t]));
      const uint16_t* disp = reinterpret_cast<const uint16_t*>(
          static_cast<uintptr_t>(disp_addrs[t]));
      const uint64_t ts = ts_arr[t];
      const uint64_t x = phash_mix(h, seeds[t]);
      uint64_t b_, p0, delta;
      phash_bpd(x, ts, nb_arr[t], &b_, &p0, &delta);
      const uint64_t pos = (p0 + disp[b_] * delta) % ts;
      const uint32_t e = slots[pos];
      const bool hit =
          e != kPhashEmpty &&
          (e >> kPhashLocBits) ==
              static_cast<uint32_t>(x >> (64 - kPhashFpBits));
      row[t] = hit ? (e & ((1u << kPhashLocBits) - 1)) : kPhashEmpty;
      hrow[t] = hit ? 1 : 0;
    }
  }
}

// Pack n encoded keys (concatenated in `heap`, row i spanning
// [offsets[i], offsets[i+1])) into:
//   keys_out     uint8[n, key_width]   zero-padded rows
//   key_len_out  int32[n]
//   hkl_out      int32[n]              big-endian u16 header
//   hash_lo_out  uint32[n]             crc64 lo lane of pegasus_key_hash
//   valid_out    uint8[n]      0 for malformed rows (len < 2, or a
//                              hashkey_len header exceeding the body)
// Returns 0 on success, -1 if any key exceeds key_width.
int32_t pegasus_pack_records(const uint8_t* heap, const int64_t* offsets,
                             int64_t n, int64_t key_width, uint8_t* keys_out,
                             int32_t* key_len_out, int32_t* hkl_out,
                             uint32_t* hash_lo_out, uint8_t* valid_out) {
  std::memset(keys_out, 0, static_cast<size_t>(n) * key_width);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t start = offsets[i];
    const int64_t len = offsets[i + 1] - start;
    if (len > key_width) return -1;
    const uint8_t* key = heap + start;
    std::memcpy(keys_out + i * key_width, key, len);
    key_len_out[i] = static_cast<int32_t>(len);
    int32_t hkl = 0;
    uint64_t hash = 0;
    bool valid = len >= 2;
    if (valid) {
      hkl = (static_cast<int32_t>(key[0]) << 8) | key[1];
      if (hkl > len - 2) {
        // header claims more hashkey bytes than the key holds: malformed
        // (the Python codec rejects such keys); never read past the row
        valid = false;
        hkl = 0;
      } else {
        // pegasus_key_hash: crc64 of the hashkey region, or of the
        // sortkey region when the hashkey is empty
        const int64_t region_len = hkl > 0 ? hkl : len - 2;
        hash = crc64(key + 2, region_len, 0);
      }
    }
    hkl_out[i] = hkl;
    hash_lo_out[i] = static_cast<uint32_t>(hash & 0xFFFFFFFFu);
    valid_out[i] = valid ? 1 : 0;
  }
  return 0;
}

// Rebuild the zero-padded key matrix of a dcz-encoded block (see
// storage/block_codec.py): per row, the 2-byte big-endian hashkey
// header + the dictionary entry + the sortkey heap slice, memcpy'd
// into a pre-zeroed uint8[n, width] matrix. Rows whose hk_idx is the
// 0xFFFFFFFF sentinel are malformed originals stored raw in the
// sortkey heap and copy back verbatim (no header synthesis).
void pegasus_cblock_decode_keys(const uint8_t* dict_heap,
                                const uint32_t* dict_offs,
                                const uint32_t* hk_idx,
                                const uint8_t* sk_heap,
                                const int64_t* sk_offs,
                                const int32_t* key_len, int64_t n,
                                int64_t width, uint8_t* keys_out) {
  for (int64_t i = 0; i < n; ++i) {
    uint8_t* row = keys_out + i * width;
    const int64_t s0 = sk_offs[i];
    const int64_t sl = sk_offs[i + 1] - s0;
    const uint32_t d = hk_idx[i];
    if (d == 0xFFFFFFFFu) {
      std::memcpy(row, sk_heap + s0, sl);
      continue;
    }
    const uint32_t h0 = dict_offs[d];
    const uint32_t hl = dict_offs[d + 1] - h0;
    row[0] = static_cast<uint8_t>(hl >> 8);
    row[1] = static_cast<uint8_t>(hl & 0xFF);
    std::memcpy(row + 2, dict_heap + h0, hl);
    std::memcpy(row + 2 + hl, sk_heap + s0, sl);
    (void)key_len;
  }
}

// Pattern-filter a column of ragged byte regions (the direct-compute
// probe over a dcz block's sortkey heap, or its hashkey dictionary):
// out[i] = 1 iff region i matches. Semantics mirror the device
// match_filter kernel (ops/predicates.py): an empty pattern matches
// everything; a region shorter than the pattern never matches; types
// are 1=anywhere, 2=prefix, 3=postfix (0=no-filter handled by the
// caller).
void pegasus_region_filter(const uint8_t* heap, const int64_t* offs,
                           int64_t n, const uint8_t* pat, int64_t plen,
                           int32_t ftype, uint8_t* out) {
  if (plen == 0) {
    std::memset(out, 1, static_cast<size_t>(n));
    return;
  }
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* r = heap + offs[i];
    const int64_t rl = offs[i + 1] - offs[i];
    uint8_t ok = 0;
    if (rl >= plen) {
      if (ftype == 2) {  // prefix
        ok = std::memcmp(r, pat, plen) == 0;
      } else if (ftype == 3) {  // postfix
        ok = std::memcmp(r + rl - plen, pat, plen) == 0;
      } else {  // anywhere
        for (int64_t t = 0; t + plen <= rl; ++t) {
          if (r[t] == pat[0] && std::memcmp(r + t, pat, plen) == 0) {
            ok = 1;
            break;
          }
        }
      }
    }
    out[i] = ok;
  }
}

// ---- encoded-domain block subsetting (compaction drop path) ---------
//
// zlib/zstd via dlopen: the value heap of a dcz block may be
// compressed, and the subset must inflate -> gather -> re-compress.
// Linking -lz/-lzstd at build time would make the WHOLE library's
// availability depend on a dev symlink; resolving the .so at first
// use keeps every other kernel alive when a compressor is absent (the
// caller falls back to the Python gather path on rc=-2).
typedef int (*z_uncompress_t)(uint8_t*, unsigned long*, const uint8_t*,
                              unsigned long);
typedef int (*z_compress2_t)(uint8_t*, unsigned long*, const uint8_t*,
                             unsigned long, int);
typedef unsigned long (*z_bound_t)(unsigned long);

namespace {

struct ZlibFns {
  z_uncompress_t uncompress_ = nullptr;
  z_compress2_t compress2_ = nullptr;
  z_bound_t bound_ = nullptr;
  ZlibFns() {
    void* h = dlopen("libz.so.1", RTLD_NOW | RTLD_LOCAL);
    if (h == nullptr) h = dlopen("libz.so", RTLD_NOW | RTLD_LOCAL);
    if (h != nullptr) {
      uncompress_ = reinterpret_cast<z_uncompress_t>(
          dlsym(h, "uncompress"));
      compress2_ = reinterpret_cast<z_compress2_t>(
          dlsym(h, "compress2"));
      bound_ = reinterpret_cast<z_bound_t>(dlsym(h, "compressBound"));
    }
  }
  bool ok() const {
    return uncompress_ != nullptr && compress2_ != nullptr &&
           bound_ != nullptr;
  }
};

const ZlibFns& zlib() {
  static ZlibFns z;  // thread-safe magic static
  return z;
}

// zstd via the same dlopen pattern: level-1 zstd runs ~6x faster than
// zlib-1 at a similar ratio, and compaction's inflate -> gather ->
// re-compress is exactly the path where that factor decides whether
// compressed output beats the disk. Decode handles BOTH heap modes
// (zlib-heap blocks written before the switch keep serving); encode
// prefers zstd and falls back to zlib when libzstd is absent.
typedef size_t (*zstd_compress_t)(void*, size_t, const void*, size_t,
                                  int);
typedef size_t (*zstd_decompress_t)(void*, size_t, const void*, size_t);
typedef size_t (*zstd_bound_t)(size_t);
typedef unsigned (*zstd_iserr_t)(size_t);

struct ZstdFns {
  zstd_compress_t compress_ = nullptr;
  zstd_decompress_t decompress_ = nullptr;
  zstd_bound_t bound_ = nullptr;
  zstd_iserr_t iserr_ = nullptr;
  ZstdFns() {
    void* h = dlopen("libzstd.so.1", RTLD_NOW | RTLD_LOCAL);
    if (h == nullptr) h = dlopen("libzstd.so", RTLD_NOW | RTLD_LOCAL);
    if (h != nullptr) {
      compress_ = reinterpret_cast<zstd_compress_t>(
          dlsym(h, "ZSTD_compress"));
      decompress_ = reinterpret_cast<zstd_decompress_t>(
          dlsym(h, "ZSTD_decompress"));
      bound_ = reinterpret_cast<zstd_bound_t>(
          dlsym(h, "ZSTD_compressBound"));
      iserr_ = reinterpret_cast<zstd_iserr_t>(dlsym(h, "ZSTD_isError"));
    }
  }
  bool ok() const {
    return compress_ != nullptr && decompress_ != nullptr &&
           bound_ != nullptr && iserr_ != nullptr;
  }
};

const ZstdFns& zstd() {
  static ZstdFns z;
  return z;
}

// mirror of block_codec._CBLK_HDR ("<IIQQQIIBBBBBBBx", 48 bytes).
// fmt: 0 = v1 (dcz), 2 = v2 (dcz2: FOR expire_ts + dict-indexed
// hash_lo) — was a zeroed pad byte before dcz2, so old blocks read v1.
#pragma pack(push, 1)
struct CBlkHdr {
  uint32_t n, key_width;
  uint64_t raw_heap, comp_heap, sk_bytes;
  uint32_t dict_n, dict_bytes;
  uint8_t klen_w, vlen_w, idx_w, flags_mode, ets_mode, heap_mode;
  uint8_t fmt, pad;
};
#pragma pack(pop)
static_assert(sizeof(CBlkHdr) == 48, "header layout drift");

// v2 (dcz2) section layouts do NOT keep uint32 sections 4-byte
// aligned (the FOR ets section is 4 + w*n bytes and the narrowed
// klen/vlen/idx columns precede the hash sections), so every u32
// section access goes through memcpy — a single mov on x86, defined
// behavior everywhere else
inline uint32_t ld_u32(const uint8_t* p, int64_t i) {
  uint32_t v;
  std::memcpy(&v, p + 4 * i, 4);
  return v;
}

inline void st_u32(uint8_t* p, int64_t i, uint32_t v) {
  std::memcpy(p + 4 * i, &v, 4);
}

inline int64_t narrow_at(const uint8_t* col, int w, int64_t i) {
  if (w == 1) return col[i];
  if (w == 2) {
    uint16_t v;
    std::memcpy(&v, col + 2 * i, 2);
    return v;
  }
  uint32_t v;
  std::memcpy(&v, col + 4 * i, 4);
  return v;
}

inline void narrow_put(uint8_t* col, int w, int64_t i, int64_t v) {
  if (w == 1) {
    col[i] = static_cast<uint8_t>(v);
  } else if (w == 2) {
    uint16_t x = static_cast<uint16_t>(v);
    std::memcpy(col + 2 * i, &x, 2);
  } else {
    uint32_t x = static_cast<uint32_t>(v);
    std::memcpy(col + 4 * i, &x, 4);
  }
}

constexpr int kHeapRaw = 0;
constexpr int kHeapZlib = 1;
constexpr int kHeapZstd = 2;
constexpr int kZlibLevel = 1;
constexpr int kZstdLevel = 1;

}  // namespace

// Subset a dcz-encoded block ENTIRELY in the encoded domain: keep[i]
// selects rows; the dictionary is re-built from the surviving rows'
// slots (order of first appearance — sorted keys keep equal hashkeys
// adjacent, so the remap is monotone), key/value length columns and
// the sortkey heap gather ragged, and the value heap subsets RAW or
// inflate->gather->re-compress for ZLIB/ZSTD heaps (the compression
// DECISION is inherited from the original block: a heap the encoder
// stored raw stays raw — no probing). `new_ets` (nullable, original indexing)
// replaces the TTL column; with `patch_value_headers` the 4-byte
// big-endian expire_ts header at the start of every kept value is
// rewritten to match (value_schema.h layout). This is the compaction
// drop path: one GIL-free pass replaces Python's decode -> gather ->
// re-encode round trip, whose many small numpy ops serialized the
// whole thread pool on the GIL.
//
// The kernel also emits everything the SST writer needs to append the
// result without re-parsing it on the GIL: per-kept-row crc64 full-key
// hashes for the bloom build (`out_hashes`, nullable — computed
// incrementally over header+dict+sortkey segments, no padded matrix),
// the first/last kept keys (`out_keys`, 2*key_width bytes), and
// `out_meta` = [kept_count, subset_raw_heap_len, first_key_len,
// last_key_len].
//
// Returns bytes written into `out`, or -1 (malformed input /
// out_cap too small), -2 (zlib unavailable for a deflated heap; the
// caller must fall back), -3 (heap inflate/deflate failed).
int64_t pegasus_cblock_subset(const uint8_t* raw, int64_t raw_len,
                              const uint8_t* keep,
                              const uint32_t* new_ets,
                              int32_t patch_value_headers, uint8_t* out,
                              int64_t out_cap, uint64_t* out_hashes,
                              uint8_t* out_keys, int64_t* out_meta) {
  if (raw_len < static_cast<int64_t>(sizeof(CBlkHdr))) return -1;
  CBlkHdr h;
  std::memcpy(&h, raw, sizeof(h));
  const int64_t n = h.n;
  const bool v2 = (h.fmt == 2);
  const int64_t sentinel = (1LL << (8 * h.idx_w)) - 1;
  // input section pointers (v1: ets? | hash | doffs | klen | vlen |
  // idx | flags? | dict | sk | heap; v2 moves the hash section after
  // flags — slot hashes + row-ordered overflow — and the ets section
  // may be FOR-encoded: u32 base + narrowed delta_plus1 per row)
  const uint8_t* p = raw + sizeof(CBlkHdr);
  const uint8_t* in_ets = nullptr;     // raw u32[n] (v1 mode!=0, v2 mode 4)
  const uint8_t* in_ets_d = nullptr;   // v2 FOR deltas
  uint32_t ets_base = 0;
  int ets_w = 0;
  if (v2 && (h.ets_mode == 1 || h.ets_mode == 2)) {
    std::memcpy(&ets_base, p, 4);
    p += 4;
    in_ets_d = p;
    ets_w = h.ets_mode;
    p += ets_w * n;
  } else if (h.ets_mode != 0) {
    in_ets = p;
    p += 4 * n;
  }
  const uint8_t* in_hash = nullptr;   // v1 per-row hash column
  if (!v2) {
    in_hash = p;
    p += 4 * n;
  }
  const uint8_t* in_doffs = p;
  p += 4 * (static_cast<int64_t>(h.dict_n) + 1);
  const uint8_t* in_klen = p;
  p += h.klen_w * n;
  const uint8_t* in_vlen = p;
  p += h.vlen_w * n;
  const uint8_t* in_idx = p;
  p += h.idx_w * n;
  const uint8_t* in_flags = nullptr;
  if (h.flags_mode != 0) {
    in_flags = p;
    p += n;
  }
  const uint8_t* in_slot_hash = nullptr;  // v2 per-dict-slot hash
  const uint8_t* in_over_hash = nullptr;  // v2 row-ordered overflow
  if (v2) {
    int64_t n_over = 0;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t d = narrow_at(in_idx, h.idx_w, i);
      if (d == sentinel || ld_u32(in_doffs, d + 1) == ld_u32(in_doffs, d))
        ++n_over;
    }
    in_slot_hash = p;
    p += 4 * static_cast<int64_t>(h.dict_n);
    in_over_hash = p;
    p += 4 * n_over;
  }
  const uint8_t* in_dict = p;
  p += h.dict_bytes;
  const uint8_t* in_sk = p;
  p += h.sk_bytes;
  const uint8_t* in_heap = p;
  if (p + h.comp_heap > raw + raw_len) return -1;
  // per-row expire_ts independent of the stored encoding
  const auto ets_at = [&](int64_t i) -> uint32_t {
    if (in_ets != nullptr) return ld_u32(in_ets, i);
    if (in_ets_d != nullptr) {
      const int64_t d = narrow_at(in_ets_d, ets_w, i);
      return d == 0 ? 0 : ets_base + static_cast<uint32_t>(d) - 1;
    }
    return 0;
  };

  // pass 1: survivor geometry + monotone dictionary remap
  int64_t* remap = static_cast<int64_t*>(
      malloc(sizeof(int64_t) * (h.dict_n + 1)));
  if (remap == nullptr) return -1;
  for (int64_t d = 0; d <= h.dict_n; ++d) remap[d] = -1;
  int64_t m = 0, new_dict_n = 0, new_dict_bytes = 0, new_sk = 0,
          vsub = 0, out_over = 0;
  bool any_ets = false, any_flags = false;
  uint32_t e_min = 0xFFFFFFFFu, e_max = 0;
  {
    int64_t sk_off = 0;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t kl = narrow_at(in_klen, h.klen_w, i);
      const int64_t d = narrow_at(in_idx, h.idx_w, i);
      const int64_t hk =
          (d == sentinel)
              ? 0
              : static_cast<int64_t>(ld_u32(in_doffs, d + 1)) -
                    ld_u32(in_doffs, d);
      const int64_t sl = (d == sentinel) ? kl : kl - 2 - hk;
      if (keep[i] != 0) {
        ++m;
        if (d != sentinel && remap[d] < 0) {
          remap[d] = new_dict_n++;
          new_dict_bytes += hk;
        }
        if (d == sentinel || hk == 0) ++out_over;
        new_sk += sl;
        vsub += narrow_at(in_vlen, h.vlen_w, i);
        const uint32_t e =
            (new_ets != nullptr) ? new_ets[i] : ets_at(i);
        if (e != 0) {
          any_ets = true;
          if (e < e_min) e_min = e;
          if (e > e_max) e_max = e;
        }
        any_flags = any_flags || (in_flags != nullptr && in_flags[i]);
      }
      sk_off += sl;
    }
    if (sk_off != static_cast<int64_t>(h.sk_bytes)) {
      free(remap);
      return -1;
    }
  }

  // inflate the value heap if compressed (subsetting needs raw bytes)
  const uint8_t* heap_raw = in_heap;
  uint8_t* inflated = nullptr;
  if (h.heap_mode == kHeapZlib || h.heap_mode == kHeapZstd) {
    const bool is_zstd = (h.heap_mode == kHeapZstd);
    if (is_zstd ? !zstd().ok() : !zlib().ok()) {
      free(remap);
      return -2;
    }
    inflated = static_cast<uint8_t*>(malloc(h.raw_heap ? h.raw_heap : 1));
    if (inflated == nullptr) {
      free(remap);
      return -3;
    }
    bool bad;
    if (is_zstd) {
      const size_t got = zstd().decompress_(inflated, h.raw_heap,
                                            in_heap, h.comp_heap);
      bad = zstd().iserr_(got) != 0 || got != h.raw_heap;
    } else {
      unsigned long dst = h.raw_heap;
      bad = zlib().uncompress_(inflated, &dst, in_heap, h.comp_heap) !=
                0 ||
            dst != h.raw_heap;
    }
    if (bad) {
      free(inflated);
      free(remap);
      return -3;
    }
    heap_raw = inflated;
  }

  // output header + section layout (output keeps the input's format
  // version: v1 in -> v1 out, v2 in -> v2 out with the FOR width
  // re-derived over the SURVIVOR values)
  CBlkHdr oh = h;
  oh.n = static_cast<uint32_t>(m);
  uint8_t out_ets_mode = 0;
  int64_t ets_sec = 0;
  if (any_ets) {
    if (v2) {
      const uint64_t spread =
          static_cast<uint64_t>(e_max) - e_min + 1;
      out_ets_mode = spread <= 0xFF ? 1 : (spread <= 0xFFFF ? 2 : 4);
      ets_sec = out_ets_mode == 4 ? 4 * m : 4 + out_ets_mode * m;
    } else {
      out_ets_mode = 4;
      ets_sec = 4 * m;
    }
  }
  oh.ets_mode = out_ets_mode;
  oh.flags_mode = any_flags ? 1 : 0;
  oh.dict_n = static_cast<uint32_t>(new_dict_n);
  oh.dict_bytes = static_cast<uint32_t>(new_dict_bytes);
  oh.sk_bytes = static_cast<uint64_t>(new_sk);
  oh.raw_heap = static_cast<uint64_t>(vsub);
  const int64_t hash_sec =
      v2 ? 4 * (new_dict_n + out_over) : 4 * m;
  const int64_t fixed = sizeof(CBlkHdr) + ets_sec + hash_sec +
                        4 * (new_dict_n + 1) + h.klen_w * m +
                        h.vlen_w * m + h.idx_w * m + (any_flags ? m : 0) +
                        new_dict_bytes + new_sk;
  if (fixed + vsub > out_cap) {
    free(inflated);
    free(remap);
    return -1;
  }
  uint8_t* q = out + sizeof(CBlkHdr);
  uint8_t* out_ets = nullptr;   // raw-u32 ets (v1, or v2 mode 4)
  uint8_t* out_ets_d = nullptr;  // v2 FOR deltas
  if (out_ets_mode == 4) {
    out_ets = q;
    q += 4 * m;
  } else if (out_ets_mode != 0) {
    std::memcpy(q, &e_min, 4);  // FOR base = min nonzero survivor
    q += 4;
    out_ets_d = q;
    q += out_ets_mode * m;
  }
  uint8_t* out_hash = nullptr;        // v1 per-row
  if (!v2) {
    out_hash = q;
    q += 4 * m;
  }
  uint8_t* out_doffs = q;
  q += 4 * (new_dict_n + 1);
  uint8_t* out_klen = q;
  q += h.klen_w * m;
  uint8_t* out_vlen = q;
  q += h.vlen_w * m;
  uint8_t* out_idx = q;
  q += h.idx_w * m;
  uint8_t* out_flags = nullptr;
  if (any_flags) {
    out_flags = q;
    q += m;
  }
  uint8_t* out_slot_hash = nullptr;   // v2 dict-slot hashes
  uint8_t* out_over_hash = nullptr;   // v2 overflow hashes
  if (v2) {
    out_slot_hash = q;
    q += 4 * new_dict_n;
    out_over_hash = q;
    q += 4 * out_over;
  }
  uint8_t* out_dict = q;
  q += new_dict_bytes;
  uint8_t* out_sk = q;
  q += new_sk;
  uint8_t* out_heap = q;  // raw subset lands here (ZLIB re-packs below)

  // dictionary: entries in new-slot order (+ v2 slot hashes riding
  // the same remap)
  st_u32(out_doffs, 0, 0);
  {
    uint32_t cur = 0;
    for (int64_t d = 0; d < h.dict_n; ++d) {
      const int64_t nd = remap[d];
      if (nd < 0) continue;
      const uint32_t len = ld_u32(in_doffs, d + 1) - ld_u32(in_doffs, d);
      std::memcpy(out_dict + cur, in_dict + ld_u32(in_doffs, d), len);
      cur += len;
      st_u32(out_doffs, nd + 1, cur);
      if (v2) st_u32(out_slot_hash, nd, ld_u32(in_slot_hash, d));
    }
  }

  // pass 2: gather survivors (+ bloom hashes and first/last keys)
  {
    int64_t j = 0, sk_off = 0, v_off = 0, osk = 0, ov = 0;
    int64_t in_over_seq = 0, out_over_seq = 0;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t kl = narrow_at(in_klen, h.klen_w, i);
      const int64_t d = narrow_at(in_idx, h.idx_w, i);
      const int64_t hk =
          (d == sentinel)
              ? 0
              : static_cast<int64_t>(ld_u32(in_doffs, d + 1)) -
                    ld_u32(in_doffs, d);
      const int64_t sl = (d == sentinel) ? kl : kl - 2 - hk;
      const int64_t vl = narrow_at(in_vlen, h.vlen_w, i);
      const bool slot_derivable = (d != sentinel) && hk > 0;
      uint32_t hrow = 0;
      if (v2) {
        hrow = slot_derivable ? ld_u32(in_slot_hash, d)
                              : ld_u32(in_over_hash, in_over_seq++);
      } else {
        hrow = ld_u32(in_hash, i);
      }
      if (keep[i] != 0) {
        const uint32_t e =
            (new_ets != nullptr) ? new_ets[i] : ets_at(i);
        if (out_ets != nullptr) st_u32(out_ets, j, e);
        if (out_ets_d != nullptr)
          narrow_put(out_ets_d, out_ets_mode, j,
                     e == 0 ? 0 : static_cast<int64_t>(e) - e_min + 1);
        if (out_hash != nullptr) st_u32(out_hash, j, hrow);
        if (v2 && !slot_derivable)
          st_u32(out_over_hash, out_over_seq++, hrow);
        narrow_put(out_klen, h.klen_w, j, kl);
        narrow_put(out_vlen, h.vlen_w, j, vl);
        narrow_put(out_idx, h.idx_w, j,
                   (d == sentinel) ? sentinel : remap[d]);
        if (out_flags != nullptr)
          out_flags[j] = (in_flags != nullptr) ? in_flags[i] : 0;
        std::memcpy(out_sk + osk, in_sk + sk_off, sl);
        std::memcpy(out_heap + ov, heap_raw + v_off, vl);
        if (patch_value_headers != 0 && new_ets != nullptr && vl >= 4) {
          out_heap[ov] = static_cast<uint8_t>(e >> 24);
          out_heap[ov + 1] = static_cast<uint8_t>(e >> 16);
          out_heap[ov + 2] = static_cast<uint8_t>(e >> 8);
          out_heap[ov + 3] = static_cast<uint8_t>(e);
        }
        if (out_hashes != nullptr) {
          // crc64 over the row's real key bytes, segment-chained
          // (crc64(x, prev) continues prev thanks to the ~init/~final
          // construction) — identical to crc64_rows over the padded
          // matrix rows the writer would otherwise rebuild
          uint64_t c;
          if (d != sentinel) {
            const uint8_t hdr2[2] = {static_cast<uint8_t>(hk >> 8),
                                     static_cast<uint8_t>(hk & 0xFF)};
            c = crc64(hdr2, 2, 0);
            c = crc64(in_dict + ld_u32(in_doffs, d), hk, c);
            c = crc64(in_sk + sk_off, sl, c);
          } else {
            c = crc64(in_sk + sk_off, sl, 0);
          }
          out_hashes[j] = c;
        }
        if (out_keys != nullptr && out_meta != nullptr) {
          // overwrite the last-key slot on every kept row (the final
          // survivor wins); the first row ALSO fills the first-key
          // slot — a single-survivor subset must land in both
          uint8_t* dst = out_keys + h.key_width;
          if (d != sentinel) {
            dst[0] = static_cast<uint8_t>(hk >> 8);
            dst[1] = static_cast<uint8_t>(hk & 0xFF);
            std::memcpy(dst + 2, in_dict + ld_u32(in_doffs, d), hk);
            std::memcpy(dst + 2 + hk, in_sk + sk_off, sl);
          } else {
            std::memcpy(dst, in_sk + sk_off, sl);
          }
          if (j == 0) {
            std::memcpy(out_keys, dst, kl);
            out_meta[2] = kl;
          }
          out_meta[3] = kl;
        }
        osk += sl;
        ov += vl;
        ++j;
      }
      sk_off += sl;
      v_off += vl;
    }
  }
  if (out_meta != nullptr) {
    out_meta[0] = m;
    out_meta[1] = vsub;
  }
  free(inflated);
  free(remap);

  int64_t stored = vsub;
  oh.heap_mode = kHeapRaw;
  if (h.heap_mode != kHeapRaw && vsub > 0) {
    // the original encoder proved this heap compressible; re-compress
    // the subset and keep it when it still clears the 5% bar. zstd
    // when resolvable (even if the input heap was zlib — compaction
    // migrates old heaps forward), zlib otherwise.
    if (zstd().ok()) {
      const size_t bound = zstd().bound_(vsub);
      uint8_t* comp = static_cast<uint8_t*>(malloc(bound));
      if (comp != nullptr) {
        const size_t clen =
            zstd().compress_(comp, bound, out_heap, vsub, kZstdLevel);
        if (zstd().iserr_(clen) == 0 &&
            static_cast<int64_t>(clen) < (vsub * 95) / 100) {
          std::memcpy(out_heap, comp, clen);
          stored = static_cast<int64_t>(clen);
          oh.heap_mode = kHeapZstd;
        }
        free(comp);
      }
    } else if (zlib().ok()) {
      unsigned long bound = zlib().bound_(vsub);
      uint8_t* comp = static_cast<uint8_t*>(malloc(bound));
      if (comp != nullptr) {
        unsigned long clen = bound;
        if (zlib().compress2_(comp, &clen, out_heap, vsub,
                              kZlibLevel) == 0 &&
            static_cast<int64_t>(clen) < (vsub * 95) / 100) {
          std::memcpy(out_heap, comp, clen);
          stored = static_cast<int64_t>(clen);
          oh.heap_mode = kHeapZlib;
        }
        free(comp);
      }
    }
  }
  oh.comp_heap = static_cast<uint64_t>(stored);
  std::memcpy(out, &oh, sizeof(oh));
  return fixed + stored;
}

// Gather `m` selected rows of a columnar block into a packed response
// page: keys concatenated into key_blob, user-data (value minus `hdr`
// header bytes) into val_blob, with running offset columns.
//
// Role parity: the reference's response-assembly loop
// (src/server/pegasus_server_impl.cpp append_key_value_for_multi_get /
// validate_key_value_for_scan) copies each surviving record into the
// response one at a time in C++; our survivors are already columnar, so
// one call packs the whole page.
//
//   keys        uint8[.., key_width]  padded key rows
//   key_len     int32[..]
//   value_offs  uint32[..+1]          row i's value = heap[offs[i],offs[i+1])
//   take        int64[m]              row indices to gather (ascending)
//   hdr         value-header bytes to strip (user data starts after it)
//   key_offs    uint32[m+1]; [0] preset by the caller (chaining base)
//   val_offs    uint32[m+1]; [0] preset; pass val_blob=NULL to skip
//                            values (no_value mode) — offsets still run
// The caller sizes key_blob/val_blob exactly (numpy sums of the same
// columns); this routine only copies.
void pegasus_gather_page(const uint8_t* keys, int64_t key_width,
                         const int32_t* key_len, const uint32_t* value_offs,
                         const uint8_t* heap, const int64_t* take, int64_t m,
                         int32_t hdr, uint8_t* key_blob, uint32_t* key_offs,
                         uint8_t* val_blob, uint32_t* val_offs) {
  uint32_t kpos = key_offs[0];
  uint32_t vpos = val_offs[0];
  for (int64_t i = 0; i < m; ++i) {
    const int64_t row = take[i];
    const int32_t kl = key_len[row];
    std::memcpy(key_blob + kpos, keys + row * key_width, kl);
    kpos += static_cast<uint32_t>(kl);
    key_offs[i + 1] = kpos;
    const uint32_t v0 = value_offs[row];
    const uint32_t v1 = value_offs[row + 1];
    const uint32_t vl = v1 - v0 > static_cast<uint32_t>(hdr)
                            ? v1 - v0 - static_cast<uint32_t>(hdr)
                            : 0;
    if (val_blob != nullptr && vl > 0)
      std::memcpy(val_blob + vpos, heap + v0 + hdr, vl);
    vpos += val_blob != nullptr ? vl : 0;
    val_offs[i + 1] = vpos;
  }
}

// Serve a whole BATCH of scan requests' base-path assembly in one
// call. The caller passes a table of the batch's unique blocks
// (pointer columns) and each request's plan as CSR rows into that
// table; rows are packed into shared key/value arenas with running
// offset columns, one offsets window per request
// ([row_base[r], row_base[r] + count_r]).
//
// Per request r, rows are taken in plan order until wants[r] rows or
// `byte_budget` response bytes (keys + stripped values; keys only when
// no_values[r]). The FIRST row of a request is taken even when it
// alone exceeds the budget (forward-progress guarantee) as long as it
// fits the arenas.
//
// out_state[r]: 0 = plan exhausted, 1 = stopped at wants[r],
//               2 = stopped by the byte budget (truncated),
//               3 = arena capacity hit (caller re-serves r in Python).
void pegasus_scan_serve_batch(
    const uint64_t* keys_ptrs, const int64_t* widths,
    const uint64_t* keylen_ptrs,
    const uint64_t* entry_mask_ptrs,  // PER-ENTRY: flavors sharing a
                                      // block carry different masks
    const uint64_t* voffs_ptrs, const uint64_t* heap_ptrs,
    const uint64_t* ets_ptrs, int64_t n_reqs, const int64_t* entry_start,
    const int64_t* entry_block, const int64_t* entry_lo,
    const int64_t* entry_hi, const int64_t* wants,
    const uint8_t* no_values, int64_t byte_budget, int32_t hdr,
    uint8_t* key_blob, int64_t key_cap, uint8_t* val_blob,
    int64_t val_cap, uint32_t* key_offs, uint32_t* val_offs,
    const int64_t* row_base, uint32_t* ets_arena, int64_t* out_count,
    int64_t* out_bytes, int32_t* out_state) {
  uint32_t kpos = 0;
  uint32_t vpos = 0;
  for (int64_t r = 0; r < n_reqs; ++r) {
    const int64_t base = row_base[r];
    const int64_t want = wants[r];
    const int32_t no_value = no_values[r];
    int64_t count = 0;
    int64_t bytes = 0;
    int32_t state = 0;
    key_offs[base] = kpos;
    val_offs[base] = vpos;
    for (int64_t e = entry_start[r];
         e < entry_start[r + 1] && count < want && state == 0; ++e) {
      const int64_t b = entry_block[e];
      const uint8_t* keys = reinterpret_cast<const uint8_t*>(keys_ptrs[b]);
      const int64_t width = widths[b];
      const int32_t* key_len =
          reinterpret_cast<const int32_t*>(keylen_ptrs[b]);
      const uint8_t* mask =
          reinterpret_cast<const uint8_t*>(entry_mask_ptrs[e]);
      const uint32_t* voffs =
          reinterpret_cast<const uint32_t*>(voffs_ptrs[b]);
      const uint8_t* heap = reinterpret_cast<const uint8_t*>(heap_ptrs[b]);
      const uint32_t* ets = reinterpret_cast<const uint32_t*>(ets_ptrs[b]);
      const int64_t hi = entry_hi[e];
      for (int64_t row = entry_lo[e]; row < hi; ++row) {
        if (!mask[row]) continue;
        const int32_t kl = key_len[row];
        const uint32_t v0 = voffs[row];
        const uint32_t v1 = voffs[row + 1];
        const uint32_t vl = (!no_value && v1 - v0 > (uint32_t)hdr)
                                ? v1 - v0 - (uint32_t)hdr
                                : 0;
        const int64_t row_bytes = kl + (int64_t)vl;
        if ((uint64_t)kpos + (uint64_t)kl > (uint64_t)key_cap ||
            (uint64_t)vpos + (uint64_t)vl > (uint64_t)val_cap) {
          state = 3;  // arena full: this request re-serves in Python
          break;
        }
        if (count > 0 && bytes + row_bytes > byte_budget) {
          state = 2;
          break;
        }
        std::memcpy(key_blob + kpos, keys + row * width, kl);
        kpos += (uint32_t)kl;
        key_offs[base + count + 1] = kpos;
        if (vl > 0) std::memcpy(val_blob + vpos, heap + v0 + hdr, vl);
        vpos += vl;
        val_offs[base + count + 1] = vpos;
        if (ets_arena) ets_arena[base - r + count] = ets[row];
        bytes += row_bytes;
        ++count;
        if (count >= want) {
          state = 1;
          break;
        }
      }
    }
    out_count[r] = count;
    out_bytes[r] = bytes;
    out_state[r] = state;
  }
}

}  // extern "C"
