"""Native host runtime (C++ via ctypes).

The reference's host hot loops are C++ (rDSN runtime + server codecs);
ours live here. The library builds on first import with the toolchain in
the image (g++); everything degrades gracefully to the pure-Python paths
when the toolchain or the build is unavailable — `available()` says which
mode is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "packer.cpp")
_SO = os.path.join(_DIR, "libpegasus_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process: concurrent
        # builders must not interleave writes into one tmp file
        result = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", tmp, "-ldl"],
            capture_output=True, timeout=120)
        if result.returncode != 0:
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        def bind():
            lib = ctypes.CDLL(_SO)
            lib.pegasus_crc64.restype = ctypes.c_uint64
            lib.pegasus_crc64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.pegasus_crc32.restype = ctypes.c_uint32
            lib.pegasus_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                          ctypes.c_uint32]
            lib.pegasus_crc64_rows.restype = None
            lib.pegasus_crc64_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p]
            lib.pegasus_bloom_probe_multi.restype = None
            lib.pegasus_bloom_probe_multi.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p]
            lib.pegasus_phash_build.restype = ctypes.c_int32
            lib.pegasus_phash_build.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.pegasus_phash_probe_multi.restype = None
            lib.pegasus_phash_probe_multi.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p]
            lib.pegasus_pack_records.restype = ctypes.c_int32
            lib.pegasus_pack_records.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
            lib.pegasus_cblock_decode_keys.restype = None
            lib.pegasus_cblock_decode_keys.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
            lib.pegasus_region_filter.restype = None
            lib.pegasus_region_filter.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_void_p]
            lib.pegasus_cblock_subset.restype = ctypes.c_int64
            lib.pegasus_cblock_subset.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            lib.pegasus_gather_page.restype = None
            lib.pegasus_gather_page.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
            lib.pegasus_scan_serve_batch.restype = None
            lib.pegasus_scan_serve_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
            return lib

        try:
            _lib = bind()
        except (OSError, AttributeError):
            # unloadable, or a STALE prebuilt .so missing a newer symbol
            # (mtime-preserving restore tools defeat the rebuild check):
            # one rebuild attempt, else degrade to the Python paths
            try:
                os.remove(_SO)
            except OSError:
                pass
            if not _build():
                return None
            try:
                _lib = bind()
            except (OSError, AttributeError):
                return None
        return _lib


def available() -> bool:
    return _load() is not None


def crc64_native(data: bytes) -> int:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return int(lib.pegasus_crc64(data, len(data)))


def crc64_rows_fn():
    """The batched crc64-over-padded-rows function, or None when the
    native library is unavailable (base.crc.crc64_rows falls back to
    the vectorized numpy loop)."""
    lib = _load()
    if lib is None:
        return None

    def crc64_rows_native(rows, lens, out) -> None:
        # rows: C-contiguous uint8[n, width]; lens: int64[n];
        # out: uint64[n] — filled in place
        lib.pegasus_crc64_rows(
            rows.ctypes.data, lens.ctypes.data, rows.shape[0],
            rows.shape[1], out.ctypes.data)

    return crc64_rows_native


def bloom_probe_multi_fn():
    """The multi-filter bloom probe, or None when the native library is
    unavailable (storage.bloom.MultiProbe falls back to scalar walks)."""
    lib = _load()
    if lib is None:
        return None

    def probe(addrs, masks, ks, n_filters, hashes, n_keys, out) -> None:
        # addrs/masks uint64[n_filters], ks int32[n_filters],
        # hashes uint64[n_keys], out uint8[n_keys * n_filters]
        lib.pegasus_bloom_probe_multi(
            addrs.ctypes.data, masks.ctypes.data, ks.ctypes.data,
            n_filters, hashes.ctypes.data, n_keys, out.ctypes.data)

    return probe


def phash_build_fn():
    """The CHD perfect-hash index build (see packer.cpp
    pegasus_phash_build), or None when the native library is
    unavailable (storage.phash falls back to the Python CHD loop —
    bit-identical output, per-bucket interpreter cost)."""
    import numpy as np

    lib = _load()
    if lib is None:
        return None

    def build(hashes, locs, seed: int, ts: int, nb: int):
        """(slots uint32[ts], disp uint16[nb]) or None when this seed
        cannot place every bucket (the caller reseeds)."""
        slots = np.empty(ts, dtype=np.uint32)
        disp = np.empty(nb, dtype=np.uint16)
        rc = lib.pegasus_phash_build(
            hashes.ctypes.data, locs.ctypes.data, hashes.shape[0],
            seed, ts, nb, disp.ctypes.data, slots.ctypes.data)
        if rc != 0:
            return None
        return slots, disp

    return build


def phash_probe_multi_fn():
    """The multi-index perfect-hash probe (the bloom multi-probe's
    sibling), or None when the native library is unavailable
    (storage.phash.PHashMultiProbe falls back to per-index vectorized
    numpy probes)."""
    lib = _load()
    if lib is None:
        return None

    def probe(fixed_ptrs, n_tables, hashes, n_keys, out,
              hit_out) -> None:
        # fixed_ptrs: the five per-table geometry pointers
        # (slots_addrs/disp_addrs/ts/nb/seeds uint64[n_tables]),
        # pre-resolved by the caller — .ctypes.data costs ~0.4 us per
        # access and the probe runs once per read flush; hashes
        # uint64[n_keys], out uint32[n_keys * n_tables], hit_out
        # uint8[n_keys * n_tables]
        lib.pegasus_phash_probe_multi(
            *fixed_ptrs, n_tables, hashes.ctypes.data, n_keys,
            out.ctypes.data, hit_out.ctypes.data)

    return probe


def crc32_fn():
    """The CRC-32C buffer function, or None when the native library is
    unavailable (base.crc falls back to its Python loop)."""
    lib = _load()
    if lib is None:
        return None

    def crc32_native(data: bytes, init_crc: int = 0) -> int:
        return int(lib.pegasus_crc32(bytes(data), len(data),
                                     init_crc & 0xFFFFFFFF))

    return crc32_native


def pack_records(keys, key_width: int):
    """Pack a list of encoded keys into columnar arrays in one native call.

    Returns (keys[n, key_width] uint8, key_len int32[n], hashkey_len
    int32[n], hash_lo uint32[n], valid bool[n]) or None when the native
    library is unavailable (callers fall back to the Python packer).
    """
    import numpy as np

    lib = _load()
    if lib is None:
        return None
    n = len(keys)
    heap = b"".join(keys)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    heap_arr = np.frombuffer(heap, dtype=np.uint8)
    keys_out = np.empty((n, key_width), dtype=np.uint8)
    key_len = np.empty(n, dtype=np.int32)
    hkl = np.empty(n, dtype=np.int32)
    hash_lo = np.empty(n, dtype=np.uint32)
    valid = np.empty(n, dtype=np.uint8)
    rc = lib.pegasus_pack_records(
        heap_arr.ctypes.data if n else None,
        offsets.ctypes.data, n, key_width,
        keys_out.ctypes.data, key_len.ctypes.data, hkl.ctypes.data,
        hash_lo.ctypes.data, valid.ctypes.data)
    if rc != 0:
        return None
    return keys_out, key_len, hkl, hash_lo, valid.astype(bool)


def cblock_decode_keys_fn():
    """Key-matrix rebuild for dcz-encoded blocks, or None when the
    native library is unavailable (block_codec falls back to numpy
    ragged scatters)."""
    lib = _load()
    if lib is None:
        return None

    def decode_keys(dict_heap, dict_offs, hk_idx, sk_heap, sk_offs,
                    key_len, n, width, out) -> None:
        lib.pegasus_cblock_decode_keys(
            dict_heap.ctypes.data if dict_heap.size else None,
            dict_offs.ctypes.data, hk_idx.ctypes.data,
            sk_heap.ctypes.data if sk_heap.size else None,
            sk_offs.ctypes.data, key_len.ctypes.data, n, width,
            out.ctypes.data)

    return decode_keys


def cblock_subset_fn():
    """Encoded-domain block subsetting for the compaction drop path
    (see packer.cpp pegasus_cblock_subset), or None when the native
    library is unavailable (bulk compaction falls back to the Python
    decode -> gather -> re-encode path)."""
    import numpy as np

    lib = _load()
    if lib is None:
        return None

    def subset(raw, raw_heap_len: int, key_width: int, keep, new_ets,
               patch_value_headers: bool, want_hashes: bool):
        """(encoded bytes, crc64 hashes|None, kept n, subset raw heap
        len, first_key, last_key), or None when the kernel cannot take
        this block (compressed heap with no zlib/zstd resolvable)."""
        a = raw if isinstance(raw, np.ndarray) \
            else np.frombuffer(raw, dtype=np.uint8)
        a = np.ascontiguousarray(a)
        keep_u8 = np.ascontiguousarray(keep, dtype=np.uint8)
        if new_ets is not None:
            new_ets = np.ascontiguousarray(new_ets, dtype=np.uint32)
        # margin covers v2 column growth: a subset can widen a
        # FOR-encoded expire_ts section back to raw u32 (new_ets
        # spreading past u16) — up to +4 bytes/row over the input
        out = np.empty(a.size + raw_heap_len + 4 * keep_u8.size + 4096,
                       dtype=np.uint8)
        hashes = (np.empty(keep_u8.size, dtype=np.uint64)
                  if want_hashes else None)
        out_keys = np.zeros(2 * key_width, dtype=np.uint8)
        out_meta = np.zeros(4, dtype=np.int64)
        rc = lib.pegasus_cblock_subset(
            a.ctypes.data, a.size, keep_u8.ctypes.data,
            new_ets.ctypes.data if new_ets is not None else None,
            1 if patch_value_headers else 0, out.ctypes.data, out.size,
            hashes.ctypes.data if hashes is not None else None,
            out_keys.ctypes.data, out_meta.ctypes.data)
        if rc < 0:
            return None
        m, vsub, fkl, lkl = (int(x) for x in out_meta)
        return (out[:rc].tobytes(),
                hashes[:m].copy() if hashes is not None else None,
                m, vsub, out_keys[:fkl].tobytes(),
                out_keys[key_width:key_width + lkl].tobytes())

    return subset


def region_filter_fn():
    """Ragged-region pattern filter (the encoded-probe primitive), or
    None when the native library is unavailable (predicates falls back
    to the scalar host_match_filter loop)."""
    lib = _load()
    if lib is None:
        return None

    def region_filter(heap, offs, n, pattern: bytes, ftype: int,
                      out) -> None:
        lib.pegasus_region_filter(
            heap.ctypes.data if heap.size else None, offs.ctypes.data,
            n, pattern, len(pattern), ftype, out.ctypes.data)

    return region_filter


def gather_page_fn():
    """The raw page-gather entry point (see packer.cpp
    pegasus_gather_page), or None when the native library is
    unavailable. server/page.py owns the calling convention."""
    lib = _load()
    return None if lib is None else lib.pegasus_gather_page


def scan_serve_fn():
    """The whole-batch scan-assembly entry point (see packer.cpp
    pegasus_scan_serve_batch), or None when the native library is
    unavailable. server/page.py owns the calling convention."""
    lib = _load()
    return None if lib is None else lib.pegasus_scan_serve_batch
