"""ctypes loader + thin wrapper for the native C++ wire client.

Parity role: a second-language client (the reference ships Go/Java/C++
clients over one wire format). The C ABI (wire_client.cpp) is the
bindable surface; this module is the Python convenience binding and the
build-on-first-use loader, following native/__init__.py's pattern.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wire_client.cpp")
_SO = os.path.join(_DIR, "libpegasus_wire_client.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        tmp = f"{_SO}.{os.getpid()}.tmp"
        result = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", tmp],
            capture_output=True, timeout=180)
        if result.returncode != 0:
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        lib = ctypes.CDLL(_SO)
        lib.pegc_open.restype = ctypes.c_void_p
        lib.pegc_open.argtypes = [ctypes.c_char_p] * 6
        lib.pegc_close.argtypes = [ctypes.c_void_p]
        lib.pegc_refresh.argtypes = [ctypes.c_void_p]
        lib.pegc_partition_count.restype = ctypes.c_long
        lib.pegc_partition_count.argtypes = [ctypes.c_void_p]
        lib.pegc_set.restype = ctypes.c_int
        lib.pegc_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_long]
        lib.pegc_del.restype = ctypes.c_int
        lib.pegc_del.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        lib.pegc_get.restype = ctypes.c_int
        lib.pegc_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.pegc_last_error.restype = ctypes.c_char_p
        lib.pegc_last_error.argtypes = [ctypes.c_void_p]
        lib.pegc_crc64.restype = ctypes.c_uint64
        lib.pegc_crc64.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.pegc_multi_get.restype = ctypes.c_int
        lib.pegc_multi_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long)]
        lib.pegc_scan_open.restype = ctypes.c_void_p
        lib.pegc_scan_open.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_long]
        lib.pegc_scan_next.restype = ctypes.c_int
        lib.pegc_scan_next.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.pegc_scan_close.argtypes = [ctypes.c_void_p]
        lib.pegc_check_and_set.restype = ctypes.c_int
        lib.pegc_check_and_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_long,
            ctypes.POINTER(ctypes.c_int)]
        lib.pegc_check_and_mutate.restype = ctypes.c_int
        lib.pegc_check_and_mutate.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        _lib = lib
        return _lib


class NativeClient:
    """The C client, bound: set/get/del over the live cluster wire."""

    def __init__(self, name: str, address_book: dict, metas: list,
                 app_name: str,
                 auth: Optional[Tuple[str, str]] = None) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native wire client unavailable (no g++?)")
        self._lib = lib
        book = ";".join(f"{n}={h}:{p}" for n, (h, p) in
                        address_book.items())
        user, token = auth if auth else ("", "")
        self._h = lib.pegc_open(
            name.encode(), book.encode(), ",".join(metas).encode(),
            app_name.encode(), user.encode(), token.encode())

    def refresh(self) -> bool:
        return self._lib.pegc_refresh(self._h) == 0

    @property
    def partition_count(self) -> int:
        return self._lib.pegc_partition_count(self._h)

    def set(self, hk: bytes, sk: bytes, value: bytes,
            expire_ts: int = 0) -> int:
        return self._lib.pegc_set(self._h, hk, len(hk), sk, len(sk),
                                  value, len(value), expire_ts)

    def get(self, hk: bytes, sk: bytes) -> Tuple[int, bytes]:
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_int(0)
        status = self._lib.pegc_get(self._h, hk, len(hk), sk, len(sk),
                                    buf, cap, ctypes.byref(out_len))
        if status != 0:
            return status, b""
        return 0, buf.raw[:out_len.value]

    def delete(self, hk: bytes, sk: bytes) -> int:
        return self._lib.pegc_del(self._h, hk, len(hk), sk, len(sk))

    def multi_get(self, hk: bytes) -> Tuple[int, dict]:
        """All (sort_key, value) pairs of one hash key."""
        import struct

        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            out_len = ctypes.c_long(0)
            st = self._lib.pegc_multi_get(self._h, hk, len(hk), buf, cap,
                                          ctypes.byref(out_len))
            if st == -2:
                cap = out_len.value + 16
                continue
            if st != 0:
                return st, {}
            blob = buf.raw[:out_len.value]
            (n,) = struct.unpack_from("<I", blob, 0)
            pos = 4
            out = {}
            for _ in range(n):
                (kl,) = struct.unpack_from("<I", blob, pos)
                pos += 4
                k = blob[pos:pos + kl]
                pos += kl
                (vl,) = struct.unpack_from("<I", blob, pos)
                pos += 4
                out[k] = blob[pos:pos + vl]
                pos += vl
            return 0, out

    def scan(self, hk: bytes, batch_size: int = 100):
        """Iterate (sort_key, value) for one hash key via the native
        paging scanner (get_scanner -> scan -> clear_scanner)."""
        s = self._lib.pegc_scan_open(self._h, hk, len(hk), batch_size)
        if not s:
            raise RuntimeError("scan_open failed")
        sk_cap, v_cap = 1 << 16, 1 << 20
        sk_buf = ctypes.create_string_buffer(sk_cap)
        v_buf = ctypes.create_string_buffer(v_cap)
        sk_len = ctypes.c_int(0)
        v_len = ctypes.c_int(0)
        try:
            while True:
                rc = self._lib.pegc_scan_next(
                    s, sk_buf, sk_cap, ctypes.byref(sk_len),
                    v_buf, v_cap, ctypes.byref(v_len))
                if rc == 1:
                    return
                if rc == -3:
                    # row larger than the buffers: grow to the exact
                    # reported sizes and re-read (row not consumed)
                    sk_cap = max(sk_cap, sk_len.value)
                    v_cap = max(v_cap, v_len.value)
                    sk_buf = ctypes.create_string_buffer(sk_cap)
                    v_buf = ctypes.create_string_buffer(v_cap)
                    continue
                if rc != 0:
                    raise RuntimeError(f"scan error {rc}")
                yield (sk_buf.raw[:sk_len.value],
                       v_buf.raw[:v_len.value])
        finally:
            self._lib.pegc_scan_close(s)

    def check_and_set(self, hk: bytes, check_sk: bytes, check_type: int,
                      operand: bytes, set_sk: bytes, set_value: bytes,
                      ttl_seconds: int = 0) -> Tuple[int, bool]:
        exist = ctypes.c_int(0)
        st = self._lib.pegc_check_and_set(
            self._h, hk, len(hk), check_sk, len(check_sk), check_type,
            operand, len(operand), set_sk, len(set_sk),
            set_value, len(set_value), ttl_seconds, ctypes.byref(exist))
        return st, bool(exist.value)

    def check_and_mutate(self, hk: bytes, check_sk: bytes,
                         check_type: int, operand: bytes, mutate_op: int,
                         m_sk: bytes, m_value: bytes = b""
                         ) -> Tuple[int, bool]:
        exist = ctypes.c_int(0)
        st = self._lib.pegc_check_and_mutate(
            self._h, hk, len(hk), check_sk, len(check_sk), check_type,
            operand, len(operand), mutate_op, m_sk, len(m_sk),
            m_value, len(m_value), ctypes.byref(exist))
        return st, bool(exist.value)

    def last_error(self) -> str:
        return self._lib.pegc_last_error(self._h).decode()

    def close(self) -> None:
        if self._h:
            self._lib.pegc_close(self._h)
            self._h = None
