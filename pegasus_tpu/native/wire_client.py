"""ctypes loader + thin wrapper for the native C++ wire client.

Parity role: a second-language client (the reference ships Go/Java/C++
clients over one wire format). The C ABI (wire_client.cpp) is the
bindable surface; this module is the Python convenience binding and the
build-on-first-use loader, following native/__init__.py's pattern.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wire_client.cpp")
_SO = os.path.join(_DIR, "libpegasus_wire_client.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        tmp = f"{_SO}.{os.getpid()}.tmp"
        result = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", tmp],
            capture_output=True, timeout=180)
        if result.returncode != 0:
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        lib = ctypes.CDLL(_SO)
        lib.pegc_open.restype = ctypes.c_void_p
        lib.pegc_open.argtypes = [ctypes.c_char_p] * 6
        lib.pegc_close.argtypes = [ctypes.c_void_p]
        lib.pegc_refresh.argtypes = [ctypes.c_void_p]
        lib.pegc_partition_count.restype = ctypes.c_long
        lib.pegc_partition_count.argtypes = [ctypes.c_void_p]
        lib.pegc_set.restype = ctypes.c_int
        lib.pegc_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_long]
        lib.pegc_del.restype = ctypes.c_int
        lib.pegc_del.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        lib.pegc_get.restype = ctypes.c_int
        lib.pegc_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.pegc_last_error.restype = ctypes.c_char_p
        lib.pegc_last_error.argtypes = [ctypes.c_void_p]
        lib.pegc_crc64.restype = ctypes.c_uint64
        lib.pegc_crc64.argtypes = [ctypes.c_char_p, ctypes.c_int]
        _lib = lib
        return _lib


class NativeClient:
    """The C client, bound: set/get/del over the live cluster wire."""

    def __init__(self, name: str, address_book: dict, metas: list,
                 app_name: str,
                 auth: Optional[Tuple[str, str]] = None) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native wire client unavailable (no g++?)")
        self._lib = lib
        book = ";".join(f"{n}={h}:{p}" for n, (h, p) in
                        address_book.items())
        user, token = auth if auth else ("", "")
        self._h = lib.pegc_open(
            name.encode(), book.encode(), ",".join(metas).encode(),
            app_name.encode(), user.encode(), token.encode())

    def refresh(self) -> bool:
        return self._lib.pegc_refresh(self._h) == 0

    @property
    def partition_count(self) -> int:
        return self._lib.pegc_partition_count(self._h)

    def set(self, hk: bytes, sk: bytes, value: bytes,
            expire_ts: int = 0) -> int:
        return self._lib.pegc_set(self._h, hk, len(hk), sk, len(sk),
                                  value, len(value), expire_ts)

    def get(self, hk: bytes, sk: bytes) -> Tuple[int, bytes]:
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_int(0)
        status = self._lib.pegc_get(self._h, hk, len(hk), sk, len(sk),
                                    buf, cap, ctypes.byref(out_len))
        if status != 0:
            return status, b""
        return 0, buf.raw[:out_len.value]

    def delete(self, hk: bytes, sk: bytes) -> int:
        return self._lib.pegc_del(self._h, hk, len(hk), sk, len(sk))

    def last_error(self) -> str:
        return self._lib.pegc_last_error(self._h).decode()

    def close(self) -> None:
        if self._h:
            self._lib.pegc_close(self._h)
            self._h = None
