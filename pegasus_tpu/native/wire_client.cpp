// Native C++ wire client for pegasus_tpu.
//
// Role parity: the reference ships native clients (src/client_lib C++,
// go-client, java-client) speaking the cluster's wire format; this is the
// pegasus_tpu equivalent — a self-contained C++17 library speaking the
// PGT1 frame + tagged value grammar (pegasus_tpu/rpc/message.py), doing
// client-side partition resolution (query_config -> crc64 routing ->
// primary dispatch, parity src/client/partition_resolver.cpp:48) with a
// C ABI so any language with FFI can bind it (the test drives it from
// ctypes against a live multi-process onebox).
//
// CRC tables re-derive from the same polynomial bit-specs as
// src/utils/crc.cpp (crc64 routing must be bit-identical everywhere).

#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <map>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

// ---------------- crc (polynomial bit-specs from the reference) ----------

uint64_t crc64_table[256];
uint32_t crc32_table[256];

struct TableInit {
  TableInit() {
    static const int bits64[] = {63, 61, 59, 58, 56, 55, 52, 49, 48, 47, 46,
                                 44, 41, 37, 36, 34, 32, 31, 28, 26, 23, 22,
                                 19, 16, 13, 12, 10, 9,  6,  4,  3,  0};
    uint64_t poly64 = 0;
    for (int n : bits64) poly64 |= 1ULL << (63 - n);
    for (uint32_t i = 0; i < 256; i++) {
      uint64_t k = i;
      for (int j = 0; j < 8; j++) k = (k & 1) ? (k >> 1) ^ poly64 : k >> 1;
      crc64_table[i] = k;
    }
    static const int bits32[] = {28, 27, 26, 25, 23, 22, 20, 19, 18,
                                 14, 13, 11, 10, 9,  8,  6,  0};
    uint32_t poly32 = 0;
    for (int n : bits32) poly32 |= 1U << (31 - n);
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t k = i;
      for (int j = 0; j < 8; j++) k = (k & 1) ? (k >> 1) ^ poly32 : k >> 1;
      crc32_table[i] = k;
    }
  }
} table_init;

uint64_t crc64(const uint8_t* data, size_t n) {
  uint64_t crc = ~0ULL;
  for (size_t i = 0; i < n; i++)
    crc = crc64_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

uint32_t crc32(const uint8_t* data, size_t n) {
  uint32_t crc = ~0U;
  for (size_t i = 0; i < n; i++)
    crc = crc32_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// ---------------- tagged value grammar (rpc/message.py) -------------------

struct Value;
using ValueList = std::vector<Value>;

struct Value {
  enum Kind { NONE, BOOL, INT, UINT, BYTES, STR, LIST, TUPLE, DICT } kind =
      NONE;
  bool b = false;
  int64_t i = 0;
  uint64_t u = 0;
  std::string s;                              // BYTES / STR payload
  std::vector<Value> items;                   // LIST / TUPLE
  std::vector<std::pair<Value, Value>> kv;    // DICT

  static Value none() { return Value{}; }
  static Value boolean(bool v) { Value x; x.kind = BOOL; x.b = v; return x; }
  static Value integer(int64_t v) { Value x; x.kind = INT; x.i = v; return x; }
  static Value uinteger(uint64_t v) {
    Value x; x.kind = UINT; x.u = v; return x;
  }
  static Value bytes(const std::string& v) {
    Value x; x.kind = BYTES; x.s = v; return x;
  }
  static Value str(const std::string& v) {
    Value x; x.kind = STR; x.s = v; return x;
  }
  const Value* get(const std::string& key) const {
    for (auto& p : kv)
      if (p.first.kind == STR && p.first.s == key) return &p.second;
    return nullptr;
  }
  int64_t as_int() const { return kind == UINT ? (int64_t)u : i; }
};

void put_u32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);  // little-endian hosts only (x86/arm LE)
  out.append(b, 4);
}

void encode(std::string& out, const Value& v) {
  switch (v.kind) {
    case Value::NONE: out += 'N'; break;
    case Value::BOOL: out += v.b ? 'T' : 'F'; break;
    case Value::INT: {
      out += 'i';
      char b[8];
      memcpy(b, &v.i, 8);
      out.append(b, 8);
      break;
    }
    case Value::UINT: {
      if (v.u <= 0x7FFFFFFFFFFFFFFFULL) {
        Value w = Value::integer((int64_t)v.u);
        encode(out, w);
      } else {
        out += 'u';
        char b[8];
        memcpy(b, &v.u, 8);
        out.append(b, 8);
      }
      break;
    }
    case Value::BYTES:
      out += 'b';
      put_u32(out, v.s.size());
      out += v.s;
      break;
    case Value::STR:
      out += 's';
      put_u32(out, v.s.size());
      out += v.s;
      break;
    case Value::LIST:
    case Value::TUPLE:
      out += v.kind == Value::LIST ? 'l' : 't';
      put_u32(out, v.items.size());
      for (auto& item : v.items) encode(out, item);
      break;
    case Value::DICT:
      out += 'm';
      put_u32(out, v.kv.size());
      for (auto& p : v.kv) {
        encode(out, p.first);
        encode(out, p.second);
      }
      break;
  }
}

struct Decoder {
  const uint8_t* data;
  size_t len;
  size_t pos = 0;
  bool ok = true;

  uint32_t u32() {
    if (pos + 4 > len) { ok = false; return 0; }
    uint32_t v;
    memcpy(&v, data + pos, 4);
    pos += 4;
    return v;
  }
  Value value() {
    Value out;
    if (pos >= len) { ok = false; return out; }
    char tag = (char)data[pos++];
    switch (tag) {
      case 'N': break;
      case 'T': out = Value::boolean(true); break;
      case 'F': out = Value::boolean(false); break;
      case 'i': {
        if (pos + 8 > len) { ok = false; break; }
        int64_t v;
        memcpy(&v, data + pos, 8);
        pos += 8;
        out = Value::integer(v);
        break;
      }
      case 'u': {
        if (pos + 8 > len) { ok = false; break; }
        uint64_t v;
        memcpy(&v, data + pos, 8);
        pos += 8;
        out = Value::uinteger(v);
        break;
      }
      case 'd': {  // float: skip payload, surface as INT(0) — unused here
        pos += 8;
        break;
      }
      case 'b':
      case 's': {
        uint32_t n = u32();
        if (pos + n > len) { ok = false; break; }
        out = tag == 'b' ? Value::bytes(std::string((const char*)data + pos, n))
                         : Value::str(std::string((const char*)data + pos, n));
        pos += n;
        break;
      }
      case 'l':
      case 't': {
        uint32_t n = u32();
        out.kind = tag == 'l' ? Value::LIST : Value::TUPLE;
        for (uint32_t i = 0; i < n && ok; i++) out.items.push_back(value());
        break;
      }
      case 'm': {
        uint32_t n = u32();
        out.kind = Value::DICT;
        for (uint32_t i = 0; i < n && ok; i++) {
          Value k = value();
          Value v = value();
          out.kv.emplace_back(std::move(k), std::move(v));
        }
        break;
      }
      case 'D': {  // registered dataclass: decode as DICT of field order
        uint32_t nn = u32();
        if (pos + nn > len) { ok = false; break; }
        std::string name((const char*)data + pos, nn);
        pos += nn;
        uint32_t nf = u32();
        out.kind = Value::DICT;
        out.kv.emplace_back(Value::str("__dataclass__"), Value::str(name));
        for (uint32_t i = 0; i < nf && ok; i++) {
          Value v = value();
          out.kv.emplace_back(Value::integer(i), std::move(v));
        }
        break;
      }
      default:
        ok = false;
    }
    return out;
  }
};

// ---------------- frame ---------------------------------------------------

std::string make_frame(const std::string& src, const std::string& dst,
                       const std::string& msg_type, const Value& payload) {
  std::string body;
  encode(body, Value::str(src));
  encode(body, Value::str(dst));
  encode(body, Value::str(msg_type));
  encode(body, payload);
  std::string frame = "PGT1";
  put_u32(frame, body.size());
  put_u32(frame, crc32((const uint8_t*)body.data(), body.size()));
  frame += body;
  return frame;
}

// ---------------- client --------------------------------------------------

struct Endpoint {
  std::string host;
  int port;
};

struct Client {
  std::string name;
  std::string app_name;
  std::string user, token;
  std::map<std::string, Endpoint> book;
  std::map<std::string, int> socks;
  std::vector<std::string> metas;
  int64_t app_id = -1;
  int64_t partition_count = 0;
  std::vector<std::string> primaries;
  uint64_t next_rid = 1;
  std::string last_error;

  int sock_for(const std::string& node) {
    auto it = socks.find(node);
    if (it != socks.end()) return it->second;
    auto b = book.find(node);
    if (b == book.end()) return -1;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(b->second.port);
    if (inet_pton(AF_INET, b->second.host.c_str(), &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
      close(fd);
      return -1;
    }
    socks[node] = fd;
    return fd;
  }

  void drop_sock(const std::string& node) {
    auto it = socks.find(node);
    if (it != socks.end()) {
      close(it->second);
      socks.erase(it);
    }
  }

  bool send_msg(const std::string& node, const std::string& msg_type,
                const Value& payload) {
    int fd = sock_for(node);
    if (fd < 0) return false;
    std::string frame = make_frame(name, node, msg_type, payload);
    size_t off = 0;
    while (off < frame.size()) {
      ssize_t n = ::send(fd, frame.data() + off, frame.size() - off, 0);
      if (n <= 0) {
        drop_sock(node);
        return false;
      }
      off += (size_t)n;
    }
    return true;
  }

  // blocking read of ONE frame from the node's connection
  bool recv_msg(const std::string& node, std::string* msg_type,
                Value* payload) {
    int fd = sock_for(node);
    if (fd < 0) return false;
    auto read_exact = [&](uint8_t* buf, size_t n) -> bool {
      size_t off = 0;
      while (off < n) {
        ssize_t r = ::recv(fd, buf + off, n - off, 0);
        if (r <= 0) return false;
        off += (size_t)r;
      }
      return true;
    };
    uint8_t hdr[12];
    if (!read_exact(hdr, 12) || memcmp(hdr, "PGT1", 4) != 0) {
      drop_sock(node);
      return false;
    }
    uint32_t blen, want;
    memcpy(&blen, hdr + 4, 4);
    memcpy(&want, hdr + 8, 4);
    std::vector<uint8_t> body(blen);
    if (!read_exact(body.data(), blen) ||
        crc32(body.data(), blen) != want) {
      drop_sock(node);
      return false;
    }
    Decoder d{body.data(), body.size()};
    d.value();  // src
    d.value();  // dst
    Value mt = d.value();
    Value pl = d.value();
    if (!d.ok || mt.kind != Value::STR) return false;
    *msg_type = mt.s;
    *payload = std::move(pl);
    return true;
  }

  Value auth_value() {
    if (user.empty()) return Value::none();
    Value t;
    t.kind = Value::TUPLE;
    t.items.push_back(Value::str(user));
    t.items.push_back(Value::str(token));
    return t;
  }

  bool refresh_config() {
    for (auto& meta : metas) {
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("app_name"), Value::str(app_name));
      req.kv.emplace_back(Value::str("rid"),
                          Value::integer((int64_t)next_rid++));
      if (!send_msg(meta, "query_config", req)) continue;
      std::string mt;
      Value reply;
      if (!recv_msg(meta, &mt, &reply) || mt != "query_config_reply")
        continue;
      const Value* err = reply.get("err");
      if (!err || err->as_int() != 0) {
        last_error = "query_config error";
        continue;
      }
      app_id = reply.get("app_id")->as_int();
      partition_count = reply.get("partition_count")->as_int();
      primaries.clear();
      for (auto& cfg : reply.get("configs")->items) {
        const Value* p = cfg.get("primary");
        primaries.push_back(p && p->kind == Value::STR ? p->s : "");
      }
      return true;
    }
    last_error = "no meta reachable";
    return false;
  }

  Value make_gpid(int64_t pidx) {
    Value g;
    g.kind = Value::TUPLE;
    g.items.push_back(Value::integer(app_id));
    g.items.push_back(Value::integer(pidx));
    return g;
  }

  // returns reply payload for a matching {msg_type, rid}; empty on failure
  bool call(const std::string& node, const std::string& send_type,
            Value req, const std::string& reply_type, uint64_t rid,
            Value* out) {
    if (!send_msg(node, send_type, req)) return false;
    for (int i = 0; i < 64; i++) {  // tolerate unrelated frames
      std::string mt;
      Value reply;
      if (!recv_msg(node, &mt, &reply)) return false;
      if (mt != reply_type) continue;
      const Value* r = reply.get("rid");
      if (r && (uint64_t)r->as_int() == rid) {
        *out = std::move(reply);
        return true;
      }
    }
    return false;
  }

  std::string full_key(const std::string& hk, const std::string& sk) {
    std::string key;
    key.push_back((char)((hk.size() >> 8) & 0xFF));
    key.push_back((char)(hk.size() & 0xFF));
    key += hk;
    key += sk;
    return key;
  }

  uint64_t route_hash(const std::string& hk, const std::string& sk) {
    const std::string& basis = hk.empty() ? sk : hk;
    return crc64((const uint8_t*)basis.data(), basis.size());
  }

  int write_op(const std::string& hk, const std::string& sk,
               const std::string& value, int64_t expire_ts, int op) {
    if (app_id < 0 && !refresh_config()) return -1;
    uint64_t h = route_hash(hk, sk);
    for (int attempt = 0; attempt < 4; attempt++) {
      if (attempt && !refresh_config()) return -1;
      int64_t pidx = (int64_t)(h % (uint64_t)partition_count);
      const std::string& primary = primaries[(size_t)pidx];
      if (primary.empty()) continue;
      uint64_t rid = next_rid++;
      Value wop;
      wop.kind = Value::TUPLE;
      wop.items.push_back(Value::integer(op));
      Value args;
      args.kind = Value::TUPLE;
      args.items.push_back(Value::bytes(full_key(hk, sk)));
      if (op == 1) {  // OP_PUT: (key, value, expire_ts)
        args.items.push_back(Value::bytes(value));
        args.items.push_back(Value::integer(expire_ts));
      }
      wop.items.push_back(std::move(args));
      Value ops;
      ops.kind = Value::LIST;
      ops.items.push_back(std::move(wop));
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("gpid"), make_gpid(pidx));
      req.kv.emplace_back(Value::str("rid"), Value::integer((int64_t)rid));
      req.kv.emplace_back(Value::str("ops"), std::move(ops));
      req.kv.emplace_back(Value::str("auth"), auth_value());
      req.kv.emplace_back(Value::str("partition_hash"),
                          Value::uinteger(h));
      Value reply;
      if (!call(primary, "client_write", std::move(req),
                "client_write_reply", rid, &reply))
        continue;
      int64_t err = reply.get("err")->as_int();
      if (err == 0) {
        const Value* results = reply.get("results");
        if (results && !results->items.empty())
          return (int)results->items[0].as_int();
        return 0;
      }
      // retryable state errors: re-resolve; anything else surfaces
      if (err == 13 || err == 14 || err == 53 || err == 56 || err == 5 ||
          err == 6)
        continue;
      return (int)err;
    }
    last_error = "write retries exhausted";
    return -1;
  }

  // returns storage status; fills value on hit
  int read_get(const std::string& hk, const std::string& sk,
               std::string* value) {
    if (app_id < 0 && !refresh_config()) return -1;
    uint64_t h = route_hash(hk, sk);
    for (int attempt = 0; attempt < 4; attempt++) {
      if (attempt && !refresh_config()) return -1;
      int64_t pidx = (int64_t)(h % (uint64_t)partition_count);
      const std::string& primary = primaries[(size_t)pidx];
      if (primary.empty()) continue;
      uint64_t rid = next_rid++;
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("gpid"), make_gpid(pidx));
      req.kv.emplace_back(Value::str("rid"), Value::integer((int64_t)rid));
      req.kv.emplace_back(Value::str("op"), Value::str("get"));
      req.kv.emplace_back(Value::str("args"),
                          Value::bytes(full_key(hk, sk)));
      req.kv.emplace_back(Value::str("auth"), auth_value());
      req.kv.emplace_back(Value::str("partition_hash"),
                          Value::uinteger(h));
      Value reply;
      if (!call(primary, "client_read", std::move(req),
                "client_read_reply", rid, &reply))
        continue;
      int64_t err = reply.get("err")->as_int();
      if (err != 0) {
        if (err == 13 || err == 14 || err == 53 || err == 56 || err == 5 ||
            err == 6)
          continue;
        return (int)err;
      }
      const Value* result = reply.get("result");
      if (!result || result->items.size() < 2) return -1;
      int status = (int)result->items[0].as_int();
      if (status == 0) *value = result->items[1].s;
      return status;
    }
    last_error = "read retries exhausted";
    return -1;
  }
};

}  // namespace

// ---------------- C ABI ---------------------------------------------------

extern "C" {

// address_book: "name=host:port;name=host:port;..."; metas: "meta0,meta1"
void* pegc_open(const char* client_name, const char* address_book,
                const char* metas, const char* app_name, const char* user,
                const char* token) {
  auto* c = new Client();
  c->name = client_name;
  c->app_name = app_name;
  if (user) c->user = user;
  if (token) c->token = token;
  std::string book(address_book);
  size_t pos = 0;
  while (pos < book.size()) {
    size_t end = book.find(';', pos);
    if (end == std::string::npos) end = book.size();
    std::string entry = book.substr(pos, end - pos);
    size_t eq = entry.find('=');
    size_t colon = entry.rfind(':');
    if (eq != std::string::npos && colon != std::string::npos && colon > eq) {
      c->book[entry.substr(0, eq)] = Endpoint{
          entry.substr(eq + 1, colon - eq - 1),
          atoi(entry.c_str() + colon + 1)};
    }
    pos = end + 1;
  }
  std::string ms(metas);
  pos = 0;
  while (pos < ms.size()) {
    size_t end = ms.find(',', pos);
    if (end == std::string::npos) end = ms.size();
    c->metas.push_back(ms.substr(pos, end - pos));
    pos = end + 1;
  }
  return c;
}

void pegc_close(void* handle) {
  auto* c = (Client*)handle;
  for (auto& p : c->socks) close(p.second);
  delete c;
}

int pegc_refresh(void* handle) {
  return ((Client*)handle)->refresh_config() ? 0 : -1;
}

long pegc_partition_count(void* handle) {
  return (long)((Client*)handle)->partition_count;
}

int pegc_set(void* handle, const char* hk, int hklen, const char* sk,
             int sklen, const char* value, int vlen, long expire_ts) {
  return ((Client*)handle)
      ->write_op(std::string(hk, hklen), std::string(sk, sklen),
                 std::string(value, vlen), expire_ts, 1 /*OP_PUT*/);
}

int pegc_del(void* handle, const char* hk, int hklen, const char* sk,
             int sklen) {
  return ((Client*)handle)
      ->write_op(std::string(hk, hklen), std::string(sk, sklen), "", 0,
                 2 /*OP_REMOVE*/);
}

// returns status (0=OK,1=NotFound,<0 transport); on OK writes min(vlen,cap)
// bytes and stores the full length into *out_len
int pegc_get(void* handle, const char* hk, int hklen, const char* sk,
             int sklen, char* out, int out_cap, int* out_len) {
  std::string value;
  int status = ((Client*)handle)
                   ->read_get(std::string(hk, hklen),
                              std::string(sk, sklen), &value);
  if (status == 0) {
    int n = (int)value.size();
    *out_len = n;
    if (n > out_cap) n = out_cap;
    memcpy(out, value.data(), n);
  }
  return status;
}

const char* pegc_last_error(void* handle) {
  return ((Client*)handle)->last_error.c_str();
}

uint64_t pegc_crc64(const char* data, int len) {
  return crc64((const uint8_t*)data, len);
}
}
