// Native C++ wire client for pegasus_tpu.
//
// Role parity: the reference ships native clients (src/client_lib C++,
// go-client, java-client) speaking the cluster's wire format; this is the
// pegasus_tpu equivalent — a self-contained C++17 library speaking the
// PGT1 frame + tagged value grammar (pegasus_tpu/rpc/message.py), doing
// client-side partition resolution (query_config -> crc64 routing ->
// primary dispatch, parity src/client/partition_resolver.cpp:48) with a
// C ABI so any language with FFI can bind it (the test drives it from
// ctypes against a live multi-process onebox).
//
// CRC tables re-derive from the same polynomial bit-specs as
// src/utils/crc.cpp (crc64 routing must be bit-identical everywhere).

#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <map>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

// ---------------- crc (polynomial bit-specs from the reference) ----------

uint64_t crc64_table[256];
uint32_t crc32_table[256];

struct TableInit {
  TableInit() {
    static const int bits64[] = {63, 61, 59, 58, 56, 55, 52, 49, 48, 47, 46,
                                 44, 41, 37, 36, 34, 32, 31, 28, 26, 23, 22,
                                 19, 16, 13, 12, 10, 9,  6,  4,  3,  0};
    uint64_t poly64 = 0;
    for (int n : bits64) poly64 |= 1ULL << (63 - n);
    for (uint32_t i = 0; i < 256; i++) {
      uint64_t k = i;
      for (int j = 0; j < 8; j++) k = (k & 1) ? (k >> 1) ^ poly64 : k >> 1;
      crc64_table[i] = k;
    }
    static const int bits32[] = {28, 27, 26, 25, 23, 22, 20, 19, 18,
                                 14, 13, 11, 10, 9,  8,  6,  0};
    uint32_t poly32 = 0;
    for (int n : bits32) poly32 |= 1U << (31 - n);
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t k = i;
      for (int j = 0; j < 8; j++) k = (k & 1) ? (k >> 1) ^ poly32 : k >> 1;
      crc32_table[i] = k;
    }
  }
} table_init;

uint64_t crc64(const uint8_t* data, size_t n) {
  uint64_t crc = ~0ULL;
  for (size_t i = 0; i < n; i++)
    crc = crc64_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

uint32_t crc32(const uint8_t* data, size_t n) {
  uint32_t crc = ~0U;
  for (size_t i = 0; i < n; i++)
    crc = crc32_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// ---------------- tagged value grammar (rpc/message.py) -------------------

struct Value;
using ValueList = std::vector<Value>;

struct Value {
  enum Kind {
    NONE, BOOL, INT, UINT, BYTES, STR, LIST, TUPLE, DICT, DATACLASS
  } kind = NONE;
  bool b = false;
  int64_t i = 0;
  uint64_t u = 0;
  std::string s;                              // BYTES / STR payload
  std::vector<Value> items;                   // LIST / TUPLE
  std::vector<std::pair<Value, Value>> kv;    // DICT

  static Value none() { return Value{}; }
  static Value boolean(bool v) { Value x; x.kind = BOOL; x.b = v; return x; }
  static Value integer(int64_t v) { Value x; x.kind = INT; x.i = v; return x; }
  static Value uinteger(uint64_t v) {
    Value x; x.kind = UINT; x.u = v; return x;
  }
  static Value bytes(const std::string& v) {
    Value x; x.kind = BYTES; x.s = v; return x;
  }
  static Value str(const std::string& v) {
    Value x; x.kind = STR; x.s = v; return x;
  }
  // registered-dataclass value: s = registry name, items = fields in
  // the dataclass's declaration order (rpc/message.py 'D' grammar)
  static Value dataclass(const std::string& name,
                         std::vector<Value> fields) {
    Value x;
    x.kind = DATACLASS;
    x.s = name;
    x.items = std::move(fields);
    return x;
  }
  // decoded-dataclass field access (Decoder surfaces 'D' as a DICT of
  // {__dataclass__: name, 0: f0, 1: f1, ...})
  const Value* field(int64_t i) const {
    for (auto& p : kv)
      if (p.first.kind == INT && p.first.i == i) return &p.second;
    return nullptr;
  }
  bool is_dataclass(const char* name) const {
    const Value* d = get("__dataclass__");
    return d && d->kind == STR && d->s == name;
  }
  const Value* get(const std::string& key) const {
    for (auto& p : kv)
      if (p.first.kind == STR && p.first.s == key) return &p.second;
    return nullptr;
  }
  int64_t as_int() const { return kind == UINT ? (int64_t)u : i; }
};

void put_u32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);  // little-endian hosts only (x86/arm LE)
  out.append(b, 4);
}

void encode(std::string& out, const Value& v) {
  switch (v.kind) {
    case Value::NONE: out += 'N'; break;
    case Value::BOOL: out += v.b ? 'T' : 'F'; break;
    case Value::INT: {
      out += 'i';
      char b[8];
      memcpy(b, &v.i, 8);
      out.append(b, 8);
      break;
    }
    case Value::UINT: {
      if (v.u <= 0x7FFFFFFFFFFFFFFFULL) {
        Value w = Value::integer((int64_t)v.u);
        encode(out, w);
      } else {
        out += 'u';
        char b[8];
        memcpy(b, &v.u, 8);
        out.append(b, 8);
      }
      break;
    }
    case Value::BYTES:
      out += 'b';
      put_u32(out, v.s.size());
      out += v.s;
      break;
    case Value::STR:
      out += 's';
      put_u32(out, v.s.size());
      out += v.s;
      break;
    case Value::LIST:
    case Value::TUPLE:
      out += v.kind == Value::LIST ? 'l' : 't';
      put_u32(out, v.items.size());
      for (auto& item : v.items) encode(out, item);
      break;
    case Value::DICT:
      out += 'm';
      put_u32(out, v.kv.size());
      for (auto& p : v.kv) {
        encode(out, p.first);
        encode(out, p.second);
      }
      break;
    case Value::DATACLASS:
      out += 'D';
      put_u32(out, v.s.size());
      out += v.s;
      put_u32(out, v.items.size());
      for (auto& item : v.items) encode(out, item);
      break;
  }
}

struct Decoder {
  const uint8_t* data;
  size_t len;
  size_t pos = 0;
  bool ok = true;

  uint32_t u32() {
    if (pos + 4 > len) { ok = false; return 0; }
    uint32_t v;
    memcpy(&v, data + pos, 4);
    pos += 4;
    return v;
  }
  Value value() {
    Value out;
    if (pos >= len) { ok = false; return out; }
    char tag = (char)data[pos++];
    switch (tag) {
      case 'N': break;
      case 'T': out = Value::boolean(true); break;
      case 'F': out = Value::boolean(false); break;
      case 'i': {
        if (pos + 8 > len) { ok = false; break; }
        int64_t v;
        memcpy(&v, data + pos, 8);
        pos += 8;
        out = Value::integer(v);
        break;
      }
      case 'u': {
        if (pos + 8 > len) { ok = false; break; }
        uint64_t v;
        memcpy(&v, data + pos, 8);
        pos += 8;
        out = Value::uinteger(v);
        break;
      }
      case 'd': {  // float: skip payload, surface as INT(0) — unused here
        pos += 8;
        break;
      }
      case 'b':
      case 's': {
        uint32_t n = u32();
        if (pos + n > len) { ok = false; break; }
        out = tag == 'b' ? Value::bytes(std::string((const char*)data + pos, n))
                         : Value::str(std::string((const char*)data + pos, n));
        pos += n;
        break;
      }
      case 'l':
      case 't': {
        uint32_t n = u32();
        out.kind = tag == 'l' ? Value::LIST : Value::TUPLE;
        for (uint32_t i = 0; i < n && ok; i++) out.items.push_back(value());
        break;
      }
      case 'm': {
        uint32_t n = u32();
        out.kind = Value::DICT;
        for (uint32_t i = 0; i < n && ok; i++) {
          Value k = value();
          Value v = value();
          out.kv.emplace_back(std::move(k), std::move(v));
        }
        break;
      }
      case 'D': {  // registered dataclass: decode as DICT of field order
        uint32_t nn = u32();
        if (pos + nn > len) { ok = false; break; }
        std::string name((const char*)data + pos, nn);
        pos += nn;
        uint32_t nf = u32();
        out.kind = Value::DICT;
        out.kv.emplace_back(Value::str("__dataclass__"), Value::str(name));
        for (uint32_t i = 0; i < nf && ok; i++) {
          Value v = value();
          out.kv.emplace_back(Value::integer(i), std::move(v));
        }
        break;
      }
      default:
        ok = false;
    }
    return out;
  }
};

// ---------------- frame ---------------------------------------------------

std::string make_frame(const std::string& src, const std::string& dst,
                       const std::string& msg_type, const Value& payload) {
  std::string body;
  encode(body, Value::str(src));
  encode(body, Value::str(dst));
  encode(body, Value::str(msg_type));
  encode(body, payload);
  std::string frame = "PGT1";
  put_u32(frame, body.size());
  put_u32(frame, crc32((const uint8_t*)body.data(), body.size()));
  frame += body;
  return frame;
}

// ---------------- client --------------------------------------------------

struct Endpoint {
  std::string host;
  int port;
};

struct Client {
  std::string name;
  std::string app_name;
  std::string user, token;
  std::map<std::string, Endpoint> book;
  std::map<std::string, int> socks;
  std::vector<std::string> metas;
  int64_t app_id = -1;
  int64_t partition_count = 0;
  std::vector<std::string> primaries;
  uint64_t next_rid = 1;
  std::string last_error;

  int sock_for(const std::string& node) {
    auto it = socks.find(node);
    if (it != socks.end()) return it->second;
    auto b = book.find(node);
    if (b == book.end()) return -1;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(b->second.port);
    if (inet_pton(AF_INET, b->second.host.c_str(), &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
      close(fd);
      return -1;
    }
    socks[node] = fd;
    return fd;
  }

  void drop_sock(const std::string& node) {
    auto it = socks.find(node);
    if (it != socks.end()) {
      close(it->second);
      socks.erase(it);
    }
  }

  bool send_msg(const std::string& node, const std::string& msg_type,
                const Value& payload) {
    int fd = sock_for(node);
    if (fd < 0) return false;
    std::string frame = make_frame(name, node, msg_type, payload);
    size_t off = 0;
    while (off < frame.size()) {
      ssize_t n = ::send(fd, frame.data() + off, frame.size() - off, 0);
      if (n <= 0) {
        drop_sock(node);
        return false;
      }
      off += (size_t)n;
    }
    return true;
  }

  // blocking read of ONE frame from the node's connection
  bool recv_msg(const std::string& node, std::string* msg_type,
                Value* payload) {
    int fd = sock_for(node);
    if (fd < 0) return false;
    auto read_exact = [&](uint8_t* buf, size_t n) -> bool {
      size_t off = 0;
      while (off < n) {
        ssize_t r = ::recv(fd, buf + off, n - off, 0);
        if (r <= 0) return false;
        off += (size_t)r;
      }
      return true;
    };
    uint8_t hdr[12];
    if (!read_exact(hdr, 12) || memcmp(hdr, "PGT1", 4) != 0) {
      drop_sock(node);
      return false;
    }
    uint32_t blen, want;
    memcpy(&blen, hdr + 4, 4);
    memcpy(&want, hdr + 8, 4);
    std::vector<uint8_t> body(blen);
    if (!read_exact(body.data(), blen) ||
        crc32(body.data(), blen) != want) {
      drop_sock(node);
      return false;
    }
    Decoder d{body.data(), body.size()};
    d.value();  // src
    d.value();  // dst
    Value mt = d.value();
    Value pl = d.value();
    if (!d.ok || mt.kind != Value::STR) return false;
    *msg_type = mt.s;
    *payload = std::move(pl);
    return true;
  }

  Value auth_value() {
    if (user.empty()) return Value::none();
    Value t;
    t.kind = Value::TUPLE;
    t.items.push_back(Value::str(user));
    t.items.push_back(Value::str(token));
    return t;
  }

  bool refresh_config() {
    for (auto& meta : metas) {
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("app_name"), Value::str(app_name));
      req.kv.emplace_back(Value::str("rid"),
                          Value::integer((int64_t)next_rid++));
      if (!send_msg(meta, "query_config", req)) continue;
      std::string mt;
      Value reply;
      if (!recv_msg(meta, &mt, &reply) || mt != "query_config_reply")
        continue;
      const Value* err = reply.get("err");
      if (!err || err->as_int() != 0) {
        last_error = "query_config error";
        continue;
      }
      app_id = reply.get("app_id")->as_int();
      partition_count = reply.get("partition_count")->as_int();
      primaries.clear();
      for (auto& cfg : reply.get("configs")->items) {
        const Value* p = cfg.get("primary");
        primaries.push_back(p && p->kind == Value::STR ? p->s : "");
      }
      return true;
    }
    last_error = "no meta reachable";
    return false;
  }

  Value make_gpid(int64_t pidx) {
    Value g;
    g.kind = Value::TUPLE;
    g.items.push_back(Value::integer(app_id));
    g.items.push_back(Value::integer(pidx));
    return g;
  }

  // returns reply payload for a matching {msg_type, rid}; empty on failure
  bool call(const std::string& node, const std::string& send_type,
            Value req, const std::string& reply_type, uint64_t rid,
            Value* out) {
    if (!send_msg(node, send_type, req)) return false;
    for (int i = 0; i < 64; i++) {  // tolerate unrelated frames
      std::string mt;
      Value reply;
      if (!recv_msg(node, &mt, &reply)) return false;
      if (mt != reply_type) continue;
      const Value* r = reply.get("rid");
      if (r && (uint64_t)r->as_int() == rid) {
        *out = std::move(reply);
        return true;
      }
    }
    return false;
  }

  std::string full_key(const std::string& hk, const std::string& sk) {
    std::string key;
    key.push_back((char)((hk.size() >> 8) & 0xFF));
    key.push_back((char)(hk.size() & 0xFF));
    key += hk;
    key += sk;
    return key;
  }

  uint64_t route_hash(const std::string& hk, const std::string& sk) {
    const std::string& basis = hk.empty() ? sk : hk;
    return crc64((const uint8_t*)basis.data(), basis.size());
  }

  int write_op(const std::string& hk, const std::string& sk,
               const std::string& value, int64_t expire_ts, int op) {
    if (app_id < 0 && !refresh_config()) return -1;
    uint64_t h = route_hash(hk, sk);
    for (int attempt = 0; attempt < 4; attempt++) {
      if (attempt && !refresh_config()) return -1;
      int64_t pidx = (int64_t)(h % (uint64_t)partition_count);
      const std::string& primary = primaries[(size_t)pidx];
      if (primary.empty()) continue;
      uint64_t rid = next_rid++;
      Value wop;
      wop.kind = Value::TUPLE;
      wop.items.push_back(Value::integer(op));
      Value args;
      args.kind = Value::TUPLE;
      args.items.push_back(Value::bytes(full_key(hk, sk)));
      if (op == 1) {  // OP_PUT: (key, value, expire_ts)
        args.items.push_back(Value::bytes(value));
        args.items.push_back(Value::integer(expire_ts));
      }
      wop.items.push_back(std::move(args));
      Value ops;
      ops.kind = Value::LIST;
      ops.items.push_back(std::move(wop));
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("gpid"), make_gpid(pidx));
      req.kv.emplace_back(Value::str("rid"), Value::integer((int64_t)rid));
      req.kv.emplace_back(Value::str("ops"), std::move(ops));
      req.kv.emplace_back(Value::str("auth"), auth_value());
      req.kv.emplace_back(Value::str("partition_hash"),
                          Value::uinteger(h));
      Value reply;
      if (!call(primary, "client_write", std::move(req),
                "client_write_reply", rid, &reply))
        continue;
      int64_t err = reply.get("err")->as_int();
      if (err == 0) {
        const Value* results = reply.get("results");
        if (results && !results->items.empty())
          return (int)results->items[0].as_int();
        return 0;
      }
      // retryable state errors: re-resolve; anything else surfaces
      if (err == 13 || err == 14 || err == 53 || err == 56 || err == 5 ||
          err == 6 || err == 58 || err == 63)
        continue;
      return (int)err;
    }
    last_error = "write retries exhausted";
    return -1;
  }

  // returns storage status; fills value on hit
  int read_get(const std::string& hk, const std::string& sk,
               std::string* value) {
    if (app_id < 0 && !refresh_config()) return -1;
    uint64_t h = route_hash(hk, sk);
    for (int attempt = 0; attempt < 4; attempt++) {
      if (attempt && !refresh_config()) return -1;
      int64_t pidx = (int64_t)(h % (uint64_t)partition_count);
      const std::string& primary = primaries[(size_t)pidx];
      if (primary.empty()) continue;
      uint64_t rid = next_rid++;
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("gpid"), make_gpid(pidx));
      req.kv.emplace_back(Value::str("rid"), Value::integer((int64_t)rid));
      req.kv.emplace_back(Value::str("op"), Value::str("get"));
      req.kv.emplace_back(Value::str("args"),
                          Value::bytes(full_key(hk, sk)));
      req.kv.emplace_back(Value::str("auth"), auth_value());
      req.kv.emplace_back(Value::str("partition_hash"),
                          Value::uinteger(h));
      Value reply;
      if (!call(primary, "client_read", std::move(req),
                "client_read_reply", rid, &reply))
        continue;
      int64_t err = reply.get("err")->as_int();
      if (err != 0) {
        if (err == 13 || err == 14 || err == 53 || err == 56 || err == 5 ||
            err == 6 || err == 58 || err == 63)
          continue;
        return (int)err;
      }
      const Value* result = reply.get("result");
      if (!result || result->items.size() < 2) return -1;
      int status = (int)result->items[0].as_int();
      if (status == 0) *value = result->items[1].s;
      return status;
    }
    last_error = "read retries exhausted";
    return -1;
  }

  // ---- generic routed calls (retry + refresh-on-stale, the same
  // discipline as write_op/read_get) ----------------------------------

  static bool retryable(int64_t err) {
    // 58/63: replica quarantined over storage corruption — the
    // refresh-and-retry lands on the healed primary post-cure
    return err == 13 || err == 14 || err == 53 || err == 56 || err == 5 ||
           err == 6 || err == 58 || err == 63;
  }

  // op result into *result; returns 0 ok, >0 server error, -1 transport
  int read_call(int64_t pidx, const std::string& op, const Value& args,
                bool with_hash, uint64_t h, Value* result) {
    if (app_id < 0 && !refresh_config()) return -1;
    for (int attempt = 0; attempt < 4; attempt++) {
      if (attempt && !refresh_config()) return -1;
      int64_t p =
          with_hash ? (int64_t)(h % (uint64_t)partition_count) : pidx;
      const std::string& primary = primaries[(size_t)p];
      if (primary.empty()) continue;
      uint64_t rid = next_rid++;
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("gpid"), make_gpid(p));
      req.kv.emplace_back(Value::str("rid"), Value::integer((int64_t)rid));
      req.kv.emplace_back(Value::str("op"), Value::str(op));
      req.kv.emplace_back(Value::str("args"), args);
      req.kv.emplace_back(Value::str("auth"), auth_value());
      if (with_hash)
        req.kv.emplace_back(Value::str("partition_hash"),
                            Value::uinteger(h));
      else
        req.kv.emplace_back(Value::str("partition_hash"), Value::none());
      Value reply;
      if (!call(primary, "client_read", std::move(req),
                "client_read_reply", rid, &reply))
        continue;
      int64_t err = reply.get("err")->as_int();
      if (err != 0) {
        if (retryable(err)) continue;
        return (int)err;
      }
      const Value* r = reply.get("result");
      if (r) *result = *r;
      return 0;
    }
    last_error = "read retries exhausted";
    return -1;
  }

  // one-op write with a prebuilt (op_code, request-dataclass) tuple;
  // NOT retried on lost replies (atomic ops would double-apply —
  // same discipline as the Python client for cas/cam)
  int write_call(uint64_t h, int op_code, Value op_args, Value* result) {
    if (app_id < 0 && !refresh_config()) return -1;
    for (int attempt = 0; attempt < 2; attempt++) {
      if (attempt && !refresh_config()) return -1;
      int64_t pidx = (int64_t)(h % (uint64_t)partition_count);
      const std::string& primary = primaries[(size_t)pidx];
      if (primary.empty()) continue;
      uint64_t rid = next_rid++;
      Value wop;
      wop.kind = Value::TUPLE;
      wop.items.push_back(Value::integer(op_code));
      wop.items.push_back(std::move(op_args));
      Value ops;
      ops.kind = Value::LIST;
      ops.items.push_back(std::move(wop));
      Value req;
      req.kind = Value::DICT;
      req.kv.emplace_back(Value::str("gpid"), make_gpid(pidx));
      req.kv.emplace_back(Value::str("rid"), Value::integer((int64_t)rid));
      req.kv.emplace_back(Value::str("ops"), std::move(ops));
      req.kv.emplace_back(Value::str("auth"), auth_value());
      req.kv.emplace_back(Value::str("partition_hash"),
                          Value::uinteger(h));
      Value reply;
      if (!call(primary, "client_write", std::move(req),
                "client_write_reply", rid, &reply))
        return -1;  // ambiguous: do NOT auto-retry an atomic write
      int64_t err = reply.get("err")->as_int();
      if (err == 0) {
        const Value* results = reply.get("results");
        if (!results || results->items.empty()) return -1;
        *result = results->items[0];
        return 0;
      }
      if (retryable(err)) continue;
      return (int)err;
    }
    last_error = "write retries exhausted";
    return -1;
  }
};

// kvs in responses arrive either as a list of KeyValue dataclasses or
// as one columnar ScanPage (key_offs/key_blob/val_offs/val_blob);
// flatten both to (full_key, value) pairs
bool decode_kvs(const Value& kvs,
                std::vector<std::pair<std::string, std::string>>* out) {
  if (kvs.kind == Value::LIST) {
    for (auto& item : kvs.items) {
      if (item.kind != Value::DICT || !item.is_dataclass("KeyValue"))
        return false;
      const Value* k = item.field(0);
      const Value* v = item.field(1);
      if (!k) return false;
      out->emplace_back(k->s, v ? v->s : std::string());
    }
    return true;
  }
  if (kvs.kind == Value::DICT && kvs.is_dataclass("ScanPage")) {
    const Value* ko = kvs.field(0);
    const Value* kb = kvs.field(1);
    const Value* vo = kvs.field(2);
    const Value* vb = kvs.field(3);
    if (!ko || !kb || !vo || !vb) return false;
    size_t n = ko->s.size() / 4;
    if (n == 0) return true;
    n -= 1;
    auto off = [](const std::string& s, size_t i) {
      uint32_t v;
      memcpy(&v, s.data() + 4 * i, 4);
      return v;
    };
    for (size_t i = 0; i < n; i++) {
      out->emplace_back(
          kb->s.substr(off(ko->s, i), off(ko->s, i + 1) - off(ko->s, i)),
          vb->s.substr(off(vo->s, i), off(vo->s, i + 1) - off(vo->s, i)));
    }
    return true;
  }
  return false;
}

// GetScannerRequest in declaration order (server/types.py:273) — the
// wire 'D' grammar is positional, so this list must track the registry
Value make_scanner_request(const std::string& start_key,
                           const std::string& stop_key,
                           int64_t batch_size) {
  std::vector<Value> f;
  f.push_back(Value::bytes(start_key));         // start_key
  f.push_back(Value::bytes(stop_key));          // stop_key
  f.push_back(Value::boolean(true));            // start_inclusive
  f.push_back(Value::boolean(false));           // stop_inclusive
  f.push_back(Value::integer(batch_size));      // batch_size
  f.push_back(Value::boolean(false));           // no_value
  f.push_back(Value::integer(0));               // hash_key_filter_type
  f.push_back(Value::bytes(""));                // hash_key_filter_pattern
  f.push_back(Value::integer(0));               // sort_key_filter_type
  f.push_back(Value::bytes(""));                // sort_key_filter_pattern
  f.push_back(Value::boolean(false));           // validate_partition_hash
  f.push_back(Value::boolean(false));           // return_expire_ts
  f.push_back(Value::boolean(false));           // full_scan
  f.push_back(Value::boolean(false));           // only_return_count
  f.push_back(Value::boolean(false));           // one_page
  return Value::dataclass("GetScannerRequest", std::move(f));
}

// Hashkey scanner: pages through [generate_key(hk, ""), next(hk))
// exactly like the Python ClusterScanner (cluster_client.py:540-586),
// including the context-expired restart past the last served key.
struct Scanner {
  Client* c;
  int64_t pidx;
  std::string start_key, stop_key;
  int64_t batch_size;
  int64_t context_id = INT64_MIN;  // INT64_MIN = no context yet
  std::string last_key;
  std::vector<std::pair<std::string, std::string>> buffer;
  size_t pos = 0;
  bool done = false;
  bool completed = false;  // server said COMPLETED: never restart
  int error = 0;

  bool fetch() {
    while (!done) {
      if (completed) {
        done = true;
        return false;
      }
      Value result;
      int rc;
      if (context_id == INT64_MIN) {
        std::string sk = start_key;
        if (!last_key.empty()) sk = last_key + std::string(1, '\0');
        rc = c->read_call(pidx, "get_scanner",
                          make_scanner_request(sk, stop_key, batch_size),
                          false, 0, &result);
      } else {
        rc = c->read_call(pidx, "scan", Value::integer(context_id),
                          false, 0, &result);
      }
      if (rc != 0) {
        error = rc;
        done = true;
        return false;
      }
      if (result.kind != Value::DICT ||
          !result.is_dataclass("ScanResponse")) {
        error = -1;
        done = true;
        return false;
      }
      const Value* err = result.field(0);
      const Value* kvs = result.field(1);
      const Value* ctx = result.field(2);
      if (!err || err->as_int() != 0) {
        error = err ? (int)err->as_int() : -1;
        done = true;
        return false;
      }
      int64_t new_ctx = ctx ? ctx->as_int() : -1;
      if (new_ctx == -2) {  // SCAN_CONTEXT_ID_NOT_EXIST: restart
        context_id = INT64_MIN;
        continue;
      }
      buffer.clear();
      pos = 0;
      if (kvs && !decode_kvs(*kvs, &buffer)) {
        error = -1;
        done = true;
        return false;
      }
      if (!buffer.empty()) last_key = buffer.back().first;
      if (new_ctx == -1) {
        completed = true;
      } else {
        context_id = new_ctx;
      }
      if (!buffer.empty()) return true;
      // empty page: COMPLETED ends the scan (next loop pass), a live
      // context keeps paging
    }
    return false;
  }
};

}  // namespace

// ---------------- C ABI ---------------------------------------------------

extern "C" {

// address_book: "name=host:port;name=host:port;..."; metas: "meta0,meta1"
void* pegc_open(const char* client_name, const char* address_book,
                const char* metas, const char* app_name, const char* user,
                const char* token) {
  auto* c = new Client();
  c->name = client_name;
  c->app_name = app_name;
  if (user) c->user = user;
  if (token) c->token = token;
  std::string book(address_book);
  size_t pos = 0;
  while (pos < book.size()) {
    size_t end = book.find(';', pos);
    if (end == std::string::npos) end = book.size();
    std::string entry = book.substr(pos, end - pos);
    size_t eq = entry.find('=');
    size_t colon = entry.rfind(':');
    if (eq != std::string::npos && colon != std::string::npos && colon > eq) {
      c->book[entry.substr(0, eq)] = Endpoint{
          entry.substr(eq + 1, colon - eq - 1),
          atoi(entry.c_str() + colon + 1)};
    }
    pos = end + 1;
  }
  std::string ms(metas);
  pos = 0;
  while (pos < ms.size()) {
    size_t end = ms.find(',', pos);
    if (end == std::string::npos) end = ms.size();
    c->metas.push_back(ms.substr(pos, end - pos));
    pos = end + 1;
  }
  return c;
}

void pegc_close(void* handle) {
  auto* c = (Client*)handle;
  for (auto& p : c->socks) close(p.second);
  delete c;
}

int pegc_refresh(void* handle) {
  return ((Client*)handle)->refresh_config() ? 0 : -1;
}

long pegc_partition_count(void* handle) {
  return (long)((Client*)handle)->partition_count;
}

int pegc_set(void* handle, const char* hk, int hklen, const char* sk,
             int sklen, const char* value, int vlen, long expire_ts) {
  return ((Client*)handle)
      ->write_op(std::string(hk, hklen), std::string(sk, sklen),
                 std::string(value, vlen), expire_ts, 1 /*OP_PUT*/);
}

int pegc_del(void* handle, const char* hk, int hklen, const char* sk,
             int sklen) {
  return ((Client*)handle)
      ->write_op(std::string(hk, hklen), std::string(sk, sklen), "", 0,
                 2 /*OP_REMOVE*/);
}

// returns status (0=OK,1=NotFound,<0 transport); on OK writes min(vlen,cap)
// bytes and stores the full length into *out_len
int pegc_get(void* handle, const char* hk, int hklen, const char* sk,
             int sklen, char* out, int out_cap, int* out_len) {
  std::string value;
  int status = ((Client*)handle)
                   ->read_get(std::string(hk, hklen),
                              std::string(sk, sklen), &value);
  if (status == 0) {
    int n = (int)value.size();
    *out_len = n;
    if (n > out_cap) n = out_cap;
    memcpy(out, value.data(), n);
  }
  return status;
}

const char* pegc_last_error(void* handle) {
  return ((Client*)handle)->last_error.c_str();
}

uint64_t pegc_crc64(const char* data, int len) {
  return crc64((const uint8_t*)data, len);
}

// ---- multi_get: all sort keys of one hash key --------------------------
// Packs results into `out` as [u32 n] then n x [u32 sk_len][sk]
// [u32 v_len][v] (sort keys decomposed from the full keys). Returns the
// storage status, or -2 when the packed blob exceeds out_cap (caller
// retries with a bigger buffer; *out_len carries the needed size).
int pegc_multi_get(void* handle, const char* hk, int hklen, char* out,
                   long out_cap, long* out_len) {
  auto* c = (Client*)handle;
  std::string hash_key(hk, hklen);
  uint64_t h = c->route_hash(hash_key, "");
  std::vector<std::pair<std::string, std::string>> rows;
  std::string start_sortkey;
  // the server's one-shot range-read budget returns INCOMPLETE with a
  // resume sort key — page until the range is exhausted, exactly like
  // the Python client's paginate_sortkeys driver
  for (int page = 0; page < 1 << 20; page++) {
    // MultiGetRequest in declaration order (server/types.py:160)
    std::vector<Value> f;
    f.push_back(Value::bytes(hash_key));   // hash_key
    Value empty_list;
    empty_list.kind = Value::LIST;
    f.push_back(empty_list);               // sort_keys (all)
    f.push_back(Value::integer(-1));       // max_kv_count
    f.push_back(Value::integer(-1));       // max_kv_size
    f.push_back(Value::boolean(false));    // no_value
    f.push_back(Value::bytes(start_sortkey));
    f.push_back(Value::bytes(""));         // stop_sortkey
    f.push_back(Value::boolean(true));     // start_inclusive
    f.push_back(Value::boolean(false));    // stop_inclusive
    f.push_back(Value::integer(0));        // sort_key_filter_type
    f.push_back(Value::bytes(""));         // sort_key_filter_pattern
    f.push_back(Value::boolean(false));    // reverse
    Value result;
    int rc = c->read_call(
        0, "multi_get",
        Value::dataclass("MultiGetRequest", std::move(f)), true, h,
        &result);
    if (rc != 0) return rc;
    if (result.kind != Value::DICT ||
        !result.is_dataclass("MultiGetResponse"))
      return -1;
    const Value* err = result.field(0);
    if (!err) return -1;
    int64_t status = err->as_int();
    if (status != 0 && status != 7 /*INCOMPLETE*/) return (int)status;
    const Value* kvs = result.field(1);
    if (kvs && !decode_kvs(*kvs, &rows)) return -1;
    if (status == 0) break;
    const Value* resume = result.field(2);
    if (!resume || resume->kind == Value::NONE) break;
    start_sortkey = resume->s;
  }
  std::string blob;
  put_u32(blob, rows.size());
  for (auto& r : rows) {
    // multi_get kvs carry the SORT KEY in KeyValue.key already
    put_u32(blob, r.first.size());
    blob += r.first;
    put_u32(blob, r.second.size());
    blob += r.second;
  }
  *out_len = (long)blob.size();
  if ((long)blob.size() > out_cap) return -2;
  memcpy(out, blob.data(), blob.size());
  return 0;
}

// ---- scanner: hashkey range scan with paging ---------------------------
void* pegc_scan_open(void* handle, const char* hk, int hklen,
                     long batch_size) {
  auto* c = (Client*)handle;
  if (c->app_id < 0 && !c->refresh_config()) return nullptr;
  std::string hash_key(hk, hklen);
  auto* s = new Scanner();
  s->c = c;
  s->batch_size = batch_size > 0 ? batch_size : 100;
  s->start_key = c->full_key(hash_key, "");
  // adjacent successor of every key with this hashkey prefix
  // (key_schema.generate_next_bytes): drop trailing 0xFF, bump last
  std::string buf = s->start_key;
  int i = (int)buf.size() - 1;
  while (i >= 0 && (uint8_t)buf[i] == 0xFF) i--;
  if (i < 0) {
    s->stop_key = "";  // unbounded
  } else {
    buf[i] = (char)((uint8_t)buf[i] + 1);
    s->stop_key = buf.substr(0, i + 1);
  }
  uint64_t h = c->route_hash(hash_key, "");
  s->pidx = (int64_t)(h % (uint64_t)c->partition_count);
  return s;
}

// 0 = row produced (sort key + value written, lengths via out params,
// truncated at the caps), 1 = exhausted, <0 / >1 = error status
// -3 = a buffer is too small: *sk_len / *v_len carry the needed sizes
// and the row is NOT consumed — the caller re-calls with bigger buffers
int pegc_scan_next(void* scanner, char* sk_out, int sk_cap, int* sk_len,
                   char* v_out, int v_cap, int* v_len) {
  auto* s = (Scanner*)scanner;
  while (true) {
    if (s->pos < s->buffer.size()) {
      auto& row = s->buffer[s->pos];
      // full key = [u16 BE hklen][hashkey][sortkey]
      if (row.first.size() < 2) {
        s->pos++;
        return -1;
      }
      int hkl = ((uint8_t)row.first[0] << 8) | (uint8_t)row.first[1];
      std::string sk = row.first.substr(2 + hkl);
      *sk_len = (int)sk.size();
      *v_len = (int)row.second.size();
      if ((int)sk.size() > sk_cap || (int)row.second.size() > v_cap)
        return -3;
      s->pos++;
      memcpy(sk_out, sk.data(), sk.size());
      memcpy(v_out, row.second.data(), row.second.size());
      return 0;
    }
    if (s->done || !s->fetch()) return s->error ? s->error : 1;
  }
}

void pegc_scan_close(void* scanner) {
  auto* s = (Scanner*)scanner;
  if (s->context_id != INT64_MIN && !s->completed && !s->done) {
    Value result;  // best-effort context release
    s->c->read_call(s->pidx, "clear_scanner",
                    Value::integer(s->context_id), false, 0, &result);
  }
  delete s;
}

// ---- check_and_set / check_and_mutate ----------------------------------
// Returns the storage status; *check_exist reports whether the checked
// value existed (meaningful when return_check_value was requested).
int pegc_check_and_set(void* handle, const char* hk, int hklen,
                       const char* check_sk, int check_sklen,
                       int check_type, const char* operand, int operand_len,
                       const char* set_sk, int set_sklen,
                       const char* set_value, int set_vlen,
                       long ttl_seconds, int* check_exist) {
  auto* c = (Client*)handle;
  std::string hash_key(hk, hklen);
  std::string csk(check_sk, check_sklen);
  std::string ssk(set_sk, set_sklen);
  // CheckAndSetRequest in declaration order (server/types.py:224)
  std::vector<Value> f;
  f.push_back(Value::bytes(hash_key));
  f.push_back(Value::bytes(csk));
  f.push_back(Value::integer(check_type));
  f.push_back(Value::bytes(std::string(operand, operand_len)));
  f.push_back(Value::boolean(csk != ssk));       // set_diff_sort_key
  f.push_back(Value::bytes(ssk));
  f.push_back(Value::bytes(std::string(set_value, set_vlen)));
  f.push_back(Value::integer(ttl_seconds));      // set_expire_ts_seconds
  f.push_back(Value::boolean(true));             // return_check_value
  Value result;
  int rc = c->write_call(
      c->route_hash(hash_key, ""), 6 /*OP_CAS*/,
      Value::dataclass("CheckAndSetRequest", std::move(f)), &result);
  if (rc != 0) return rc;
  if (result.kind == Value::INT || result.kind == Value::UINT)
    return (int)result.as_int();  // per-op status (gate deny/throttle)
  if (result.kind != Value::DICT ||
      !result.is_dataclass("CheckAndSetResponse"))
    return -1;
  const Value* err = result.field(0);
  const Value* exist = result.field(2);
  if (check_exist) *check_exist = exist && exist->b ? 1 : 0;
  return err ? (int)err->as_int() : -1;
}

// One-mutate check_and_mutate: mutate_op 0 = SET, 1 = DELETE
// (MutateOperation, server/types.py:45).
int pegc_check_and_mutate(void* handle, const char* hk, int hklen,
                          const char* check_sk, int check_sklen,
                          int check_type, const char* operand,
                          int operand_len, int mutate_op,
                          const char* m_sk, int m_sklen,
                          const char* m_value, int m_vlen,
                          int* check_exist) {
  auto* c = (Client*)handle;
  std::string hash_key(hk, hklen);
  // Mutate in declaration order (server/types.py:246)
  std::vector<Value> mf;
  mf.push_back(Value::integer(mutate_op));
  mf.push_back(Value::bytes(std::string(m_sk, m_sklen)));
  mf.push_back(Value::bytes(std::string(m_value, m_vlen)));
  mf.push_back(Value::integer(0));
  Value mutates;
  mutates.kind = Value::LIST;
  mutates.items.push_back(Value::dataclass("Mutate", std::move(mf)));
  // CheckAndMutateRequest in declaration order (server/types.py:254)
  std::vector<Value> f;
  f.push_back(Value::bytes(hash_key));
  f.push_back(Value::bytes(std::string(check_sk, check_sklen)));
  f.push_back(Value::integer(check_type));
  f.push_back(Value::bytes(std::string(operand, operand_len)));
  f.push_back(std::move(mutates));
  f.push_back(Value::boolean(true));             // return_check_value
  Value result;
  int rc = c->write_call(
      c->route_hash(hash_key, ""), 7 /*OP_CAM*/,
      Value::dataclass("CheckAndMutateRequest", std::move(f)), &result);
  if (rc != 0) return rc;
  if (result.kind == Value::INT || result.kind == Value::UINT)
    return (int)result.as_int();  // per-op status (gate deny/throttle)
  if (result.kind != Value::DICT ||
      !result.is_dataclass("CheckAndMutateResponse"))
    return -1;
  const Value* err = result.field(0);
  const Value* exist = result.field(2);
  if (check_exist) *check_exist = exist && exist->b ? 1 : 0;
  return err ? (int)err->as_int() : -1;
}
}
