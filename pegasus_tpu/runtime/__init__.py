"""Host runtime: deterministic simulation + async scheduling.

Parity: the reference's rDSN tool layer — `nativerun` vs `simulator`
(src/runtime/simulator.h:63, env.sim.h:36): the same service code can run
under a deterministic single-process scheduler with a simulated network
(drop/delay injectable, src/rpc/network.sim.h:86). This package provides
that seam for the replication layer: the SAME replica state machines run
under the in-proc direct transport in production paths and under
`SimLoop`/`SimNetwork` for seeded, reproducible whole-cluster tests.
"""

from pegasus_tpu.runtime.sim import SimLoop, SimNetwork
