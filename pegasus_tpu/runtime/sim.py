"""Deterministic discrete-event loop + simulated network.

Parity: the reference's simulator tool (src/runtime/simulator.h:63) with
its seeded random env (src/runtime/env.sim.h:36) and fault-injectable
simulated network (src/rpc/network.sim.h:86). Every delay and every
drop decision comes from one seeded RNG, so a failing cluster schedule
replays exactly from its seed — the property the reference's simple_kv
.act harness is built on (SURVEY §4.2).
"""

from __future__ import annotations

from time import perf_counter as _perf_counter

import heapq
import itertools
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from pegasus_tpu.rpc.fault import link_rule_lookup
from pegasus_tpu.rpc.transport import WRITE_REQS

from pegasus_tpu.utils import tracing as _tracing
from pegasus_tpu.utils.profiler import PROFILER as _PROFILER

class SimLoop:
    """Virtual-clock event loop. Time only advances between events."""

    def __init__(self, seed: int = 0) -> None:
        self.rng = np.random.default_rng(seed)
        self.now = 0.0
        self._heap: list = []
        self._seq = itertools.count()

    def schedule(self, delay: float, fn: Callable[[], None]) -> None:
        heapq.heappush(self._heap,
                       (self.now + max(0.0, delay), next(self._seq), fn))

    def run_until_idle(self, max_events: int = 1_000_000) -> int:
        """Drain all events; returns the number processed."""
        n = 0
        while self._heap and n < max_events:
            t, _, fn = heapq.heappop(self._heap)
            self.now = t
            fn()
            n += 1
        return n

    def run_for(self, duration: float, max_events: int = 1_000_000) -> int:
        deadline = self.now + duration
        n = 0
        while self._heap and n < max_events and self._heap[0][0] <= deadline:
            t, _, fn = heapq.heappop(self._heap)
            self.now = t
            fn()
            n += 1
        self.now = max(self.now, deadline)
        return n


class SimNetwork:
    """Message delivery with seeded delay and per-link fault injection.

    Parity: network.sim + the toollet fault_injector's rpc drop/delay
    knobs (src/runtime/fault_injector.cpp:62-118), configured per link
    (src, dst) or globally.
    """

    def __init__(self, loop: SimLoop, base_delay: float = 0.001,
                 jitter: float = 0.001) -> None:
        self.loop = loop
        self.base_delay = base_delay
        self.jitter = jitter
        self._handlers: Dict[str, Callable[[str, str, Any], None]] = {}
        self._drop_prob: Dict[Optional[Tuple[str, str]], float] = {}
        self._extra_delay: Dict[Optional[Tuple[str, str]], float] = {}
        self._dup_prob: Dict[Optional[Tuple[str, str]], float] = {}
        self._partitioned: set = set()
        # per-link FIFO: messages on one (src, dst) link never reorder
        # (parity: rDSN rides TCP; the 2PC protocol assumes ordered
        # delivery per connection)
        self._link_clock: Dict[Tuple[str, str], float] = {}
        self.delivered = 0
        self.dropped = 0

    def register(self, addr: str,
                 handler: Callable[[str, str, Any], None]) -> None:
        """handler(src, msg_type, payload)"""
        self._handlers[addr] = handler

    def offload(self, fn: Callable[[], None]) -> None:
        """Run slow IO 'in the background': inline here (determinism is
        the sim's whole point), a real thread on the TCP transport."""
        fn()

    def set_drop(self, prob: float, src: Optional[str] = None,
                 dst: Optional[str] = None) -> None:
        key = None if src is None and dst is None else (src, dst)
        self._drop_prob[key] = prob

    def set_delay(self, extra_s: float, src: Optional[str] = None,
                  dst: Optional[str] = None) -> None:
        """Add a fixed extra latency to a link (or globally) — the
        fault_injector's rpc-delay knob. Per-link FIFO order holds."""
        key = None if src is None and dst is None else (src, dst)
        if extra_s <= 0:
            self._extra_delay.pop(key, None)
        else:
            self._extra_delay[key] = extra_s

    def set_duplicate(self, prob: float, src: Optional[str] = None,
                      dst: Optional[str] = None) -> None:
        """Deliver a link's messages twice with probability `prob` —
        the redelivery fault the real transport's FaultPlan injects
        (protocols must tolerate duplicates; TCP alone never makes
        them, so chaos has to)."""
        key = None if src is None and dst is None else (src, dst)
        if prob <= 0:
            self._dup_prob.pop(key, None)
        else:
            self._dup_prob[key] = prob

    def partition(self, addr: str) -> None:
        """Cut a node off entirely (both directions)."""
        self._partitioned.add(addr)

    def heal(self, addr: str) -> None:
        self._partitioned.discard(addr)

    def send(self, src: str, dst: str, msg_type: str, payload: Any) -> None:
        if isinstance(payload, dict) and "trace" not in payload:
            # trace context rides the payload envelope — the exact
            # stamping rule the TCP transport applies, so a sim schedule
            # exercises the same propagation the real wire does
            ctx = _tracing.current_ctx()
            if ctx is not None:
                payload["trace"] = ctx
        if src in self._partitioned or dst in self._partitioned:
            self.dropped += 1
            return
        prob = link_rule_lookup(self._drop_prob, src, dst)
        if prob > 0 and self.loop.rng.random() < prob:
            self.dropped += 1
            return
        # write requests exempt from duplication, like FaultPlan.outbound:
        # a duplicated atomic write would double-apply (no rid dedup)
        dup = link_rule_lookup(self._dup_prob, src, dst)
        copies = 2 if (dup > 0 and msg_type not in WRITE_REQS
                       and self.loop.rng.random() < dup) else 1
        for _copy in range(copies):
            delay = (self.base_delay + self.loop.rng.random() * self.jitter
                     + link_rule_lookup(self._extra_delay, src, dst))
            deliver_at = max(self.loop.now + delay,
                             self._link_clock.get((src, dst), 0.0))
            self._link_clock[(src, dst)] = deliver_at
            delay = deliver_at - self.loop.now

            def deliver(delay=delay) -> None:
                handler = self._handlers.get(dst)
                if handler is not None and dst not in self._partitioned:
                    self.delivered += 1
                    # tracing join point (same rule as the TCP
                    # dispatcher): a sampled request context opens a
                    # dispatch span; replies/acks only pin tail-keep
                    span = None
                    if isinstance(payload, dict):
                        t_ctx = payload.get("trace")
                        if t_ctx is not None:
                            name = msg_type
                            if msg_type == "replica":
                                name = f"replica.{payload.get('type')}"
                            if _tracing.is_reply_type(name):
                                _tracing.on_inbound_ctx(dst, t_ctx)
                            else:
                                span = _tracing.start_server_span(
                                    dst, name, t_ctx)
                                if span is not None:
                                    span.tags["queue_ms"] = round(
                                        delay * 1000.0, 3)
                    try:
                        with _tracing.activate(span):
                            if _PROFILER.enabled:
                                # toollet join point (profiler.cpp:
                                # 90-198): queue delay is the SIM link
                                # latency; exec is wall time
                                t0 = _perf_counter()
                                handler(src, msg_type, payload)
                                _PROFILER.observe(
                                    msg_type, delay * 1000.0,
                                    (_perf_counter() - t0) * 1000.0)
                            else:
                                handler(src, msg_type, payload)
                    finally:
                        if span is not None:
                            span.finish()

            self.loop.schedule(delay, deliver)
