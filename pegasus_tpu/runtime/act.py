"""Declarative scripted cluster cases (the .act harness).

Parity: src/replica/storage/simple_kv/test — the reference verifies
PacificA with declarative .act scripts run under the deterministic
simulator (case-000.act:30-64: client ops, config assertions, state
assertions, fault injection), numbered by fault class. This runner
executes the same idea against SimCluster: one line per step, seeded
determinism, every assertion against live cluster state.

Case grammar (one `verb: args` per line; '#' comments):

    create: <table> partitions=N replicas=N     create the table
    set: <hk> <sk> <value>                      client write (must ack)
    set_fail: <hk> <sk> <value>                 client write must NOT ack
    expect_read: <hk> <sk> <value|NOT_FOUND>    client read assertion
    kill: <node>     revive: <node>             crash / restore a node
    drop: <src> <dst> <prob>                    inject link loss
    heal_links:                                 clear loss injection
    step: <rounds>                              beacon/guardian rounds
    expect_primary_not: <pidx> <node>           cure assertion
    expect_members: <pidx> <count>              replication level
    expect_ballot_ge: <pidx> <n>                ballot monotonicity
    expect_consistent: <hk> <sk>                every member agrees
    fail_point: <name> <action>                 e.g. node1::plog_append raise(io)
    split: <table>                              start the online 2x split
    expect_partition_count: <table> <n>         (after steps) count settled
    dup: <master> <follower>                    add duplication
    expect_follower_read: <follower> <hk> <sk> <value>
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import PegasusError, StorageStatus

OK = int(StorageStatus.OK)


class ActError(AssertionError):
    pass


def _parse(text: str) -> List[Tuple[int, str, List[str]]]:
    steps = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if ":" not in line:
            raise ValueError(f"line {lineno}: expected 'verb: args'")
        verb, _sep, rest = line.partition(":")
        steps.append((lineno, verb.strip(), rest.split()))
    return steps


class ActRunner:
    def __init__(self, data_dir: str, n_nodes: int = 4,
                 seed: int = 0) -> None:
        self.cluster = SimCluster(data_dir, n_nodes=n_nodes, seed=seed)
        self.dir = data_dir
        self.client = None
        self._auth_clients: dict = {}
        self.app_id: Optional[int] = None
        self._follower_clients: dict = {}
        self._backup_id = None
        self.last_killed: Optional[str] = None

    def close(self) -> None:
        from pegasus_tpu.utils.fail_point import FAIL_POINTS

        FAIL_POINTS.teardown()  # a case must not leak faults
        self.cluster.close()

    def run_text(self, text: str, name: str = "<case>") -> None:
        for lineno, verb, args in _parse(text):
            try:
                self._step(verb, args)
            except (ActError, AssertionError) as e:
                raise ActError(
                    f"{name}:{lineno}: `{verb}: {' '.join(args)}` "
                    f"failed: {e}") from e

    def run_file(self, path: str) -> None:
        with open(path) as f:
            self.run_text(f.read(), os.path.basename(path))

    # ---- verbs ---------------------------------------------------------

    def _step(self, verb: str, args: List[str]) -> None:
        c = self.cluster
        if verb == "create":
            kw = dict(kv.split("=") for kv in args[1:])
            app_id = c.create_table(
                args[0], partition_count=int(kw.get("partitions", 4)),
                replica_count=int(kw.get("replicas", 3)))
            if self.client is None:
                # the FIRST table is the case's subject; later creates
                # (dup followers etc.) are reached via their own verbs
                self.app_id = app_id
                self.client = c.client(args[0])
        elif verb == "set":
            hk, sk, value = (a.encode() for a in args)
            err = self.client.set(hk, sk, value)
            if err != OK:
                raise ActError(f"write not acked (err {err})")
        elif verb == "set_fail":
            hk, sk, value = (a.encode() for a in args)
            try:
                err = self.client.set(hk, sk, value)
            except PegasusError:
                return
            if err == OK:
                raise ActError("write unexpectedly acked")
        elif verb == "expect_read":
            hk, sk = args[0].encode(), args[1].encode()
            want = args[2]
            err, value = self.client.get(hk, sk)
            if want == "NOT_FOUND":
                if err == OK:
                    raise ActError(f"found {value!r}, wanted NOT_FOUND")
            else:
                if err != OK or value != want.encode():
                    raise ActError(f"got (err={err}, {value!r}), "
                                   f"wanted {want!r}")
        elif verb == "kill":
            c.kill(args[0])
        elif verb == "revive":
            c.revive(args[0])
        elif verb == "revive_last_killed":
            if self.last_killed is None:
                raise ActError("nothing was killed via kill_primary")
            c.revive(self.last_killed)
        elif verb == "drop":
            c.net.set_drop(float(args[2]), args[0], args[1])
        elif verb == "drop_all":
            c.net.set_drop(float(args[0]))
        elif verb == "delay":
            # delay: [<src> <dst>] <ms> — extra fixed latency on one
            # link, or on EVERY link when only <ms> is given
            if len(args) == 1:
                c.net.set_delay(float(args[0]) / 1000.0)
            else:
                c.net.set_delay(float(args[2]) / 1000.0, args[0],
                                args[1])
        elif verb == "partition":
            # cut a live node off the network entirely (unlike kill:, the
            # process keeps running — lease expiry, not crash recovery)
            c.net.partition(args[0])
        elif verb == "heal":
            c.net.heal(args[0])
        elif verb == "heal_links":
            c.net._drop_prob.clear()
            c.net._extra_delay.clear()
        elif verb == "fail_point":
            from pegasus_tpu.utils.fail_point import FAIL_POINTS

            FAIL_POINTS.setup()
            FAIL_POINTS.cfg(args[0], " ".join(args[1:]))
        elif verb == "fail_point_primary":
            # fail_point_primary: <pidx> <site> <action> — configure
            # <current primary of pidx>::<site> (cases must not hardcode
            # which node the seed elected)
            from pegasus_tpu.utils.fail_point import FAIL_POINTS

            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if not pc.primary:
                raise ActError("partition has no primary")
            self.last_fault_node = pc.primary
            FAIL_POINTS.setup()
            FAIL_POINTS.cfg(f"{pc.primary}::{args[1]}",
                            " ".join(args[2:]))
        elif verb == "fail_point_all":
            # fail_point_all: <site> <action> — every node
            from pegasus_tpu.utils.fail_point import FAIL_POINTS

            FAIL_POINTS.setup()
            for name in c.stubs:
                FAIL_POINTS.cfg(f"{name}::{args[0]}",
                                " ".join(args[1:]))
        elif verb == "split":
            c.meta.split.start_partition_split(args[0])
        elif verb == "expect_partition_count":
            app = c.meta.state.find_app(args[0])
            if app is None or app.partition_count != int(args[1]):
                raise ActError(
                    f"partition_count "
                    f"{app.partition_count if app else None}, "
                    f"wanted {args[1]}")
        elif verb == "dup":
            c.meta.duplication.add_duplication(args[0], "meta", args[1])
        elif verb == "config":
            if self.client is not None:
                raise ActError("config: must precede create:")
            kw = dict(kv.split("=") for kv in args)
            import shutil
            self.cluster.close()
            shutil.rmtree(self.dir, ignore_errors=True)
            self.cluster = SimCluster(
                self.dir, n_nodes=int(kw.get("nodes", 4)),
                seed=int(kw.get("seed", 7)),
                n_meta=int(kw.get("n_meta", 1)),
                auth_secret=kw.get("auth_secret"))
        elif verb == "app_env":
            # app_env: <key> <value> — set a table env (ACLs, throttles)
            # on the acting app; config-sync delivers it to replicas
            app_name = c.meta.state.apps[self.app_id].app_name
            c.meta.update_app_envs(app_name, {args[0]: args[1]})
            c.step()
        elif verb == "auth":
            # auth: <user> — subsequent client ops run as this identity
            app_name = c.meta.state.apps[self.app_id].app_name
            key = args[0]
            cl = self._auth_clients.get(key)
            if cl is None:
                cl = c.client(app_name, name=f"act-auth-{key}",
                              user=key)
                self._auth_clients[key] = cl
            self.client = cl
        elif verb == "kill_primary":
            # kill partition <pidx>'s current primary; remembered for
            # expect_primary_unchanged / expect_primary_recovered
            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if not pc.primary:
                raise ActError("partition has no primary to kill")
            self.last_killed = pc.primary
            c.kill(pc.primary)
        elif verb == "expect_primary_unchanged":
            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if pc.primary != self.last_killed:
                raise ActError(
                    f"primary moved to {pc.primary!r} (expected still "
                    f"{self.last_killed!r})")
        elif verb == "expect_primary_recovered":
            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if not pc.primary or pc.primary == self.last_killed:
                raise ActError(f"primary {pc.primary!r} not recovered "
                               f"away from {self.last_killed!r}")
        elif verb == "kill_meta_leader":
            leader = [m for m in c.metas
                      if m.election.is_leader]
            if not leader:
                raise ActError("no meta leader to kill")
            c.kill(leader[0].name)
        elif verb == "bulkload_stage":
            # stage offline SSTs for the FIRST table: keys k<000..n-1>
            from pegasus_tpu.server.bulk_load import SSTGenerator
            from pegasus_tpu.storage.block_service import LocalBlockService

            opts = dict(kv.split("=") for kv in args)
            n = int(opts.get("records", 40))
            app = c.meta.state.apps[self.app_id]
            root = os.path.join(self.dir, "bulk_root")
            gen = SSTGenerator(LocalBlockService(root), app.app_name,
                               partition_count=app.partition_count)
            gen.generate([(b"bl%04d" % i, b"s", b"ingested-%d" % i, 0)
                          for i in range(n)])
        elif verb == "bulkload_start":
            app = c.meta.state.apps[self.app_id]
            root = os.path.join(self.dir, "bulk_root")
            c.meta.bulk_load.start_bulk_load(app.app_name, root)
        elif verb == "expect_bulkload_done":
            app = c.meta.state.apps[self.app_id]
            st = c.meta.bulk_load.bulk_load_status(app.app_name)
            if not st.get("complete"):
                raise ActError(f"bulk load incomplete: {st}")
        elif verb == "backup":
            root = os.path.join(self.dir, "backup_root")
            self._backup_id = c.meta.backup.start_backup(
                args[0], root, "act")
        elif verb == "expect_backup_done":
            if self._backup_id is None:
                raise ActError("expect_backup_done: no backup: ran")
            st = c.meta.backup.backup_status(self._backup_id)
            if not st["complete"]:
                raise ActError(f"backup incomplete: {st}")
        elif verb == "restore":
            if self._backup_id is None:
                raise ActError("restore: no backup: ran")
            root = os.path.join(self.dir, "backup_root")
            c.meta.backup.create_app_from_backup(
                args[0], root, "act", self._backup_id, replica_count=3)
        elif verb == "expect_follower_read":
            fc = self._follower_clients.get(args[0])
            if fc is None:
                # NOT setdefault: its eagerly-evaluated default would
                # register a fresh client over the same transport name
                # each call, stealing replies from the kept instance
                fc = c.client(args[0], name=f"act-f-{args[0]}")
                self._follower_clients[args[0]] = fc
            hk, sk, want = (a.encode() for a in args[1:])
            err, value = fc.get(hk, sk)
            if err != OK or value != want:
                raise ActError(f"follower got (err={err}, {value!r}), "
                               f"wanted {want!r}")
        elif verb == "write_many":
            # write_many: <prefix> <n> — n writes fanned over hashkeys;
            # each must ack (drives schedule diversity under faults)
            prefix, n = args[0], int(args[1])
            for i in range(n):
                hk = f"{prefix}{i % max(1, n // 4)}".encode()
                err = self.client.set(hk, b"s%04d" % i,
                                      b"v%04d" % i)
                if err != OK:
                    raise ActError(f"write {i} not acked (err {err})")
        elif verb == "write_many_any":
            # like write_many but individual writes MAY fail (loss storms,
            # dead primaries); remembers which acked for expect_many
            prefix, n = args[0], int(args[1])
            acked = self.__dict__.setdefault("_acked", {})
            for i in range(n):
                hk = f"{prefix}{i % max(1, n // 4)}".encode()
                try:
                    err = self.client.set(hk, b"s%04d" % i, b"v%04d" % i)
                except PegasusError:
                    continue
                if err == OK:
                    acked[(hk, b"s%04d" % i)] = b"v%04d" % i
        elif verb == "expect_many":
            # every ACKED write from write_many/_any must read back
            prefix, n = args[0], int(args[1])
            acked = self.__dict__.get("_acked")
            if acked is None:
                acked = {}
                for i in range(n):
                    hk = f"{prefix}{i % max(1, n // 4)}".encode()
                    acked[(hk, b"s%04d" % i)] = b"v%04d" % i
            missing = []
            for (hk, sk), want in acked.items():
                err, value = self.client.get(hk, sk)
                if err != OK or value != want:
                    missing.append((hk, sk, err, value))
            if missing:
                raise ActError(
                    f"{len(missing)}/{len(acked)} acked writes lost; "
                    f"first: {missing[0]}")
        elif verb == "flush":
            # flush: <node>|all — checkpoint storage + GC the WAL on a
            # node's replicas (pushes later learns onto the LT_APP path)
            for name, stub in c.stubs.items():
                if args and args[0] != "all" and name != args[0]:
                    continue
                if name in c._dead:
                    continue
                for r in list(stub.replicas.values()):
                    r.flush_and_gc_log()
            c.loop.run_until_idle()
        elif verb == "step":
            c.step(rounds=int(args[0]) if args else 1)
        elif verb == "expect_primary_not":
            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if pc.primary == args[1]:
                raise ActError(f"primary still {args[1]}")
            if not pc.primary:
                raise ActError("partition has NO primary")
        elif verb == "expect_members":
            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if len(pc.members()) != int(args[1]):
                raise ActError(f"{len(pc.members())} members "
                               f"({pc.members()}), wanted {args[1]}")
        elif verb == "expect_ballot_ge":
            pc = c.meta.state.get_partition(self.app_id, int(args[0]))
            if pc.ballot < int(args[1]):
                raise ActError(f"ballot {pc.ballot} < {args[1]}")
        elif verb == "set_replica_count":
            c.meta.set_app_replica_count(
                c.meta.state.apps[self.app_id].app_name, int(args[0]))
        elif verb == "meta_level":
            c.meta.set_meta_level(args[0])
        elif verb == "expect_ddd":
            gpids = {tuple(d["gpid"]) for d in c.meta.ddd_diagnose()}
            want = (self.app_id, int(args[0]))
            if want not in gpids:
                raise ActError(f"{want} not in ddd list {gpids}")
        elif verb == "propose":
            # propose: <pidx> <action> <node> [force]
            c.meta.propose(c.meta.state.apps[self.app_id].app_name,
                           int(args[0]), args[1], args[2],
                           force="force" in args[3:])
        elif verb == "wipe_meta_state":
            # simulate total meta-state loss for the case's table (the
            # `recover` scenario: replicas become the source of truth)
            c.meta.state.apps.pop(self.app_id, None)
            c.meta.state.configs.pop(self.app_id, None)
        elif verb == "config_sync":
            for stub in c.stubs.values():
                if stub.name not in c._dead:
                    stub.config_sync()
            c.loop.run_until_idle()
        elif verb == "recover":
            res = c.meta.recover_from_reports()
            if not res["created"]:
                raise ActError(f"recover created nothing: {res}")
        elif verb == "rename":
            c.meta.rename_app(args[0], args[1])
        elif verb == "expect_hosted_count":
            # replicas of the case's table still hosted across the
            # cluster (freezed GC protection assertion)
            n = sum(1 for stub in c.stubs.values()
                    for gpid in stub.replicas if gpid[0] == self.app_id)
            if n != int(args[0]):
                raise ActError(f"hosted {n} != expected {args[0]}")
        elif verb == "expect_consistent":
            from pegasus_tpu.base.key_schema import (
                generate_key,
                key_hash_parts,
            )

            hk, sk = args[0].encode(), args[1].encode()
            app = c.meta.state.apps[self.app_id]
            pidx = key_hash_parts(hk, sk) % app.partition_count
            pc = c.meta.state.get_partition(self.app_id, pidx)
            key = generate_key(hk, sk)
            seen = {}
            for node in pc.members():
                if node in c._dead:
                    continue
                r = c.stubs[node].get_replica((self.app_id, pidx))
                seen[node] = r.server.engine.get(key)
            if len({repr(v) for v in seen.values()}) > 1:
                raise ActError(f"members disagree: {seen}")
        else:
            raise ValueError(f"unknown act verb {verb!r}")
