"""Columnar response-page assembly.

The response-assembly half of the scan hot path: given the surviving
row indices of each planned block (the device/static mask AND the host
TTL mask, already applied), pack every survivor's key and user-data
into ONE ScanPage — a single native call per block
(native/packer.cpp pegasus_gather_page) instead of a per-record Python
loop building KeyValue objects.

Parity role: src/server/pegasus_server_impl.cpp:2434-2489
(append_key_value_for_multi_get / validate_key_value_for_scan) — the
reference's C++ per-record response append. Ours is batch-shaped
because the survivors are already columnar in the SST block.

Falls back to a per-record Python gather when the native library is
unavailable (same output, slower).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pegasus_tpu import native
from pegasus_tpu.server.types import ScanPage


def block_native_ptrs(blk):
    """Cached static pointer row for one Block: (keys, key_len, voffs,
    heap, ets, width). `.ctypes.data` costs ~a µs per access, so the
    serving path resolves each block's pointers once per process, not
    once per request."""
    nat = getattr(blk, "_nat", None)
    if nat is None:
        heap = blk.value_heap
        if not isinstance(heap, np.ndarray):
            heap = np.frombuffer(heap, dtype=np.uint8)
        nat = (blk.keys.ctypes.data, blk.key_len.ctypes.data,
               blk.value_offs.ctypes.data,
               heap.ctypes.data if heap.size else 0,
               blk.expire_ts.ctypes.data, blk.keys.shape[1], heap)
        blk._nat = nat
    return nat


def plan_geometry(plan):
    """(total_rows, value-heap span upper bound, max key width) of a
    plan — the native assembly's arena sizing. Computed once per cached
    plan (partition_server.plan_scan_batch) and carried in the window
    tuple; recomputed here only for callers without a cache."""
    total_rows = 0
    span = 0
    max_w = 2
    for _ckey, blk, lo, hi in plan:
        total_rows += hi - lo
        vo = blk.value_offs
        span += int(vo[hi]) - int(vo[lo])
        if blk.keys.shape[1] > max_w:
            max_w = blk.keys.shape[1]
    return total_rows, span, max_w


def serve_batch(req_windows, unique, byte_cap: int, hdr: int):
    """Whole-BATCH base-path assembly in ONE native call.

    req_windows: per fast-path request (plan, want, no_value,
    want_ets, live_masks, geom) where plan is [(ckey, Block, lo, hi)]
    in key order, live_masks maps ckey -> bool[count] (that request's
    static keep AND host TTL — PER WINDOW, because filter flavors
    sharing a block carry different masks), and geom is
    plan_geometry(plan) (may be omitted — recomputed then); unique:
    OrderedDict ckey -> (run, bm, blk) covering every planned block
    (may span partitions).

    Packs every request's surviving rows into shared arenas via
    packer.cpp pegasus_scan_serve_batch — the C++ twin of the
    reference's per-record serving loop
    (src/server/pegasus_server_impl.cpp:643) — then cuts per-request
    ScanPages out of the arenas.

    Returns [(page, size, last_key, truncated) | None] per request
    (None = re-serve that request in Python: arena capacity hit), or
    None entirely when the native library is unavailable.
    """
    fn = native.scan_serve_fn()
    if fn is None or not req_windows:
        return None
    want_ets = any(w[3] for w in req_windows)
    n_blocks = len(unique)
    ptrs = np.empty((6, n_blocks), dtype=np.uint64)
    block_idx = {}
    for b, (ckey, (_run, _bm, blk)) in enumerate(unique.items()):
        kp, lp, vp, hp, ep, w, _heap = block_native_ptrs(blk)
        ptrs[0, b] = kp
        ptrs[1, b] = w
        ptrs[2, b] = lp
        ptrs[3, b] = vp
        ptrs[4, b] = hp
        ptrs[5, b] = ep
        block_idx[ckey] = b
    widths = ptrs[1].astype(np.int64)

    n_reqs = len(req_windows)
    n_entries = sum(len(w[0]) for w in req_windows)
    entry_start = np.zeros(n_reqs + 1, dtype=np.int64)
    entry_block = np.empty(n_entries, dtype=np.int64)
    entry_mask = np.empty(n_entries, dtype=np.uint64)
    entry_lo = np.empty(n_entries, dtype=np.int64)
    entry_hi = np.empty(n_entries, dtype=np.int64)
    wants = np.empty(n_reqs, dtype=np.int64)
    no_values = np.empty(n_reqs, dtype=np.uint8)
    row_base = np.empty(n_reqs, dtype=np.int64)
    mask_refs = []  # keep per-flavor mask arrays alive across the call
    mask_ptr_cache = {}
    e = 0
    rows_total = 0
    key_cap = 0
    val_cap = 0
    for r, window in enumerate(req_windows):
        plan, want, no_value, _we, live_masks = window[:5]
        geom = window[5] if len(window) > 5 else None
        row_base[r] = rows_total + r  # +r: offsets windows are count+1
        for ckey, blk, lo, hi in plan:
            b = block_idx[ckey]
            entry_block[e] = b
            mkey = (id(live_masks), ckey)
            mp = mask_ptr_cache.get(mkey)
            if mp is None:
                mask = live_masks[ckey]
                mask_refs.append(mask)
                mp = mask.ctypes.data
                mask_ptr_cache[mkey] = mp
            entry_mask[e] = mp
            entry_lo[e] = lo
            entry_hi[e] = hi
            e += 1
        total_rows, span, max_w = (geom if geom is not None
                                   else plan_geometry(plan))
        entry_start[r + 1] = e
        cap_rows = min(want, total_rows)
        wants[r] = cap_rows
        no_values[r] = no_value
        rows_total += cap_rows
        key_cap += cap_rows * max_w
        val_cap += 0 if no_value else min(byte_cap + (64 << 10), span)
    if key_cap >= 1 << 32 or val_cap >= 1 << 32:
        # running arena offsets are uint32: a flush whose combined
        # spans pass 4 GiB must take the per-request Python path (which
        # enforces its own per-request caps) instead of wrapping
        return None
    key_blob = np.empty(max(1, key_cap), dtype=np.uint8)
    val_blob = np.empty(max(1, val_cap), dtype=np.uint8)
    key_offs = np.zeros(rows_total + n_reqs + 1, dtype=np.uint32)
    val_offs = np.zeros(rows_total + n_reqs + 1, dtype=np.uint32)
    ets_arena = (np.empty(max(1, rows_total), dtype=np.uint32)
                 if want_ets else None)
    out_count = np.zeros(n_reqs, dtype=np.int64)
    out_bytes = np.zeros(n_reqs, dtype=np.int64)
    out_state = np.zeros(n_reqs, dtype=np.int32)
    fn(ptrs[0].ctypes.data, widths.ctypes.data, ptrs[2].ctypes.data,
       entry_mask.ctypes.data, ptrs[3].ctypes.data, ptrs[4].ctypes.data,
       ptrs[5].ctypes.data, n_reqs, entry_start.ctypes.data,
       entry_block.ctypes.data, entry_lo.ctypes.data,
       entry_hi.ctypes.data, wants.ctypes.data, no_values.ctypes.data,
       byte_cap, hdr, key_blob.ctypes.data, key_cap,
       val_blob.ctypes.data, val_cap, key_offs.ctypes.data,
       val_offs.ctypes.data, row_base.ctypes.data,
       ets_arena.ctypes.data if want_ets else None,
       out_count.ctypes.data, out_bytes.ctypes.data,
       out_state.ctypes.data)

    results = []
    for r in range(n_reqs):
        state = int(out_state[r])
        if state == 3:
            results.append(None)  # arena full: Python re-serves
            continue
        count = int(out_count[r])
        truncated = state == 2
        if count == 0:
            results.append((ScanPage(), 0, None, truncated))
            continue
        base = int(row_base[r])
        ko = key_offs[base:base + count + 1]
        vo = val_offs[base:base + count + 1]
        k0, k1 = int(ko[0]), int(ko[count])
        v0, v1 = int(vo[0]), int(vo[count])
        page = ScanPage(
            key_offs=(ko - np.uint32(k0)).tobytes(),
            key_blob=key_blob[k0:k1].tobytes(),
            val_offs=(vo - np.uint32(v0)).tobytes(),
            val_blob=val_blob[v0:v1].tobytes())
        if req_windows[r][3]:
            page.ets = ets_arena[base - r:base - r + count].astype(
                "<u4").tobytes()
        last_key = key_blob[int(ko[count - 1]):k1].tobytes()
        results.append((page, int(out_bytes[r]), last_key, truncated))
    return results


def build_page(chunks: List[Tuple[object, np.ndarray]], hdr: int,
               no_value: bool = False, want_ets: bool = False,
               ) -> Tuple[ScanPage, int, Optional[bytes]]:
    """Pack survivors into one page.

    chunks: [(Block, ascending int64 row indices)] in key order across
    blocks. Returns (page, byte_size, last_key) where byte_size is the
    capacity-unit accounting sum (key bytes + user-data bytes) and
    last_key is the final packed key (resume cursor) or None for an
    empty page.
    """
    chunks = [(blk, take) for blk, take in chunks if len(take)]
    n = sum(len(take) for _b, take in chunks)
    if n == 0:
        return ScanPage(), 0, None

    # UPPER-BOUND blob capacities from scalar offset reads (takes are
    # ascending, so a chunk's value bytes fit in [offs[first],
    # offs[last+1])); the gather writes the exact running offsets and
    # the blobs are trimmed afterwards — O(1) sizing per chunk instead
    # of per-take vector math on this per-request path
    key_cap = 0
    val_cap = 0
    for blk, take in chunks:
        key_cap += len(take) * blk.keys.shape[1]
        if not no_value:
            vo = blk.value_offs
            val_cap += int(vo[int(take[-1]) + 1]) - int(vo[int(take[0])])
    if key_cap >= 1 << 32 or val_cap >= 1 << 32:
        # offsets are uint32 (here and in pegasus_gather_page); callers
        # cap batch_size (SCAN_BATCH_CAP) so this only trips on a bug
        raise ValueError(
            f"scan page exceeds 4GiB blob limit "
            f"(keys={key_cap}, values={val_cap}); split the batch")

    key_offs = np.zeros(n + 1, dtype=np.uint32)
    val_offs = np.zeros(n + 1, dtype=np.uint32)
    key_buf = bytearray(key_cap)
    val_buf = bytearray(val_cap)
    kb = np.frombuffer(key_buf, dtype=np.uint8)
    vb = np.frombuffer(val_buf, dtype=np.uint8) if val_cap else None

    fn = native.gather_page_fn()
    pos = 0
    for blk, take in chunks:
        m = len(take)
        take = np.ascontiguousarray(take, dtype=np.int64)
        if fn is not None:
            heap = blk.value_heap
            if not isinstance(heap, np.ndarray):
                heap = np.frombuffer(heap, dtype=np.uint8)
            fn(blk.keys.ctypes.data, blk.keys.shape[1],
               blk.key_len.ctypes.data, blk.value_offs.ctypes.data,
               heap.ctypes.data if heap.size else None,
               take.ctypes.data, m, hdr,
               kb.ctypes.data, key_offs[pos:].ctypes.data,
               (vb.ctypes.data if not no_value and vb is not None
                else None),
               val_offs[pos:].ctypes.data)
        else:
            _gather_python(blk, take, hdr, no_value, kb, key_offs,
                           vb, val_offs, pos)
        pos += m

    key_total = int(key_offs[n])
    val_total = int(val_offs[n])
    last_i = int(key_offs[n - 1])
    page = ScanPage(
        key_offs=key_offs.tobytes(), key_blob=bytes(key_buf[:key_total]),
        val_offs=val_offs.tobytes(), val_blob=bytes(val_buf[:val_total]))
    if want_ets:
        page.ets = np.concatenate(
            [np.asarray(blk.expire_ts)[take]
             for blk, take in chunks]).astype("<u4").tobytes()
    return page, key_total + val_total, bytes(key_buf[last_i:key_total])


def _gather_python(blk, take, hdr, no_value, kb, key_offs, vb, val_offs,
                   pos) -> None:
    """Pure-Python twin of pegasus_gather_page (no toolchain)."""
    kpos = int(key_offs[pos])
    vpos = int(val_offs[pos])
    vo = blk.value_offs
    heap = blk.value_heap
    for j, row in enumerate(take):
        row = int(row)
        kl = int(blk.key_len[row])
        kb[kpos:kpos + kl] = blk.keys[row, :kl]
        kpos += kl
        key_offs[pos + j + 1] = kpos
        v0, v1 = int(vo[row]), int(vo[row + 1])
        vl = max(0, v1 - v0 - hdr)
        if not no_value:
            if vl:
                if not isinstance(heap, np.ndarray):
                    heap = np.frombuffer(heap, dtype=np.uint8)
                vb[vpos:vpos + vl] = heap[v0 + hdr:v1]
            vpos += vl
        val_offs[pos + j + 1] = vpos
