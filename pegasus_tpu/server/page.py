"""Columnar response-page assembly.

The response-assembly half of the scan hot path: given the surviving
row indices of each planned block (the device/static mask AND the host
TTL mask, already applied), pack every survivor's key and user-data
into ONE ScanPage — a single native call per block
(native/packer.cpp pegasus_gather_page) instead of a per-record Python
loop building KeyValue objects.

Parity role: src/server/pegasus_server_impl.cpp:2434-2489
(append_key_value_for_multi_get / validate_key_value_for_scan) — the
reference's C++ per-record response append. Ours is batch-shaped
because the survivors are already columnar in the SST block.

Falls back to a per-record Python gather when the native library is
unavailable (same output, slower).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pegasus_tpu import native
from pegasus_tpu.server.types import ScanPage


def build_page(chunks: List[Tuple[object, np.ndarray]], hdr: int,
               no_value: bool = False, want_ets: bool = False,
               ) -> Tuple[ScanPage, int, Optional[bytes]]:
    """Pack survivors into one page.

    chunks: [(Block, ascending int64 row indices)] in key order across
    blocks. Returns (page, byte_size, last_key) where byte_size is the
    capacity-unit accounting sum (key bytes + user-data bytes) and
    last_key is the final packed key (resume cursor) or None for an
    empty page.
    """
    chunks = [(blk, take) for blk, take in chunks if len(take)]
    n = sum(len(take) for _b, take in chunks)
    if n == 0:
        return ScanPage(), 0, None

    # UPPER-BOUND blob capacities from scalar offset reads (takes are
    # ascending, so a chunk's value bytes fit in [offs[first],
    # offs[last+1])); the gather writes the exact running offsets and
    # the blobs are trimmed afterwards — O(1) sizing per chunk instead
    # of per-take vector math on this per-request path
    key_cap = 0
    val_cap = 0
    for blk, take in chunks:
        key_cap += len(take) * blk.keys.shape[1]
        if not no_value:
            vo = blk.value_offs
            val_cap += int(vo[int(take[-1]) + 1]) - int(vo[int(take[0])])
    if key_cap >= 1 << 32 or val_cap >= 1 << 32:
        # offsets are uint32 (here and in pegasus_gather_page); callers
        # cap batch_size (SCAN_BATCH_CAP) so this only trips on a bug
        raise ValueError(
            f"scan page exceeds 4GiB blob limit "
            f"(keys={key_cap}, values={val_cap}); split the batch")

    key_offs = np.zeros(n + 1, dtype=np.uint32)
    val_offs = np.zeros(n + 1, dtype=np.uint32)
    key_buf = bytearray(key_cap)
    val_buf = bytearray(val_cap)
    kb = np.frombuffer(key_buf, dtype=np.uint8)
    vb = np.frombuffer(val_buf, dtype=np.uint8) if val_cap else None

    fn = native.gather_page_fn()
    pos = 0
    for blk, take in chunks:
        m = len(take)
        take = np.ascontiguousarray(take, dtype=np.int64)
        if fn is not None:
            fn(blk.keys.ctypes.data, blk.keys.shape[1],
               blk.key_len.ctypes.data, blk.value_offs.ctypes.data,
               bytes(blk.value_heap),
               take.ctypes.data, m, hdr,
               kb.ctypes.data, key_offs[pos:].ctypes.data,
               (vb.ctypes.data if not no_value and vb is not None
                else None),
               val_offs[pos:].ctypes.data)
        else:
            _gather_python(blk, take, hdr, no_value, kb, key_offs,
                           vb, val_offs, pos)
        pos += m

    key_total = int(key_offs[n])
    val_total = int(val_offs[n])
    last_i = int(key_offs[n - 1])
    page = ScanPage(
        key_offs=key_offs.tobytes(), key_blob=bytes(key_buf[:key_total]),
        val_offs=val_offs.tobytes(), val_blob=bytes(val_buf[:val_total]))
    if want_ets:
        page.ets = np.concatenate(
            [np.asarray(blk.expire_ts)[take]
             for blk, take in chunks]).astype("<u4").tobytes()
    return page, key_total + val_total, bytes(key_buf[last_i:key_total])


def _gather_python(blk, take, hdr, no_value, kb, key_offs, vb, val_offs,
                   pos) -> None:
    """Pure-Python twin of pegasus_gather_page (no toolchain)."""
    kpos = int(key_offs[pos])
    vpos = int(val_offs[pos])
    vo = blk.value_offs
    heap = blk.value_heap
    for j, row in enumerate(take):
        row = int(row)
        kl = int(blk.key_len[row])
        kb[kpos:kpos + kl] = blk.keys[row, :kl]
        kpos += kl
        key_offs[pos + j + 1] = kpos
        v0, v1 = int(vo[row]), int(vo[row + 1])
        vl = max(0, v1 - v0 - hdr)
        if not no_value:
            if vl:
                vb[vpos:vpos + vl] = np.frombuffer(
                    heap, dtype=np.uint8, count=vl, offset=v0 + hdr)
            vpos += vl
        val_offs[pos + j + 1] = vpos
