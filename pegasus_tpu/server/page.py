"""Columnar response-page assembly.

The response-assembly half of the scan hot path: given the surviving
row indices of each planned block (the device/static mask AND the host
TTL mask, already applied), pack every survivor's key and user-data
into ONE ScanPage — a single native call per block
(native/packer.cpp pegasus_gather_page) instead of a per-record Python
loop building KeyValue objects.

Parity role: src/server/pegasus_server_impl.cpp:2434-2489
(append_key_value_for_multi_get / validate_key_value_for_scan) — the
reference's C++ per-record response append. Ours is batch-shaped
because the survivors are already columnar in the SST block.

Falls back to a per-record Python gather when the native library is
unavailable (same output, slower).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from pegasus_tpu import native
from pegasus_tpu.server.types import ScanPage

_scratch_tls = threading.local()


def _scratch(name: str, size: int, dtype, alloc=np.empty):
    """Grow-only per-thread scratch array + cached base pointer.

    The assembly arenas are consumed within one serve_batch call (pages
    cut out by copy), so reusing them across flushes avoids an
    mmap/page-fault round per flush for the multi-MB value arena —
    and caching `.ctypes.data` (a ~µs property that builds a fresh
    ctypes view per access) with the buffer trims the per-call ctypes
    overhead. Per-thread because onebox nodes serve from their own
    dispatch threads. `alloc` fills the buffer at (re)allocation
    (np.arange for the identity block table)."""
    pool = getattr(_scratch_tls, "pool", None)
    if pool is None:
        pool = _scratch_tls.pool = {}
    hit = pool.get(name)
    if hit is None or hit[0].size < size:
        arr = alloc(int(size * 3 // 2) + 64, dtype=dtype)
        hit = pool[name] = (arr, arr.ctypes.data)
    return hit


def block_native_ptrs(blk):
    """Cached static pointer row for one Block: (keys, key_len, voffs,
    heap, ets, width). `.ctypes.data` costs ~a µs per access, so the
    serving path resolves each block's pointers once per process, not
    once per request."""
    nat = getattr(blk, "_nat", None)
    if nat is None:
        heap = blk.value_heap
        if not isinstance(heap, np.ndarray):
            heap = np.frombuffer(heap, dtype=np.uint8)
        nat = (blk.keys.ctypes.data, blk.key_len.ctypes.data,
               blk.value_offs.ctypes.data,
               heap.ctypes.data if heap.size else 0,
               blk.expire_ts.ctypes.data, blk.keys.shape[1], heap)
        blk._nat = nat
    return nat


def probe_nat(blk):
    """Cached point-probe entry table for one Block: the contiguous key
    matrix, an int64 key-length column, and the memcmp-ordered void
    view the batched searchsorted probes run over — resolved once per
    block lifetime (like block_native_ptrs for the scan path) so the
    point-get path's vectorized probes skip per-call dtype/contiguity
    work."""
    nat = blk._probe
    if nat is None:
        km = np.ascontiguousarray(blk.keys)
        vt = np.dtype((np.void, km.shape[1]))
        nat = (km, np.asarray(blk.key_len, dtype=np.int64),
               km.view(vt).ravel())
        blk._probe = nat
    return nat


def probe_rows(blk, probe_keys) -> np.ndarray:
    """int64[P] row indices of exact-match probe keys in `blk` (-1 =
    absent): one vectorized searchsorted over the cached probe table
    instead of P Python bisects."""
    from pegasus_tpu.ops.predicates import point_probe_rows

    km, kl, bv = probe_nat(blk)
    return point_probe_rows(km, kl, probe_keys, block_void=bv)


def plan_geometry(plan):
    """(total_rows, value-heap span upper bound, max key width) of a
    plan — the native assembly's arena sizing. Computed once per cached
    plan (partition_server.plan_scan_batch) and carried in the window
    tuple; recomputed here only for callers without a cache."""
    total_rows = 0
    span = 0
    max_w = 2
    for _ckey, blk, lo, hi in plan:
        total_rows += hi - lo
        vo = blk.value_offs
        span += int(vo[hi]) - int(vo[lo])
        if blk.keys.shape[1] > max_w:
            max_w = blk.keys.shape[1]
    return total_rows, span, max_w


def plan_nat(plan):
    """Per-plan native entry table, cached WITH the plan
    (partition_server.plan_scan_batch): the pointer rows (keys, width,
    key_len, value_offs, heap, expire_ts) for every entry as one
    uint64[6, n] plus int64 lo/hi bounds and the ckey tuple. Plans are
    pure over the immutable run set, so these arrays are too —
    serve_batch concatenates them instead of re-resolving per-entry
    pointer rows through Python dicts on every flush."""
    n = len(plan)
    ptr6 = np.empty((6, n), dtype=np.uint64)
    lo_arr = np.empty(n, dtype=np.int64)
    hi_arr = np.empty(n, dtype=np.int64)
    ckeys = []
    for j, (ckey, blk, lo, hi) in enumerate(plan):
        kp, lp, vp, hp, ep, w, _heap = block_native_ptrs(blk)
        ptr6[0, j] = kp
        ptr6[1, j] = w
        ptr6[2, j] = lp
        ptr6[3, j] = vp
        ptr6[4, j] = hp
        ptr6[5, j] = ep
        lo_arr[j] = lo
        hi_arr[j] = hi
        ckeys.append(ckey)
    return ptr6, lo_arr, hi_arr, tuple(ckeys), ptr6[1].astype(np.int64)


def serve_batch(req_windows, unique, byte_cap: int, hdr: int):
    """Whole-BATCH base-path assembly in ONE native call.

    req_windows: per fast-path request (plan, want, no_value,
    want_ets, live_masks, geom[, nat[, live_ptrs]]) where plan is
    [(ckey, Block, lo, hi)] in key order, live_masks maps ckey ->
    bool[count] (that request's static keep AND host TTL — PER WINDOW,
    because filter flavors sharing a block carry different masks),
    geom is plan_geometry(plan), nat is plan_nat(plan) and live_ptrs
    maps ckey -> live-mask base pointer (resolved once per (block,
    flavor, second) in prepare_serve). Trailing elements may be
    omitted — recomputed then; the serving path passes 8-tuples, which
    ride a fully vectorized bookkeeping path (no per-window numpy
    scalar stores). `unique` is unused (kept for caller compatibility;
    the entry table is per-entry now, so no flush-wide block dedup is
    needed).

    Packs every request's surviving rows into shared arenas via
    packer.cpp pegasus_scan_serve_batch — the C++ twin of the
    reference's per-record serving loop
    (src/server/pegasus_server_impl.cpp:643) — then cuts per-request
    ScanPages out of the arenas.

    Returns [(page, size, last_key, truncated) | None] per request
    (None = re-serve that request in Python: arena capacity hit), or
    None entirely when the native library is unavailable.
    """
    fn = native.scan_serve_fn()
    if fn is None or not req_windows:
        return None
    want_ets = any(w[3] for w in req_windows)
    n_reqs = len(req_windows)
    mask_refs = []  # keep ad-hoc mask arrays alive across the call
    if all(len(w) > 7 for w in req_windows):
        # serving fast path: every per-window quantity comes cached
        # (geom + nat with the plan, live_ptrs with the second's live
        # masks), so the bookkeeping is pure array math over the flush
        nats = [w[6] for w in req_windows]
        geoms = np.array([w[5] for w in req_windows], dtype=np.int64)
        wants_in = np.fromiter((w[1] for w in req_windows),
                               dtype=np.int64, count=n_reqs)
        no_vals = np.fromiter((bool(w[2]) for w in req_windows),
                              dtype=np.bool_, count=n_reqs)
        counts = np.fromiter((len(n[3]) for n in nats),
                             dtype=np.int64, count=n_reqs)
        entry_start = np.zeros(n_reqs + 1, dtype=np.int64)
        np.cumsum(counts, out=entry_start[1:])
        e = int(entry_start[-1])
        entry_mask = np.fromiter(
            (w[7][ck] for w in req_windows for ck in w[6][3]),
            dtype=np.uint64, count=e)
        wants = np.minimum(wants_in, geoms[:, 0])
        rows_total = int(wants.sum())
        row_base = np.zeros(n_reqs, dtype=np.int64)
        np.cumsum(wants[:-1], out=row_base[1:])
        row_base += np.arange(n_reqs)  # +r: offset windows are count+1
        key_cap = int((wants * geoms[:, 2]).sum())
        val_cap = int(np.where(
            no_vals, 0,
            np.minimum(byte_cap + (64 << 10), geoms[:, 1])).sum())
        no_values = no_vals.astype(np.uint8)
    else:
        # ad-hoc callers (tests, fallbacks) may omit nat/live_ptrs
        nats = []
        mask_arrays = []
        entry_start = np.zeros(n_reqs + 1, dtype=np.int64)
        wants = np.empty(n_reqs, dtype=np.int64)
        no_values = np.empty(n_reqs, dtype=np.uint8)
        row_base = np.empty(n_reqs, dtype=np.int64)
        e = 0
        rows_total = 0
        key_cap = 0
        val_cap = 0
        for r, window in enumerate(req_windows):
            plan, want, no_value, _we, live_masks = window[:5]
            geom = (window[5] if len(window) > 5
                    and window[5] is not None else plan_geometry(plan))
            nat = window[6] if len(window) > 6 else plan_nat(plan)
            masks = [live_masks[ck] for ck in nat[3]]
            mask_refs.extend(masks)
            mask_arrays.append(np.fromiter(
                (m.ctypes.data for m in masks),
                dtype=np.uint64, count=len(masks)))
            nats.append(nat)
            e += len(nat[3])
            entry_start[r + 1] = e
            total_rows, span, max_w = geom
            row_base[r] = rows_total + r
            cap_rows = min(want, total_rows)
            wants[r] = cap_rows
            no_values[r] = no_value
            rows_total += cap_rows
            key_cap += cap_rows * max_w
            val_cap += 0 if no_value else min(byte_cap + (64 << 10),
                                              span)
        entry_mask = (mask_arrays[0] if n_reqs == 1
                      else np.concatenate(mask_arrays))
    if key_cap >= 1 << 32 or val_cap >= 1 << 32:
        # running arena offsets are uint32: a flush whose combined
        # spans pass 4 GiB must take the per-request Python path (which
        # enforces its own per-request caps) instead of wrapping
        return None
    if n_reqs == 1:
        ptr6, entry_lo, entry_hi = nats[0][:3]
        widths = nats[0][4]
    else:
        ptr6 = np.concatenate([n[0] for n in nats], axis=1)
        entry_lo = np.concatenate([n[1] for n in nats])
        entry_hi = np.concatenate([n[2] for n in nats])
        widths = np.concatenate([n[4] for n in nats])
    # grow-only arenas + outputs (the C call writes every cell the
    # result loop reads — no zeroing needed); entry_block is a cached
    # arange prefix (the per-entry block table is identity now)
    _entry_block, eb_ptr = _scratch("entry_block", e, np.int64,
                                    alloc=np.arange)
    key_blob, kb_ptr = _scratch("key_blob", max(1, key_cap), np.uint8)
    val_blob, vb_ptr = _scratch("val_blob", max(1, val_cap), np.uint8)
    n_offs = rows_total + n_reqs + 1
    key_offs, ko_ptr = _scratch("key_offs", n_offs, np.uint32)
    val_offs, vo_ptr = _scratch("val_offs", n_offs, np.uint32)
    if want_ets:
        ets_arena, ets_ptr = _scratch("ets", max(1, rows_total),
                                      np.uint32)
    else:
        ets_arena, ets_ptr = None, None
    out_count, oc_ptr = _scratch("out_count", n_reqs, np.int64)
    out_bytes, ob_ptr = _scratch("out_bytes", n_reqs, np.int64)
    out_state, os_ptr = _scratch("out_state", n_reqs, np.int32)
    fn(ptr6[0].ctypes.data, widths.ctypes.data, ptr6[2].ctypes.data,
       entry_mask.ctypes.data, ptr6[3].ctypes.data, ptr6[4].ctypes.data,
       ptr6[5].ctypes.data, n_reqs, entry_start.ctypes.data,
       eb_ptr, entry_lo.ctypes.data,
       entry_hi.ctypes.data, wants.ctypes.data, no_values.ctypes.data,
       byte_cap, hdr, kb_ptr, key_cap,
       vb_ptr, val_cap, ko_ptr,
       vo_ptr, row_base.ctypes.data,
       ets_ptr,
       oc_ptr, ob_ptr, os_ptr)

    results = []
    for r in range(n_reqs):
        state = int(out_state[r])
        if state == 3:
            results.append(None)  # arena full: Python re-serves
            continue
        count = int(out_count[r])
        truncated = state == 2
        if count == 0:
            results.append((ScanPage(), 0, None, truncated))
            continue
        base = int(row_base[r])
        ko = key_offs[base:base + count + 1]
        vo = val_offs[base:base + count + 1]
        k0, k1 = int(ko[0]), int(ko[count])
        v0, v1 = int(vo[0]), int(vo[count])
        page = ScanPage(
            key_offs=(ko - np.uint32(k0)).tobytes(),
            key_blob=key_blob[k0:k1].tobytes(),
            val_offs=(vo - np.uint32(v0)).tobytes(),
            val_blob=val_blob[v0:v1].tobytes())
        if req_windows[r][3]:
            page.ets = ets_arena[base - r:base - r + count].astype(
                "<u4").tobytes()
        last_key = key_blob[int(ko[count - 1]):k1].tobytes()
        results.append((page, int(out_bytes[r]), last_key, truncated))
    return results


def build_page(chunks: List[Tuple[object, np.ndarray]], hdr: int,
               no_value: bool = False, want_ets: bool = False,
               ) -> Tuple[ScanPage, int, Optional[bytes]]:
    """Pack survivors into one page.

    chunks: [(Block, ascending int64 row indices)] in key order across
    blocks. Returns (page, byte_size, last_key) where byte_size is the
    capacity-unit accounting sum (key bytes + user-data bytes) and
    last_key is the final packed key (resume cursor) or None for an
    empty page.
    """
    chunks = [(blk, take) for blk, take in chunks if len(take)]
    n = sum(len(take) for _b, take in chunks)
    if n == 0:
        return ScanPage(), 0, None

    # UPPER-BOUND blob capacities from scalar offset reads (takes are
    # ascending, so a chunk's value bytes fit in [offs[first],
    # offs[last+1])); the gather writes the exact running offsets and
    # the blobs are trimmed afterwards — O(1) sizing per chunk instead
    # of per-take vector math on this per-request path
    key_cap = 0
    val_cap = 0
    for blk, take in chunks:
        key_cap += len(take) * blk.keys.shape[1]
        if not no_value:
            vo = blk.value_offs
            val_cap += int(vo[int(take[-1]) + 1]) - int(vo[int(take[0])])
    if key_cap >= 1 << 32 or val_cap >= 1 << 32:
        # offsets are uint32 (here and in pegasus_gather_page); callers
        # cap batch_size (SCAN_BATCH_CAP) so this only trips on a bug
        raise ValueError(
            f"scan page exceeds 4GiB blob limit "
            f"(keys={key_cap}, values={val_cap}); split the batch")

    key_offs = np.zeros(n + 1, dtype=np.uint32)
    val_offs = np.zeros(n + 1, dtype=np.uint32)
    key_buf = bytearray(key_cap)
    val_buf = bytearray(val_cap)
    kb = np.frombuffer(key_buf, dtype=np.uint8)
    vb = np.frombuffer(val_buf, dtype=np.uint8) if val_cap else None

    fn = native.gather_page_fn()
    pos = 0
    for blk, take in chunks:
        m = len(take)
        take = np.ascontiguousarray(take, dtype=np.int64)
        if fn is not None:
            heap = blk.value_heap
            if not isinstance(heap, np.ndarray):
                heap = np.frombuffer(heap, dtype=np.uint8)
            fn(blk.keys.ctypes.data, blk.keys.shape[1],
               blk.key_len.ctypes.data, blk.value_offs.ctypes.data,
               heap.ctypes.data if heap.size else None,
               take.ctypes.data, m, hdr,
               kb.ctypes.data, key_offs[pos:].ctypes.data,
               (vb.ctypes.data if not no_value and vb is not None
                else None),
               val_offs[pos:].ctypes.data)
        else:
            _gather_python(blk, take, hdr, no_value, kb, key_offs,
                           vb, val_offs, pos)
        pos += m

    key_total = int(key_offs[n])
    val_total = int(val_offs[n])
    last_i = int(key_offs[n - 1])
    page = ScanPage(
        key_offs=key_offs.tobytes(), key_blob=bytes(key_buf[:key_total]),
        val_offs=val_offs.tobytes(), val_blob=bytes(val_buf[:val_total]))
    if want_ets:
        page.ets = np.concatenate(
            [np.asarray(blk.expire_ts)[take]
             for blk, take in chunks]).astype("<u4").tobytes()
    return page, key_total + val_total, bytes(key_buf[last_i:key_total])


def _gather_python(blk, take, hdr, no_value, kb, key_offs, vb, val_offs,
                   pos) -> None:
    """Pure-Python twin of pegasus_gather_page (no toolchain)."""
    kpos = int(key_offs[pos])
    vpos = int(val_offs[pos])
    vo = blk.value_offs
    heap = blk.value_heap
    for j, row in enumerate(take):
        row = int(row)
        kl = int(blk.key_len[row])
        kb[kpos:kpos + kl] = blk.keys[row, :kl]
        kpos += kl
        key_offs[pos + j + 1] = kpos
        v0, v1 = int(vo[row]), int(vo[row + 1])
        vl = max(0, v1 - v0 - hdr)
        if not no_value:
            if vl:
                if not isinstance(heap, np.ndarray):
                    heap = np.frombuffer(heap, dtype=np.uint8)
                vb[vpos:vpos + vl] = heap[v0 + hdr:v1]
            vpos += vl
        val_offs[pos + j + 1] = vpos
