"""EXPLAIN: execute one op and render its plan with real counters.

`shell explain <table> <op-spec>` runs one captured op through the
REAL serving path (the batched point planner / the batched scan
planner — never a side path that could drift from production) with a
forced PerfContext and a zeroed slow-log threshold, then renders the
stage chain with the per-stage cost counters next to the timings —
the report a RocksDB operator gets from perf_context + EXPLAIN in a
SQL engine, for this engine's plan shapes.

`shell explain --from-trace <id>` rebuilds the same report from a kept
slow trace: the serving paths stamp their cost vector onto the op's
span (`span.tags["perf"]`), so any tail-kept slow trace already
carries everything this module needs — the after-the-fact explain for
an op nobody planned to debug.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pegasus_tpu.utils import perf_context as perf

# which cost-vector fields belong to which stage of the known chains
# (plan/bloom/phash_probe/block_probe/decode/finish for point flushes;
# plan/block_scan|block_probe/decode/assemble|finish for scans)
STAGE_FIELDS: Dict[str, tuple] = {
    "plan": ("ops", "keys_resolved", "runs_considered", "overlay_hits",
             "row_cache_hit", "row_cache_miss"),
    "bloom": ("bloom_pruned",),
    "phash_probe": ("phash_pruned", "phash_located"),
    "block_probe": ("blocks_decoded", "block_cache_hit", "bytes_read",
                    "blocks_planned"),
    "block_scan": ("blocks_decoded", "block_cache_hit", "bytes_read",
                   "rows_evaluated", "mesh_partitions"),
    "pushdown": ("pushdown_rows_pruned", "rows_aggregated"),
    "decode": ("bytes_decoded",),
    "assemble": ("rows_survived", "bytes_returned"),
    "finish": ("rows_evaluated", "rows_survived", "expired_rows",
               "bytes_returned"),
}


def _summarize_result(op: str, result) -> Dict[str, Any]:
    if op in ("get", "ttl"):
        status, payload = result
        out = {"status": int(status)}
        if op == "get":
            out["value_bytes"] = len(payload)
        else:
            out["ttl"] = payload
        return out
    kvs = getattr(result, "kvs", None)
    if kvs is None:
        kvs = getattr(result, "data", None)
    out = {"error": int(getattr(result, "error", 0)),
           "rows": len(kvs) if kvs is not None else 0}
    if getattr(result, "pushdown_applied", False):
        out["pushdown_applied"] = True
    agg = getattr(result, "agg", None)
    if agg is not None:
        out["agg"] = {k: agg[k] for k in ("kind", "count", "total")
                      if agg.get(k) or k == "kind"}
    return out


def explain_op(server, op: str, args,
               partition_hash: Optional[int] = None) -> Dict[str, Any]:
    """Execute ONE op on `server` (a PartitionServer) under a forced
    PerfContext and return the explain report (stage chain + cost
    vector + placement audit). The op really executes — an explain of
    a write-heavy table's scan costs what the scan costs — through the
    REAL batched phases, reading the stage chain off the op's own
    tracer (never the shared slow ring, whose tail a concurrently
    served request could own)."""
    import time as _time

    from pegasus_tpu.server.workload import DRIFT

    pc = perf.PerfContext(f"explain:{op}")
    tracer = None
    t0 = _time.perf_counter()
    with perf.activate(pc):
        if op in ("get", "ttl", "multi_get", "batch_get"):
            state = server.plan_get_batch([(op, args, partition_hash)])
            result = server.serve_get_batch(state)[0]
            tracer = state.get("tracer")
        elif op == "scan":
            state = server.plan_scan_batch([args])
            if state is None:
                # store shape can't take the batched path (big
                # overlay / exotic filter): solo serve — the cost
                # vector still fills, the stage chain doesn't
                result = server.on_get_scanner(args)
            elif "precomputed" in state:
                result = state["precomputed"][0]
            else:
                keep = server.eval_planned_masks(state)
                result = server.finish_scan_batch(state, keep)[0]
                tracer = state.get("tracer")
        else:
            raise ValueError(f"explain: unknown op {op!r}")
    wall_ms = (_time.perf_counter() - t0) * 1000.0
    report = tracer.report() if tracer is not None else {}
    return {
        "op": op,
        "gpid": [server.app_id, server.pidx],
        "total_ms": report.get("total_ms", round(wall_ms, 3)),
        "stages": report.get("stages", []),
        "perf": pc.to_dict(),
        "result": _summarize_result(op, result),
        "drift": DRIFT.status(),
    }


def op_from_spec(spec: Dict[str, Any]):
    """(op, op_args, partition_hash) from a compact spec dict
    ``{op, hash_key, sort_key?|sort_keys?, batch_size?}`` (keys utf-8
    strings) — shared by the shell's --root mode and the node's
    ``perf.explain`` verb so the two surfaces cannot drift."""
    from pegasus_tpu.base.key_schema import (
        generate_key,
        generate_next_bytes,
        key_hash_parts,
    )

    op = spec.get("op", "get")
    hk = spec.get("hash_key", "").encode()
    if op in ("get", "ttl"):
        sk = spec.get("sort_key", "").encode()
        return op, generate_key(hk, sk), key_hash_parts(hk, sk)
    if op == "multi_get":
        from pegasus_tpu.server.types import MultiGetRequest

        return op, MultiGetRequest(
            hash_key=hk,
            sort_keys=[s.encode()
                       for s in spec.get("sort_keys", [])]), \
            key_hash_parts(hk, b"")
    if op == "scan":
        from pegasus_tpu.server.types import GetScannerRequest

        pushdown = None
        if spec.get("filter") or spec.get("agg"):
            from pegasus_tpu.ops.predicates import FT_MATCH_ANYWHERE
            from pegasus_tpu.ops.pushdown import PushdownSpec

            pushdown = PushdownSpec(
                value_filter_type=(FT_MATCH_ANYWHERE if spec.get("filter")
                                   else 0),
                value_filter_pattern=spec.get("filter", "").encode(),
                aggregate=spec.get("agg", ""),
                k=int(spec.get("k", 0)))
        return op, GetScannerRequest(
            start_key=generate_key(hk, b"") if hk else b"",
            stop_key=(generate_next_bytes(hk) if hk else b""),
            batch_size=int(spec.get("batch_size", 100)),
            one_page=True,
            pushdown=pushdown), None
    raise ValueError(f"explain: unknown op {op!r}")


def spec_from_words(words: List[str]) -> Dict[str, Any]:
    """The shell's positional op-spec -> spec dict:
    ``get <hk> [sk]`` / ``multi_get <hk> <sk> [sk...]`` /
    ``scan [hk] [batch_size]``."""
    if not words:
        raise ValueError("empty op spec")
    op = words[0]
    if op in ("get", "ttl"):
        if len(words) < 2:
            raise ValueError(f"usage: explain <table> {op} "
                             "<hash_key> [sort_key]")
        return {"op": op, "hash_key": words[1],
                "sort_key": words[2] if len(words) > 2 else ""}
    if op == "multi_get":
        if len(words) < 3:
            raise ValueError("usage: explain <table> multi_get "
                             "<hash_key> <sort_key> [sort_key...]")
        return {"op": op, "hash_key": words[1],
                "sort_keys": words[2:]}
    if op == "scan":
        spec: Dict[str, Any] = {"op": op}
        pos = 1
        for w in words[1:]:
            # pushdown spec words: filter=<pattern> pushes an ANYWHERE
            # value filter; agg=count|sum|top_k|sample (+ k=<n>)
            if "=" in w:
                key, _, val = w.partition("=")
                if key not in ("filter", "agg", "k", "batch_size"):
                    raise ValueError(f"explain scan: unknown option "
                                     f"{key!r} (filter|agg|k|batch_size)")
                spec[key] = int(val) if key in ("k", "batch_size") else val
                continue
            if pos == 1:
                spec["hash_key"] = w
            elif pos == 2:
                spec["batch_size"] = int(w)
            else:
                raise ValueError("usage: explain <table> scan [hash_key]"
                                 " [batch_size] [filter=<pat>]"
                                 " [agg=<kind>] [k=<n>]")
            pos += 1
        return spec
    raise ValueError(f"explain: unknown op {op!r} "
                     "(get|ttl|multi_get|scan)")


def from_trace(spans: List[dict], trace_id: str) -> Dict[str, Any]:
    """Rebuild explain reports from a (stitched or raw) span dump: every
    span carrying a perf tag becomes one op report, its stage chain
    recovered from the span's annotations."""
    ops = []
    for d in sorted(spans, key=lambda s: s.get("start", 0.0)):
        tags = d.get("tags") or {}
        pc = tags.get("perf")
        if pc is None:
            continue
        t0 = d.get("start", 0.0)
        stages = []
        prev = t0
        for stage, at in d.get("ann") or []:
            stages.append({"stage": stage,
                           "delta_ms": round((at - prev) * 1000.0, 3),
                           "at_ms": round((at - t0) * 1000.0, 3)})
            prev = at
        ops.append({
            "op": pc.get("op", d.get("name", "?")),
            "span": d.get("name"),
            "node": d.get("node"),
            "total_ms": round(
                (d.get("end", t0) - t0) * 1000.0, 3),
            "stages": stages,
            "perf": pc,
        })
    return {"trace": trace_id, "ops": ops}


def _stage_line(stage: Dict[str, Any], pc: Dict[str, Any],
                last: bool) -> str:
    name = stage.get("stage", "?")
    fields = STAGE_FIELDS.get(name, ())
    shown = " ".join(f"{f}={pc[f]}" for f in fields
                     if pc.get(f) not in (None, 0, 0.0))
    tee = "└─" if last else "├─"
    base = f"{tee} {name:<12} {stage.get('delta_ms', 0.0):8.3f} ms"
    return f"{base}  {shown}" if shown else base


def render_report(report: Dict[str, Any]) -> str:
    """One op's explain report as a tree: header, per-stage timings
    with that stage's counters, then the placement/kernel audit."""
    pc = report.get("perf") or {}
    gpid = report.get("gpid")
    where = (f" @ {gpid[0]}.{gpid[1]}" if gpid
             else f" @ {report.get('node', '?')}")
    lines = [f"EXPLAIN {report.get('op', '?')}{where} — "
             f"{report.get('total_ms', 0.0):.3f} ms"
             + (f", placement {pc.get('placement')}"
                if pc.get("placement") else "")
             + (f", served_by {pc.get('served_by')}"
                if pc.get("served_by") else "")]
    stages = report.get("stages") or []
    for i, st in enumerate(stages):
        lines.append("  " + _stage_line(st, pc, i == len(stages) - 1))
    # rows/bytes rollup + the unmapped remainder
    lines.append(
        f"  rows: evaluated={pc.get('rows_evaluated', 0)} "
        f"survived={pc.get('rows_survived', 0)} "
        f"expired={pc.get('expired_rows', 0)}   "
        f"bytes: read={pc.get('bytes_read', 0)} "
        f"decoded={pc.get('bytes_decoded', 0)} "
        f"returned={pc.get('bytes_returned', 0)}")
    if pc.get("measured_kernel_ms") or pc.get("predicted_kernel_ms"):
        lines.append(
            f"  kernel: predicted={pc.get('predicted_kernel_ms', 0.0)} ms "
            f"measured={pc.get('measured_kernel_ms', 0.0)} ms")
    if pc.get("mesh_partitions") or pc.get("mesh_wave_ms"):
        lines.append(
            f"  mesh: partitions={pc.get('mesh_partitions', 0)} "
            f"wave={pc.get('mesh_wave_ms', 0.0)} ms (resident SPMD "
            "dispatch answered this scan's waves)")
    if pc.get("queue_wait_ms"):
        lines.append(f"  queue_wait: {pc['queue_wait_ms']} ms")
    res = report.get("result")
    if res is not None:
        lines.append(f"  result: {res}")
    drift = report.get("drift")
    if drift and drift.get("classes"):
        lines.append(f"  cost-model drift: {drift['drift_ratio']}x "
                     "(measured/predicted, worst class)")
    return "\n".join(lines)


def render_trace_report(report: Dict[str, Any]) -> str:
    lines = [f"EXPLAIN --from-trace {report.get('trace')}: "
             f"{len(report.get('ops') or [])} op(s) with cost vectors"]
    for op in report.get("ops") or []:
        lines.append("")
        lines.append(render_report(dict(op, op=(
            f"{op.get('op')} [{op.get('span')} on {op.get('node')}]"))))
    if not report.get("ops"):
        lines.append("  (no spans with perf tags — was the op sampled "
                     "and served by an instrumented path?)")
    return "\n".join(lines)
