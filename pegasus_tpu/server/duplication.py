"""Cross-cluster duplication: tail the private log, batch, ship, confirm.

Parity: src/replica/duplication/ — the per-replica pipeline
(replica_duplicator.h:79): load_mutation (tail the private log from the
last confirmed decree, load_from_private_log.h:47) -> mutation_batch ->
ship_mutation (duplication_pipeline.h:66) through a pluggable backend
(mutation_duplicator.h, implemented for Pegasus targets by
pegasus_mutation_duplicator.h:56 shipping via the remote cluster's
client). Progress (confirmed decree) is reported upward the way
duplication_sync_timer syncs it to meta.

Conflict handling on the follower: value-v1 timetags decide
(base/pegasus_value_schema.h:175-209) — the shipped write applies only if
its timetag beats the follower's current record (WriteService.duplicate_*).

Limitation (parity note): non-idempotent atomic ops (incr/cas/cam) must
be translated to idempotent puts BEFORE duplication, as the reference
does with idempotent_writer (replica/idempotent_writer.h); this pipeline
refuses to ship raw atomic mutations.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.base.value_schema import (
    PEGASUS_EPOCH_BEGIN,
    expire_ts_from_ttl,
    generate_timetag,
)
from pegasus_tpu.replica.mutation import ATOMIC_OPS, Mutation
from pegasus_tpu.rpc.codec import (
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
)

DS_INIT = "init"
DS_START = "start"
DS_PAUSE = "pause"
DS_REMOVED = "removed"


@dataclass
class DuplicationInfo:
    """Parity: duplication_info (meta/duplication/duplication_info.h) —
    id, follower cluster, status, per-partition confirmed decrees."""

    dupid: int
    follower_cluster: str
    status: str = DS_START
    progress: Dict[int, int] = field(default_factory=dict)  # pidx -> decree


class TableShipper:
    """Applies shipped mutations to a follower table, routing every key by
    the FOLLOWER's partition count (clusters may differ) and resolving
    conflicts via timetags (parity: pegasus_mutation_duplicator sending
    duplicate-tagged writes through the remote client).

    `source_cluster_id` is the master cluster's id — it rides in every
    shipped timetag so equal-timestamp master-master writes still resolve
    deterministically (the cluster-id tiebreak in the timetag layout)."""

    def __init__(self, follower_table, source_cluster_id: int = 1) -> None:
        self.table = follower_table
        self.source_cluster_id = source_cluster_id

    def ship(self, mu: Mutation) -> int:
        """Ships one mutation; returns how many writes applied (lost
        conflicts still confirm — they were delivered)."""
        applied = 0
        # the mutation's own timestamp anchors TTL arithmetic: shipping
        # delay must not restart TTL clocks on the follower
        mu_now = max(0, mu.timestamp_us // 1_000_000 - PEGASUS_EPOCH_BEGIN)
        for i, wo in enumerate(mu.ops):
            # per-op timetags stay unique + ordered within the mutation
            # (the primary reserves len(ops) microseconds per mutation)
            timetag = generate_timetag(mu.timestamp_us + i,
                                       self.source_cluster_id, False)
            applied += self._ship_op(wo.op, wo.request, timetag, mu_now)
        return applied

    def _server_for(self, key: bytes):
        pidx = key_hash(key) % self.table.partition_count
        return self.table.partitions[pidx]

    def _ship_op(self, op: int, req, timetag: int, mu_now: int) -> int:
        if op in ATOMIC_OPS:
            raise ValueError(
                "atomic mutations must be idempotent-translated before "
                "duplication (reference: idempotent_writer)")
        applied = 0
        if op == OP_PUT:
            key, user_data, expire_ts = req
            server = self._server_for(key)
            with server._write_lock:
                applied += server.write_service.duplicate_put(
                    key, user_data, expire_ts, timetag,
                    server._next_decree())
        elif op == OP_REMOVE:
            (key,) = req
            server = self._server_for(key)
            with server._write_lock:
                applied += server.write_service.duplicate_remove(
                    key, timetag, server._next_decree())
        elif op == OP_MULTI_PUT:
            expire_ts = expire_ts_from_ttl(req.expire_ts_seconds, now=mu_now)
            for kv in req.kvs:
                key = generate_key(req.hash_key, kv.key)
                server = self._server_for(key)
                with server._write_lock:
                    applied += server.write_service.duplicate_put(
                        key, kv.value, expire_ts, timetag,
                        server._next_decree())
        elif op == OP_MULTI_REMOVE:
            for sk in req.sort_keys:
                key = generate_key(req.hash_key, sk)
                server = self._server_for(key)
                with server._write_lock:
                    applied += server.write_service.duplicate_remove(
                        key, timetag, server._next_decree())
        else:
            raise ValueError(f"unknown op {op}")
        return applied


class ReplicaDuplicator:
    """The per-partition pipeline owner (parity: replica_duplicator.h:79).

    `shipper` is any object with ship(mutation) — a TableShipper for
    in-proc follower clusters, an RPC client for remote ones.
    """

    def __init__(self, replica, shipper, dupid: int = 1,
                 confirmed_decree: int = 0,
                 on_progress: Optional[Callable[[int, int], None]] = None
                 ) -> None:
        self.replica = replica
        self.shipper = shipper
        self.dupid = dupid
        self.confirmed_decree = confirmed_decree
        self.on_progress = on_progress  # (dupid, confirmed) -> meta sync
        # incremental log tailing state (parity: load_from_private_log);
        # reset when the log is rewritten by GC
        self._log_offset = 0
        self._log_generation = self.replica.log.generation
        # registering holds the replica's log GC back to our progress
        self.replica.duplicators.append(self)

    def sync_round(self) -> int:
        """One load->ship->confirm round (parity: duplication_sync_timer).
        Tails the private log incrementally; ships committed mutations
        beyond the confirmed decree; returns how many shipped.

        Offset discipline: the offset only advances past frames that were
        actually consumed (shipped, or skippable as <= confirmed). A frame
        whose decree is still uncommitted, or a ship failure, stops the
        round WITHOUT advancing — the next round re-reads from there.
        Committed re-proposed frames (same decree, higher ballot) carry
        identical ops, so shipping the first-seen committed frame is safe.
        """
        last_committed = self.replica.last_committed_decree
        log = self.replica.log
        if log.generation != self._log_generation:
            self._log_offset = 0
            self._log_generation = log.generation
        shipped = 0
        for mu, frame_end in log.read_tail(self._log_offset):
            if mu.decree > last_committed:
                break  # not committed yet: do NOT advance past it
            if mu.decree > self.confirmed_decree:
                self.shipper.ship(mu)  # a raise leaves the offset put
                self.confirmed_decree = mu.decree
                shipped += 1
            self._log_offset = frame_end
        if shipped and self.on_progress is not None:
            self.on_progress(self.dupid, self.confirmed_decree)
        return shipped
