"""Bulk load: ingest externally-generated SST files from the block service.

Parity: src/replica/bulk_load/replica_bulk_loader.h:49 (replica side:
download SSTs from the block service, verify, ingest through the write
path) + src/meta/meta_bulk_load_service.h:143 (per-partition
download->ingest state machine with rolling concurrency). The external
generator produces one columnar SST per target partition under

    <root>/<app_name>/<pidx>/bulk_load.sst          (+ .md5 sidecars)
    <root>/<app_name>/bulk_load_info.json           {partition_count, ...}

`SSTGenerator` is the offline-writer the reference leaves to Spark
pipelines: it partitions records by the TARGET table's partition count and
emits per-partition sorted columnar SSTs ready to ingest.
"""

from __future__ import annotations

import enum
import json
import os
import tempfile
from typing import Dict, Iterable, List, Optional, Tuple

from pegasus_tpu.base.key_schema import generate_key, partition_index
from pegasus_tpu.base.value_schema import generate_value
from pegasus_tpu.storage.block_service import BlockService
from pegasus_tpu.storage.sstable import SSTableWriter

BULK_LOAD_INFO = "bulk_load_info.json"
BULK_LOAD_FILE = "bulk_load.sst"


class BulkLoadStatus(enum.Enum):
    INVALID = "invalid"
    DOWNLOADING = "downloading"
    INGESTING = "ingesting"
    SUCCEED = "succeed"
    FAILED = "failed"


class SSTGenerator:
    """Offline: records -> per-partition columnar SSTs in a block service."""

    def __init__(self, block_service: BlockService, app_name: str,
                 partition_count: int, data_version: int = 1) -> None:
        self.bs = block_service
        self.app_name = app_name
        self.partition_count = partition_count
        self.data_version = data_version

    def generate(self, records: Iterable[Tuple[bytes, bytes, bytes, int]]
                 ) -> Dict[int, int]:
        """records: (hash_key, sort_key, value, expire_ts). Returns per-
        partition record counts."""
        # routing MUST match the single-key write path (pegasus_key_hash
        # of the full key, Table.resolve(hk, sk)), or empty-hashkey records
        # would land where reads never look; dict insertion keeps the LAST
        # occurrence of duplicates
        buckets: Dict[int, Dict[bytes, Tuple[bytes, int]]] = {}
        for hk, sk, value, ets in records:
            key = generate_key(hk, sk)
            pidx = partition_index(hk, self.partition_count, sk)
            buckets.setdefault(pidx, {})[key] = (
                generate_value(self.data_version, value, ets), ets)
        counts = {}
        with tempfile.TemporaryDirectory(prefix="pegbl") as tmp:
            for pidx, rows in buckets.items():
                local = os.path.join(tmp, f"{pidx}.sst")
                writer = SSTableWriter(local)
                for key in sorted(rows):
                    value, ets = rows[key]
                    writer.add(key, value, ets)
                writer.finish()
                self.bs.upload(local,
                               f"{self.app_name}/{pidx}/{BULK_LOAD_FILE}")
                counts[pidx] = len(rows)
        self.bs.write_file(f"{self.app_name}/{BULK_LOAD_INFO}", json.dumps({
            "app_name": self.app_name,
            "partition_count": self.partition_count,
            "data_version": self.data_version,
        }).encode())
        return counts


class BulkLoader:
    """Online: drive download+ingest across a table's partitions (the
    meta bulk-load state machine, collapsed to the in-proc table)."""

    def __init__(self, block_service: BlockService) -> None:
        self.bs = block_service
        self.status: Dict[int, BulkLoadStatus] = {}

    def load_into(self, table, app_name: Optional[str] = None) -> int:
        """Ingest every partition's staged SST; returns records ingested.
        The staged partition_count must match the table's (the reference
        rejects mismatched bulk loads)."""
        app_name = app_name or table.app_name
        info = json.loads(self.bs.read_file(f"{app_name}/{BULK_LOAD_INFO}"))
        if info["partition_count"] != table.partition_count:
            raise ValueError(
                f"bulk load built for {info['partition_count']} partitions, "
                f"table has {table.partition_count}")
        if info.get("data_version", 1) != table.data_version:
            raise ValueError(
                f"bulk load encoded with data_version "
                f"{info.get('data_version')}, table uses "
                f"{table.data_version}")
        total = 0
        with tempfile.TemporaryDirectory(prefix="pegbl") as tmp:
            for pidx in range(table.partition_count):
                remote = f"{app_name}/{pidx}/{BULK_LOAD_FILE}"
                if not self.bs.exists(remote):
                    self.status[pidx] = BulkLoadStatus.SUCCEED
                    continue  # no data staged for this partition
                self.status[pidx] = BulkLoadStatus.DOWNLOADING
                local = os.path.join(tmp, f"{pidx}.sst")
                try:
                    self.bs.download(remote, local)
                    self.status[pidx] = BulkLoadStatus.INGESTING
                    server = table.partitions[pidx]
                    with server._write_lock:
                        server.engine.ingest_sst_file(
                            local, server.engine.last_committed_decree + 1)
                    from pegasus_tpu.storage.sstable import SSTable
                    t = SSTable(local)
                    total += t.total_count
                    t.close()
                    self.status[pidx] = BulkLoadStatus.SUCCEED
                except Exception:
                    self.status[pidx] = BulkLoadStatus.FAILED
                    raise
        return total
