"""Cold backup + restore over the block service.

Parity: the replica-side backup flow (src/replica/backup/
cold_backup_context.*, replica_backup_manager.*) and the meta-side
policy/one-shot orchestration (src/meta/meta_backup_service.h:360,
backup_engine.h:68), plus restore (src/replica/replica_restore.cpp,
meta/server_state_restore.cpp: a new table created "from cold backup"
downloads its checkpoint from the block service).

Remote layout (policy-compatible shape):
    <root>/<policy>/<backup_id>/<app_id>/<pidx>/<sst files + meta.json>
    <root>/<policy>/<backup_id>/backup_metadata.json
"""

from __future__ import annotations

import json
import os

from pegasus_tpu.storage.efile import open_data_file
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pegasus_tpu.storage.block_service import BlockService
from pegasus_tpu.storage.engine import StorageEngine


@dataclass
class BackupPolicy:
    """Parity: policy (meta_backup_service.h) — which apps, where, how
    often, how many kept."""

    name: str
    app_ids: List[int]
    interval_seconds: int = 86400
    backup_history_count: int = 3


class BackupEngine:
    """One-shot backup of a table across its partitions (parity:
    backup_engine.h:68 driving per-partition checkpoint uploads)."""

    def __init__(self, block_service: BlockService, policy_name: str) -> None:
        self.bs = block_service
        self.policy_name = policy_name

    def backup_partition(self, backup_id: int, app_id: int, pidx: int,
                         engine: StorageEngine, server=None) -> int:
        """Checkpoint one partition and upload it. Returns the decree.
        `server`: the owning PartitionServer when available — its
        checkpoint() carries the single-writer lock against the async
        env-compaction thread; bare engines (offline tooling) snapshot
        directly."""
        with tempfile.TemporaryDirectory(prefix="pegbk") as tmp:
            decree = (server.checkpoint(tmp) if server is not None
                      else engine.checkpoint(tmp))
            self.upload_checkpoint(backup_id, app_id, pidx, tmp, decree)
            return decree

    def upload_checkpoint(self, backup_id: int, app_id: int, pidx: int,
                          ckpt_dir: str, decree: int) -> None:
        """Upload a materialized checkpoint dir (the slow half — safe to
        run off the replica's dispatch thread; only the checkpoint itself
        needs engine serialization)."""
        base = f"{self.policy_name}/{backup_id}/{app_id}/{pidx}"
        files = []
        for name in sorted(os.listdir(ckpt_dir)):
            with open_data_file(os.path.join(ckpt_dir, name), "rb") as f:
                self.bs.write_file(f"{base}/{name}", f.read())
            files.append(name)
        self.bs.write_file(f"{base}/meta.json", json.dumps({
            "decree": decree, "files": files}).encode())

    def finish_backup(self, backup_id: int, app_id: int, app_name: str,
                      partition_count: int) -> None:
        self.bs.write_file(
            f"{self.policy_name}/{backup_id}/backup_metadata.json",
            json.dumps({
                "backup_id": backup_id, "app_id": app_id,
                "app_name": app_name, "partition_count": partition_count,
                "complete": True}).encode())

    def list_backups(self) -> List[int]:
        out = []
        for name in self.bs.list_dir(self.policy_name):
            if name.isdigit() and self.bs.exists(
                    f"{self.policy_name}/{name}/backup_metadata.json"):
                out.append(int(name))
        return sorted(out)

    def gc_old_backups(self, keep: int) -> List[int]:
        """Parity: policy backup_history_count GC."""
        backups = self.list_backups()
        dropped = backups[:-keep] if keep > 0 else []
        for backup_id in dropped:
            self.bs.remove_path(f"{self.policy_name}/{backup_id}")
        return dropped

    def restore_partition(self, backup_id: int, app_id: int, pidx: int,
                          data_dir: str) -> StorageEngine:
        """Download one partition's checkpoint and open an engine on it."""
        base = f"{self.policy_name}/{backup_id}/{app_id}/{pidx}"
        meta = json.loads(self.bs.read_file(f"{base}/meta.json"))
        with tempfile.TemporaryDirectory(prefix="pegrs") as tmp:
            for name in meta["files"]:
                self.bs.download(f"{base}/{name}", os.path.join(tmp, name))
            return StorageEngine.restore_from_checkpoint(tmp, data_dir)

    def read_backup_metadata(self, backup_id: int) -> dict:
        return json.loads(self.bs.read_file(
            f"{self.policy_name}/{backup_id}/backup_metadata.json"))


class BackupScheduler:
    """Policy-driven periodic backups (parity: the policy scheduler loop
    in meta_backup_service). Call tick(now) from a timer; each due policy
    produces one backup of each of its tables via the provided
    `backup_table(policy, backup_id, app_id)` callback."""

    def __init__(self, backup_table, clock) -> None:
        self._policies: Dict[str, BackupPolicy] = {}
        self._last_run: Dict[str, float] = {}
        self._backup_table = backup_table
        self._clock = clock

    def add_policy(self, policy: BackupPolicy) -> None:
        if policy.name in self._policies:
            raise ValueError(f"policy {policy.name} exists")
        self._policies[policy.name] = policy

    def policies(self) -> List[BackupPolicy]:
        return list(self._policies.values())

    def tick(self) -> List[int]:
        now = self._clock()
        started = []
        for policy in self._policies.values():
            last = self._last_run.get(policy.name)
            if last is not None and now - last < policy.interval_seconds:
                continue
            self._last_run[policy.name] = now
            backup_id = int(now * 1000) or 1
            for app_id in policy.app_ids:
                self._backup_table(policy, backup_id, app_id)
            started.append(backup_id)
        return started
