"""Multi-tenant QoS: the bounded tenant registry and CU-budget governor.

Prior to this layer every protection mechanism was cluster-global —
the transport shed gate, the read limiter, overload backpressure all
punish every client equally, and `tools/scale_test.py` faked per-tenant
QoS with client-side throttles. This module promotes tenancy into the
data plane (shared-cloud stores like Taurus treat per-tenant isolation
as a first-class server obligation, PAPERS.md):

- **Bounded registry** (``TENANTS``): tenants are REGISTERED — from
  per-table app-envs (``qos.tenants = "name:weight:cu_rate,..."``) or
  explicitly — never minted from raw wire strings. An unknown or
  malformed wire tag folds into the ``default`` tenant, so metric
  entity cardinality is bounded by the registry cap, not by whatever
  bytes clients send (the tools/metrics_lint.py tenant rule enforces
  that entity creation stays inside this module).

- **CU budgets, post-debit**: each tenant may carry a token bucket
  (utils/token_bucket.py) denominated in capacity units. Serving paths
  charge the ACTUAL capacity units after the fact (the existing
  CapacityUnitCalculator funnels feed `charge_ambient`), and admission
  gates the NEXT op on the bucket's sign — over-budget ops get typed
  retryable ERR_CU_OVERBUDGET (jittered-backoff retry, no config
  refresh). **Borrow when idle**: when every OTHER budgeted tenant has
  been quiet for `tenant_idle_borrow_s`, an over-budget tenant is
  admitted anyway — budgets cap contention, not idle throughput.

- **Weighted-fair admission inputs**: per-tenant weights (env-set,
  clamped by the operator-mutable ``tenant_min_weight``/
  ``tenant_max_weight`` flags) feed the transport dispatcher's
  deficit-weighted round-robin.

- **Aggressor-only brownout**: per-tenant metric series
  (``tenant_cu_rate``, ``tenant_shed_count``, ``tenant_queue_age_ms``,
  ``tenant_cu_ratio``) ride the flight recorder; the
  ``tenant_brownout`` health rule fires on the tenant whose
  consumed-rate/budget ratio is sustained over threshold, and the
  stub's read gate sheds ONLY that tenant while the rule holds.

Process-global singleton (the METRICS/FLAGS/DRIFT pattern): in-process
sim clusters share one registry, exactly like they share one metric
registry — per-node attribution rides the flight recorder's ownership
predicate, not separate registries.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, Optional

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS
from pegasus_tpu.utils.token_bucket import TokenBucket

define_flag("pegasus.qos", "tenant_enforce", True,
            "enforce per-tenant CU budgets and brownout shedding (kill "
            "switch; weighted-fair dispatch stays on — it is "
            "work-conserving and free when single-tenant)", mutable=True)
define_flag("pegasus.qos", "tenant_min_weight", 0.25,
            "operator floor for per-tenant admission weights (env-set "
            "weights clamp into [min, max])", mutable=True)
define_flag("pegasus.qos", "tenant_max_weight", 16.0,
            "operator ceiling for per-tenant admission weights",
            mutable=True)
define_flag("pegasus.qos", "tenant_cu_burst_s", 2.0,
            "CU bucket burst, in seconds of budget rate: a tenant may "
            "burst rate*burst_s units before admission gates it")
define_flag("pegasus.qos", "tenant_borrow_when_idle", True,
            "admit over-budget ops while every OTHER budgeted tenant "
            "is idle — budgets cap contention, not idle throughput",
            mutable=True)
define_flag("pegasus.qos", "tenant_idle_borrow_s", 2.0,
            "quiescence horizon for borrow-when-idle: other tenants "
            "count as idle after this many seconds without a charge",
            mutable=True)

DEFAULT_TENANT = "default"

# wire-tag sanitizer: lowercase slug, bounded length. Anything else
# folds into the default tenant (never into a fresh metric entity).
TENANT_RE = re.compile(r"^[a-z0-9][a-z0-9_\-]{0,31}$")

# registry cap: tenants beyond this fold into default. Keeps the
# per-tenant entity space (and the recorder rings over it) bounded no
# matter what envs ask for.
MAX_TENANTS = 64

# app-env key carrying per-table tenant declarations:
#   qos.tenants = "gold:4:10000,free:1:500"   (name:weight:cu_rate;
#   weight and cu_rate optional — "gold", "gold:4", "gold:4:10000")
TENANTS_ENV_KEY = "qos.tenants"
# app-env naming the tenant tag clients of this table default to
DEFAULT_TENANT_ENV_KEY = "qos.default_tenant"


def sanitize_tenant(raw) -> str:
    """Fold a wire tenant tag into the bounded label space."""
    if isinstance(raw, str) and TENANT_RE.match(raw):
        return raw
    return DEFAULT_TENANT


class TenantState:
    """One registered tenant: weight, optional CU bucket, metrics."""

    def __init__(self, name: str, weight: float, cu_rate: float,
                 clock) -> None:
        self.name = name
        self.weight = weight
        self.cu_rate = cu_rate  # CU/s budget; 0 = unlimited
        burst_s = FLAGS.get("pegasus.qos", "tenant_cu_burst_s")
        self.bucket: Optional[TokenBucket] = (
            TokenBucket(cu_rate, cu_rate * burst_s, clock=clock)
            if cu_rate > 0 else None)
        self.last_active = 0.0  # last charge timestamp (governor clock)
        ent = METRICS.entity("tenant", name, {"tenant": name})
        # counter named for the series the recorder derives from it:
        # rings record counters as per-second rates, and the health
        # rule watches the RATE of CU consumption
        self.cu_counter = ent.counter("tenant_cu_rate")
        self.shed = ent.counter("tenant_shed_count")
        self.overbudget = ent.counter("tenant_overbudget_count")
        self.queue_age = ent.percentile("tenant_queue_age_ms")
        # consumed-rate / budget ratio, refreshed each governor tick —
        # the series the aggressor-only brownout rule fires on
        self.ratio = ent.gauge("tenant_cu_ratio")
        self.brownout_gauge = ent.gauge("tenant_brownout_active")
        self._ratio_last_cu = 0
        self._ratio_last_ts: Optional[float] = None

    def config(self, weight: float, cu_rate: float, clock) -> None:
        """Re-apply env config in place (full_set env pushes re-send
        everything; bucket level carries over only if rate unchanged —
        a budget change is an operator action, restart the bucket)."""
        self.weight = weight
        if cu_rate != self.cu_rate:
            self.cu_rate = cu_rate
            burst_s = FLAGS.get("pegasus.qos", "tenant_cu_burst_s")
            self.bucket = (TokenBucket(cu_rate, cu_rate * burst_s,
                                       clock=clock)
                           if cu_rate > 0 else None)


# ambient tenant: bound by the serving seams (stub handlers, batch
# coordinators) so the CU funnels deep below can attribute charges
# without threading a tenant argument through every storage call —
# the same discipline as utils/perf_context.py
_tls = threading.local()


def current() -> Optional[str]:
    return getattr(_tls, "tenant", None)


class bind:
    """Context manager: make `tenant` the ambient tenant for CU
    attribution on this thread (None = leave unattributed)."""

    __slots__ = ("_tenant", "_prev")

    def __init__(self, tenant: Optional[str]) -> None:
        self._tenant = tenant
        self._prev = None

    def __enter__(self) -> "bind":
        self._prev = getattr(_tls, "tenant", None)
        if self._tenant is not None:
            _tls.tenant = self._tenant
        return self

    def __exit__(self, *exc) -> None:
        _tls.tenant = self._prev


class TenantRegistry:
    """The process-global governor. All lookups resolve through the
    bounded registry; unknown tags fold into the default tenant."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._clock = time.monotonic
        self._tenants: Dict[str, TenantState] = {}
        self._browned: set = set()
        self._default = self._make(DEFAULT_TENANT, 1.0, 0.0)

    # -- clock (sim support) ------------------------------------------

    def set_clock(self, clock) -> None:
        """Switch the governor (and every bucket) onto a virtual
        clock — SimCluster stubs call this so budget refill tracks
        virtual seconds, the same threading scrub_tick/health_tick
        use. Existing buckets are rebuilt on the new timebase."""
        with self._lock:
            if clock is self._clock:
                return
            self._clock = clock
            burst_s = FLAGS.get("pegasus.qos", "tenant_cu_burst_s")
            for st in self._tenants.values():
                if st.cu_rate > 0:
                    st.bucket = TokenBucket(
                        st.cu_rate, st.cu_rate * burst_s, clock=clock)

    def _now(self) -> float:
        return self._clock()

    # -- registration --------------------------------------------------

    def _make(self, name: str, weight: float,
              cu_rate: float) -> TenantState:
        st = TenantState(name, weight, cu_rate, self._clock)
        self._tenants[name] = st
        return st

    def ensure(self, name: str, weight: float = 1.0,
               cu_rate: float = 0.0) -> TenantState:
        """Register (or reconfigure) one tenant. Beyond MAX_TENANTS the
        registration folds into default — bounded cardinality is a
        hard property, not a convention."""
        name = sanitize_tenant(name)
        with self._lock:
            st = self._tenants.get(name)
            if st is None:
                if len(self._tenants) >= MAX_TENANTS:
                    return self._tenants[DEFAULT_TENANT]
                return self._make(name, weight, cu_rate)
            st.config(weight, cu_rate, self._clock)
            return st

    def configure_from_envs(self, envs: Dict[str, str]) -> None:
        """Apply a table's app-envs: ``qos.tenants`` declares tenants
        with weights/budgets. Called from the stubs' update_app_envs
        seam, so `shell set_app_envs` re-shapes QoS online."""
        spec = (envs or {}).get(TENANTS_ENV_KEY, "")
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            name = fields[0].strip()
            try:
                weight = float(fields[1]) if len(fields) > 1 else 1.0
                cu_rate = float(fields[2]) if len(fields) > 2 else 0.0
            except ValueError:
                continue  # malformed field: skip, never crash env sync
            self.ensure(name, weight, cu_rate)

    def resolve(self, raw) -> TenantState:
        """Wire tag -> registered state; unknown folds into default."""
        # lock-free fast path for the per-request funnels: registered
        # names are already sanitized, and dict reads are atomic under
        # the GIL while registration (the only writer) is rare
        if type(raw) is str:
            st = self._tenants.get(raw)
            if st is not None:
                return st
        name = sanitize_tenant(raw)
        with self._lock:
            return self._tenants.get(name) or self._default

    def known(self, raw) -> bool:
        return sanitize_tenant(raw) in self._tenants

    def names(self):
        with self._lock:
            return sorted(self._tenants)

    # -- weighted-fair inputs -----------------------------------------

    def weight(self, raw) -> float:
        """Admission weight, clamped into the operator min/max flags."""
        st = self.resolve(raw)
        lo = FLAGS.get("pegasus.qos", "tenant_min_weight")
        hi = FLAGS.get("pegasus.qos", "tenant_max_weight")
        return max(lo, min(hi, st.weight))

    # -- CU budget enforcement ----------------------------------------

    def admit(self, raw, kind: str = "read") -> int:
        """Gate one op. Returns 0 (admitted) or ERR_CU_OVERBUDGET.

        Post-debit model: the bucket went negative because of PAST
        consumption; refill pays the debt down and admission resumes.
        Brownout shedding is separate (`browned()` + the stub's read
        gate) — this is the budget, not the outlier response.
        """
        if not FLAGS.get("pegasus.qos", "tenant_enforce"):
            return 0
        st = self.resolve(raw)
        if st.bucket is None or st.bucket.level() > 0.0:
            return 0
        if (FLAGS.get("pegasus.qos", "tenant_borrow_when_idle")
                and self._others_idle(st)):
            return 0  # soft mode: nobody is contending, let it run
        st.overbudget.increment()
        from pegasus_tpu.utils.errors import ErrorCode

        return int(ErrorCode.ERR_CU_OVERBUDGET)

    def _others_idle(self, st: TenantState) -> bool:
        horizon = FLAGS.get("pegasus.qos", "tenant_idle_borrow_s")
        now = self._now()
        with self._lock:
            for other in self._tenants.values():
                if other is st:
                    continue
                if now - other.last_active <= horizon:
                    return False
        return True

    def charge(self, raw, cu: int) -> None:
        """Post-debit: bill `cu` capacity units to the tenant (reads
        and writes alike — the budget is total capacity)."""
        if cu <= 0:
            return
        st = self.resolve(raw)
        st.cu_counter.increment(cu)
        st.last_active = self._now()
        if st.bucket is not None:
            st.bucket.debit(float(cu))

    def charge_ambient(self, cu: int) -> None:
        """The CapacityUnitCalculator hook: bill the thread's bound
        tenant (no-op when no tenant is ambient — background work like
        compaction/scrub is not client traffic)."""
        t = current()
        if t is not None:
            self.charge(t, cu)

    # -- shed / queue-age series --------------------------------------

    def note_shed(self, raw) -> None:
        self.resolve(raw).shed.increment()

    def note_queue_age(self, raw, age_ms: float) -> None:
        self.resolve(raw).queue_age.set(age_ms)

    # -- brownout ------------------------------------------------------

    def refresh(self) -> None:
        """Governor tick (ridden by stub.health_tick, the scrub_tick/
        health_tick cadence): publish each tenant's consumed-rate /
        budget ratio so the `tenant_brownout` rule has its series."""
        now = self._now()
        with self._lock:
            states = list(self._tenants.values())
        for st in states:
            cu = st.cu_counter.value()
            if st._ratio_last_ts is None:
                st._ratio_last_ts, st._ratio_last_cu = now, cu
                continue
            dt = now - st._ratio_last_ts
            if dt <= 0:
                continue
            rate = (cu - st._ratio_last_cu) / dt
            st._ratio_last_ts, st._ratio_last_cu = now, cu
            st.ratio.set(round(rate / st.cu_rate, 4)
                         if st.cu_rate > 0 else 0.0)

    def set_brownout(self, name: str, firing: bool) -> None:
        """Driven by the HealthEngine's `tenant_brownout` transitions:
        ONLY the outlier tenant gets shed-gated (and released when the
        rule clears — the hold/clear_hold hysteresis is the damper)."""
        st = self.resolve(name)
        with self._lock:
            if firing:
                self._browned.add(st.name)
            else:
                self._browned.discard(st.name)
        st.brownout_gauge.set(1.0 if firing else 0.0)

    def browned(self, raw) -> bool:
        if not self._browned:  # hot-path fast exit, before the flag
            return False
        if not FLAGS.get("pegasus.qos", "tenant_enforce"):
            return False
        return sanitize_tenant(raw) in self._browned

    # -- surfaces ------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant stats for shell `tenants`, the collector's
        `_tenants` row, and the meta config-sync tenant block."""
        with self._lock:
            states = list(self._tenants.values())
            browned = set(self._browned)
        out: Dict[str, dict] = {}
        for st in states:
            out[st.name] = {
                "weight": st.weight,
                "cu_budget": st.cu_rate,
                "cu_total": st.cu_counter.value(),
                "cu_level": (round(st.bucket.level(), 1)
                             if st.bucket is not None else None),
                "cu_ratio": st.ratio.value(),
                "shed": st.shed.value(),
                "overbudget": st.overbudget.value(),
                "browned": st.name in browned,
            }
        return out

    def reset(self) -> None:
        """Test isolation: drop every registration (metric entities
        persist — counters are monotonic, same rule as workload
        entities) and clear brownout state."""
        with self._lock:
            self._tenants.clear()
            self._browned.clear()
            self._clock = time.monotonic
            self._default = self._make(DEFAULT_TENANT, 1.0, 0.0)


TENANTS = TenantRegistry()
