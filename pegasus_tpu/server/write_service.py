"""Write service: translate client writes into engine batches, then apply.

Parity: src/server/pegasus_write_service.{h,cpp} +
pegasus_write_service_impl.h — the two-phase shape mirrors
batch_prepare/batch_commit: `translate_*` turns client requests into
WriteBatchItems (atomic ops are read-modify-write evaluated here, under
the single-writer-per-partition invariant, replica_2pc.cpp:115), and
`apply_items` commits ONE engine batch per decree. Replication calls
translate+apply at mutation-apply time on every replica (deterministic by
decree order, like the reference's default non-idempotent mode); the
standalone server fuses them per request.

Determinism: the timetag timestamp comes from the caller (the mutation's
primary-assigned timestamp) so every replica writes identical value bytes
— reference parity: mutation timestamps are primary-assigned
(src/replica/mutation.h) and duplication relies on them.

Batching rule parity (mutation.cpp:390,553): multiple put/remove-class
requests may share one mutation; atomic ops (incr/cas/cam) never batch
with anything else.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import (
    check_if_ts_expired,
    epoch_now,
    expire_ts_from_ttl,
    extract_user_data,
    generate_timetag,
    generate_value,
)
from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
from pegasus_tpu.storage.wal import OP_DEL, OP_PUT
from pegasus_tpu.utils.errors import StorageStatus
from pegasus_tpu.server.types import (
    CasCheckType,
    CheckAndMutateRequest,
    CheckAndMutateResponse,
    CheckAndSetRequest,
    CheckAndSetResponse,
    IncrRequest,
    IncrResponse,
    MultiPutRequest,
    MultiRemoveRequest,
    MutateOperation,
)

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


def cas_check_passed(check_type: int, operand: bytes,
                     value: Optional[bytes]) -> bool:
    """Evaluate a cas_check_type against the current check value.

    Parity: pegasus_write_service_impl.h validate_check — `value` is None
    when the record doesn't exist. Raises ValueError for malformed int
    compares (mapped to kInvalidArgument by callers).
    """
    ct = CasCheckType(check_type)
    exists = value is not None
    if ct == CasCheckType.CT_NO_CHECK:
        return True
    if ct == CasCheckType.CT_VALUE_NOT_EXIST:
        return not exists
    if ct == CasCheckType.CT_VALUE_NOT_EXIST_OR_EMPTY:
        return not exists or value == b""
    if ct == CasCheckType.CT_VALUE_EXIST:
        return exists
    if ct == CasCheckType.CT_VALUE_NOT_EMPTY:
        return exists and value != b""
    if not exists:
        return False
    if ct == CasCheckType.CT_VALUE_MATCH_ANYWHERE:
        return operand in value
    if ct == CasCheckType.CT_VALUE_MATCH_PREFIX:
        return value.startswith(operand)
    if ct == CasCheckType.CT_VALUE_MATCH_POSTFIX:
        return value.endswith(operand)
    if ct == CasCheckType.CT_VALUE_BYTES_LESS:
        return value < operand
    if ct == CasCheckType.CT_VALUE_BYTES_LESS_OR_EQUAL:
        return value <= operand
    if ct == CasCheckType.CT_VALUE_BYTES_EQUAL:
        return value == operand
    if ct == CasCheckType.CT_VALUE_BYTES_GREATER_OR_EQUAL:
        return value >= operand
    if ct == CasCheckType.CT_VALUE_BYTES_GREATER:
        return value > operand
    # int compares: both sides must parse as int64 (reference buf2int64;
    # failure -> kInvalidArgument)
    v = _parse_int64(value)
    o = _parse_int64(operand)
    if ct == CasCheckType.CT_VALUE_INT_LESS:
        return v < o
    if ct == CasCheckType.CT_VALUE_INT_LESS_OR_EQUAL:
        return v <= o
    if ct == CasCheckType.CT_VALUE_INT_EQUAL:
        return v == o
    if ct == CasCheckType.CT_VALUE_INT_GREATER_OR_EQUAL:
        return v >= o
    if ct == CasCheckType.CT_VALUE_INT_GREATER:
        return v > o
    raise ValueError(f"unsupported check type {check_type}")


def _parse_int64(data: bytes) -> int:
    s = data.decode("ascii", errors="strict")
    if not s or s.strip() != s:
        raise ValueError(f"not an int64: {data!r}")
    v = int(s)  # raises ValueError on garbage
    if not (_INT64_MIN <= v <= _INT64_MAX):
        raise ValueError("int64 out of range")
    return v


class WriteService:
    """All writes for one partition; the caller (partition server or
    replica) provides the decree and holds the single-writer lock."""

    def __init__(self, engine: StorageEngine, data_version: int = 1,
                 cluster_id: int = 1) -> None:
        self.engine = engine
        self.data_version = data_version
        self.cluster_id = cluster_id
        # the owning partition's WorkloadStats (set by PartitionServer):
        # apply_items is the single funnel every write shape routes
        # through — standalone AND replicated — so the op-mix/batch-size
        # profile feeds here exactly once per applied mutation
        self.workload = None

    # -- helpers --------------------------------------------------------

    def _make_value(self, user_data: bytes, expire_ts: int,
                    timestamp_us: Optional[int]) -> bytes:
        timetag = 0
        if self.data_version >= 1:
            ts = (timestamp_us if timestamp_us is not None
                  else int(time.time() * 1_000_000))
            timetag = generate_timetag(ts, self.cluster_id, False)
        return generate_value(self.data_version, user_data, expire_ts, timetag)

    def _visible(self, key: bytes, now: int
                 ) -> Optional[Tuple[bytes, int]]:
        hit = self.engine.get(key)
        if hit is None:
            return None
        value, ets = hit
        if check_if_ts_expired(now, ets):
            return None
        return value, ets

    def _visible_user_data(self, key: bytes, now: int) -> Optional[bytes]:
        hit = self._visible(key, now)
        if hit is None:
            return None
        return extract_user_data(self.data_version, hit[0])

    # -- translate phase ------------------------------------------------

    def translate_put(self, key: bytes, user_data: bytes, expire_ts: int,
                      timestamp_us: Optional[int] = None
                      ) -> List[WriteBatchItem]:
        value = self._make_value(user_data, expire_ts, timestamp_us)
        return [WriteBatchItem(OP_PUT, key, value, expire_ts)]

    def translate_remove(self, key: bytes) -> List[WriteBatchItem]:
        return [WriteBatchItem(OP_DEL, key)]

    def translate_put_run(self, reqs: List[Tuple[bytes, bytes, int]],
                          timestamp_us: Optional[int] = None
                          ) -> List[WriteBatchItem]:
        """A homogeneous run of puts [(key, user_data, expire_ts)] in
        ONE pass: the timetag is computed once for the whole run (every
        op in a mutation shares the primary-assigned timestamp, so the
        per-op sweep produced identical tags anyway) — byte-identical
        to translate_put called per op."""
        timetag = 0
        if self.data_version >= 1:
            ts = (timestamp_us if timestamp_us is not None
                  else int(time.time() * 1_000_000))
            timetag = generate_timetag(ts, self.cluster_id, False)
        ver = self.data_version
        return [WriteBatchItem(OP_PUT, key,
                               generate_value(ver, ud, ets, timetag), ets)
                for key, ud, ets in reqs]

    def translate_remove_run(self, keys: List[bytes]
                             ) -> List[WriteBatchItem]:
        return [WriteBatchItem(OP_DEL, key) for key in keys]

    def translate_multi_put(self, req: MultiPutRequest,
                            timestamp_us: Optional[int] = None,
                            now: Optional[int] = None
                            ) -> Tuple[int, List[WriteBatchItem]]:
        if not req.kvs:
            return int(StorageStatus.INVALID_ARGUMENT), []
        expire_ts = expire_ts_from_ttl(req.expire_ts_seconds, now)
        items = [
            WriteBatchItem(
                OP_PUT, generate_key(req.hash_key, kv.key),
                self._make_value(kv.value, expire_ts, timestamp_us),
                expire_ts)
            for kv in req.kvs
        ]
        return int(StorageStatus.OK), items

    def translate_multi_remove(self, req: MultiRemoveRequest
                               ) -> Tuple[int, int, List[WriteBatchItem]]:
        if not req.sort_keys:
            return int(StorageStatus.INVALID_ARGUMENT), 0, []
        items = [WriteBatchItem(OP_DEL, generate_key(req.hash_key, sk))
                 for sk in req.sort_keys]
        return int(StorageStatus.OK), len(items), items

    def translate_incr(self, req: IncrRequest,
                       timestamp_us: Optional[int] = None,
                       now: Optional[int] = None
                       ) -> Tuple[IncrResponse, List[WriteBatchItem]]:
        """Parity: pegasus_write_service_impl.h incr — missing/expired
        record counts as 0; non-numeric or overflow -> kInvalidArgument;
        expire_ts_seconds: 0 keeps the old TTL, >0 resets, <0 clears."""
        now = epoch_now() if now is None else now
        resp = IncrResponse()
        old = self._visible(req.key, now)
        if old is None:
            old_int, old_ets = 0, 0
        else:
            raw, old_ets = old
            data = extract_user_data(self.data_version, raw)
            if data == b"":
                old_int = 0
            else:
                try:
                    old_int = _parse_int64(data)
                except ValueError:
                    resp.error = int(StorageStatus.INVALID_ARGUMENT)
                    return resp, []
        new_int = old_int + req.increment
        if not (_INT64_MIN <= new_int <= _INT64_MAX):
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            resp.new_value = old_int
            return resp, []
        if req.expire_ts_seconds == 0:
            new_ets = old_ets
        elif req.expire_ts_seconds > 0:
            new_ets = expire_ts_from_ttl(req.expire_ts_seconds, now)
        else:
            new_ets = 0
        resp.error = int(StorageStatus.OK)
        resp.new_value = new_int
        return resp, self.translate_put(req.key, str(new_int).encode(),
                                        new_ets, timestamp_us)

    def translate_check_and_set(self, req: CheckAndSetRequest,
                                timestamp_us: Optional[int] = None,
                                now: Optional[int] = None
                                ) -> Tuple[CheckAndSetResponse,
                                           List[WriteBatchItem]]:
        now = epoch_now() if now is None else now
        resp = CheckAndSetResponse()
        check_key = generate_key(req.hash_key, req.check_sort_key)
        check_value = self._visible_user_data(check_key, now)
        if req.return_check_value:
            resp.check_value_returned = True
            if check_value is not None:
                resp.check_value_exist = True
                resp.check_value = check_value
        try:
            passed = cas_check_passed(req.check_type, req.check_operand,
                                      check_value)
        except ValueError:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp, []
        if not passed:
            resp.error = int(StorageStatus.TRY_AGAIN)
            return resp, []
        set_sort_key = (req.set_sort_key if req.set_diff_sort_key
                        else req.check_sort_key)
        expire_ts = (expire_ts_from_ttl(req.set_expire_ts_seconds, now)
                     if req.set_expire_ts_seconds > 0 else 0)
        resp.error = int(StorageStatus.OK)
        return resp, self.translate_put(
            generate_key(req.hash_key, set_sort_key), req.set_value,
            expire_ts, timestamp_us)

    def translate_check_and_mutate(self, req: CheckAndMutateRequest,
                                   timestamp_us: Optional[int] = None,
                                   now: Optional[int] = None
                                   ) -> Tuple[CheckAndMutateResponse,
                                              List[WriteBatchItem]]:
        now = epoch_now() if now is None else now
        resp = CheckAndMutateResponse()
        if not req.mutate_list:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp, []
        check_key = generate_key(req.hash_key, req.check_sort_key)
        check_value = self._visible_user_data(check_key, now)
        if req.return_check_value:
            resp.check_value_returned = True
            if check_value is not None:
                resp.check_value_exist = True
                resp.check_value = check_value
        try:
            passed = cas_check_passed(req.check_type, req.check_operand,
                                      check_value)
        except ValueError:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp, []
        if not passed:
            resp.error = int(StorageStatus.TRY_AGAIN)
            return resp, []
        items: List[WriteBatchItem] = []
        for m in req.mutate_list:
            key = generate_key(req.hash_key, m.sort_key)
            if m.operation == MutateOperation.MO_DELETE:
                items.append(WriteBatchItem(OP_DEL, key))
            else:
                ets = (expire_ts_from_ttl(m.set_expire_ts_seconds, now)
                       if m.set_expire_ts_seconds > 0 else 0)
                items.append(WriteBatchItem(
                    OP_PUT, key, self._make_value(m.value, ets, timestamp_us),
                    ets))
        resp.error = int(StorageStatus.OK)
        return resp, items

    # -- duplicated writes (parity: the duplicate-apply variants in
    # pegasus_write_service_impl + value timetag conflict resolution,
    # base/pegasus_value_schema.h:175-209) ------------------------------

    def _existing_timetag(self, key: bytes) -> int:
        hit = self.engine.get(key)
        if hit is None:
            return 0
        value, _ = hit
        if self.data_version < 1 or len(value) < 12:
            return 0
        from pegasus_tpu.base.value_schema import extract_timetag
        return extract_timetag(self.data_version, value)

    def translate_duplicate_put(self, key: bytes, user_data: bytes,
                                expire_ts: int, timetag: int,
                                floor_tag: int = 0):
        """(applied, items) for a shipped write: applies iff its timetag
        wins (larger timestamp, then cluster id — master-master conflict
        resolution). `floor_tag` lets a caller batching several dup ops in
        one mutation account for an earlier write to the same key that is
        not in the engine yet."""
        if timetag <= max(self._existing_timetag(key), floor_tag):
            return False, []
        from pegasus_tpu.base.value_schema import generate_value
        value = generate_value(self.data_version, user_data, expire_ts,
                               timetag)
        return True, [WriteBatchItem(OP_PUT, key, value, expire_ts)]

    def translate_duplicate_remove(self, key: bytes, timetag: int,
                                   floor_tag: int = 0):
        if timetag <= max(self._existing_timetag(key), floor_tag):
            return False, []
        return True, [WriteBatchItem(OP_DEL, key)]

    def duplicate_put(self, key: bytes, user_data: bytes, expire_ts: int,
                      timetag: int, decree: int) -> bool:
        """translate_duplicate_put + apply (the in-process shipper path);
        the decree advances even on a lost conflict."""
        applied, items = self.translate_duplicate_put(key, user_data,
                                                      expire_ts, timetag)
        self.apply_items(items, decree)
        return applied

    def duplicate_remove(self, key: bytes, timetag: int, decree: int) -> bool:
        applied, items = self.translate_duplicate_remove(key, timetag)
        self.apply_items(items, decree)
        return applied

    # -- apply phase ----------------------------------------------------

    def apply_items(self, items: List[WriteBatchItem], decree: int,
                    wal_flush: bool = True) -> None:
        """One engine batch per decree; empty item lists still advance the
        decree (reference empty_put, pegasus_write_service.cpp:210 — a
        no-op write that carries the decree watermark). `wal_flush=False`
        defers the engine-WAL flush into the caller's group-commit
        window."""
        wl = self.workload
        if wl is not None and items:
            wl.note_write(1, len(items),
                          [len(it.value) for it in items[:8]])
        self.engine.write_batch(items, decree, wal_flush=wal_flush)

    # -- fused convenience (standalone mode) ----------------------------

    def put(self, key: bytes, user_data: bytes, expire_ts: int,
            decree: int) -> int:
        self.apply_items(self.translate_put(key, user_data, expire_ts),
                         decree)
        return int(StorageStatus.OK)

    def remove(self, key: bytes, decree: int) -> int:
        self.apply_items(self.translate_remove(key), decree)
        return int(StorageStatus.OK)

    def multi_put(self, req: MultiPutRequest, decree: int) -> int:
        err, items = self.translate_multi_put(req)
        if err == int(StorageStatus.OK):
            self.apply_items(items, decree)
        return err

    def multi_remove(self, req: MultiRemoveRequest, decree: int
                     ) -> Tuple[int, int]:
        err, count, items = self.translate_multi_remove(req)
        if err == int(StorageStatus.OK):
            self.apply_items(items, decree)
        return err, count

    def incr(self, req: IncrRequest, decree: int) -> IncrResponse:
        resp, items = self.translate_incr(req)
        if resp.error == int(StorageStatus.OK):
            self.apply_items(items, decree)
            resp.decree = decree
        return resp

    def check_and_set(self, req: CheckAndSetRequest, decree: int
                      ) -> CheckAndSetResponse:
        resp, items = self.translate_check_and_set(req)
        if resp.error == int(StorageStatus.OK):
            self.apply_items(items, decree)
            resp.decree = decree
        return resp

    def check_and_mutate(self, req: CheckAndMutateRequest, decree: int
                         ) -> CheckAndMutateResponse:
        resp, items = self.translate_check_and_mutate(req)
        if resp.error == int(StorageStatus.OK):
            self.apply_items(items, decree)
            resp.decree = decree
        return resp
