"""Write service: applies one decree's worth of client writes.

Parity: src/server/pegasus_write_service.{h,cpp} +
pegasus_write_service_impl.h — batch_prepare/batch_commit produce ONE
engine write batch per decree; atomic ops (incr / check_and_set /
check_and_mutate) are read-modify-write evaluated here under the
single-writer-per-partition invariant (enforced by the partition server's
write lock, mirroring the reference's per-gpid thread pinning,
replica_2pc.cpp:115).

Value encoding: every stored value is pegasus-encoded
([expire_ts][timetag?][user_data], base/pegasus_value_schema.h) and the
decoded expire_ts additionally rides the engine's columnar expiry column.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import (
    check_if_ts_expired,
    epoch_now,
    expire_ts_from_ttl,
    extract_user_data,
    generate_timetag,
    generate_value,
)
from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
from pegasus_tpu.storage.wal import OP_DEL, OP_PUT
from pegasus_tpu.utils.errors import StorageStatus
from pegasus_tpu.server.types import (
    CasCheckType,
    CheckAndMutateRequest,
    CheckAndMutateResponse,
    CheckAndSetRequest,
    CheckAndSetResponse,
    IncrRequest,
    IncrResponse,
    MultiPutRequest,
    MultiRemoveRequest,
    MutateOperation,
)

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


def cas_check_passed(check_type: int, operand: bytes,
                     value: Optional[bytes]) -> bool:
    """Evaluate a cas_check_type against the current check value.

    Parity: pegasus_write_service_impl.h validate_check — `value` is None
    when the record doesn't exist. Raises ValueError for malformed int
    compares (mapped to kInvalidArgument by callers).
    """
    ct = CasCheckType(check_type)
    exists = value is not None
    if ct == CasCheckType.CT_NO_CHECK:
        return True
    if ct == CasCheckType.CT_VALUE_NOT_EXIST:
        return not exists
    if ct == CasCheckType.CT_VALUE_NOT_EXIST_OR_EMPTY:
        return not exists or value == b""
    if ct == CasCheckType.CT_VALUE_EXIST:
        return exists
    if ct == CasCheckType.CT_VALUE_NOT_EMPTY:
        return exists and value != b""
    if not exists:
        return False
    if ct == CasCheckType.CT_VALUE_MATCH_ANYWHERE:
        return operand in value
    if ct == CasCheckType.CT_VALUE_MATCH_PREFIX:
        return value.startswith(operand)
    if ct == CasCheckType.CT_VALUE_MATCH_POSTFIX:
        return value.endswith(operand)
    if ct in (CasCheckType.CT_VALUE_BYTES_LESS,
              CasCheckType.CT_VALUE_BYTES_LESS_OR_EQUAL,
              CasCheckType.CT_VALUE_BYTES_EQUAL,
              CasCheckType.CT_VALUE_BYTES_GREATER_OR_EQUAL,
              CasCheckType.CT_VALUE_BYTES_GREATER):
        if ct == CasCheckType.CT_VALUE_BYTES_LESS:
            return value < operand
        if ct == CasCheckType.CT_VALUE_BYTES_LESS_OR_EQUAL:
            return value <= operand
        if ct == CasCheckType.CT_VALUE_BYTES_EQUAL:
            return value == operand
        if ct == CasCheckType.CT_VALUE_BYTES_GREATER_OR_EQUAL:
            return value >= operand
        return value > operand
    # int compares: both sides must parse as int64 (reference uses
    # buf2int64; failure -> kInvalidArgument)
    v = _parse_int64(value)
    o = _parse_int64(operand)
    if ct == CasCheckType.CT_VALUE_INT_LESS:
        return v < o
    if ct == CasCheckType.CT_VALUE_INT_LESS_OR_EQUAL:
        return v <= o
    if ct == CasCheckType.CT_VALUE_INT_EQUAL:
        return v == o
    if ct == CasCheckType.CT_VALUE_INT_GREATER_OR_EQUAL:
        return v >= o
    if ct == CasCheckType.CT_VALUE_INT_GREATER:
        return v > o
    raise ValueError(f"unsupported check type {check_type}")


def _parse_int64(data: bytes) -> int:
    s = data.decode("ascii", errors="strict")
    if not s or s.strip() != s:
        raise ValueError(f"not an int64: {data!r}")
    v = int(s)  # raises ValueError on garbage
    if not (_INT64_MIN <= v <= _INT64_MAX):
        raise ValueError("int64 out of range")
    return v


class WriteService:
    """All writes for one partition; the caller (partition server or
    replica) provides the decree and holds the single-writer lock."""

    def __init__(self, engine: StorageEngine, data_version: int = 1,
                 cluster_id: int = 1) -> None:
        self.engine = engine
        self.data_version = data_version
        self.cluster_id = cluster_id

    # -- helpers --------------------------------------------------------

    def _make_value(self, user_data: bytes, expire_ts: int) -> bytes:
        timetag = 0
        if self.data_version >= 1:
            timetag = generate_timetag(int(time.time() * 1_000_000),
                                       self.cluster_id, False)
        return generate_value(self.data_version, user_data, expire_ts, timetag)

    def _visible_user_data(self, key: bytes,
                           now: int) -> Optional[bytes]:
        hit = self.engine.get(key)
        if hit is None:
            return None
        value, ets = hit
        if check_if_ts_expired(now, ets):
            return None
        return extract_user_data(self.data_version, value)

    def _visible(self, key: bytes, now: int
                 ) -> Optional[Tuple[bytes, int]]:
        hit = self.engine.get(key)
        if hit is None:
            return None
        value, ets = hit
        if check_if_ts_expired(now, ets):
            return None
        return value, ets

    # -- simple writes --------------------------------------------------

    def put(self, key: bytes, user_data: bytes, expire_ts: int,
            decree: int) -> int:
        value = self._make_value(user_data, expire_ts)
        self.engine.write_batch(
            [WriteBatchItem(OP_PUT, key, value, expire_ts)], decree)
        return int(StorageStatus.OK)

    def remove(self, key: bytes, decree: int) -> int:
        self.engine.write_batch([WriteBatchItem(OP_DEL, key)], decree)
        return int(StorageStatus.OK)

    def multi_put(self, req: MultiPutRequest, decree: int) -> int:
        if not req.kvs:
            return int(StorageStatus.INVALID_ARGUMENT)
        expire_ts = expire_ts_from_ttl(req.expire_ts_seconds)
        items = []
        for kv in req.kvs:
            key = generate_key(req.hash_key, kv.key)
            items.append(WriteBatchItem(
                OP_PUT, key, self._make_value(kv.value, expire_ts), expire_ts))
        self.engine.write_batch(items, decree)
        return int(StorageStatus.OK)

    def multi_remove(self, req: MultiRemoveRequest, decree: int
                     ) -> Tuple[int, int]:
        """Returns (error, removed_count)."""
        if not req.sort_keys:
            return int(StorageStatus.INVALID_ARGUMENT), 0
        items = [WriteBatchItem(OP_DEL, generate_key(req.hash_key, sk))
                 for sk in req.sort_keys]
        self.engine.write_batch(items, decree)
        return int(StorageStatus.OK), len(items)

    # -- atomic ops -----------------------------------------------------

    def incr(self, req: IncrRequest, decree: int) -> IncrResponse:
        """Parity: pegasus_write_service_impl.h incr — missing/expired
        record counts as 0; non-numeric or overflow -> kInvalidArgument;
        expire_ts_seconds: 0 keeps the old TTL, >0 resets, <0 clears."""
        now = epoch_now()
        resp = IncrResponse()
        old = self._visible(req.key, now)
        if old is None:
            old_int, old_ets = 0, 0
        else:
            raw, old_ets = old
            data = extract_user_data(self.data_version, raw)
            if data == b"":
                old_int = 0
            else:
                try:
                    old_int = _parse_int64(data)
                except ValueError:
                    resp.error = int(StorageStatus.INVALID_ARGUMENT)
                    return resp
        new_int = old_int + req.increment
        if not (_INT64_MIN <= new_int <= _INT64_MAX):
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            resp.new_value = old_int
            return resp
        if req.expire_ts_seconds == 0:
            new_ets = old_ets
        elif req.expire_ts_seconds > 0:
            new_ets = expire_ts_from_ttl(req.expire_ts_seconds, now)
        else:
            new_ets = 0
        self.put(req.key, str(new_int).encode(), new_ets, decree)
        resp.error = int(StorageStatus.OK)
        resp.new_value = new_int
        resp.decree = decree
        return resp

    def check_and_set(self, req: CheckAndSetRequest, decree: int
                      ) -> CheckAndSetResponse:
        now = epoch_now()
        resp = CheckAndSetResponse()
        check_key = generate_key(req.hash_key, req.check_sort_key)
        check_value = self._visible_user_data(check_key, now)
        if req.return_check_value:
            resp.check_value_returned = True
            if check_value is not None:
                resp.check_value_exist = True
                resp.check_value = check_value
        try:
            passed = cas_check_passed(req.check_type, req.check_operand,
                                      check_value)
        except ValueError:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        if not passed:
            resp.error = int(StorageStatus.TRY_AGAIN)
            return resp
        set_sort_key = (req.set_sort_key if req.set_diff_sort_key
                        else req.check_sort_key)
        expire_ts = expire_ts_from_ttl(req.set_expire_ts_seconds, now) \
            if req.set_expire_ts_seconds > 0 else 0
        self.put(generate_key(req.hash_key, set_sort_key), req.set_value,
                 expire_ts, decree)
        resp.error = int(StorageStatus.OK)
        resp.decree = decree
        return resp

    def check_and_mutate(self, req: CheckAndMutateRequest, decree: int
                         ) -> CheckAndMutateResponse:
        now = epoch_now()
        resp = CheckAndMutateResponse()
        if not req.mutate_list:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        check_key = generate_key(req.hash_key, req.check_sort_key)
        check_value = self._visible_user_data(check_key, now)
        if req.return_check_value:
            resp.check_value_returned = True
            if check_value is not None:
                resp.check_value_exist = True
                resp.check_value = check_value
        try:
            passed = cas_check_passed(req.check_type, req.check_operand,
                                      check_value)
        except ValueError:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp
        if not passed:
            resp.error = int(StorageStatus.TRY_AGAIN)
            return resp
        items = []
        for m in req.mutate_list:
            key = generate_key(req.hash_key, m.sort_key)
            if m.operation == MutateOperation.MO_DELETE:
                items.append(WriteBatchItem(OP_DEL, key))
            else:
                ets = expire_ts_from_ttl(m.set_expire_ts_seconds, now) \
                    if m.set_expire_ts_seconds > 0 else 0
                items.append(WriteBatchItem(
                    OP_PUT, key, self._make_value(m.value, ets), ets))
        self.engine.write_batch(items, decree)
        resp.error = int(StorageStatus.OK)
        resp.decree = decree
        return resp
