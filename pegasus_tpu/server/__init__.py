"""Server layer: the rrdb storage app (reference: src/server/)."""

from pegasus_tpu.server.partition_server import PartitionServer
from pegasus_tpu.server.write_service import WriteService, cas_check_passed
from pegasus_tpu.server.scan_context import ScanContext, ScanContextCache
from pegasus_tpu.server.read_limiter import RangeReadLimiter
from pegasus_tpu.server.capacity_units import CapacityUnitCalculator
from pegasus_tpu.server.types import (
    BatchGetRequest,
    BatchGetResponse,
    CasCheckType,
    CheckAndMutateRequest,
    CheckAndMutateResponse,
    CheckAndSetRequest,
    CheckAndSetResponse,
    FullData,
    FullKey,
    GetScannerRequest,
    IncrRequest,
    IncrResponse,
    KeyValue,
    MultiGetRequest,
    MultiGetResponse,
    MultiPutRequest,
    MultiRemoveRequest,
    Mutate,
    MutateOperation,
    SCAN_CONTEXT_ID_COMPLETED,
    SCAN_CONTEXT_ID_NOT_EXIST,
    ScanRequest,
    ScanResponse,
)
