"""Node-level cross-partition point-read coordination.

The point-read twin of scan_coordinator: a node hosting many partitions
of a table receives one flush of concurrent get / ttl /
multi_get(sort_keys) / batch_get requests, plans each partition's batch
(per-generation point-location cache + vectorized block probes), then
serves the WHOLE flush's value gathers through one batched native path —
page.build_page concatenates every partition's (block, rows) chunks so
the flush pays one native gather call per unique touched block instead
of a Python key/value materialization loop per request.

Where the scan coordinator's win is device-dispatch amortization (stacked
mask programs), the point path's win is host-side: point predicates are
compute-trivial per byte (the "probe" workload class in ops/placement.py
— a crc compare and a TTL compare), so nothing here belongs on a
tunneled accelerator; what batching buys instead is

- ONE clock read, ONE gate/accounting pass, ONE slow-log observation per
  flush instead of per request;
- per-generation location caching: zipfian traffic re-probes the same
  hot keys, and a key's (block, row) is pure over the immutable run set;
- vectorized key-list bisects: each touched block answers every probe in
  the flush with one searchsorted over its sorted key matrix;
- one native gather per block for co-located keys (hot hash keys cluster
  in the same SST block) with per-second TTL masks read straight off the
  host-resident expire_ts column;
- batched sidecar pruning AND location: each partition's plan hashes
  its disk-bound residue ONCE (ops.predicates.bloom_key_hashes — the
  crc64 column every sidecar shares) and answers every (key x
  L0-table / L1-run) candidacy from the per-SSTable structures before
  any block is decoded. Indexed runs (storage/phash.py, the
  CompassDB-style perfect-hash index) answer candidacy and LOCATION
  in the same `pegasus_phash_probe_multi` cell: misses die with zero
  block touches and hits go straight to their (block, slot) row — no
  index bisect at all; filter-only runs keep the bloom+bisect path
  (storage/bloom.py), so mixed-format stores serve correctly. The
  plan's stage chain (plan/bloom/phash_probe/block_probe/decode/
  finish) shows which structure answered on slow logs and traces;
- the node row cache (server/row_cache.py): hot rows admitted by repeat
  traffic (or a hotkey-detection fast-admit) serve before the engine is
  touched at all, write-through-invalidated on the mutation apply path
  and wholesale on store publishes/generation bumps.

Used by the replica stub's client_read_batch handler (the rpc/transport
batch-dispatch hook delivers consecutive queued point reads as one
flush) and by both clients' point_read_multi.
"""

from __future__ import annotations

from typing import List, Tuple


def is_point_read(op: str, args) -> bool:
    """Ops the batched point path serves; everything else (ranged
    multi_get, scans, sortkey_count) keeps its own path. Defensive
    against malformed wire args — a shape this returns True for must
    never make plan_get_batch raise anything but ValueError."""
    if op in ("get", "ttl"):
        return isinstance(args, (bytes, bytearray))
    if op == "batch_get":
        return isinstance(getattr(args, "keys", None), (list, tuple))
    if op == "multi_get":
        return bool(getattr(args, "hash_key", b"")) \
            and bool(getattr(args, "sort_keys", ()))
    return False


def point_read_multi(servers_and_ops: List[Tuple[object, list]],
                     now=None, deadline=None, clock=None,
                     tenants=None) -> List[list]:
    """[(PartitionServer, [(op, args, partition_hash)])] -> [[result]].

    Results are byte-identical to the solo handlers (on_get / on_ttl /
    on_multi_get with sort keys / on_batch_get). One build_page call
    assembles every partition's L1 value gathers per value-header
    width (one native gather per unique block across the whole flush).

    `deadline`/`clock`: the flush's end-to-end deadline on the serving
    node's clock. Checked between the per-partition planning passes and
    again before the cross-partition gather — the two places a large
    flush spends real time — raising ERR_TIMEOUT instead of finishing
    work every requester already abandoned.

    `tenants`: optional per-pair QoS tenant names aligned with
    `servers_and_ops`. The finish pass (where the CU funnel fires) runs
    under that pair's ambient tenant, so a transport flush coalescing
    several tenants' reads still bills each tenant its own capacity
    units; None (or a None slot) leaves attribution to whatever tenant
    the caller already bound.
    """
    from pegasus_tpu.base.value_schema import epoch_now, header_length
    from pegasus_tpu.server.page import build_page

    def _check_deadline() -> None:
        if deadline is not None and clock is not None \
                and clock() > deadline:
            from pegasus_tpu.utils.errors import ErrorCode, PegasusError

            raise PegasusError(ErrorCode.ERR_TIMEOUT,
                               "point-read flush deadline exceeded")

    from pegasus_tpu.utils.tracing import annotate

    if now is None:
        now = epoch_now()
    states = []
    for server, ops in servers_and_ops:
        _check_deadline()
        states.append((server, server.plan_get_batch(ops, now=now)))
    _check_deadline()
    annotate("coord_plan")  # read-coordinator join point (active span)

    # cross-partition native assembly: group by value-header width (the
    # only per-partition parameter of the gather), concatenate chunks
    groups: dict = {}
    for server, state in states:
        chunks = server.point_chunks(state)
        if not chunks:
            state["_page"] = (None, 0)
            continue
        hdr = header_length(server.data_version)
        groups.setdefault(hdr, []).append((state, chunks))
    for hdr, grp in groups.items():
        all_chunks = []
        base = 0
        for state, chunks in grp:
            state["_page_base"] = base
            all_chunks.extend(chunks)
            base += state["chunk_rows"]
        page, _size, _last = build_page(all_chunks, hdr)
        for state, _chunks in grp:
            state["_page"] = (page, state.pop("_page_base"))

    annotate("coord_gather")

    out = []
    if tenants is None:
        tenants = [None] * len(states)
    from pegasus_tpu.server import tenancy

    for (server, state), tenant in zip(states, tenants):
        page, base = state.pop("_page", (None, 0))
        with tenancy.bind(tenant):
            out.append(server.finish_get_batch(state, page, base))
    annotate("coord_finish")
    return out
