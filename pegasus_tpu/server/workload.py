"""Workload profiler: per-table shape stats + the cost-model drift gauge.

Two consumers motivated this layer (ROADMAP): scan pushdown needs
per-table SELECTIVITY (what fraction of evaluated rows survive the
masks — that is exactly what a server-side filter would save on the
wire), and the device-mesh item needs to know when the placement cost
model (ops/placement.py) is LYING (predicted vs measured kernel time).
Neither existed: the cluster knew where time went (traces) and when it
got sick (health rules), but not what the workload *looks like*.

Everything records onto ordinary metric entities, so the PR 12 flight
recorder rings the series for free and the PR 12 health engine can
rule on them:

- per-partition ``workload`` entity (id ``app.pidx``, table/partition
  attrs like the replica entity): op-mix counters (ring→rates), batch-
  size / value-size / scan-selectivity percentile windows, hot-hashkey
  share gauge fed by the existing HotkeyCollector.
- ONE process-wide ``("workload", "node")`` entity carrying
  ``cost_model_drift_ratio``: a warmup-discarding rolling MEDIAN of
  measured/predicted kernel time per workload class (stale classes
  age out), fed by the scan mask-evaluation sites. A
  default health rule fires when the ratio crosses threshold, so a
  mis-calibrated placement model raises a HealthEvent instead of
  silently mis-placing kernels. (Process-wide because the placement
  probe itself is per-process — the same known sim artifact as the
  node "storage" entity.)

Summaries ride config-sync to meta exactly like the CU/hotkey load
signals (stub.config_sync), surfacing as `shell workload <table>`, and
tools/collector.py folds the entities into a `_workload` stat row.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from pegasus_tpu.utils.fail_point import fail_point
from pegasus_tpu.utils.metrics import METRICS

# predictions are estimates of STEADY-state kernel cost; the first few
# calls per workload class pay XLA compiles / lazy session setup that
# the model deliberately excludes, so they are discarded, not averaged
DRIFT_WARMUP = 3
# ratios fold through a short rolling MEDIAN, not a mean: one
# re-compile spike (a fresh batch shape) must not prop the gauge over
# the health threshold, while a genuinely mis-calibrated model shifts
# every sample and moves the median within half a window
DRIFT_WINDOW = 8
# a class with no kernel waves for this long stops contributing to the
# alerting gauge: a stale window must not pin `cost_model_drift` firing
# after traffic shifted away from the workload that drifted
DRIFT_STALE_S = 300.0


class CostModelDrift:
    """measured/predicted offload-time ratio per workload class.

    `note()` is called from the kernel dispatch sites with the
    cost-model prediction and the measured wall time; the fail point
    ``perf::kernel_time_scale`` scales the measured time (the planted
    mis-prediction the acceptance test drives across threshold). The
    published gauge is the WORST class's windowed median — one series
    for the health rule to watch.
    """

    def __init__(self) -> None:
        from collections import deque

        self._deque = deque
        self._lock = threading.Lock()
        # class -> {"window": deque[ratio], "n": int, "predicted_ms",
        #           "measured_ms"} (last sample, for reporting)
        self._classes: Dict[str, dict] = {}
        self._gauge = METRICS.entity("workload", "node").gauge(
            "cost_model_drift_ratio")

    @staticmethod
    def _median(window) -> float:
        s = sorted(window)
        return s[len(s) // 2]

    def note(self, workload: str, predicted_s: float,
             measured_s: float) -> None:
        import time as _time

        scale = fail_point("perf::kernel_time_scale")
        if scale is not None:
            measured_s *= float(scale)
        if predicted_s <= 0.0:
            return
        ratio = measured_s / predicted_s
        with self._lock:
            st = self._classes.setdefault(
                workload, {"window": self._deque(maxlen=DRIFT_WINDOW),
                           "n": 0, "predicted_ms": 0.0,
                           "measured_ms": 0.0, "at": 0.0})
            st["n"] += 1
            st["at"] = _time.monotonic()
            st["predicted_ms"] = predicted_s * 1000.0
            st["measured_ms"] = measured_s * 1000.0
            if st["n"] <= DRIFT_WARMUP:
                return  # compile/session warmup: not model error
            st["window"].append(ratio)
            self._publish(st["at"])

    def _publish(self, now: float) -> None:
        """caller holds self._lock: gauge = worst FRESH class."""
        fresh = [self._median(s["window"])
                 for s in self._classes.values()
                 if s["window"] and now - s["at"] <= DRIFT_STALE_S]
        self._gauge.set(round(max(fresh), 4) if fresh else 0.0)

    def refresh(self) -> None:
        """Periodic decay hook (the node health tick): a class whose
        kernel waves stopped ages out of the alerting gauge instead of
        pinning `cost_model_drift` at its last value forever."""
        import time as _time

        with self._lock:
            self._publish(_time.monotonic())

    def status(self) -> dict:
        with self._lock:
            return {
                "drift_ratio": self._gauge.value(),
                "classes": {
                    k: {"median": (round(self._median(s["window"]), 4)
                                   if s["window"] else None),
                        "samples": s["n"],
                        "last_predicted_ms": round(s["predicted_ms"], 3),
                        "last_measured_ms": round(s["measured_ms"], 3)}
                    for k, s in sorted(self._classes.items())},
            }

    def reset(self) -> None:
        """Test isolation."""
        with self._lock:
            self._classes.clear()
            self._gauge.set(0.0)


DRIFT = CostModelDrift()


# cheap sampling bound: percentile windows cost one lock round per
# set(); a 10k-op flush must not pay 10k value-size samples
_SAMPLE_CAP = 8


class WorkloadStats:
    """One partition's rolling shape stats. All writes are batched —
    at most one counter touch and a handful of percentile samples per
    served flush — so the profiler inherits the serving paths' own
    batching instead of adding per-row cost."""

    def __init__(self, app_id: int, pidx: int,
                 hotkey_collectors: Optional[dict] = None) -> None:
        self.app_id = app_id
        self.pidx = pidx
        self._hc = hotkey_collectors or {}
        ent = METRICS.entity(
            "workload", f"{app_id}.{pidx}",
            {"table": str(app_id), "partition": str(pidx)})
        self._read_ops = ent.counter("workload_read_ops")
        self._scan_ops = ent.counter("workload_scan_ops")
        self._write_ops = ent.counter("workload_write_ops")
        self._read_batch = ent.percentile("workload_read_batch")
        self._write_batch = ent.percentile("workload_write_batch")
        self._value_bytes = ent.percentile("workload_value_bytes")
        # percent of mask-evaluated rows that SURVIVED (scan pushdown's
        # win is exactly 100 minus this)
        self._selectivity = ent.percentile("workload_scan_selectivity")
        self._hot_share = ent.gauge("workload_hot_share")
        # pushdown scans (requests carrying a PushdownSpec the server
        # evaluated) vs plain scans: workload_scan_ops counts BOTH, this
        # counts the pushdown subset so `shell workload` can label the
        # mix. The pruned/aggregated counters are the metric twins of
        # the PerfContext fields of the same names (same kind, so
        # metrics_lint's conflict rule holds) — EXPLAIN reconciles a
        # pushdown scan's cost vector against these deltas
        self._pushdown_ops = ent.counter("workload_pushdown_ops")
        self._pushdown_pruned = ent.counter("pushdown_rows_pruned")
        self._rows_aggregated = ent.counter("rows_aggregated")

    # -- feed sites (serving paths) -------------------------------------

    def note_point(self, ops: int, keys: int,
                   value_sizes=()) -> None:
        self._read_ops.increment(ops)
        self._read_batch.set(float(keys))
        for v in value_sizes[:_SAMPLE_CAP]:
            self._value_bytes.set(float(v))

    def note_scan(self, reqs: int, rows_evaluated: int,
                  rows_survived: int) -> None:
        self._scan_ops.increment(reqs)
        if rows_evaluated > 0:
            self._selectivity.set(
                100.0 * rows_survived / rows_evaluated)

    def note_pushdown(self, reqs: int, rows_pruned: int,
                      rows_aggregated: int) -> None:
        """Pushdown leg of a scan flush (always paired with a
        note_scan for the same requests — pushdown scans ARE scans)."""
        self._pushdown_ops.increment(reqs)
        if rows_pruned > 0:
            self._pushdown_pruned.increment(rows_pruned)
        if rows_aggregated > 0:
            self._rows_aggregated.increment(rows_aggregated)

    def note_write(self, ops: int, rows: int, value_sizes=()) -> None:
        self._write_ops.increment(ops)
        self._write_batch.set(float(rows))
        for v in value_sizes[:_SAMPLE_CAP]:
            self._value_bytes.set(float(v))

    # -- read surfaces ---------------------------------------------------

    def _hot_hashkey_share(self) -> float:
        """Share (0..1) of fine-phase traffic owned by the detected-hot
        hashkey, from whichever HotkeyCollector finished a detection —
        0 when no detection has concluded."""
        best = 0.0
        for hc in self._hc.values():
            best = max(best, hc.hot_share())
        share = round(best, 4)
        self._hot_share.set(share)
        return share

    def summary(self) -> dict:
        """The compact digest riding config-sync (and the shell's
        --root fallback): op mix, batch/value/selectivity percentiles,
        hot-hashkey share."""
        rb = self._read_batch.quantiles((50.0, 99.0))
        wb = self._write_batch.quantiles((50.0, 99.0))
        vb = self._value_bytes.quantiles((50.0, 99.0))
        sel = self._selectivity.quantiles((50.0,))
        return {
            "read_ops": self._read_ops.value(),
            "scan_ops": self._scan_ops.value(),
            "pushdown_ops": self._pushdown_ops.value(),
            "write_ops": self._write_ops.value(),
            "read_batch_p50": rb[0], "read_batch_p99": rb[1],
            "write_batch_p50": wb[0], "write_batch_p99": wb[1],
            "value_bytes_p50": vb[0], "value_bytes_p99": vb[1],
            "scan_selectivity_p50": round(sel[0], 2),
            "hot_share": self._hot_hashkey_share(),
        }


def fold_summaries(rows) -> dict:
    """Roll per-partition summaries into one table row (meta's
    `workload` admin verb and the collector's `_workload` stat row
    share this): counters sum, percentiles take the worst partition
    (max — the honest aggregate, same rule the collector applies to
    latency percentiles), shares take the max."""
    out = {"partitions": 0, "read_ops": 0, "scan_ops": 0,
           "pushdown_ops": 0, "write_ops": 0, "read_batch_p99": 0.0,
           "write_batch_p99": 0.0, "value_bytes_p99": 0.0,
           "scan_selectivity_p50": 0.0, "hot_share": 0.0}
    for row in rows:
        out["partitions"] += 1
        for k in ("read_ops", "scan_ops", "pushdown_ops", "write_ops"):
            out[k] += int(row.get(k, 0))
        for k in ("read_batch_p99", "write_batch_p99",
                  "value_bytes_p99", "scan_selectivity_p50",
                  "hot_share"):
            out[k] = max(out[k], float(row.get(k, 0.0)))
    return out
