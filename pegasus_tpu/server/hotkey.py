"""Hotkey detection: find hot hashkeys from the request stream.

Parity: src/server/hotkey_collector.h:93 — two-phase detection started
on demand (on_detect_hotkey RPC, pegasus_server_impl.h:470):
1. COARSE: hashkeys bucket by hash into a small array of counters; a
   bucket whose count is a variance outlier (z-score over buckets,
   hotkey_collector.cpp find_outlier_index) flags phase 2.
2. FINE: only keys landing in the hot bucket are counted individually;
   the dominant key is reported.

Counting is vectorized (numpy) over batches of captured hashkeys — the
server feeds whole request batches, not one key at a time.
"""

from __future__ import annotations

import enum
from collections import Counter
from typing import List, Optional, Sequence

import numpy as np

from pegasus_tpu.base.crc import crc64_batch

BUCKET_COUNT = 37  # prime, parity with the reference's small bucket array
COARSE_ZSCORE_THRESHOLD = 3.0
FINE_DOMINANCE = 0.5  # a key owning half the hot bucket's traffic wins


class HotkeyState(enum.Enum):
    STOPPED = "stopped"
    COARSE = "coarse"
    FINE = "fine"
    FINISHED = "finished"


class HotkeyCollector:
    def __init__(self) -> None:
        self.state = HotkeyState.STOPPED
        self._coarse = np.zeros(BUCKET_COUNT, dtype=np.int64)
        self._hot_bucket: Optional[int] = None
        self._fine: Counter = Counter()
        self.result: Optional[bytes] = None

    def start(self) -> None:
        self.state = HotkeyState.COARSE
        self._coarse[:] = 0
        self._hot_bucket = None
        self._fine.clear()
        self.result = None

    def stop(self) -> None:
        self.state = HotkeyState.STOPPED

    def hot_hash_key(self) -> Optional[bytes]:
        """The detected-hot hashkey once a detection FINISHES, else
        None — the node row cache's fast-admit signal: a hashkey the
        two-phase detector already flagged earns caching on first
        touch instead of waiting out the repeat-hit gate."""
        return self.result if self.state is HotkeyState.FINISHED else None

    def hot_share(self) -> float:
        """Share (0..1) of fine-phase traffic owned by the detected-hot
        hashkey; 0 before a detection FINISHES. Owned here (not read
        through the private counter from outside) because a concurrent
        `start()` clears the counter mid-iteration — callers on other
        threads (the config-sync workload digest) get 0 for that racy
        instant instead of a RuntimeError."""
        hot = self.hot_hash_key()
        if hot is None:
            return 0.0
        try:
            total = sum(self._fine.values())
            top = self._fine.get(hot, 0)
        except RuntimeError:  # restart cleared the counter mid-sum
            return 0.0
        return top / total if total else 0.0

    def capture(self, hash_keys: Sequence[bytes]) -> None:
        """Feed a batch of request hashkeys (called from read/write
        dispatch paths while a detection is running)."""
        if self.state not in (HotkeyState.COARSE, HotkeyState.FINE):
            return
        if not hash_keys:
            return
        # vectorized bucketing: one crc64_batch over the padded batch
        # instead of a per-key Python loop on the dispatch path
        width = max(len(hk) for hk in hash_keys)
        arr = np.zeros((len(hash_keys), max(1, width)), dtype=np.uint8)
        lens = np.zeros(len(hash_keys), dtype=np.int64)
        for i, hk in enumerate(hash_keys):
            arr[i, :len(hk)] = np.frombuffer(hk, dtype=np.uint8)
            lens[i] = len(hk)
        buckets = (crc64_batch(arr, lens)
                   % np.uint64(BUCKET_COUNT)).astype(np.int64)
        if self.state == HotkeyState.COARSE:
            np.add.at(self._coarse, buckets, 1)
            self._maybe_promote()
        if self.state == HotkeyState.FINE:
            for hk, b in zip(hash_keys, buckets):
                if b == self._hot_bucket:
                    self._fine[hk] += 1
            self._maybe_finish()

    def _maybe_promote(self) -> None:
        """Coarse -> fine when one bucket is a z-score outlier (parity:
        find_outlier_index)."""
        total = int(self._coarse.sum())
        if total < 100:
            return
        mean = self._coarse.mean()
        std = self._coarse.std()
        if std == 0:
            return
        z = (self._coarse - mean) / std
        hot = int(z.argmax())
        if z[hot] >= COARSE_ZSCORE_THRESHOLD:
            self._hot_bucket = hot
            self.state = HotkeyState.FINE

    def _maybe_finish(self) -> None:
        total = sum(self._fine.values())
        if total < 100:
            return
        key, count = self._fine.most_common(1)[0]
        if count >= total * FINE_DOMINANCE:
            self.result = key
            self.state = HotkeyState.FINISHED


def hotspot_partition_indices(partition_qps: Sequence[float],
                              threshold: float = 3.0) -> List[int]:
    """Cluster-side hotspot detection: z-score over per-partition QPS
    (parity: src/server/hotspot_partition_calculator.h:46 — the collector
    flags partitions whose load is a variance outlier)."""
    qps = np.asarray(partition_qps, dtype=float)
    if len(qps) < 2:
        return []
    std = qps.std()
    if std == 0:
        return []
    z = (qps - qps.mean()) / std
    return [int(i) for i in np.flatnonzero(z >= threshold)]
