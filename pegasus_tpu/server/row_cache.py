"""Node-level hot-row cache — hot hashkeys served without the LSM.

Role parity: RocksDB's row cache in front of the table stack (the
reference's `pegasus_server_impl` rides rocksdb block/row caching);
here ONE byte-capped LRU is shared by every partition a node hosts, so
a handful of viral hashkeys cannot each pin a partition-private cache.

Keying and correctness:

- Entries are keyed `(gid, store_uid, generation, key)` — the store
  identity token plus its run-set generation. A flush, compaction
  publish, ingest, or wholesale engine swap (restore / learner
  checkpoint) changes the generation or the store uid, so every prior
  entry silently stops matching; `invalidate_gid` additionally drops
  the bytes eagerly on publish/swap so dead entries don't occupy the
  cap.
- Writes invalidate WRITE-THROUGH: the engine's mutation apply hook
  removes the touched keys and bumps the gid's invalidation epoch
  BEFORE the write is acknowledged, so a later read can never hit a
  value the writer already replaced.
- The populate race (read resolves an old value from the LSM, a write
  lands, then the read admits the old value) is closed by the epoch:
  admission passes the epoch observed BEFORE the LSM lookup and the
  cache refuses the entry if any invalidation touched the gid since.

Admission is gated by repeat traffic: a key must miss twice (bounded
touch table) before its bytes are admitted — one-shot scans must not
flush the working set — and the partition HotkeyCollector's published
result is a fast-admit: a detected-hot hashkey caches on first touch.

Knob: `[pegasus.server] row_cache_bytes` (mutable; 0 disables).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.server", "row_cache_bytes", 33_554_432,
            "node-level hot-row cache capacity in bytes (0 = disabled)",
            mutable=True)

# per-entry bookkeeping overhead charged against the byte cap (tuple +
# dict slot + key copies), so a million tiny rows cannot blow past the
# configured budget on Python object overhead alone
_ENTRY_OVERHEAD = 120

_TOUCH_CAP = 8192


class RowCache:
    """Byte-capped LRU of (full encoded value, expire_ts) rows."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Tuple[bytes, int, int]]" = \
            OrderedDict()
        self._bytes = 0
        self._epochs: dict = {}       # gid -> invalidation epoch
        # node-wide epoch component: bumped by disable-time clears and
        # by writes that arrive while the cache is disabled (the
        # lock-free fast path below). A gid that was never invalidated
        # has implicit per-gid epoch 0 — without this term, a write
        # landing in a disabled window would leave that gid's epoch
        # unchanged and a plan spanning the off/on flag toggle could
        # admit the pre-write value. epoch() sums both terms: both only
        # grow, so any invalidation event changes the sum.
        self._flush_epoch = 0
        # gid -> {entry keys}: publishes drop one partition wholesale,
        # and a node-shared cache must not scan every other partition's
        # entries under the global lock to do it
        self._gid_index: dict = {}
        self._touch: "OrderedDict[tuple, int]" = OrderedDict()
        ent = METRICS.entity("storage", "node")
        self._hit = ent.relaxed_counter("row_cache_hit")
        self._miss = ent.relaxed_counter("row_cache_miss")
        self._evicted = ent.relaxed_counter("row_cache_evict_bytes")

    @property
    def capacity(self) -> int:
        return int(FLAGS.get("pegasus.server", "row_cache_bytes"))

    @property
    def enabled(self) -> bool:
        cap = self.capacity
        if cap <= 0:
            if self._entries or self._touch:
                # the mutable knob was turned off with rows resident:
                # free them now (eviction otherwise only runs inside
                # admit, which a disabled cache never reaches) and bump
                # the node epoch so an in-flight admission that
                # observed the enabled cache can never land later
                with self._lock:
                    evicted = self._bytes
                    self._entries.clear()
                    self._gid_index.clear()
                    self._touch.clear()
                    self._bytes = 0
                    self._flush_epoch += 1
                if evicted:
                    self._evicted.increment(evicted)
            return False
        return True

    def epoch(self, gid) -> int:
        return self._epochs.get(gid, 0) + self._flush_epoch

    # ---- serve --------------------------------------------------------

    def get_many(self, gid, store_uid: int, generation: int, keys
                 ) -> dict:
        """{key -> (value, expire_ts)} for the hits; a hit refreshes
        LRU recency. ONE lock round serves a whole flush — the plan
        loop must not pay a node-global lock acquisition per key. TTL
        semantics stay the caller's job (identical to the engine
        contract), so a cached row expires exactly like an LSM row."""
        out: dict = {}
        entries = self._entries
        with self._lock:
            for key in keys:
                k = (gid, store_uid, generation, key)
                ent = entries.get(k)
                if ent is not None:
                    entries.move_to_end(k)
                    out[key] = (ent[0], ent[1])
        hits = len(out)
        if hits:
            self._hit.increment(hits)
        misses = len(keys) - hits
        if misses:
            self._miss.increment(misses)
        return out

    def get(self, gid, store_uid: int, generation: int, key: bytes
            ) -> Optional[Tuple[bytes, int]]:
        return self.get_many(gid, store_uid, generation, [key]).get(key)

    # ---- admit --------------------------------------------------------

    def note_and_check_many(self, gid, keys, fast=()) -> list:
        """Count one base-resolved miss per key; return the keys that
        have earned admission (second touch, or membership in `fast` —
        the hotkey fast-admit set). One lock round per flush."""
        if not self.enabled:
            return []
        granted = []
        touch = self._touch
        with self._lock:
            for key in keys:
                if key in fast:
                    granted.append(key)
                    continue
                t = (gid, key)
                c = touch.get(t, 0) + 1
                touch[t] = c
                touch.move_to_end(t)
                if c >= 2:
                    granted.append(key)
            while len(touch) > _TOUCH_CAP:
                touch.popitem(last=False)
        return granted

    def note_and_check(self, gid, key: bytes, fast: bool = False) -> bool:
        return bool(self.note_and_check_many(
            gid, [key], fast={key} if fast else ()))

    def admit_many(self, gid, store_uid: int, generation: int, items,
                   epoch: Optional[int] = None) -> None:
        """Insert [(key, full encoded value, expire_ts)] rows, evicting
        LRU past the byte cap — one lock round per flush. `epoch` is
        the invalidation epoch observed BEFORE the LSM reads that
        produced these rows: a mismatch means a write/publish raced the
        plan, and caching would preserve the overwritten value."""
        cap = self.capacity
        if cap <= 0:
            return
        evicted = 0
        with self._lock:
            if epoch is not None and self._epochs.get(gid, 0) \
                    + self._flush_epoch != epoch:
                return  # a write/publish raced this read: don't cache
            for key, value, expire_ts in items:
                nbytes = len(key) + len(value) + _ENTRY_OVERHEAD
                if nbytes > cap:
                    continue
                k = (gid, store_uid, generation, key)
                old = self._entries.pop(k, None)
                if old is not None:
                    self._bytes -= old[2]
                self._entries[k] = (value, expire_ts, nbytes)
                self._gid_index.setdefault(gid, set()).add(k)
                self._bytes += nbytes
            while self._bytes > cap and self._entries:
                ek, (_v, _e, nb) = self._entries.popitem(last=False)
                idx = self._gid_index.get(ek[0])
                if idx is not None:
                    idx.discard(ek)
                self._bytes -= nb
                evicted += nb
        if evicted:
            self._evicted.increment(evicted)

    def admit(self, gid, store_uid: int, generation: int, key: bytes,
              value: bytes, expire_ts: int,
              epoch: Optional[int] = None) -> None:
        self.admit_many(gid, store_uid, generation,
                        [(key, value, expire_ts)], epoch=epoch)

    # ---- invalidate ---------------------------------------------------

    def invalidate(self, gid, store_uid: int, generation: int,
                   keys) -> None:
        """Write-through invalidation from the mutation apply path:
        drop the touched keys and bump the gid epoch (which also voids
        any in-flight admission that read before this write)."""
        if not self._entries and self.capacity <= 0:
            # disabled and empty: no rows to drop and none can be
            # admitted while capacity <= 0 — but a plan that observed
            # the ENABLED cache may still be in flight across the flag
            # toggle, so this write must still void its admission: the
            # node-epoch bump below is lock-free (a lost increment
            # under a concurrent bump still leaves the sum changed,
            # which is all the admission check needs)
            self._flush_epoch += 1
            return
        with self._lock:
            self._epochs[gid] = self._epochs.get(gid, 0) + 1
            entries = self._entries
            idx = self._gid_index.get(gid)
            for key in keys:
                k = (gid, store_uid, generation, key)
                ent = entries.pop(k, None)
                if ent is not None:
                    self._bytes -= ent[2]
                    if idx is not None:
                        idx.discard(k)
                self._touch.pop((gid, key), None)

    def invalidate_gid(self, gid) -> None:
        """Wholesale drop for one partition: store publish (compaction
        / flush visible-set swap) and engine swaps. O(entries of THIS
        gid) via the per-gid index — a publish must not scan every
        other partition's rows under the node-shared lock (the touch
        table scan stays: it is bounded at _TOUCH_CAP)."""
        with self._lock:
            self._epochs[gid] = self._epochs.get(gid, 0) + 1
            dead = self._gid_index.pop(gid, None)
            if dead:
                for k in dead:
                    ent = self._entries.pop(k, None)
                    if ent is not None:
                        self._bytes -= ent[2]
            for t in [t for t in self._touch if t[0] == gid]:
                del self._touch[t]

    # ---- observability ------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            per_gid: dict = {}
            for (gid, _su, _gen, _key), (_v, _e, nb) in \
                    self._entries.items():
                g = per_gid.setdefault(str(gid), {"entries": 0, "bytes": 0})
                g["entries"] += 1
                g["bytes"] += nb
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "capacity": self.capacity, "per_gid": per_gid}


# the node-level shared instance (parity: one rocksdb row cache object
# shared across column families / replicas on a server)
ROW_CACHE = RowCache()
