"""Range-read iteration budget.

Parity: src/server/range_read_limiter.h:37 — a range read (multi_get/
sortkey_count/scan) stops early when it has examined
FLAGS_rocksdb_max_iteration_count records or spent
FLAGS_rocksdb_iteration_threshold_time_ms; the handler then reports an
incomplete result the client resumes from.
"""

from __future__ import annotations

import time

from pegasus_tpu.utils.flags import FLAGS, define_flag

define_flag("pegasus.server", "rocksdb_max_iteration_count", 1000,
            "max records examined by one ranged read", mutable=True)
define_flag("pegasus.server", "rocksdb_iteration_threshold_time_ms", 30_000,
            "max milliseconds for one ranged read (<=0: unlimited)",
            mutable=True)


class RangeReadLimiter:
    def __init__(self, max_iteration_count: int | None = None,
                 threshold_time_ms: int | None = None,
                 clock_ns=None) -> None:
        """`clock_ns`: nanosecond time source (default wall
        perf_counter_ns). Sim-hosted partitions thread their virtual
        clock here — the same threading scrub_tick/health_tick use —
        because a compressed sim schedule burns thousands of virtual
        seconds in milliseconds of wall (and vice versa a wall-stalled
        sim host could trip the budget with zero virtual time spent)."""
        self._max_count = (FLAGS.get("pegasus.server",
                                     "rocksdb_max_iteration_count")
                           if max_iteration_count is None
                           else max_iteration_count)
        self._threshold_ns = 1_000_000 * (
            FLAGS.get("pegasus.server", "rocksdb_iteration_threshold_time_ms")
            if threshold_time_ms is None else threshold_time_ms)
        self._clock_ns = (clock_ns if clock_ns is not None
                          else time.perf_counter_ns)
        self._count = 0
        self._start_ns = self._clock_ns()

    def add_count(self, n: int = 1) -> None:
        self._count += n

    @property
    def iteration_count(self) -> int:
        return self._count

    def count_exceeded(self) -> bool:
        return self._max_count > 0 and self._count >= self._max_count

    def time_exceeded(self) -> bool:
        return (self._threshold_ns > 0 and
                self._clock_ns() - self._start_ns > self._threshold_ns)

    def valid(self) -> bool:
        return not self.count_exceeded() and not self.time_exceeded()
