"""Node boot: the config-driven service host.

Parity: src/server/main.cpp:34-74 + dsn_run (runtime/service_api_c.cpp:279)
— ONE entry point; the cluster config decides whether this process runs
the meta role or a replica role (the rDSN idea that applications are
plugins selected by config, SURVEY §1). Timers stand in for the task
engine's timer tasks: FD beacons, group checks, config-sync, meta ticks.

Run:  python -m pegasus_tpu.server.node_main --config cluster.json --name node0

cluster.json:
    {"data_root": "/path",
     "nodes": {"meta":  {"host": "127.0.0.1", "port": 34601, "role": "meta"},
               "node0": {"host": "127.0.0.1", "port": 34801, "role": "replica"},
               ...}}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def load_config(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def address_book(cfg: dict) -> dict:
    return {name: (n["host"], n["port"])
            for name, n in cfg["nodes"].items()}


def run_node(cfg: dict, name: str) -> None:
    from pegasus_tpu.rpc.transport import TcpTransport

    node_cfg = cfg["nodes"][name]
    role = node_cfg["role"]
    data_root = cfg["data_root"]
    book = address_book(cfg)
    transport = TcpTransport((node_cfg["host"], node_cfg["port"]), book)
    if cfg.get("fault_plan"):
        # config-driven chaos (rpc/fault.py): every node of a chaos
        # onebox installs the same seeded schedule, so link faults are
        # charged once at the sender and the run replays from its seed
        from pegasus_tpu.rpc.fault import FaultPlan

        transport.install_fault_plan(
            FaultPlan.from_config(cfg["fault_plan"]))
        print(f"[{name}] fault plan armed: {cfg['fault_plan']}",
              flush=True)
    if cfg.get("disk_fault_plan"):
        # the disk twin of fault_plan (storage/vfs.py): bit-flip /
        # torn-write / EIO / ENOSPC injection on the data-file layer,
        # seeded so a chaos run replays exactly
        from pegasus_tpu.storage.vfs import install_disk_faults

        install_disk_faults(cfg["disk_fault_plan"])
        print(f"[{name}] disk fault plan armed: "
              f"{cfg['disk_fault_plan']}", flush=True)
    meta_names = [n for n, c in cfg["nodes"].items()
                  if c["role"] == "meta"]

    stop = {"flag": False}

    def on_term(_sig, _frm):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    http_server = None
    if role == "meta":
        from pegasus_tpu.http.http_server import MetricsHttpServer
        from pegasus_tpu.meta.meta_service import MetaService

        svc = MetaService(name, os.path.join(data_root, name), transport,
                          clock=time.monotonic, peers=meta_names)
        transport.run_timer(1.0, svc.tick)
        http_server = MetricsHttpServer(
            port=node_cfg.get("http_port", 0), commands=svc.commands,
            routes=svc.http_routes()).start()
        print(f"[{name}] meta serving on {node_cfg['host']}:"
              f"{node_cfg['port']} http={http_server.port}", flush=True)
    elif role == "replica":
        from pegasus_tpu.replica.replica import PartitionStatus
        from pegasus_tpu.replica.stub import ReplicaStub

        dirs = node_cfg.get("data_dirs") or [os.path.join(data_root, name)]
        stub = ReplicaStub(name, dirs, transport,
                           clock=time.time, sim_clock=time.monotonic,
                           cluster_id=int(cfg.get("cluster_id", 1)))
        stub.auth_secret = cfg.get("auth_secret")
        stub.meta_addrs = meta_names
        stub.meta_addr = meta_names[0]
        transport.run_timer(1.0, stub.send_beacon)
        transport.run_timer(2.5, stub.config_sync)

        def group_checks() -> None:
            for r in stub.replicas.values():
                if r.status == PartitionStatus.PRIMARY:
                    r.broadcast_group_check()

        transport.run_timer(1.0, group_checks)
        transport.run_timer(1.0, stub.dup_tick)
        transport.run_timer(1.0, stub.split_tick)
        transport.run_timer(2.0, stub.transfer_tick)
        # paced background scrub: verify at-rest block CRCs so latent
        # corruption on a non-serving replica is found and repaired
        # (quarantine + re-learn) before a promotion serves it
        transport.run_timer(1.0, stub.scrub_tick)
        # flight recorder + health watchdog (rings, rules, auto-pin);
        # the tick coalesces itself to the configured cadence
        transport.run_timer(2.0, stub.health_tick)
        # keep device predicate masks warm across TTL-seconds so scans
        # never block on an accelerator round-trip (scan_coordinator)
        from pegasus_tpu.server.scan_coordinator import MaskPrefresher

        MaskPrefresher(lambda: [r.server
                                for r in stub.replicas.values()]).start()
        # disk cleaner (parity: replica/disk_cleaner.*): age out trashed
        # replica dirs so rebalancing churn cannot fill the disk
        transport.run_timer(600.0, stub.fs.clean_trash)
        from pegasus_tpu.http.http_server import MetricsHttpServer

        http_server = MetricsHttpServer(
            port=node_cfg.get("http_port", 0),
            commands=stub.commands).start()
        print(f"[{name}] replica serving on {node_cfg['host']}:"
              f"{node_cfg['port']} http={http_server.port}", flush=True)
    else:
        raise SystemExit(f"unknown role {role!r} for {name}")

    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        transport.close()
        if role == "replica":
            stub.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--name", required=True)
    args = ap.parse_args()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    run_node(load_config(args.config), args.name)


if __name__ == "__main__":
    main()
