"""Node-level cross-partition scan coordination.

The SURVEY §2.6 dispatch model realized: partitions are the batch
dimension of ONE device program. A node hosting many partitions of a
table receives one multi-partition scan message, plans each partition's
batch, stacks every uncached block ACROSS partitions (same key width →
one [B*cap, W] program with a per-record partition-index column for the
stale-split check), evaluates once, and hands each partition its masks
back. Per-flush device dispatches drop from
O(partitions × blocks) to O(key-width buckets).

Masks are STATIC per (block, filter, partition_version): TTL expiry —
the only `now`-dependent predicate — is applied host-side from the
block's expire_ts column at assembly time (ops/predicates.py
static_block_predicate). A block therefore needs exactly one device
evaluation in its lifetime, and steady-state serving performs zero
device round-trips.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Tuple

import numpy as np

from pegasus_tpu.ops.predicates import (
    FT_NO_FILTER,
    FilterSpec,
    static_block_predicate,
)


def scan_multi(servers_and_reqs: List[Tuple[object, list]],
               now: int) -> List[list]:
    """[(PartitionServer, [GetScannerRequest])] -> [[ScanResponse]].

    Partitions that cannot take the batched fast path (filters, big
    overlay, gates) serve per-request; qualifying ones share one stacked
    evaluation wave.
    """
    states = []
    for server, reqs in servers_and_reqs:
        state = server.plan_scan_batch(reqs, now=now)
        states.append((server, reqs, state))

    # gather misses across partitions; stacking requires a shared
    # effective (validate, partition_version) — one table's partitions
    # satisfy that; mixed groups fall back to per-server evaluation
    flavor_groups: Dict[tuple, list] = {}
    for server, reqs, state in states:
        if state is None or "precomputed" in state:
            continue
        misses = server.planned_misses(state)
        flavor = (state["validate"], server.partition_version,
                  state["filter_key"])
        for ckey, dev in misses.items():
            flavor_groups.setdefault(flavor, []).append(
                (server, state, ckey, dev))

    for (validate, pv, filter_key), entries in flavor_groups.items():
        _eval_cross_partition(entries, validate, pv, filter_key)

    out = []
    for server, reqs, state in states:
        if state is None:
            out.append([server.on_get_scanner(r) for r in reqs])
        elif "precomputed" in state:
            out.append(state["precomputed"])
        else:
            out.append(server.finish_scan_batch(
                state, state["cached_keep"]))
    return out


def stacked_block_eval(blocks, validate: bool, pv: int,
                       filter_key=None):
    """The ONE stacking implementation both the per-partition and the
    cross-partition paths use. `blocks`: [(tag, dev_block, pidx)] —
    yields (tag, static_keep).

    Two phases: SUBMIT every chunk's program to the device (async — XLA
    queues them all), then GATHER every result with the transfers
    started together. On a tunneled accelerator each synchronous fetch
    of a fresh result pays a full round-trip (~tens of ms measured), so
    starting all copies before the first wait overlaps compute and
    transfer across chunks instead of serializing round-trips."""
    submitted = list(stacked_block_submit(blocks, validate, pv,
                                          filter_key))
    for o in submitted:
        _start_host_copy(o[2])
    for group, cap, keep_dev in submitted:
        keep_all = np.asarray(keep_dev)
        if len(group) == 1:
            yield group[0][0], keep_all
            continue
        for i, (tag, _d, _p) in enumerate(group):
            yield tag, keep_all[i * cap:(i + 1) * cap]


def stacked_block_submit(blocks, validate: bool, pv: int,
                         filter_key=None):
    """Phase 1: dispatch predicate programs WITHOUT waiting. Yields
    (group, cap, keep_device_array). Buckets by (key width, capacity) so
    differently-capped tail blocks can never misalign mask slices; fixed
    STACK_CHUNK keeps exactly two compiled shapes per key width
    ([cap, W] and [STACK_CHUNK*cap, W]) — variable stack sizes made
    every batch a fresh XLA compile. A stack mixing hash_lo and
    non-hash_lo blocks drops the precomputed column (the kernel computes
    the hash on device instead)."""
    hft, hfp, sft, sfp = filter_key or (FT_NO_FILTER, b"",
                                        FT_NO_FILTER, b"")
    hash_f = FilterSpec.make(hft, hfp)
    sort_f = FilterSpec.make(sft, sfp)
    buckets: "OrderedDict[tuple, list]" = OrderedDict()
    for tag, dev, pidx in blocks:
        key = (int(dev.keys.shape[1]), int(dev.keys.shape[0]))
        buckets.setdefault(key, []).append((tag, dev, pidx))
    for (_w, cap), group in buckets.items():
        for off in range(0, len(group), STACK_CHUNK):
            yield _submit_chunk(group[off:off + STACK_CHUNK], cap,
                                validate, pv, hash_f, sort_f)


STACK_CHUNK = 16


def _start_host_copy(arr) -> None:
    """Begin the device->host transfer without blocking (no-op for
    backends/arrays that don't support it)."""
    start = getattr(arr, "copy_to_host_async", None)
    if start is not None:
        try:
            start()
        except Exception:  # noqa: BLE001 - purely an overlap hint
            pass


def _submit_chunk(group, cap, validate, pv, hash_f, sort_f):
    import jax.numpy as jnp

    from pegasus_tpu.ops.record_block import RecordBlock

    if len(group) == 1:
        tag, dev, pidx = group[0]
        keep = static_block_predicate(
            dev, hash_filter=hash_f, sort_filter=sort_f,
            validate_hash=validate, pidx=pidx, partition_version=pv)
        return group, cap, keep
    padded = group + [group[0]] * (STACK_CHUNK - len(group))
    pidx_col = np.concatenate([
        np.full(cap, pidx, dtype=np.uint32)
        for _t, _d, pidx in padded])
    all_hash_lo = all(d.hash_lo is not None for _t, d, _p in padded)
    stacked = RecordBlock(
        jnp.concatenate([d.keys for _t, d, _p in padded]),
        jnp.concatenate([d.key_len for _t, d, _p in padded]),
        jnp.concatenate([d.hashkey_len for _t, d, _p in padded]),
        jnp.concatenate([d.expire_ts for _t, d, _p in padded]),
        jnp.concatenate([d.valid for _t, d, _p in padded]),
        (jnp.concatenate([d.hash_lo for _t, d, _p in padded])
         if all_hash_lo else None))
    keep = static_block_predicate(
        stacked, hash_filter=hash_f, sort_filter=sort_f,
        validate_hash=validate, pidx=pidx_col,
        partition_version=pv)
    return group, cap, keep


def _eval_cross_partition(entries, validate: bool,
                          pv: int, filter_key=None) -> None:
    """Stack blocks from MANY partitions; each record carries its owning
    partition index so one program validates all."""
    blocks = [((server, state, ckey), dev, server.pidx)
              for server, state, ckey, dev in entries]
    for (server, state, ckey), keep in stacked_block_eval(
            blocks, validate, pv, filter_key=filter_key):
        state["cached_keep"][ckey] = keep
        server.store_mask(state, ckey, keep)


class MaskPrefresher:
    """Background mask warmer — keeps first-touch device work off the
    serving path's critical latency.

    Static masks never expire (TTL is host-applied), so in steady state
    this thread has NOTHING to do: it only evaluates masks for blocks
    that recently appeared (flush/compaction rewrote the SSTs) or for a
    filter flavor seen for the first time, slightly ahead of the next
    scan. Serving that miss synchronously would cost a full device
    round-trip inside a client's scan — on a tunneled accelerator tens
    of milliseconds of dead wait.

    One per node (replica stub / bench cluster). Scans register their
    flavor (validate + filter) in PartitionServer.planned_misses (the
    `_warm_flavors` map); flavors age out after `horizon_s` without a
    scan. Daemon thread; safe to leave running.
    """

    def __init__(self, servers, horizon_s: float = 15.0,
                 poll_s: float = 0.2, device=None):
        import threading

        # `servers`: a list of PartitionServers, or a zero-arg callable
        # returning one (a replica stub's live set changes over time)
        self._servers = servers if callable(servers) \
            else (lambda s=list(servers): s)
        self.horizon_s = horizon_s
        self.poll_s = poll_s
        # jax.default_device is THREAD-local: a caller pinning a device
        # for serving must pin the warmer thread too or it computes on
        # the global default
        self.device = device
        self._stop = threading.Event()
        self._thread = None
        self.refreshed = 0  # masks warmed (for tests/metrics)

    @property
    def servers(self):
        return self._servers()

    def start(self) -> "MaskPrefresher":
        import threading

        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="mask-prefresher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        import contextlib

        ctx = contextlib.nullcontext()
        if self.device is not None:
            import jax

            ctx = jax.default_device(self.device)
        with ctx:
            while not self._stop.is_set():
                try:
                    self.refresh_once()
                except Exception:  # noqa: BLE001 - a dead warmer only
                    pass           # costs latency; serving recomputes
                self._stop.wait(self.poll_s)

    def refresh_once(self, now: int = 0) -> int:
        """One warm pass over hot blocks missing their static mask;
        returns masks stored. Synchronous; tests call this directly.
        (`now` accepted for back-compat; static masks don't depend on
        it.)"""
        import time as _time

        wall = _time.monotonic()
        warmed = 0
        flavors: Dict[tuple, list] = {}
        for srv in self.servers:
            for ckey, blk, validate, fkey in srv.hot_block_entries(
                    wall, self.horizon_s):
                dev = srv._device_cached_block(ckey, blk)
                flavors.setdefault(
                    (validate, srv.partition_version, fkey),
                    []).append((srv, ckey, dev))
        for (validate, pv, fkey), entries in flavors.items():
            blocks = [((srv, ckey), dev, srv.pidx)
                      for srv, ckey, dev in entries]
            for (srv, ckey), keep in stacked_block_eval(
                    blocks, validate, pv, filter_key=fkey):
                srv.store_mask_for(ckey, validate, fkey,
                                   keep, computed_pv=pv)
                warmed += 1
        self.refreshed += warmed
        return warmed
