"""Node-level cross-partition scan coordination.

The SURVEY §2.6 dispatch model realized: partitions are the batch
dimension of ONE device program. A node hosting many partitions of a
table receives one multi-partition scan message, plans each partition's
batch, stacks every uncached block ACROSS partitions (same key width →
one [B*cap, W] program with a per-record partition-index column for the
stale-split check), evaluates once, and hands each partition its masks
back. Per-flush device dispatches drop from
O(partitions × blocks) to O(key-width buckets).

Two further batch axes target the tunnel-accelerator cost model
(~70 ms fixed per dispatched program, ~25-37 MB/s device→host, measured):

- FLAVOR axis: requests carrying DIFFERENT filter patterns of the same
  filter type are planned as separate per-flavor groups, but their
  missing masks evaluate in ONE program ([K flavors × stacked records],
  ops/predicates.multi_static_block_predicate_submit) over the union of
  their blocks — each uploaded byte does K flavors of work, and every
  (flavor, block) pair in the union gets its mask cached (free sibling
  warming).
- PACKED masks: device programs return bit-packed uint8 masks (8x
  fewer bytes over the link); hosts unpack with numpy.

Masks are STATIC per (block, filter, partition_version): TTL expiry —
the only `now`-dependent predicate — is applied host-side from the
block's expire_ts column at assembly time (ops/predicates.py
static_block_predicate). A block therefore needs exactly one device
evaluation in its lifetime, and steady-state serving performs zero
device round-trips.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Tuple

import numpy as np

from pegasus_tpu.ops.predicates import (
    FT_NO_FILTER,
    FilterSpec,
    multi_static_block_predicate_submit,
    static_block_predicate,
    unpack_masks,
)
from pegasus_tpu.ops.record_block import next_bucket


def scan_multi(servers_and_reqs: List[Tuple[object, list]],
               now: int) -> List[list]:
    """[(PartitionServer, [GetScannerRequest])] -> [[ScanResponse]].

    Requests are grouped per (validate, filter) flavor so a batch mixing
    filter patterns still rides the batched device path (one plan per
    flavor, one multi-flavor evaluation wave); partitions that cannot
    take the fast path (big overlay, gates, exotic filters) serve
    per-request.
    """
    from pegasus_tpu.server.partition_server import _normalize_filter_key

    states = []
    for server, reqs in servers_and_reqs:
        groups: "OrderedDict[tuple, list]" = OrderedDict()
        for i, r in enumerate(reqs):
            # pushdown identity joins the GROUP key (one request with a
            # different value filter or an aggregate must not knock the
            # whole flavor off the batched path) but not the plan
            # flavor — the device mask inputs are key-side only
            fl = (bool(r.validate_partition_hash
                       and server.validate_partition_hash),
                  _normalize_filter_key(r),
                  r.pushdown.key if r.pushdown is not None else None)
            groups.setdefault(fl, []).append(i)
        sub = []
        for fl, idxs in groups.items():
            state = server.plan_scan_batch([reqs[i] for i in idxs],
                                           now=now, flavor=fl[:2])
            sub.append((idxs, state))
        states.append((server, reqs, sub))

    # gather misses across partitions AND flavors; an eval group shares
    # (validate, partition_version, filter types, pattern pad widths) —
    # everything that must be static/uniform in one device program
    eval_groups: Dict[tuple, dict] = {}
    for server, reqs, sub in states:
        for _idxs, state in sub:
            if state is None or "precomputed" in state:
                continue
            misses = server.planned_misses(state)
            if not misses:
                continue
            hft, hfp, sft, sfp = state["filter_key"]
            gkey = (state["validate"], server.partition_version,
                    hft, sft, next_bucket(len(hfp)),
                    next_bucket(len(sfp)))
            grp = eval_groups.setdefault(gkey, {})
            flavor = grp.setdefault(state["filter_key"], [])
            for ckey, dev in misses.items():
                flavor.append((server, state, ckey, dev))

    for (validate, pv, _hft, _sft, _hw, _sw), flavors in \
            eval_groups.items():
        if len(flavors) == 1:
            (fkey, entries), = flavors.items()
            _eval_cross_partition(entries, validate, pv, fkey)
        else:
            _eval_cross_partition_multi(flavors, validate, pv)

    # cross-partition native assembly: concatenate every partition's
    # fast-path (overlay-free) requests and pack them with ONE native
    # call per flush (page.serve_batch -> pegasus_scan_serve_batch) —
    # per-partition batches are tiny (a 32-scan flush spread over 64
    # partitions), so amortizing the call setup across the whole flush
    # is what makes the C++ path pay
    from pegasus_tpu.server.page import serve_batch
    from pegasus_tpu.server.partition_server import (
        SCAN_BYTES_CAP,
        header_length,
    )

    fast_all: list = []
    fast_refs: list = []
    hdr_set = set()
    for server, reqs, sub in states:
        for _idxs, state in sub:
            if state is None or "precomputed" in state:
                continue
            fast = server.prepare_serve(state, state["cached_keep"])
            if not fast:
                continue
            hdr_set.add(header_length(server.data_version))
            fast_refs.append((state, len(fast)))
            fast_all.extend(fast)
    if fast_all and len(hdr_set) == 1:
        served_all = serve_batch(fast_all, None,
                                 SCAN_BYTES_CAP, hdr_set.pop())
        if served_all is not None:
            off = 0
            for state, n in fast_refs:
                state["_served"] = served_all[off:off + n]
                off += n

    out = []
    for server, reqs, sub in states:
        resps = [None] * len(reqs)
        for idxs, state in sub:
            if state is None:
                rs = [server.on_get_scanner(reqs[i]) for i in idxs]
            elif "precomputed" in state:
                rs = state["precomputed"]
            else:
                rs = server.finish_scan_batch(
                    state, state["cached_keep"],
                    served=state.pop("_served", None))
            for i, r in zip(idxs, rs):
                resps[i] = r
        out.append(resps)
    return out


def stacked_block_eval(blocks, validate: bool, pv: int,
                       filter_key=None, perf_ctxs=()):
    """The ONE stacking implementation both the per-partition and the
    cross-partition paths use. `blocks`: [(tag, dev_block, pidx)] —
    yields (tag, static_keep).

    Two phases: SUBMIT every chunk's program to the device (async — XLA
    queues them all), then GATHER every result with the transfers
    started together. On a tunneled accelerator each synchronous fetch
    of a fresh result pays a full round-trip (~tens of ms measured), so
    starting all copies before the first wait overlaps compute and
    transfer across chunks instead of serializing round-trips. Masks
    come back bit-packed (8x smaller on the link) and unpack host-side.

    Being the one kernel dispatch site, this is also where the
    placement cost model is AUDITED: the wave's wall time is compared
    against ops/placement's prediction and fed to the workload
    profiler's cost-model drift gauge (server/workload.DRIFT), and the
    ambient PerfContext (when an op is being tracked) records the
    verdict + predicted/measured kernel ms.
    """
    import time as _time

    blocks = list(blocks)
    if not blocks:
        return
    # resident mesh first: when the whole wave's blocks live in a
    # table's stacked device image and the cost model says one mesh
    # round beats the per-chunk host programs, ONE dispatch answers
    # everything (mesh_resident does its own drift audit under the
    # "mesh" class). Any decline — unattached, unresolved block, model
    # says host, watchdog trip — falls through unchanged.
    from pegasus_tpu.parallel.mesh_resident import MESH_SERVING

    if MESH_SERVING.enabled:
        served = MESH_SERVING.try_wave(blocks, validate, pv,
                                       filter_key=filter_key,
                                       perf_ctxs=perf_ctxs)
        if served is not None:
            yield from served
            return
    t0 = _time.perf_counter()
    submitted = list(stacked_block_submit(blocks, validate, pv,
                                          filter_key))
    fetched = _fetch_wave([o[2] for o in submitted])
    measured_s = _time.perf_counter() - t0
    _audit_kernel_wave(blocks, filter_key, measured_s, perf_ctxs)
    for (group, cap, _dev), packed in zip(submitted, fetched):
        keep_all = unpack_masks(packed, len(group) * cap)
        if len(group) == 1:
            yield group[0][0], keep_all
            continue
        for i, (tag, _d, _p) in enumerate(group):
            yield tag, keep_all[i * cap:(i + 1) * cap]


def _audit_kernel_wave(blocks, filter_key, measured_s: float,
                       perf_ctxs=()) -> None:
    """One drift sample per evaluated wave: predicted (cost model) vs
    measured (wall) kernel time, recorded process-wide and on the
    participating ops' PerfContexts (the ambient one, plus every
    coordinated state's context passed in `perf_ctxs` — the
    cross-partition path has no single ambient op). Filter-free masks
    are the compute-trivial "ttl" class; pattern-matching masks are
    "rules"."""
    from pegasus_tpu.ops.placement import (
        placement_verdict,
        predict_kernel_seconds,
    )
    from pegasus_tpu.server.workload import DRIFT
    from pegasus_tpu.utils import perf_context as perf

    cls = ("ttl" if filter_key is None
           or (filter_key[0] == FT_NO_FILTER
               and filter_key[2] == FT_NO_FILTER) else "rules")
    batch_bytes = sum(int(dev.keys.size) + 9 * int(dev.expire_ts.size)
                      for _t, dev, _p in blocks)
    predicted_s = predict_kernel_seconds(cls, batch_bytes)
    DRIFT.note(cls, predicted_s, measured_s)
    pcs = {id(pc): pc for pc in perf_ctxs if pc is not None}
    amb = perf.current()
    if amb is not None:
        pcs[id(amb)] = amb
    verdict = placement_verdict(cls) if pcs else ""
    for pc in pcs.values():
        # every participating op WAITED this wave, so each context
        # carries the wave's full wall time (not an apportioned share)
        pc.placement = verdict
        pc.predicted_kernel_ms += predicted_s * 1000.0
        pc.measured_kernel_ms += measured_s * 1000.0


def stacked_block_submit(blocks, validate: bool, pv: int,
                         filter_key=None):
    """Phase 1: dispatch predicate programs WITHOUT waiting. Yields
    (group, cap, packed_keep_device_array). Buckets by (key width,
    capacity) so differently-capped tail blocks can never misalign mask
    slices; fixed STACK_CHUNK keeps exactly two compiled shapes per key
    width ([cap, W] and [STACK_CHUNK*cap, W]) — variable stack sizes
    made every batch a fresh XLA compile. A stack mixing hash_lo and
    non-hash_lo blocks drops the precomputed column (the kernel computes
    the hash on device instead)."""
    hft, hfp, sft, sfp = filter_key or (FT_NO_FILTER, b"",
                                        FT_NO_FILTER, b"")
    hash_f = FilterSpec.make(hft, hfp)
    sort_f = FilterSpec.make(sft, sfp)
    for group, cap, stacked, pidx in _stacked_chunks(blocks):
        keep = static_block_predicate(
            stacked, hash_filter=hash_f, sort_filter=sort_f,
            validate_hash=validate, pidx=pidx, partition_version=pv,
            pack=True)
        yield group, cap, keep


STACK_CHUNK = 16

# flavor-axis sizes are bucketed to powers of two (list padded by
# repeating the last flavor) so K distinct patterns never compile more
# than log2(MULTI_FLAVOR_MAX) program shapes per (type, width) combo
MULTI_FLAVOR_MAX = 64


def _stacked_chunks(blocks):
    """Shared chunking: yields (group, cap, stacked RecordBlock, pidx)
    where pidx is a scalar (single block) or per-record column."""
    import jax.numpy as jnp

    from pegasus_tpu.ops.record_block import RecordBlock

    buckets: "OrderedDict[tuple, list]" = OrderedDict()
    for tag, dev, pidx in blocks:
        key = (int(dev.keys.shape[1]), int(dev.keys.shape[0]))
        buckets.setdefault(key, []).append((tag, dev, pidx))
    for (_w, cap), group in buckets.items():
        for off in range(0, len(group), STACK_CHUNK):
            chunk = group[off:off + STACK_CHUNK]
            if len(chunk) == 1:
                tag, dev, pidx = chunk[0]
                yield chunk, cap, dev, pidx
                continue
            padded = chunk + [chunk[0]] * (STACK_CHUNK - len(chunk))
            pidx_col = np.concatenate([
                np.full(cap, pidx, dtype=np.uint32)
                for _t, _d, pidx in padded])
            all_hash_lo = all(d.hash_lo is not None
                              for _t, d, _p in padded)
            stacked = RecordBlock(
                jnp.concatenate([d.keys for _t, d, _p in padded]),
                jnp.concatenate([d.key_len for _t, d, _p in padded]),
                jnp.concatenate([d.hashkey_len for _t, d, _p in padded]),
                jnp.concatenate([d.expire_ts for _t, d, _p in padded]),
                jnp.concatenate([d.valid for _t, d, _p in padded]),
                (jnp.concatenate([d.hash_lo for _t, d, _p in padded])
                 if all_hash_lo else None))
            yield chunk, cap, stacked, pidx_col


def _fetch_wave(arrays: list) -> list:
    """Fetch a whole wave of device results in ONE transfer round.

    The tunnel charges ~69 ms PER synchronous fetch round regardless of
    size (measured; marginal bandwidth ~37 MB/s) — fetching each chunk's
    mask separately multiplies that fixed cost by the chunk count, so
    the wave gathers every submitted result with a single device_get."""
    if not arrays:
        return []
    import jax

    try:
        return jax.device_get(arrays)
    except Exception:  # noqa: BLE001 - fall back to per-array fetch
        return [np.asarray(a) for a in arrays]


def _eval_cross_partition(entries, validate: bool,
                          pv: int, filter_key=None) -> None:
    """Stack blocks from MANY partitions; each record carries its owning
    partition index so one program validates all. Every participating
    state's PerfContext gets the wave's placement/kernel audit."""
    blocks = [((server, state, ckey), dev, server.pidx)
              for server, state, ckey, dev in entries]
    pcs = _state_perf_ctxs(state for _srv, state, _ck, _d in entries)
    for (server, state, ckey), keep in stacked_block_eval(
            blocks, validate, pv, filter_key=filter_key,
            perf_ctxs=pcs):
        state["cached_keep"][ckey] = keep
        server.store_mask(state, ckey, keep)


def _state_perf_ctxs(states) -> list:
    """Distinct PerfContexts of the coordinated states (the prefresher
    passes placeholder states with no dict surface — skip those)."""
    out = {}
    for state in states:
        getter = getattr(state, "get", None)
        if getter is None:
            continue
        pc = getter("perf")
        if pc is not None:
            out[id(pc)] = pc
    return list(out.values())


def _flavor_specs(fkeys):
    """[(hash_FilterSpec, sort_FilterSpec)] for the flavor axis, padded
    to a power-of-two K by repeating the last flavor (bounded compile
    shapes)."""
    specs = [(FilterSpec.make(hft, hfp), FilterSpec.make(sft, sfp))
             for hft, hfp, sft, sfp in fkeys]
    k = 1
    while k < len(specs):
        k <<= 1
    specs = specs + [specs[-1]] * (k - len(specs))
    return specs


def _eval_cross_partition_multi(flavors: dict, validate: bool,
                                pv: int) -> None:
    """K filter flavors × the UNION of their missing blocks in one
    program per stack chunk. Every (flavor, block) mask that comes back
    is cached — pairs beyond the flavor's own miss set are free warm
    masks for the next scan with that pattern."""
    fkeys = list(flavors.keys())
    if len(fkeys) > MULTI_FLAVOR_MAX:
        # beyond the cap: evaluate in slabs
        items = list(flavors.items())
        mid = len(items) // 2
        _eval_cross_partition_multi(dict(items[:mid]), validate, pv)
        _eval_cross_partition_multi(dict(items[mid:]), validate, pv)
        return
    specs = _flavor_specs(fkeys)

    # union of blocks across flavors (a block may be missed by several)
    union: "OrderedDict[tuple, tuple]" = OrderedDict()
    wanted: Dict[tuple, list] = {}
    for fkey, entries in flavors.items():
        for server, state, ckey, dev in entries:
            ukey = (id(server), ckey)
            union.setdefault(ukey, (server, ckey, dev))
            wanted.setdefault((fkey, ukey), []).append(state)

    blocks = [((server, ckey), dev, server.pidx)
              for server, ckey, dev in union.values()]
    import time as _time

    t0 = _time.perf_counter()
    submitted = []
    for group, cap, stacked, pidx in _stacked_chunks(blocks):
        packed = multi_static_block_predicate_submit(
            stacked, specs, validate, pidx, pv)
        submitted.append((group, cap, packed))
    fetched = _fetch_wave([p for _g, _c, p in submitted])
    # the multi-flavor wave audits like the single-flavor one: any
    # filtered flavor makes it the "rules" class (its compute bound)
    audit_fkey = next(
        (fk for fk in fkeys
         if fk[0] != FT_NO_FILTER or fk[2] != FT_NO_FILTER),
        fkeys[0])
    _audit_kernel_wave(
        blocks, audit_fkey, _time.perf_counter() - t0,
        _state_perf_ctxs(st for states in wanted.values()
                         for st in states))
    for (group, cap, _p), packed in zip(submitted, fetched):
        masks = unpack_masks(packed, len(group) * cap)     # [K, S*cap]
        for ki, fkey in enumerate(fkeys):
            row = masks[ki]
            for i, ((server, ckey), _d, _p) in enumerate(group):
                keep = row[i * cap:(i + 1) * cap] if len(group) > 1 \
                    else row
                ukey = (id(server), ckey)
                states = wanted.get((fkey, ukey))
                # sibling (flavor, block) pairs beyond a flavor's own
                # miss set are cached only for WARM flavors — a flood of
                # one-shot patterns must not LRU-evict the long-lived
                # warm masks steady-state serving depends on (the same
                # guard _register_flavor applies to background warming)
                if states is None:
                    with server._mask_lock:
                        warm = (validate, fkey) in server._warm_flavors
                    if not warm:
                        continue
                server.store_mask_for(ckey, validate, fkey, keep,
                                      computed_pv=pv)
                for state in states or ():
                    state["cached_keep"][ckey] = np.asarray(keep)


class MaskPrefresher:
    """Background mask warmer — keeps first-touch device work off the
    serving path's critical latency.

    Static masks never expire (TTL is host-applied), so in steady state
    this thread has NOTHING to do: it only evaluates masks for blocks
    that recently appeared (flush/compaction rewrote the SSTs) or for a
    filter flavor seen for the first time, slightly ahead of the next
    scan. Serving that miss synchronously would cost a full device
    round-trip inside a client's scan — on a tunneled accelerator tens
    of milliseconds of dead wait.

    One per node (replica stub / bench cluster). Scans register their
    flavor (validate + filter) in PartitionServer.planned_misses (the
    `_warm_flavors` map); flavors age out after `horizon_s` without a
    scan. Daemon thread; safe to leave running.
    """

    def __init__(self, servers, horizon_s: float = 15.0,
                 poll_s: float = 0.2, device=None):
        import threading

        # `servers`: a list of PartitionServers, or a zero-arg callable
        # returning one (a replica stub's live set changes over time)
        self._servers = servers if callable(servers) \
            else (lambda s=list(servers): s)
        self.horizon_s = horizon_s
        self.poll_s = poll_s
        # jax.default_device is THREAD-local: a caller pinning a device
        # for serving must pin the warmer thread too or it computes on
        # the global default
        self.device = device
        self._stop = threading.Event()
        self._thread = None
        self.refreshed = 0  # masks warmed (for tests/metrics)

    @property
    def servers(self):
        return self._servers()

    def start(self) -> "MaskPrefresher":
        import threading

        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="mask-prefresher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        import contextlib

        ctx = contextlib.nullcontext()
        if self.device is not None:
            import jax

            ctx = jax.default_device(self.device)
        with ctx:
            while not self._stop.is_set():
                try:
                    self.refresh_once()
                except Exception:  # noqa: BLE001 - a dead warmer only
                    pass           # costs latency; serving recomputes
                self._stop.wait(self.poll_s)

    def refresh_once(self, now: int = 0) -> int:
        """One warm pass over hot blocks missing their static mask;
        returns masks stored. Synchronous; tests call this directly.
        (`now` accepted for back-compat; static masks don't depend on
        it.) Flavors sharing filter types and pattern widths warm in
        one multi-flavor program per stack chunk."""
        import time as _time

        wall = _time.monotonic()
        warmed = 0
        groups: Dict[tuple, dict] = {}
        for srv in self.servers:
            for ckey, blk, validate, fkey in srv.hot_block_entries(
                    wall, self.horizon_s):
                dev = srv._device_cached_block(ckey, blk)
                hft, hfp, sft, sfp = fkey
                gkey = (validate, srv.partition_version, hft, sft,
                        next_bucket(len(hfp)), next_bucket(len(sfp)))
                grp = groups.setdefault(gkey, {})
                grp.setdefault(fkey, []).append((srv, ckey, dev))
        for (validate, pv, *_rest), flavors in groups.items():
            if len(flavors) == 1:
                (fkey, entries), = flavors.items()
                blocks = [((srv, ckey), dev, srv.pidx)
                          for srv, ckey, dev in entries]
                for (srv, ckey), keep in stacked_block_eval(
                        blocks, validate, pv, filter_key=fkey):
                    srv.store_mask_for(ckey, validate, fkey,
                                       keep, computed_pv=pv)
                    warmed += 1
            else:
                # no serving batch to hand masks back to: store-only
                _eval_cross_partition_multi(
                    {fkey: [(srv, _NO_STATE, ckey, dev)
                            for srv, ckey, dev in entries]
                     for fkey, entries in flavors.items()}, validate, pv)
                warmed += sum(len(e) for e in flavors.values())
        self.refreshed += warmed
        return warmed


class _NoStateType:
    """Placeholder state for prefresher-driven multi evals (no serving
    batch to hand masks back to) — swallows cached_keep writes."""

    def __getitem__(self, k):
        return {}


_NO_STATE = _NoStateType()
