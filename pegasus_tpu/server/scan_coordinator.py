"""Node-level cross-partition scan coordination.

The SURVEY §2.6 dispatch model realized: partitions are the batch
dimension of ONE device program. A node hosting many partitions of a
table receives one multi-partition scan message, plans each partition's
batch, stacks every uncached block ACROSS partitions (same key width →
one [B*cap, W] program with a per-record partition-index column for the
stale-split check), evaluates once, and hands each partition its masks
back. Per-flush device dispatches drop from
O(partitions × blocks) to O(key-width buckets).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Tuple

import numpy as np

from pegasus_tpu.ops.predicates import FilterSpec, scan_block_predicate


def scan_multi(servers_and_reqs: List[Tuple[object, list]],
               now: int) -> List[list]:
    """[(PartitionServer, [GetScannerRequest])] -> [[ScanResponse]].

    Partitions that cannot take the batched fast path (filters, big
    overlay, gates) serve per-request; qualifying ones share one stacked
    evaluation wave.
    """
    states = []
    for server, reqs in servers_and_reqs:
        state = server.plan_scan_batch(reqs, now=now)
        states.append((server, reqs, state))

    # gather misses across partitions; stacking requires a shared
    # effective (validate, partition_version) — one table's partitions
    # satisfy that; mixed groups fall back to per-server evaluation
    flavor_groups: Dict[tuple, list] = {}
    for server, reqs, state in states:
        if state is None or "precomputed" in state:
            continue
        misses = server.planned_misses(state)
        flavor = (state["validate"], server.partition_version)
        for ckey, dev in misses.items():
            flavor_groups.setdefault(flavor, []).append(
                (server, state, ckey, dev))

    for (validate, pv), entries in flavor_groups.items():
        _eval_cross_partition(entries, now, validate, pv)

    out = []
    for server, reqs, state in states:
        if state is None:
            out.append([server.on_get_scanner(r) for r in reqs])
        elif "precomputed" in state:
            out.append(state["precomputed"])
        else:
            out.append(server.finish_scan_batch(
                state, state["cached_keep"], state["cached_expired"]))
    return out


def stacked_block_eval(blocks, now: int, validate: bool, pv: int):
    """The ONE stacking implementation both the per-partition and the
    cross-partition paths use. `blocks`: [(tag, dev_block, pidx)] —
    yields (tag, keep, expired). Buckets by (key width, capacity) so
    differently-capped tail blocks can never misalign mask slices; the
    padded count rounds to a power of two to bound compilations; a
    stack mixing hash_lo and non-hash_lo blocks drops the precomputed
    column (the kernel computes the hash on device instead)."""
    import jax.numpy as jnp

    from pegasus_tpu.ops.record_block import RecordBlock

    none_f = FilterSpec.none()
    buckets: "OrderedDict[tuple, list]" = OrderedDict()
    for tag, dev, pidx in blocks:
        key = (int(dev.keys.shape[1]), int(dev.keys.shape[0]))
        buckets.setdefault(key, []).append((tag, dev, pidx))
    for (_w, cap), group in buckets.items():
        if len(group) == 1:
            tag, dev, pidx = group[0]
            m = scan_block_predicate(
                dev, now, hash_filter=none_f, sort_filter=none_f,
                validate_hash=validate, pidx=pidx,
                partition_version=pv)
            yield tag, np.asarray(m.keep), np.asarray(m.expired)
            continue
        # FIXED chunk size: exactly two compiled shapes per key width
        # ([cap, W] and [STACK_CHUNK*cap, W]) — variable power-of-two
        # buckets made every batch's stack a fresh XLA compile
        for off in range(0, len(group), STACK_CHUNK):
            yield from _eval_chunk(group[off:off + STACK_CHUNK], cap,
                                   now, validate, pv, none_f)


STACK_CHUNK = 16


def _eval_chunk(group, cap, now, validate, pv, none_f):
    import jax.numpy as jnp

    from pegasus_tpu.ops.record_block import RecordBlock

    if len(group) == 1:
        tag, dev, pidx = group[0]
        m = scan_block_predicate(
            dev, now, hash_filter=none_f, sort_filter=none_f,
            validate_hash=validate, pidx=pidx, partition_version=pv)
        yield tag, np.asarray(m.keep), np.asarray(m.expired)
        return
    padded = group + [group[0]] * (STACK_CHUNK - len(group))
    pidx_col = np.concatenate([
        np.full(cap, pidx, dtype=np.uint32)
        for _t, _d, pidx in padded])
    all_hash_lo = all(d.hash_lo is not None for _t, d, _p in padded)
    stacked = RecordBlock(
        jnp.concatenate([d.keys for _t, d, _p in padded]),
        jnp.concatenate([d.key_len for _t, d, _p in padded]),
        jnp.concatenate([d.hashkey_len for _t, d, _p in padded]),
        jnp.concatenate([d.expire_ts for _t, d, _p in padded]),
        jnp.concatenate([d.valid for _t, d, _p in padded]),
        (jnp.concatenate([d.hash_lo for _t, d, _p in padded])
         if all_hash_lo else None))
    m = scan_block_predicate(
        stacked, now, hash_filter=none_f, sort_filter=none_f,
        validate_hash=validate, pidx=pidx_col,
        partition_version=pv)
    keep_all = np.asarray(m.keep)
    exp_all = np.asarray(m.expired)
    for i, (tag, _d, _p) in enumerate(group):
        yield (tag, keep_all[i * cap:(i + 1) * cap],
               exp_all[i * cap:(i + 1) * cap])


def _eval_cross_partition(entries, now: int, validate: bool,
                          pv: int) -> None:
    """Stack blocks from MANY partitions; each record carries its owning
    partition index so one program validates all."""
    blocks = [((server, state, ckey), dev, server.pidx)
              for server, state, ckey, dev in entries]
    for (server, state, ckey), keep, expired in stacked_block_eval(
            blocks, now, validate, pv):
        state["cached_keep"][ckey] = keep
        state["cached_expired"][ckey] = expired
        server.store_mask(state, ckey, keep, expired)
