"""rrdb request/response structs.

Parity: idl/rrdb.thrift — same field sets and semantics, as Python
dataclasses (the wire codec arrives with the RPC layer; these are the
canonical in-process forms used by servers and clients alike).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    FT_NO_FILTER,
)
from pegasus_tpu.ops.pushdown import PushdownSpec


class CasCheckType(enum.IntEnum):
    """idl/rrdb.thrift:35-62."""

    CT_NO_CHECK = 0
    CT_VALUE_NOT_EXIST = 1
    CT_VALUE_NOT_EXIST_OR_EMPTY = 2
    CT_VALUE_EXIST = 3
    CT_VALUE_NOT_EMPTY = 4
    CT_VALUE_MATCH_ANYWHERE = 5
    CT_VALUE_MATCH_PREFIX = 6
    CT_VALUE_MATCH_POSTFIX = 7
    CT_VALUE_BYTES_LESS = 8
    CT_VALUE_BYTES_LESS_OR_EQUAL = 9
    CT_VALUE_BYTES_EQUAL = 10
    CT_VALUE_BYTES_GREATER_OR_EQUAL = 11
    CT_VALUE_BYTES_GREATER = 12
    CT_VALUE_INT_LESS = 13
    CT_VALUE_INT_LESS_OR_EQUAL = 14
    CT_VALUE_INT_EQUAL = 15
    CT_VALUE_INT_GREATER_OR_EQUAL = 16
    CT_VALUE_INT_GREATER = 17


class MutateOperation(enum.IntEnum):
    MO_PUT = 0
    MO_DELETE = 1


@dataclass(slots=True)
class KeyValue:
    """slots=True: scan responses create one per returned record — the
    single hottest allocation in the serving path."""

    key: bytes                    # sort_key in multi_* responses
    value: bytes = b""
    expire_ts_seconds: Optional[int] = None


_EMPTY_OFFS = b"\x00\x00\x00\x00"


@dataclass
class ScanPage:
    """A whole response page as FOUR packed blobs instead of a
    per-record KeyValue list — the columnar twin of the SST block
    layout, assembled by one native gather call (native/packer.cpp
    pegasus_gather_page) and wire-encoded in O(1) fields rather than
    O(records) values.

    Parity role: the kvs list of idl/rrdb.thrift scan_response — the
    reference serializes each key_value via thrift per record
    (src/server/pegasus_server_impl.cpp append_key_value_for_multi_get);
    here survivors are gathered straight from the columnar block into
    the page.  Supports the sequence protocol (len / index / iterate →
    KeyValue) so every existing kvs consumer works unchanged; iteration
    is the lazy path clients pay only for records they actually touch.

    key_offs/val_offs are little-endian uint32[n+1]; ets (present only
    when the scanner asked for expire timestamps) is uint32[n].
    """

    key_offs: bytes = _EMPTY_OFFS
    key_blob: bytes = b""
    val_offs: bytes = _EMPTY_OFFS
    val_blob: bytes = b""
    ets: bytes = b""

    def _offs(self):
        import numpy as np

        ko = self.__dict__.get("_ko")
        if ko is None:
            ko = np.frombuffer(self.key_offs, dtype="<u4")
            self.__dict__["_ko"] = ko
            self.__dict__["_vo"] = np.frombuffer(self.val_offs,
                                                 dtype="<u4")
        return ko, self.__dict__["_vo"]

    def __len__(self) -> int:
        return max(0, len(self.key_offs) // 4 - 1)

    def __bool__(self) -> bool:
        return len(self) > 0

    def key_at(self, i: int) -> bytes:
        ko, _ = self._offs()
        return self.key_blob[ko[i]:ko[i + 1]]

    def value_at(self, i: int) -> bytes:
        _, vo = self._offs()
        return self.val_blob[vo[i]:vo[i + 1]]

    def ets_at(self, i: int) -> Optional[int]:
        if not self.ets:
            return None
        import struct as _s

        return _s.unpack_from("<I", self.ets, 4 * i)[0]

    def __getitem__(self, i):
        n = len(self)
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(n))]
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return KeyValue(self.key_at(i), self.value_at(i), self.ets_at(i))

    def __iter__(self):
        ko, vo = self._offs()
        kb, vb = self.key_blob, self.val_blob
        if self.ets:
            import numpy as np

            ets = np.frombuffer(self.ets, dtype="<u4")
            for i in range(len(self)):
                yield KeyValue(kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]],
                               int(ets[i]))
        else:
            for i in range(len(self)):
                yield KeyValue(kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]])


@dataclass
class MultiPutRequest:
    hash_key: bytes
    kvs: List[KeyValue]           # sort_key -> value
    expire_ts_seconds: int = 0


@dataclass
class MultiRemoveRequest:
    hash_key: bytes
    sort_keys: List[bytes]


@dataclass
class MultiGetRequest:
    hash_key: bytes
    sort_keys: List[bytes] = field(default_factory=list)
    max_kv_count: int = -1        # <= 0 means no limit
    max_kv_size: int = -1
    no_value: bool = False
    start_sortkey: bytes = b""
    stop_sortkey: bytes = b""     # empty = to the last sort key
    start_inclusive: bool = True
    stop_inclusive: bool = False
    sort_key_filter_type: int = FT_NO_FILTER
    sort_key_filter_pattern: bytes = b""
    reverse: bool = False


@dataclass
class MultiGetResponse:
    error: int = 0
    kvs: List[KeyValue] = field(default_factory=list)
    # set on INCOMPLETE (forward range mode): the sort key a follow-up
    # page should start FROM (inclusive). Lets clients resume even when
    # an entire page was filtered out (all-expired run) and kvs is empty.
    resume_sort_key: Optional[bytes] = None


@dataclass
class FullKey:
    hash_key: bytes
    sort_key: bytes


@dataclass
class FullData:
    hash_key: bytes
    sort_key: bytes
    value: bytes


@dataclass
class BatchGetRequest:
    keys: List[FullKey]


@dataclass
class BatchGetResponse:
    error: int = 0
    data: List[FullData] = field(default_factory=list)


@dataclass
class IncrRequest:
    key: bytes                    # full encoded key
    increment: int
    expire_ts_seconds: int = 0    # 0 keep, >0 reset, <0 clear


@dataclass
class IncrResponse:
    error: int = 0
    new_value: int = 0
    decree: int = -1


@dataclass
class CheckAndSetRequest:
    hash_key: bytes
    check_sort_key: bytes
    check_type: int
    check_operand: bytes = b""
    set_diff_sort_key: bool = False
    set_sort_key: bytes = b""
    set_value: bytes = b""
    set_expire_ts_seconds: int = 0
    return_check_value: bool = False


@dataclass
class CheckAndSetResponse:
    error: int = 0
    check_value_returned: bool = False
    check_value_exist: bool = False
    check_value: bytes = b""
    decree: int = -1


@dataclass
class Mutate:
    operation: int                # MutateOperation
    sort_key: bytes
    value: bytes = b""
    set_expire_ts_seconds: int = 0


@dataclass
class CheckAndMutateRequest:
    hash_key: bytes
    check_sort_key: bytes
    check_type: int
    check_operand: bytes = b""
    mutate_list: List[Mutate] = field(default_factory=list)
    return_check_value: bool = False


@dataclass
class CheckAndMutateResponse:
    error: int = 0
    check_value_returned: bool = False
    check_value_exist: bool = False
    check_value: bytes = b""
    decree: int = -1


@dataclass
class GetScannerRequest:
    start_key: bytes = b""        # full encoded keys
    stop_key: bytes = b""
    start_inclusive: bool = True
    stop_inclusive: bool = False
    batch_size: int = 1000
    no_value: bool = False
    hash_key_filter_type: int = FT_NO_FILTER
    hash_key_filter_pattern: bytes = b""
    sort_key_filter_type: int = FT_NO_FILTER
    sort_key_filter_pattern: bytes = b""
    validate_partition_hash: bool = False
    return_expire_ts: bool = False
    full_scan: bool = False
    only_return_count: bool = False
    # one-shot ranged read: serve a single page and never cache a scan
    # context — the client promises not to page further, saving it the
    # clear_scanner round-trip (the YCSB-E "scan N records" shape)
    one_page: bool = False
    # server-side pushdown (ops/pushdown.py): a value-region filter
    # and/or an aggregate evaluated inside the scan-page path. A server
    # that predates (or has disabled) pushdown simply ignores this
    # field and leaves `pushdown_applied` False on its responses — the
    # soft version gate clients detect to fall back to local evaluation
    pushdown: Optional[PushdownSpec] = None


@dataclass
class ScanRequest:
    context_id: int


@dataclass
class ScanResponse:
    error: int = 0
    kvs: List[KeyValue] = field(default_factory=list)
    context_id: int = -1
    kv_count: int = -1
    # True iff the server evaluated the request's PushdownSpec for this
    # page (False from pre-pushdown / pushdown-disabled servers)
    pushdown_applied: bool = False
    # aggregate-mode only: the partition's PARTIAL aggregate in
    # ops/pushdown wire form (AggState.to_wire), carried ONLY on the
    # final page of the partition's scan so a lost context / split
    # bounce can restart from scratch without double counting
    agg: Optional[Dict[str, Any]] = None

    def wire_bytes(self) -> int:
        """Approximate serialized size of this response — what the
        shipped-bytes counters accumulate to assert aggregate-mode
        replies stay O(partitions), not O(rows), on the wire."""
        n = 24  # error/context_id/kv_count/flags framing
        kvs = self.kvs
        if isinstance(kvs, ScanPage):
            n += (len(kvs.key_offs) + len(kvs.key_blob)
                  + len(kvs.val_offs) + len(kvs.val_blob)
                  + len(kvs.ets))
        else:
            for kv in kvs:
                n += 8 + len(kv.key) + len(kv.value)
        if self.agg is not None:
            n += 64
            for it in self.agg.get("items") or ():
                n += 16 + sum(len(x) for x in it
                              if isinstance(x, (bytes, bytearray)))
        return n


# scan context ids (parity: src/base/pegasus_const.h SCAN_CONTEXT_ID_*)
SCAN_CONTEXT_ID_COMPLETED = -1
SCAN_CONTEXT_ID_NOT_EXIST = -2
