"""PartitionServer: the rrdb storage app for one partition.

Parity: src/server/pegasus_server_impl.{h,cpp} — implements the full rrdb
service surface (idl/rrdb.thrift:347-364): get / multi_get / batch_get /
sortkey_count / ttl / get_scanner / scan / clear_scanner on the read side,
put / multi_put / remove / multi_remove / incr / check_and_set /
check_and_mutate on the write side.

The TPU-first difference is the ranged-read hot loop: where the reference
validates records one-by-one in scalar C++ (on_multi_get:496, hot loop
:643; validate_key_value_for_scan:2382), we gather candidates into
columnar batches and evaluate filter/TTL/partition-hash predicates for a
whole batch in one device program (ops.scan_block_predicate).

Standalone mode assigns decrees locally; under replication the replica
layer drives apply with its own decrees.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Iterator, List, Optional, Tuple

import numpy as np

from pegasus_tpu.base.key_schema import (
    generate_key,
    generate_next_bytes,
    restore_key,
)
from pegasus_tpu.base.value_schema import (
    PEGASUS_EPOCH_BEGIN,
    check_if_ts_expired,
    epoch_now,
    extract_expire_ts,
    extract_user_data,
    expire_ts_from_ttl,
    header_length,
)
from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    FT_NO_FILTER,
    FilterSpec,
    host_match_filter,
    scan_block_predicate,
)
from pegasus_tpu.ops import pushdown as pushdown_ops

from pegasus_tpu.ops.record_block import build_record_block
from pegasus_tpu.server.capacity_units import (
    CapacityUnitCalculator,
    units as cu_units,
)
from pegasus_tpu.server.read_limiter import RangeReadLimiter
from pegasus_tpu.server.row_cache import ROW_CACHE
from pegasus_tpu.server.scan_context import ScanContext, ScanContextCache
from pegasus_tpu.server.types import (
    BatchGetRequest,
    BatchGetResponse,
    CheckAndMutateRequest,
    CheckAndMutateResponse,
    CheckAndSetRequest,
    CheckAndSetResponse,
    FullData,
    GetScannerRequest,
    IncrRequest,
    IncrResponse,
    KeyValue,
    MultiGetRequest,
    MultiGetResponse,
    MultiPutRequest,
    MultiRemoveRequest,
    SCAN_CONTEXT_ID_COMPLETED,
    SCAN_CONTEXT_ID_NOT_EXIST,
    ScanResponse,
)
from pegasus_tpu.server.write_service import WriteService

from pegasus_tpu.storage.bloom import bloom_probe_enabled
from pegasus_tpu.storage.phash import phash_probe_enabled
from pegasus_tpu.storage.engine import StorageEngine
from pegasus_tpu.utils.errors import (
    ErrorCode,
    StorageCorruptionError,
    StorageStatus,
)
from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.server", "scan_pushdown_enabled", True,
            "evaluate GetScannerRequest.pushdown specs (value filters "
            "+ aggregates) inside the scan-page path; off simulates a "
            "pre-pushdown server — specs are ignored, pushdown_applied "
            "stays False, clients fall back to local evaluation",
            mutable=True)

# the no-filter flavor's mask key component (and the normal form of any
# empty-pattern filter, which matches everything)
_NO_FILTER_KEY = (FT_NO_FILTER, b"", FT_NO_FILTER, b"")


def _normalize_filter_key(r) -> tuple:
    """(hash type, hash pattern, sort type, sort pattern), with
    empty-pattern components collapsed to FT_NO_FILTER and patterns
    under FT_NO_FILTER dropped — the matchers treat both as match-all,
    so distinct keys would only split batches and duplicate masks."""
    hft, hfp = r.hash_key_filter_type, r.hash_key_filter_pattern
    sft, sfp = r.sort_key_filter_type, r.sort_key_filter_pattern
    if hft == FT_NO_FILTER or not hfp:
        hft, hfp = FT_NO_FILTER, b""
    if sft == FT_NO_FILTER or not sfp:
        sft, sfp = FT_NO_FILTER, b""
    return (hft, hfp, sft, sfp)

# candidate records gathered per device predicate dispatch
PREDICATE_BATCH = 2048

# node-wide twin of the per-replica bloom counter (same RelaxedCounter
# object the sstable solo path ticks — the registry dedupes by name)
_STORAGE_BLOOM_USEFUL = METRICS.entity(
    "storage", "node").relaxed_counter("bloom_useful_count")

# requests bounced for routing under a stale partition count (the
# ERR_PARENT_PARTITION_MISUSED hash-gate) — the node-level split-fence
# observability the stub's ERR_SPLITTING rejects share
_SPLIT_FENCE_REJECTS = METRICS.entity(
    "storage", "node").counter("split_fence_reject_count")



# point-location-cache miss sentinel (None is a valid cached value:
# "definitively absent from the L1 runs")
_POINT_MISS = object()


def _after(key: bytes) -> bytes:
    """Immediate lexicographic successor of an exact key."""
    return key + b"\x00"


# Server-side caps on one scan page: client-supplied batch_size is
# untrusted, and page blob offsets are uint32 (ScanPage /
# pegasus_gather_page) — a >4GiB page would silently wrap them. The
# byte cap bounds the page by VALUE weight too (values can be multi-MB
# each); a capped page returns stop_early with a resume cursor, exactly
# like a record-capped one. The reference likewise caps scan batches
# server-side (pegasus_server_impl scan batch limits).
SCAN_BATCH_CAP = 65536
SCAN_BYTES_CAP = 64 << 20


def _lower_bound(blk, key: bytes) -> int:
    """First row index in a sorted SST block whose key >= `key`.

    Hot blocks (zipfian traffic re-plans the same boundaries) bisect
    C-speed over the materialized key list; cold blocks keep the
    O(log n) row-probe loop so a one-shot uniform scan never pays the
    full materialization (same gating as SSTable.get)."""
    import bisect as _b

    kl = blk._key_list
    if kl is None:
        blk._gets += 1
        if blk._gets >= 4:
            kl = blk.key_list()
    if kl is not None:
        return _b.bisect_left(kl, key)
    lo, hi = 0, blk.count
    while lo < hi:
        mid = (lo + hi) // 2
        if blk.key_at(mid) < key:
            lo = mid + 1
        else:
            hi = mid
    return lo


class PartitionServer:
    def __init__(self, data_dir: str, app_id: int = 1, pidx: int = 0,
                 partition_count: int = 1, data_version: int = 1,
                 cluster_id: int = 1) -> None:
        self.app_id = app_id
        self.pidx = pidx
        self.partition_count = partition_count
        # partition_version starts at count-1; split updates it
        # (parity: replica_split semantics via key_ttl/scan hash checks).
        # The &-mask check (check_pegasus_key_hash) is only meaningful for
        # power-of-two counts — routing is `% partition_count`, and
        # `& (count-1)` disagrees with it otherwise, silently dropping
        # records from scans. The reference only runs this check around
        # partition split, where counts are powers of two by construction.
        self.partition_version = partition_count - 1
        self.validate_partition_hash = (
            partition_count > 1 and (partition_count & (partition_count - 1)) == 0)
        self.data_version = data_version
        self.engine = StorageEngine(data_dir, data_version=data_version,
                                    values_carry_expire_header=True)
        self.write_service = WriteService(self.engine, data_version,
                                          cluster_id)
        self._write_lock = threading.Lock()  # single-writer invariant
        self._scan_cache = ScanContextCache()
        # (store-instance, generation, {(start, stop, want-bucket) ->
        # (plan, unique-entries)}): one dict PER GENERATION, replaced
        # wholesale when the run set (or the whole engine — learner
        # checkpoint apply / restore swap it) changes, so stale plans
        # can neither serve pre-swap blocks nor pin dead files
        self._plan_cache = None
        # (ckey, static-mask-id) -> (second, alive, expired_count, live):
        # per-second TTL-applied serving masks (see prepare_serve)
        self._live_cache: dict = {}
        # ((generation, second), {plan-id -> (plan, expired-count)}):
        # flavor-independent per-request expired accounting, reset
        # wholesale each second / store generation so it never pins
        # compacted-away blocks (see finish_scan_batch)
        self._plan_expired_cache: tuple = (None, {})
        # (store-instance, generation, {key -> None | ("l1", blk,
        # row)}): the point-read location cache — zipfian point traffic
        # re-probes the same hot keys constantly, and a key's (block,
        # row) location is pure over the immutable run set, so cache
        # hits skip the run/block/row bisects entirely. Same
        # invalidation discipline as _plan_cache (replaced wholesale on
        # generation change).
        self._point_cache = None
        # (store, generation, phash-flag, MultiProbe, {id(table) ->
        # filter col}, PHashMultiProbe, {id(table) -> index col}): the
        # run set's sidecar structures prepared for the one-call
        # batched probes; pure over the immutable run set (+ the
        # mutable phash kill switch, which decides whether indexed
        # tables still need bloom columns)
        self._index_probe_cache = None
        self.metrics = METRICS.entity(
            "replica", f"{app_id}.{pidx}",
            {"table": str(app_id), "partition": str(pidx)})
        self.cu = CapacityUnitCalculator(self.metrics)
        # nanosecond time source for range-read time budgets: None =
        # wall perf_counter_ns; sim-hosted partitions get the virtual
        # clock threaded in by the stub (the scrub_tick/health_tick
        # discipline) so compressed schedules can't spuriously trip —
        # or never trip — rocksdb_iteration_threshold_time_ms
        self.clock_ns = None
        self._abnormal_reads = self.metrics.counter("abnormal_read_count")
        # filter/row-cache observability, per partition (the node-wide
        # twins live on the "storage" entity): incremented BATCHED, once
        # per read flush
        self._bloom_useful = self.metrics.counter("bloom_useful_count")
        self._phash_useful = self.metrics.counter("phash_useful_count")
        self._row_cache_hits = self.metrics.counter("row_cache_hit")
        self._row_cache_misses = self.metrics.counter("row_cache_miss")
        # follower-read observability, per partition (node-wide twins on
        # the "storage" entity): reads this SECONDARY answered, reads it
        # bounced ERR_STALE_REPLICA, and the subset of those bounces
        # caused by a lapsed beacon lease — incremented by the hosting
        # stub's consistency gate
        self._follower_reads = self.metrics.counter("follower_read_count")
        self._stale_bounces = self.metrics.counter("stale_bounce_count")
        self._lease_rejects = self.metrics.counter(
            "read_lease_reject_count")
        # resident index memory as a first-class signal: per-table
        # bloom-vs-phash byte split, refreshed whenever the probe
        # structures rebuild (exactly when the run set changes) and
        # scraped by tools/collector.py
        self._index_bloom_bytes = self.metrics.gauge("index_bloom_bytes")
        self._index_phash_bytes = self.metrics.gauge("index_phash_bytes")
        # slow-read dumps (parity: slow-query threshold app-env +
        # latency_tracer dumps); threshold configurable per table via
        # replica.slow_query_threshold_ms
        from pegasus_tpu.utils.latency_tracer import SlowQueryLog

        self.slow_log = SlowQueryLog()
        self._scan_log_key = f"scan_batch.{app_id}.{pidx}"
        self._get_log_key = f"point_get_batch.{app_id}.{pidx}"
        # per-table read-latency percentile (the collector aggregates
        # p50/p99 per table from these each round)
        self._read_latency = self.metrics.percentile("read_latency_ms")
        # env-driven remote manual compaction (one-shot trigger times)
        self._mc_trigger_seen = 0
        self._mc_running = False
        # on-demand hotkey detection (parity: hotkey_collector.h:93 —
        # started via on_detect_hotkey; the request stream feeds capture
        # while a detection runs, else a None-check costs nothing)
        from pegasus_tpu.server.hotkey import HotkeyCollector

        self.hotkey_collectors = {"read": HotkeyCollector(),
                                  "write": HotkeyCollector()}
        # per-table workload shape stats (server/workload.py): op mix,
        # batch/value-size distributions, scan selectivity, hot-hashkey
        # share — recorded on a "workload" metric entity so the flight
        # recorder rings them and config-sync ships the summary to meta
        from pegasus_tpu.server.workload import WorkloadStats

        self.workload = WorkloadStats(app_id, pidx,
                                      self.hotkey_collectors)
        self.write_service.workload = self.workload
        # device-resident block cache: hot SST blocks stay in device memory
        # across scans (the HBM analogue of RocksDB's block cache), keyed by
        # (sst path, block offset) which is immutable per file
        self._device_block_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._device_block_cache_cap = 1024
        # materialized keep-mask cache keyed by (block, now, pv): the
        # predicate is a deterministic function of immutable block content
        # + the CURRENT SECOND (epoch_now granularity) + the partition
        # static masks: (ckey, pv, validate, filter_key) -> bool[cap].
        # `now`-independent (TTL applies host-side at assembly), so a
        # block's mask lives as long as the block — the device evaluates
        # each block ONCE, proportional to data instead of requests or
        # elapsed seconds
        self._mask_cache: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._mask_cache_cap = 4096
        # value-filter keep masks keyed by (ckey, (type, pattern)):
        # the pushdown twin of _mask_cache — a block's value bytes are
        # immutable, so the vectorized region match runs once per
        # (block, pattern) lifetime, like the static key masks. Not
        # part of the device mask flavors: value heaps never ride the
        # device (placement class "scan_pushdown" routes host)
        self._vmask_cache: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._vmask_cache_cap = 8192
        # mask/device caches are shared with the MaskPrefresher thread
        self._mask_lock = threading.Lock()
        # scan flavors (validate, filter_key) seen recently: after a
        # flush/compaction replaces the SSTs, the prefresher re-evaluates
        # the NEW blocks for these flavors in the background
        self._warm_flavors: "OrderedDict[tuple, float]" = OrderedDict()
        self._warm_flavors_cap = 64
        # filter flavors seen recently: filter_key -> last wall_ts. A
        # filtered flavor joins the warm set on its SECOND occurrence
        # within the window — one-shot filter patterns must not multiply
        # background device work
        self._filter_seen: "OrderedDict[tuple, float]" = OrderedDict()
        self._filter_seen_cap = 256
        self._filter_seen_window = 30.0
        # per-table dynamic app-envs (parity: src/common/replica_envs.h:39-83
        # propagated through config-sync; here set via update_app_envs)
        self.app_envs: dict = {}
        self._deny_client = ""          # "", "all", "write", "read"
        self._write_throttle = None     # TokenBucket (reject mode)
        self._read_throttle = None
        self._default_ttl = 0
        self._compaction_rules = None   # compiled rules_filter
        # external publish subscribers (e.g. the resident mesh layer):
        # fanned out from _on_store_publish AFTER the server's own cache
        # eviction, and rewired for free across engine swaps because the
        # single lsm.on_publish slot always points at this server's
        # bound method
        self.publish_listeners: list = []
        self.install_engine(self.engine)

    def install_engine(self, engine: StorageEngine) -> None:
        """(Re)wire a storage engine into this server: write service,
        auto-compaction filter context, and the store publish hook that
        keeps the serving caches from pinning dead runs. Used at
        __init__ and by every path that swaps the engine wholesale
        (restore from backup, learner checkpoint apply)."""
        self.engine = engine
        ws = getattr(self, "write_service", None)
        if ws is not None:
            ws.engine = engine
        # auto-compaction runs with THIS partition's filter context
        # (TTL + stale-split + user rules), like every rocksdb
        # compaction runs the filter in the reference
        engine.auto_compact_ctx = lambda: {
            "default_ttl": self._default_ttl,
            "pidx": self.pidx,
            "partition_version": self.partition_version,
            "validate_hash": self.validate_partition_hash,
            "rules_filter": self._compaction_rules,
        }
        engine.lsm.on_publish = self._on_store_publish
        # write-through row-cache invalidation: every applied mutation
        # batch drops its keys from the node cache BEFORE the write is
        # acked, and an engine swap orphans every entry of the old store
        engine.on_write_keys = self._invalidate_rows
        ROW_CACHE.invalidate_gid((self.app_id, self.pidx))

    def _invalidate_rows(self, keys) -> None:
        lsm = self.engine.lsm
        ROW_CACHE.invalidate((self.app_id, self.pidx), lsm.store_uid,
                             lsm.generation, keys)

    def _on_store_publish(self, live_paths: set) -> None:
        """Store publish hook (every compaction publish, including the
        write path's auto-compaction): evict cache entries keyed by
        runs that just left the manifest, so idle-scan partitions stop
        pinning pre-compaction fds/mmaps/device blocks/disk until GC.
        Warm FLAVORS survive — the prefresher re-evaluates the NEW
        blocks' masks in the background before the next scan pays the
        round-trip."""
        with self._mask_lock:
            for mkey in [k for k in self._mask_cache
                         if k[0][0] not in live_paths]:
                del self._mask_cache[mkey]
            for vkey in [k for k in self._vmask_cache
                         if k[0][0] not in live_paths]:
                del self._vmask_cache[vkey]
            for ckey in [k for k in self._device_block_cache
                         if k[0] not in live_paths]:
                del self._device_block_cache[ckey]
        # per-second / per-generation caches: rebind wholesale (cheap to
        # rebuild, and rebinding is safe against concurrent readers on
        # the serving thread)
        self._live_cache = {}
        self._plan_cache = None
        self._point_cache = None
        self._plan_expired_cache = (None, {})
        ROW_CACHE.invalidate_gid((self.app_id, self.pidx))
        for fn in list(self.publish_listeners):
            try:
                fn(live_paths)
            except Exception:  # noqa: BLE001 - a subscriber must never
                pass           # break the publish path

    # env key -> (derived attr, reset-to-default parsed value); used when
    # a FULL env set arrives and a previously-set key is now absent
    # (del_app_envs/clear_app_envs must un-apply, not just stop updating)
    _ENV_DEFAULTS = {
        "replica.deny_client_request": ("_deny_client", ""),
        "replica.write_throttling": ("_write_throttle", None),
        "replica.read_throttling": ("_read_throttle", None),
        "default_ttl": ("_default_ttl", 0),
        "replica.slow_query_threshold_ms": ("_slow_threshold_ms", 20.0),
        "rocksdb.usage_scenario": ("_usage_scenario", "normal"),
        "user_specified_compaction": ("_compaction_rules", None),
    }

    def update_app_envs(self, envs: dict, full_set: bool = False) -> None:
        """Apply per-table dynamic settings (parity: replica_envs keys
        ROCKSDB_ENV_* / deny_client_request / *throttling /
        user_specified_compaction / default_ttl). Validation is two-phase:
        every value parses first, then everything applies — a malformed
        env never leaves half-applied state (parity:
        meta/app_env_validator rejects before propagation).

        `full_set=True` means `envs` is the table's COMPLETE env map
        (meta propagation / config sync): recognized keys that were set
        before but are absent now reset to their defaults, so
        del_app_envs/clear_app_envs converge on the replicas."""
        from pegasus_tpu.ops.compaction_rules import compile_rules
        from pegasus_tpu.utils.token_bucket import parse_throttle_env

        staged = []
        if full_set:
            for key, (attr, dflt) in self._ENV_DEFAULTS.items():
                if key in self.app_envs and key not in envs:
                    staged.append((attr, dflt))
        for key, value in envs.items():
            try:
                if key == "replica.deny_client_request":
                    staged.append(("_deny_client",
                                   value.split("*")[-1] if value else ""))
                elif key == "replica.write_throttling":
                    staged.append(("_write_throttle",
                                   parse_throttle_env(value)))
                elif key == "replica.read_throttling":
                    staged.append(("_read_throttle",
                                   parse_throttle_env(value)))
                elif key == "default_ttl":
                    staged.append(("_default_ttl", int(value)))
                elif key == "replica.slow_query_threshold_ms":
                    staged.append(("_slow_threshold_ms", float(value)))
                elif key == "rocksdb.usage_scenario":
                    if value not in ("normal", "prefer_write",
                                     "bulk_load"):
                        raise ValueError("unknown scenario")
                    staged.append(("_usage_scenario", value))
                elif key == "user_specified_compaction":
                    staged.append(("_compaction_rules",
                                   compile_rules(value) if value else None))
                elif key == "manual_compact.once.trigger_time":
                    # accepts unix seconds (the reference's `date +%s`
                    # convention) or pegasus-epoch seconds; normalized
                    # to pegasus epoch (unambiguous: pegasus-epoch
                    # "now" stays far below PEGASUS_EPOCH_BEGIN)
                    ts = int(value) if value else 0
                    if ts > PEGASUS_EPOCH_BEGIN:
                        ts -= PEGASUS_EPOCH_BEGIN
                    staged.append(("_mc_once_trigger", ts))
            except Exception as exc:
                raise ValueError(f"invalid app-env {key}={value!r}: {exc}") \
                    from exc
        for attr, parsed in staged:
            if attr == "_slow_threshold_ms":
                self.slow_log.threshold_ms = parsed
            elif attr == "_usage_scenario":
                self._apply_usage_scenario(parsed)
            elif attr == "_mc_once_trigger":
                self._maybe_start_manual_compact(parsed)
            else:
                setattr(self, attr, parsed)
        if full_set:
            self.app_envs = dict(envs)
        else:
            self.app_envs.update(envs)

    def _maybe_start_manual_compact(self, trigger_ts: int) -> None:
        """Env-driven remote manual compaction (parity:
        pegasus_manual_compact_service.cpp, the
        `manual_compact.once.trigger_time` replica env): a trigger time
        NEWER than the last one seen starts one asynchronous full
        compaction; config-sync re-deliveries of the same env value are
        idempotent, and a trigger arriving while one run is in flight
        is absorbed (the running compaction already covers it — the
        reference's queued/running distinction). A trigger older than
        the store's recorded compaction finish time is already
        satisfied — a restarted replica re-syncing a stale env must not
        re-compact (check_once_compact's trigger-vs-finish compare).

        Why a thread is safe against concurrent serving: writes race
        only the brief freeze-flush and publish cut-over (manual_compact
        merges OFF the write lock from an immutable snapshot and
        revalidates the run set at publish); point reads and
        per-request scans snapshot the run list once and read
        memtable-before-runs (the safe order against the publish
        sequence); the batch planners bracket their reads with the
        store generation and fall back to per-key/per-request serving
        on a torn read (plan_scan_batch / plan_get_batch); superseded
        runs are unlinked but their handles are released by GC so
        in-flight readers — including encrypted CipherFile stores —
        finish on the files they hold (lsm._publish_l1); dead-run cache
        entries evict through the store publish hook
        (_on_store_publish). Running it synchronously instead would
        hold the node lock (timers + dispatch share it) for the whole
        compaction — stalling FD beacons long enough to get the node
        declared dead."""
        from pegasus_tpu.storage.compact_governor import GOVERNOR

        # <=: a re-delivered trigger that already STARTED a run is
        # absorbed even when the trigger is future-dated relative to
        # the recorded finish time (an operator stamping a skewed-ahead
        # timestamp must not re-compact every sync round). A DEFERRED
        # trigger never advances trigger_seen, so its re-delivery
        # passes this guard and re-attempts under a fresh grant.
        if trigger_ts <= 0 or trigger_ts <= self._mc_trigger_seen:
            return
        if trigger_ts <= self.engine.lsm.compact_finish_time:
            # persisted in the manifest independently of the run set, so
            # an all-tombstone compaction still satisfies its trigger
            # across restarts
            self._mc_trigger_seen = trigger_ts
            return
        if self._mc_running:
            self._mc_trigger_seen = trigger_ts
            return
        if not GOVERNOR.heavy_allowed():
            # cluster stagger: another node holds the heavy-compaction
            # slot. DEFER, don't block — the trigger env is
            # re-delivered by every config-sync round, and trigger_seen
            # is deliberately NOT advanced, so the next delivery
            # re-attempts under a (possibly fresh) grant. The governor
            # records the demand so this node's report asks for a slot.
            GOVERNOR.note_deferred()
            return
        self._mc_trigger_seen = trigger_ts
        self._mc_running = True
        GOVERNOR.begin_heavy()

        def run() -> None:
            try:
                # a recent trigger doubles as the table-shared filter
                # timestamp: every partition of the table sees the same
                # env in the same sync round, so they all filter at
                # `now=trigger_ts` — identical params let the mesh
                # filter stage serve the whole table from ONE dispatch
                # (a stale/future-skewed trigger falls back to each
                # partition's own clock)
                shared_now = (trigger_ts
                              if abs(epoch_now() - trigger_ts) <= 600
                              else None)
                self.manual_compact(now=shared_now)
            finally:
                self._mc_running = False
                GOVERNOR.end_heavy()

        threading.Thread(
            target=run, daemon=True,
            name=f"manual-compact-{self.app_id}.{self.pidx}").start()

    def _apply_usage_scenario(self, scenario: str) -> None:
        """Parity: the usage-scenario dynamic tuning
        (pegasus_server_impl.cpp:1758; envs common/replica_envs.h:81):
        normal serves balanced; prefer_write buffers more before
        flushing; bulk_load buffers maximally and defers compaction
        entirely until the load finishes (ingest-behind style)."""
        eng = self.engine
        if scenario == "normal":
            eng.memtable_flush_trigger = 100_000
            eng.auto_compact = True
            eng.lsm._l0_trigger = 4
        elif scenario == "prefer_write":
            eng.memtable_flush_trigger = 250_000
            eng.auto_compact = True
            eng.lsm._l0_trigger = 8
        else:  # bulk_load
            eng.memtable_flush_trigger = 500_000
            eng.auto_compact = False

    def _gate(self, bucket, denied: bool) -> int:
        """Shared deny/throttle gate (parity: the gate stack at
        replica_2pc.cpp:117-207 and replica_throttle.cpp). Delay-mode
        throttling sleeps briefly (capped); reject-mode returns
        TryAgain."""
        if denied:
            return int(StorageStatus.TRY_AGAIN)
        if bucket is not None:
            delay_b, reject_b = bucket
            if reject_b is not None and not reject_b.try_consume():
                return int(StorageStatus.TRY_AGAIN)
            if reject_b is None and delay_b is not None:
                wait = delay_b.consume_or_delay()
                if wait > 0:
                    time.sleep(min(wait, 0.1))
        return int(StorageStatus.OK)

    def _write_gate(self) -> int:
        return self._gate(self._write_throttle,
                          self._deny_client in ("all", "write"))

    def _read_gate(self) -> int:
        return self._gate(self._read_throttle,
                          self._deny_client in ("all", "read"))

    def close(self) -> None:
        self.engine.close()

    # ---- decree management (standalone mode) --------------------------

    def _next_decree(self) -> int:
        return self.engine.last_committed_decree + 1

    def _hash_gate(self, partition_hash: Optional[int]) -> int:
        """Reject requests whose routing hash no longer maps to this
        partition. The reference client carries its routing hash in the rpc
        header (rpc_message.h:81-126 `partition_hash`) and the replica
        rejects mismatches during/after a split so the client re-resolves
        (ERR_PARENT_PARTITION_MISUSED, replica_split_manager.h). Without
        this, a write that resolved under the old partition count but
        reached the parent after the count flip would be acked and then
        dropped as stale-half data. Callers on the write path must invoke
        this AFTER taking the write lock so the check is against the
        post-flip partition_version."""
        if partition_hash is None or not self.validate_partition_hash:
            return 0
        if (partition_hash & self.partition_version) != self.pidx:
            _SPLIT_FENCE_REJECTS.increment()
            return int(ErrorCode.ERR_PARENT_PARTITION_MISUSED)
        return 0

    # ---- write handlers ----------------------------------------------

    def on_put(self, key: bytes, user_data: bytes, ttl_seconds: int = 0,
               decree: Optional[int] = None,
               partition_hash: Optional[int] = None) -> int:
        gate = self._write_gate()
        if gate:
            return gate
        hc = self.hotkey_collectors["write"]
        if hc.state.value != "stopped":
            from pegasus_tpu.base.key_schema import restore_key

            hc.capture([restore_key(key)[0]])
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                return gate
            d = self._next_decree() if decree is None else decree
            expire_ts = expire_ts_from_ttl(ttl_seconds)
            self.cu.add_write(len(key) + len(user_data))
            return self.write_service.put(key, user_data, expire_ts, d)

    def on_remove(self, key: bytes, decree: Optional[int] = None,
                  partition_hash: Optional[int] = None) -> int:
        gate = self._write_gate()
        if gate:
            return gate
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                return gate
            d = self._next_decree() if decree is None else decree
            self.cu.add_write(len(key))
            return self.write_service.remove(key, d)

    def on_multi_put(self, req: MultiPutRequest,
                     decree: Optional[int] = None,
                     partition_hash: Optional[int] = None) -> int:
        gate = self._write_gate()
        if gate:
            return gate
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                return gate
            d = self._next_decree() if decree is None else decree
            self.cu.add_write(sum(len(kv.key) + len(kv.value)
                                  for kv in req.kvs) + len(req.hash_key))
            return self.write_service.multi_put(req, d)

    def on_multi_remove(self, req: MultiRemoveRequest,
                        decree: Optional[int] = None,
                        partition_hash: Optional[int] = None
                        ) -> Tuple[int, int]:
        gate = self._write_gate()
        if gate:
            return gate, 0
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                return gate, 0
            d = self._next_decree() if decree is None else decree
            self.cu.add_write(len(req.hash_key)
                              + sum(len(sk) for sk in req.sort_keys))
            return self.write_service.multi_remove(req, d)

    def on_incr(self, req: IncrRequest,
                decree: Optional[int] = None,
                partition_hash: Optional[int] = None) -> IncrResponse:
        gate = self._write_gate()
        if gate:
            resp = IncrResponse()
            resp.error = gate
            return resp
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                resp = IncrResponse()
                resp.error = gate
                return resp
            d = self._next_decree() if decree is None else decree
            self.cu.add_write(len(req.key))
            return self.write_service.incr(req, d)

    def on_check_and_set(self, req: CheckAndSetRequest,
                         decree: Optional[int] = None,
                         partition_hash: Optional[int] = None
                         ) -> CheckAndSetResponse:
        gate = self._write_gate()
        if gate:
            resp = CheckAndSetResponse()
            resp.error = gate
            return resp
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                resp = CheckAndSetResponse()
                resp.error = gate
                return resp
            d = self._next_decree() if decree is None else decree
            self.cu.add_write(len(req.hash_key) + len(req.set_sort_key)
                              + len(req.set_value))
            return self.write_service.check_and_set(req, d)

    def on_check_and_mutate(self, req: CheckAndMutateRequest,
                            decree: Optional[int] = None,
                            partition_hash: Optional[int] = None
                            ) -> CheckAndMutateResponse:
        gate = self._write_gate()
        if gate:
            resp = CheckAndMutateResponse()
            resp.error = gate
            return resp
        with self._write_lock:
            gate = self._hash_gate(partition_hash)
            if gate:
                resp = CheckAndMutateResponse()
                resp.error = gate
                return resp
            d = self._next_decree() if decree is None else decree
            self.cu.add_write(len(req.hash_key) + sum(
                len(m.sort_key) + len(m.value) for m in req.mutate_list))
            return self.write_service.check_and_mutate(req, d)

    # ---- point reads --------------------------------------------------

    def on_get(self, key: bytes,
               partition_hash: Optional[int] = None) -> Tuple[int, bytes]:
        """Parity: on_get (pegasus_server_impl.cpp:418): expired records are
        NotFound and counted as abnormal reads.

        The solo fallback populates the SAME PerfContext fields as the
        batched path (LSMStore.get / SSTable.get tick the ambient
        context), so a solo slow-log entry stays field-comparable with
        a batched one — the observe_simple fallback attaches it."""
        from pegasus_tpu.utils import perf_context as perf

        hc = self.hotkey_collectors["read"]
        if hc.state.value != "stopped":
            from pegasus_tpu.base.key_schema import restore_key

            hc.capture([restore_key(key)[0]])
        gate = self._read_gate() or self._hash_gate(partition_hash)
        if gate:
            return gate, b""
        pc = perf.current()
        if pc is None:
            pc = perf.start("point_get")
        t0 = time.perf_counter()
        with perf.activate(pc):
            now = epoch_now()
            hit = self.engine.get(key)
            status = int(StorageStatus.OK)
            data = b""
            if hit is None:
                status = int(StorageStatus.NOT_FOUND)
            else:
                value, ets = hit
                if check_if_ts_expired(now, ets):
                    self._abnormal_reads.increment()
                    if pc is not None:
                        pc.expired_rows += 1
                    status = int(StorageStatus.NOT_FOUND)
                else:
                    data = extract_user_data(self.data_version, value)
                    self.cu.add_read(len(key) + len(data))
            if pc is not None:
                pc.ops += 1
                pc.keys_resolved += 1
                pc.rows_evaluated += 1
                pc.placement = pc.placement or "native"
                if status == int(StorageStatus.OK):
                    pc.rows_survived += 1
                    pc.bytes_returned += len(key) + len(data)
                from pegasus_tpu.utils.tracing import current_span

                sp = current_span()
                if sp is not None:
                    # the solo op's cost vector rides its dispatch
                    # span, same as the batched paths — `shell
                    # explain --from-trace` reads both shapes
                    perf.merge_span_perf(sp.tags, pc)
            self.workload.note_point(1, 1, [len(data)] if data else ())
            self.slow_log.observe_simple(
                f"point_get.{self.app_id}.{self.pidx}",
                (time.perf_counter() - t0) * 1000.0)
        return status, data

    def on_ttl(self, key: bytes,
               partition_hash: Optional[int] = None) -> Tuple[int, int]:
        """Returns (error, ttl_seconds); -1 = no TTL (parity on_ttl:1092)."""
        gate = self._read_gate() or self._hash_gate(partition_hash)
        if gate:
            return gate, 0
        now = epoch_now()
        hit = self.engine.get(key)
        if hit is None:
            return int(StorageStatus.NOT_FOUND), 0
        _, ets = hit
        if check_if_ts_expired(now, ets):
            self._abnormal_reads.increment()
            return int(StorageStatus.NOT_FOUND), 0
        return int(StorageStatus.OK), (ets - now) if ets > 0 else -1

    def on_batch_get(self, req: BatchGetRequest) -> BatchGetResponse:
        """Parity: on_batch_get (pegasus_server_impl.cpp:906)."""
        gate = self._read_gate()
        if gate:
            resp = BatchGetResponse()
            resp.error = gate
            return resp
        if self.validate_partition_hash:
            # per-key staleness gate: a client that grouped this batch
            # under a pre-split partition count must be told to re-resolve
            # (missing-with-OK would silently hide moved keys)
            from pegasus_tpu.base.key_schema import key_hash_parts

            for fk in req.keys:
                h = key_hash_parts(fk.hash_key, fk.sort_key)
                if (h & self.partition_version) != self.pidx:
                    resp = BatchGetResponse()
                    resp.error = int(
                        ErrorCode.ERR_PARENT_PARTITION_MISUSED)
                    return resp
        now = epoch_now()
        resp = BatchGetResponse()
        size = 0
        for fk in req.keys:
            key = generate_key(fk.hash_key, fk.sort_key)
            hit = self.engine.get(key)
            if hit is None:
                continue
            value, ets = hit
            if check_if_ts_expired(now, ets):
                self._abnormal_reads.increment()
                continue
            data = extract_user_data(self.data_version, value)
            resp.data.append(FullData(fk.hash_key, fk.sort_key, data))
            size += len(key) + len(data)
        self.cu.add_read(size)
        return resp

    # ---- batched point reads (the point-read twin of the batched scan
    # path: a flush of concurrent get / ttl / multi_get(sort_keys) /
    # batch_get requests resolves overlay hits host-side, locates base
    # keys through the per-generation point cache with ONE vectorized
    # probe per touched block, gathers every needed value with one
    # native call per block, and batches expired/CU accounting — the
    # plan/serve/finish split mirrors plan_scan_batch so the node-level
    # read coordinator can stack the gathers across partitions) --------

    POINT_CACHE_CAP = 65536
    # keys in one OP before its blocks are routed through the native
    # page gather (the co-located multi_get/batch_get shape); below it
    # a direct per-row heap slice beats the per-chunk ctypes call
    POINT_GATHER_MIN = 16

    def on_point_read_batch(self, ops) -> list:
        """Solo-node form of the batched point-read path. `ops`:
        [(op, args, partition_hash)] with op in get / ttl / multi_get
        (explicit sort keys) / batch_get; returns one result per op,
        byte-identical to the corresponding single-request handler."""
        return self.serve_get_batch(self.plan_get_batch(ops))

    def serve_get_batch(self, state) -> list:
        """Solo-form phases 2+3: gather this batch's co-located values
        (one native call per block via page.build_page) and assemble
        responses. The node-level read coordinator splits these phases
        apart to stack the gathers ACROSS partitions into one page."""
        from pegasus_tpu.server.page import build_page

        chunks = self.point_chunks(state)
        page = None
        if chunks:
            page, _size, _last = build_page(
                chunks, header_length(self.data_version))
        return self.finish_get_batch(state, page, 0)

    def plan_get_batch(self, ops, now: Optional[int] = None) -> dict:
        """Phase 1: gates + key decomposition + location.

        Per-op gates replicate the solo handlers exactly (per-request
        throttle consumption, per-key split-staleness for batch_get —
        batched through ops.predicates.host_key_hash_lo). Unique keys
        resolve once whatever the hot-key overlap: overlay first
        (memtable-before-runs, the safe order against a concurrent
        flush/compaction publish), then the per-generation point cache,
        then batched run/block bisects + vectorized block probes for
        the misses. A publish racing the plan (generation moved) makes
        the batch re-resolve every key through the per-key safe order
        instead of trusting the possibly-torn snapshot.

        A PerfContext (utils/perf_context.py) rides the flush: ambient
        while planning so the storage layer's block/sidecar hooks tick
        it, stashed in the state so finish_get_batch can complete it —
        an outer ambient context (shell explain) is reused instead."""
        from pegasus_tpu.utils import perf_context as perf

        pc = perf.current()
        if pc is None:
            pc = perf.start("point_get_batch")
        with perf.activate(pc):
            return self._plan_get_batch_inner(ops, now, pc)

    def _plan_get_batch_inner(self, ops, now, ppc) -> dict:
        from pegasus_tpu.storage.memtable import TOMBSTONE
        from pegasus_tpu.utils.latency_tracer import LatencyTracer

        t0 = time.perf_counter()
        # real stage chain for the batched point-read window (parity
        # with the write path's per-mutation tracer): slow_queries shows
        # WHERE a read stalled, and the stages double as annotations on
        # the active distributed-tracing span
        tracer = LatencyTracer(self._get_log_key)
        tracer.perf = ppc
        now = epoch_now() if now is None else now
        lsm = self.engine.lsm
        gen = lsm.generation  # read BEFORE the overlay/run snapshots
        results: list = [None] * len(ops)
        op_keys: list = [None] * len(ops)
        probes: List[Tuple[bytes, bool]] = []
        capture_hks: list = []
        wide = False  # any op wide enough for the native gather path
        hc = self.hotkey_collectors["read"]
        hc_running = hc.state.value != "stopped"
        for i, (op, args, ph) in enumerate(ops):
            if op in ("get", "ttl"):
                gate = self._read_gate() or self._hash_gate(ph)
                if gate:
                    results[i] = (gate, b"") if op == "get" else (gate, 0)
                    continue
                if op == "get" and hc_running:
                    capture_hks.append(restore_key(args)[0])
                op_keys[i] = (args,)
                probes.append((args, op == "get"))
            elif op == "multi_get":
                capture_hks.append(args.hash_key)
                # split-staleness gate per op, like the stub applies to
                # every solo wire read — a stale-routed multi_get must
                # tell the client to re-resolve, not silently miss
                gate = self._read_gate() or self._hash_gate(ph)
                if gate:
                    resp = MultiGetResponse()
                    resp.error = gate
                    results[i] = resp
                    continue
                if not args.hash_key:
                    resp = MultiGetResponse()
                    resp.error = int(StorageStatus.INVALID_ARGUMENT)
                    results[i] = resp
                    continue
                keys = tuple(generate_key(args.hash_key, sk)
                             for sk in args.sort_keys)
                op_keys[i] = keys
                want = not args.no_value
                if want and len(keys) >= self.POINT_GATHER_MIN:
                    wide = True
                probes.extend((k, want) for k in keys)
            elif op == "batch_get":
                gate = self._read_gate()
                if gate:
                    resp = BatchGetResponse()
                    resp.error = gate
                    results[i] = resp
                    continue
                if self.validate_partition_hash and args.keys:
                    # per-key staleness gate, one vectorized crc pass
                    # for the whole request (parity: on_batch_get)
                    from pegasus_tpu.ops.predicates import host_key_hash_lo

                    lo = host_key_hash_lo(
                        [fk.hash_key for fk in args.keys],
                        [fk.sort_key for fk in args.keys])
                    pv = np.uint32(self.partition_version & 0xFFFFFFFF)
                    if np.any((lo & pv) != np.uint32(self.pidx)):
                        resp = BatchGetResponse()
                        resp.error = int(
                            ErrorCode.ERR_PARENT_PARTITION_MISUSED)
                        results[i] = resp
                        continue
                keys = tuple(generate_key(fk.hash_key, fk.sort_key)
                             for fk in args.keys)
                op_keys[i] = keys
                if len(keys) >= self.POINT_GATHER_MIN:
                    wide = True
                probes.extend((k, True) for k in keys)
            else:
                # a ValueError so the RPC handler can answer
                # INVALID_PARAMETERS instead of dying unreplied
                raise ValueError(f"unknown point-read op {op!r}")
        if capture_hks:
            hc.capture(capture_hks)
        tracer.add_point("plan")

        memget = lsm.memtable.get
        l0 = lsm.l0
        runs = lsm.l1_runs
        pc = self._point_cache
        if pc is None or pc[0] is not lsm or pc[1] != gen:
            pc = self._point_cache = (lsm, gen, {})
        loc_cache = pc[2]
        gid = (self.app_id, self.pidx)
        suid = lsm.store_uid
        rc = ROW_CACHE
        rc_on = rc.enabled
        # invalidation epoch observed BEFORE any LSM read: admission
        # below hands it back, and the cache refuses the entry if a
        # write/publish invalidated this gid in between (the populate
        # race a plain write-through LRU would lose)
        rc_epoch = rc.epoch(gid) if rc_on else 0
        rc_hits = rc_misses = 0
        rc_cached = None
        if rc_on and probes:
            # ONE lock round against the node-shared cache serves the
            # whole flush (get_many); per-key acquisition would make
            # every partition's read flush contend on one lock
            ukeys = list(dict.fromkeys(k for k, _nv in probes))
            rc_cached = rc.get_many(gid, suid, gen, ukeys)
            rc_hits = len(rc_cached)
            rc_misses = len(ukeys) - rc_hits
        uniq: dict = {}
        base_pending: list = []  # missed the row cache AND the overlay
        ov_hits = 0
        for key, _nv in probes:
            if key in uniq:
                continue
            if rc_cached is not None:
                ent = rc_cached.get(key)
                if ent is not None:
                    # cached rows carry the FULL encoded value + ets, so
                    # the serve path below is byte-identical to the
                    # overlay form; hot hashkeys never enter the LSM
                    uniq[key] = ("ov", ent[0], ent[1])
                    continue
            hit = memget(key)
            if hit is not None:
                ov_hits += 1
                uniq[key] = (None if hit[0] is TOMBSTONE
                             else ("ov", hit[0], hit[1]))
                continue
            uniq[key] = None  # placeholder until base resolution
            base_pending.append(key)

        # disk-bound residue: ONE vectorized full-key hash pass feeds
        # BOTH sidecar probes — one native multi-filter bloom call for
        # filter-only tables, one native multi-index perfect-hash call
        # (`pegasus_phash_probe_multi`) for indexed tables — answering
        # the whole (key x L0-table / L1-run) candidacy AND location
        # matrix of the flush before any block is decoded. Definitive
        # "absent" cells skip the decode + bisect entirely; located
        # cells go straight to their (block, slot) row with no fence
        # bisect and no in-block search
        probe = None  # (matrix bytes, {id(table)->col}, {key->row base})
        pprobe = None  # (loc memoryview, hit-mask bytes, cols, mp, rows)
        bloom_useful = 0
        phash_useful = 0
        useful_box = [0, 0]  # [phash-pruned, phash-located]
        want_phash = phash_probe_enabled()
        if base_pending and (bloom_probe_enabled() or want_phash):
            mp, cols, pp, pcols = self._index_probes(lsm, gen,
                                                     want_phash)
            # ONE shared hash pass, and only when a probe will consume
            # it (bloom filters present with probing on, or any
            # indexed run) — a store with probing killed or no
            # structures must not pay the vectorized crc per flush
            if (mp is not None and bloom_probe_enabled()) \
                    or pp is not None:
                from pegasus_tpu.ops.predicates import bloom_key_hashes

                hashes = bloom_key_hashes(base_pending)
                key_row = {k: i for i, k in enumerate(base_pending)}
            if mp is not None and bloom_probe_enabled():
                mat = mp.probe(hashes)
                nfil = mp.n
                probe = (mat, cols,
                         {k: i * nfil for i, k in enumerate(
                             base_pending)})
            tracer.add_point("bloom")
            if pp is not None:
                pmat, pmask = pp.probe(hashes)
                pprobe = (pmat, pmask, pcols, pp, key_row)
            tracer.add_point("phash_probe")
        else:
            tracer.add_point("bloom")
            tracer.add_point("phash_probe")
        pending = base_pending
        if pending and l0:
            pending, bloom_useful = self._probe_l0(
                l0, pending, probe, uniq, pprobe, useful_box)
        if pending:
            still = []
            for key in pending:
                ent = loc_cache.get(key, _POINT_MISS)
                if ent is not _POINT_MISS:
                    uniq[key] = ent
                else:
                    still.append(key)
            pending = still
        if pending:
            bloom_useful += self._locate_points(runs, pending, uniq,
                                                probe, pprobe,
                                                useful_box)
        phash_useful = useful_box[0]
        if lsm.generation != gen:
            # a compaction/flush published mid-plan: the overlay misses
            # above may have raced the cut-over (key consumed from the
            # overlay before the run snapshot saw its new home) —
            # re-resolve every key through the per-key safe order and
            # cache nothing (neither locations nor rows)
            for key in list(uniq):
                hit = lsm.get(key)
                uniq[key] = (None if hit is None
                             else ("ov", hit[0], hit[1]))
        else:
            if pending and self._point_cache is pc:
                for key in pending:
                    loc_cache[key] = uniq[key]
                while len(loc_cache) > self.POINT_CACHE_CAP:
                    loc_cache.pop(next(iter(loc_cache)))
            if rc_on and base_pending:
                self._maybe_admit_rows(rc, gid, suid, gen, rc_epoch,
                                       base_pending, uniq, hc)
        if bloom_useful:
            self._bloom_useful.increment(bloom_useful)
            _STORAGE_BLOOM_USEFUL.increment(bloom_useful)
        if phash_useful:
            from pegasus_tpu.storage.phash import PHASH_USEFUL

            self._phash_useful.increment(phash_useful)
            PHASH_USEFUL.increment(phash_useful)
        if useful_box[1]:
            from pegasus_tpu.storage.phash import PHASH_HIT

            PHASH_HIT.increment(useful_box[1])
        if rc_hits:
            self._row_cache_hits.increment(rc_hits)
        if rc_misses:
            self._row_cache_misses.increment(rc_misses)
        if ppc is not None:
            # the flush's cost vector, batched like the counters it
            # mirrors: ONE attribute pass per plan, never per key.
            # (blocks_decoded / block_cache_hit / bytes ticked ambient
            # by the storage layer during the probes above.)
            ppc.ops += len(ops)
            ppc.keys_resolved += len(uniq)
            ppc.overlay_hits += ov_hits
            ppc.runs_considered += len(l0) + len(runs)
            ppc.bloom_pruned += bloom_useful
            ppc.phash_pruned += phash_useful
            ppc.phash_located += useful_box[1]
            ppc.row_cache_hit += rc_hits
            ppc.row_cache_miss += rc_misses
            # point predicates are the "probe" workload class: host
            # native kernels, never a device round-trip
            ppc.placement = ppc.placement or "native"
        tracer.add_point("block_probe")
        return {"ops": ops, "results": results, "op_keys": op_keys,
                "uniq": uniq, "now": now, "t0": t0, "wide": wide,
                "tracer": tracer, "perf": ppc}

    def _index_probes(self, lsm, gen: int, want_phash: bool):
        """The run set's sidecar structures prepared for the one-call
        batched probes: (bloom MultiProbe, {id(table) -> filter col},
        PHashMultiProbe, {id(table) -> index col}). When phash probing
        is ON, indexed tables are EXCLUDED from the bloom probe — the
        perfect hash already answers candidacy (definitive absent) and
        location in one gather, so probing both structures would just
        double the per-pair work ("retiring the bloom+bisect pair" at
        probe time). Pure over the immutable run set (+ the phash
        flag) — rebuilt once per store generation, so the plan hot
        path pays one identity compare; the rebuild also refreshes the
        per-table resident-index-memory gauges."""
        c = self._index_probe_cache
        if c is not None and c[0] is lsm and c[1] == gen \
                and c[2] == want_phash:
            return c[3], c[4], c[5], c[6]
        from pegasus_tpu.storage.bloom import MultiProbe
        from pegasus_tpu.storage.phash import PHashMultiProbe

        filters = []
        cols: dict = {}
        indexes = []
        pcols: dict = {}
        bloom_bytes = phash_bytes = 0
        for t in list(lsm.l0) + list(lsm.l1_runs):
            if t.bloom is not None:
                bloom_bytes += t.bloom.bits.nbytes
            if t.phash is not None:
                phash_bytes += t.phash.mem_bytes()
            if want_phash and t.phash is not None:
                pcols[id(t)] = len(indexes)
                indexes.append(t.phash)
            elif t.bloom is not None:
                cols[id(t)] = len(filters)
                filters.append(t.bloom)
        mp = MultiProbe(filters) if filters else None
        pp = PHashMultiProbe(indexes) if indexes else None
        self._index_bloom_bytes.set(bloom_bytes)
        self._index_phash_bytes.set(phash_bytes)
        self._index_probe_cache = (lsm, gen, want_phash, mp, cols, pp,
                                   pcols)
        return mp, cols, pp, pcols

    def _probe_l0(self, l0, keys: list, probe, uniq: dict,
                  pprobe=None, useful_box=None) -> Tuple[list, int]:
        """Resolve `keys` through the L0 overlay newest-first (first
        table hit wins, the solo-get order). `probe` is the flush's
        precomputed bloom answer (matrix bytes, {id(table) -> column},
        {key -> row base}): a 0 cell is a definitive absent — no block
        is touched. `pprobe` is the perfect-hash LOCATION answer (u32
        loc memoryview, hit-mask bytes, {id(table) -> index column},
        multiprobe, {key -> row}): a 0 mask cell is definitive with
        zero block touches, and a hit cell's loc reads its (block,
        slot) row directly — one row compare (against a fingerprint
        collision) replaces the whole table bisect. Filterless,
        index-less tables (pre-filter files) gate on their
        first/last-key fences instead. Returns (unresolved keys,
        bloom-pruned count); phash-pruned probes accumulate into
        `useful_box[0]`."""
        useful = 0
        p_useful = 0
        p_hits = 0
        if probe is not None:
            mat, cols, key_row = probe
        else:
            mat = cols = key_row = None
        if pprobe is not None:
            pmat, pmask, pcols, pp, pkey_row = pprobe
            npt = pp.n
        else:
            pmat = pmask = pcols = pp = pkey_row = None
            npt = 0
        # (table, filter column | None, index column | None, index
        # geometry) resolved once per flush — id()+dict (and per-hit
        # attribute walks) per (key, table) pair was measurable at
        # depth 16
        pairs = [(t, cols.get(id(t)) if cols is not None else None,
                  pcols.get(id(t)) if pcols is not None else None,
                  t.phash.slot_bits if t.phash is not None else 0)
                 for t in l0]
        out_keys = []
        for k in keys:
            row = key_row[k] if key_row is not None else 0
            prow = pkey_row[k] * npt if pkey_row is not None else 0
            resolved = False
            for table, col, pcol, sb in pairs:
                if pcol is not None:
                    cell = prow + pcol
                    if not pmask[cell]:
                        p_useful += 1
                        continue
                    loc = pmat[cell]
                    bi = loc >> sb
                    slot = loc & ((1 << sb) - 1)
                    if bi >= len(table.blocks) \
                            or slot >= table.blocks[bi].count:
                        h = table.get(k)  # corrupt loc: bisect path
                    else:
                        blk = table.read_block(bi)
                        if blk.key_at(slot) != k:
                            p_useful += 1  # fp collision: absent here
                            continue
                        p_hits += 1
                        h = ((None, 0) if blk.is_tombstone(slot)
                             else (blk.value_at(slot),
                                   int(blk.expire_ts[slot])))
                elif col is not None:
                    if not mat[row + col]:
                        useful += 1
                        continue
                    h = table.get(k)
                else:
                    fk = table.first_key
                    if fk is None or k < fk or k > table.last_key:
                        continue
                    h = table.get(k)
                if h is not None:
                    uniq[k] = (None if h[0] is None
                               else ("ov", h[0], h[1]))
                    resolved = True
                    break
            if not resolved:
                out_keys.append(k)
        if useful_box is not None:
            useful_box[0] += p_useful
            useful_box[1] += p_hits
        return out_keys, useful

    def _maybe_admit_rows(self, rc, gid, suid: int, gen: int, epoch: int,
                          keys: list, uniq: dict, hc) -> None:
        """Offer this flush's base-resolved rows (L0/L1 hits — overlay
        hits are already memory-speed) to the node row cache. Admission
        is repeat-gated inside the cache; a FINISHED hotkey detection
        fast-admits its hashkey; `epoch` voids the admission if any
        write invalidated this gid since planning began. One lock
        round for the touch gate, one for the inserts — never per
        key."""
        cands = [k for k in keys if uniq.get(k)]
        if not cands:
            return  # absent / tombstone rows are never cached
        hot = hc.hot_hash_key()
        fast = ()
        if hot is not None:
            fast = {k for k in cands if restore_key(k)[0] == hot}
        granted = rc.note_and_check_many(gid, cands, fast)
        if not granted:
            return
        items = []
        for key in granted:
            ent = uniq[key]
            if ent[0] == "ov":
                value, ets = ent[1], int(ent[2])
            else:
                _t, blk, row = ent
                value = blk.value_at(row)
                ets = int(blk.expire_ts[row])
            items.append((key, value, ets))
        rc.admit_many(gid, suid, gen, items, epoch=epoch)

    def _locate_points(self, runs, keys: list, out: dict,
                       probe=None, pprobe=None, useful_box=None) -> int:
        """Batch-locate keys in the non-overlapping L1 runs: bisect each
        key to its run, then answer each candidacy from the flush's
        precomputed sidecar matrices. An INDEXED run (`pprobe`, the
        perfect-hash location matrix) answers candidacy and location
        in the same cell: ABSENT is definitive with zero block
        touches, a located cell goes straight to its (block, slot) row
        — no block-fence bisect, no searchsorted — and the row's key
        is verified in one vectorized compare per touched block
        (ops.predicates.phash_verify_rows) to reject fingerprint
        collisions. Filter-only runs keep the bloom cell + bisect +
        probe_rows path; structure-less runs bisect unconditionally.
        out[key] = ("l1", blk, row) | None (absent or tombstone — L1
        is the last level). Returns the bloom-pruned count;
        phash-pruned probes accumulate into `useful_box[0]`."""
        import bisect as _b

        from pegasus_tpu.server.page import probe_rows

        if not runs:
            for key in keys:
                out[key] = None
            return 0
        if probe is not None:
            mat, cols, key_row = probe
        else:
            mat = cols = key_row = None
        if pprobe is not None:
            pmat, pmask, pcols, pp, pkey_row = pprobe
            npt = pp.n
        else:
            pmat = pmask = pcols = pp = pkey_row = None
            npt = 0
        run_last = [r.last_key or b"" for r in runs]
        by_run: "OrderedDict[int, list]" = OrderedDict()
        for key in keys:
            ri = _b.bisect_left(run_last, key)
            if ri >= len(runs) or (runs[ri].first_key or b"") > key:
                out[key] = None
                continue
            by_run.setdefault(ri, []).append(key)
        useful = 0
        p_useful = 0
        by_block: "OrderedDict[tuple, list]" = OrderedDict()
        by_slot: "OrderedDict[tuple, list]" = OrderedDict()
        for ri, ks in by_run.items():
            run = runs[ri]
            pcol = pcols.get(id(run)) if pcols is not None else None
            if pcol is not None:
                sb = run.phash.slot_bits
                sm = (1 << sb) - 1
                nblocks = len(run.blocks)
                blocks = run.blocks
                for k in ks:
                    cell = pkey_row[k] * npt + pcol
                    if not pmask[cell]:
                        p_useful += 1
                        out[k] = None
                        continue
                    loc = pmat[cell]
                    bi = loc >> sb
                    slot = loc & sm
                    if bi >= nblocks or slot >= blocks[bi].count:
                        # corrupt loc: this key takes the bisect path
                        bj = run._block_for_key(k)
                        if bj is None:
                            out[k] = None
                        else:
                            by_block.setdefault((ri, bj),
                                                []).append(k)
                        continue
                    by_slot.setdefault((ri, bi), []).append((k, slot))
                continue
            col = cols.get(id(run)) if cols is not None else None
            if col is not None:
                kept = []
                for k in ks:
                    if mat[key_row[k] + col]:
                        kept.append(k)
                    else:
                        useful += 1
                        out[k] = None
                ks = kept
            for key in ks:
                bi = run._block_for_key(key)
                if bi is None:
                    out[key] = None
                    continue
                by_block.setdefault((ri, bi), []).append(key)
        if by_slot:
            from pegasus_tpu.ops.predicates import phash_verify_rows
        for (ri, bi), pairs in by_slot.items():
            # located rows: ONE vectorized key-verify per touched
            # block (the fingerprint-collision rejector); hits were
            # going to read this block for their values anyway
            blk = runs[ri].read_block(bi)
            rows = np.fromiter((s for _k, s in pairs), dtype=np.int64,
                               count=len(pairs))
            ok = phash_verify_rows(blk.keys, blk.key_len, rows,
                                   [k for k, _s in pairs])
            verified = 0
            for (key, slot), good in zip(pairs, ok):
                if not good:
                    p_useful += 1  # fp collision: definitively absent
                    out[key] = None
                    continue
                verified += 1
                if blk.is_tombstone(slot):
                    out[key] = None
                else:
                    out[key] = ("l1", blk, slot)
            if useful_box is not None:
                useful_box[1] += verified
        for (ri, bi), ks in by_block.items():
            blk = runs[ri].read_block(bi)
            for key, row in zip(ks, probe_rows(blk, ks)):
                row = int(row)
                if row < 0 or blk.is_tombstone(row):
                    out[key] = None
                else:
                    out[key] = ("l1", blk, row)
        if useful_box is not None:
            useful_box[0] += p_useful
        return useful

    def point_chunks(self, state) -> list:
        """Phase 2: this batch's L1 value-gather work as [(blk,
        ascending rows)] chunks for one page.build_page call (one
        native gather per block). Only alive rows some op wants the
        VALUE of are gathered; TTL-only probes read expire_ts straight
        from the block column. The node-level coordinator concatenates
        these chunks ACROSS partitions into a single page; `base` at
        finish maps this state's ordinals into it."""
        if not state["wide"]:
            # the common all-singleton flush: nothing can reach the
            # gather threshold, so skip the grouping pass entirely
            state["page_pos"] = {}
            state["chunk_rows"] = 0
            return []
        now = state["now"]
        uniq = state["uniq"]
        gmin = self.POINT_GATHER_MIN
        by_block: "OrderedDict[int, list]" = OrderedDict()
        blocks: dict = {}
        seen: set = set()
        for i, (op, args, _ph) in enumerate(state["ops"]):
            keys = state["op_keys"][i]
            # only wide ops (the co-located multi_get/batch_get shape)
            # reach the native gather: a flush of independent gets
            # scatters 1-2 rows per block, where a direct heap slice
            # beats the per-chunk ctypes call
            if (state["results"][i] is not None or keys is None
                    or len(keys) < gmin or op == "ttl"
                    or (op == "multi_get" and args.no_value)):
                continue
            for key in keys:
                if key in seen:
                    continue
                seen.add(key)
                ent = uniq.get(key)
                if not ent or ent[0] != "l1":
                    continue
                _tag, blk, row = ent
                # wide ops touch many rows per block: one per-second
                # vectorized alive mask (shared with the scan path's
                # prepare_serve cache) beats per-row scalar checks
                if not blk.alive_mask(now)[row]:
                    continue  # expired rows are never gathered
                bid = id(blk)
                blocks[bid] = blk
                by_block.setdefault(bid, []).append((row, key))
        chunks = []
        pos = 0
        page_pos: dict = {}
        for bid, entries in by_block.items():
            entries.sort()
            rows = np.fromiter((r for r, _k in entries), dtype=np.int64,
                               count=len(entries))
            for j, (_r, key) in enumerate(entries):
                page_pos[key] = pos + j
            chunks.append((blocks[bid], rows))
            pos += len(entries)
        state["page_pos"] = page_pos
        state["chunk_rows"] = pos
        return chunks

    def finish_get_batch(self, state, page=None, base: int = 0) -> list:
        """Phase 3: assemble per-op responses byte-identical to the
        solo handlers, with batched expired/CU accounting (one counter
        touch per flush). `page`/`base`: the (possibly cross-partition)
        build_page result and this state's first row in it."""
        from pegasus_tpu.utils import perf_context as perf

        with perf.activate(state.get("perf")):
            return self._finish_get_batch_inner(state, page, base)

    def _finish_get_batch_inner(self, state, page, base: int) -> list:
        ops = state["ops"]
        results = state["results"]
        op_keys = state["op_keys"]
        uniq = state["uniq"]
        now = state["now"]
        tracer = state.get("tracer")
        if tracer is not None:
            # the (possibly cross-partition) value gather ran between
            # the phases — the time since block_probe is decode/gather
            tracer.add_point("decode")
        page_pos = state.get("page_pos") or {}
        dv = self.data_version
        hdr = header_length(dv)
        expired_total = 0
        cu_total = 0
        looked = 0
        survived = 0
        bytes_out = 0
        vsizes: list = []  # bounded value-size sample (workload stats)

        def lookup(key, want_value):
            """(found, data, ets) with solo-handler TTL semantics."""
            nonlocal expired_total, looked
            looked += 1
            ent = uniq.get(key)
            if ent is None:
                return False, b"", 0
            if ent[0] == "ov":
                _t, value, ets = ent
                if check_if_ts_expired(now, ets):
                    expired_total += 1
                    return False, b"", 0
                return True, (extract_user_data(dv, value)
                              if want_value else b""), ets
            _t, blk, row = ent
            # per-second TTL mask reuse: when the SCAN path already
            # built this block's alive mask for the current second
            # (Block.alive_mask caches one per second), a point probe
            # reads one cell of it instead of re-deriving expiry
            cmp = getattr(blk, "_cmp", None)  # unset slot on cold blocks
            if cmp is not None and cmp[0] == now:
                alive = bool(cmp[1][row])
                ets = int(blk.expire_ts[row])
            else:
                ets = int(blk.expire_ts[row])
                alive = not check_if_ts_expired(now, ets)
            if not alive:
                expired_total += 1
                return False, b"", 0
            if not want_value:
                return True, b"", ets
            pos = page_pos.get(key)
            if pos is not None:
                return True, page.value_at(base + pos), ets
            # sparse block: direct header-stripped heap slice (same
            # bytes as extract_user_data over Block.value_at)
            vo = blk.value_offs
            heap = blk.value_heap
            v0 = int(vo[row]) + hdr
            v1 = int(vo[row + 1])
            data = (heap[v0:v1].tobytes()
                    if isinstance(heap, np.ndarray) else heap[v0:v1])
            return True, data, ets

        out = []
        for i, (op, args, _ph) in enumerate(ops):
            if results[i] is not None:
                out.append(results[i])
                continue
            if op == "get":
                key = op_keys[i][0]
                found, data, _ets = lookup(key, True)
                if not found:
                    out.append((int(StorageStatus.NOT_FOUND), b""))
                else:
                    survived += 1
                    bytes_out += len(key) + len(data)
                    if len(vsizes) < 8:
                        vsizes.append(len(data))
                    cu_total += cu_units(len(key) + len(data))
                    out.append((int(StorageStatus.OK), data))
            elif op == "ttl":
                found, _data, ets = lookup(op_keys[i][0], False)
                if not found:
                    out.append((int(StorageStatus.NOT_FOUND), 0))
                else:
                    survived += 1
                    out.append((int(StorageStatus.OK),
                                (ets - now) if ets > 0 else -1))
            elif op == "multi_get":
                resp = MultiGetResponse()
                want = not args.no_value
                size = 0
                for sk, key in zip(args.sort_keys, op_keys[i]):
                    found, data, _ets = lookup(key, want)
                    if not found:
                        continue
                    survived += 1
                    if len(vsizes) < 8:
                        vsizes.append(len(data))
                    resp.kvs.append(KeyValue(sk, data))
                    size += len(sk) + len(data)
                cu_total += cu_units(size)
                bytes_out += size
                resp.error = int(StorageStatus.OK)
                out.append(resp)
            else:  # batch_get
                resp = BatchGetResponse()
                size = 0
                for fk, key in zip(args.keys, op_keys[i]):
                    found, data, _ets = lookup(key, True)
                    if not found:
                        continue
                    survived += 1
                    if len(vsizes) < 8:
                        vsizes.append(len(data))
                    resp.data.append(FullData(fk.hash_key, fk.sort_key,
                                              data))
                    size += len(key) + len(data)
                cu_total += cu_units(size)
                bytes_out += size
                out.append(resp)
        if expired_total:
            self._abnormal_reads.increment(expired_total)
        self.cu.add_read_units(cu_total)
        self.workload.note_point(len(ops), len(uniq), vsizes)
        pc = state.get("perf")
        if pc is not None:
            pc.rows_evaluated += looked
            pc.rows_survived += survived
            pc.expired_rows += expired_total
            pc.bytes_returned += bytes_out
            sp = tracer.span if tracer is not None else None
            if sp is not None:
                # the cost vector rides the op's span: `shell trace`
                # (and explain --from-trace) shows counts, not just
                # durations. MERGED, not assigned — a batched carrier
                # span collects every partition's flush vector
                from pegasus_tpu.utils import perf_context as perf

                perf.merge_span_perf(sp.tags, pc)
        elapsed_ms = (time.perf_counter() - state["t0"]) * 1000.0
        self._read_latency.set(elapsed_ms)
        if tracer is not None:
            tracer.add_point("finish")
            # the full stage chain (plan/bloom/block_probe/decode/
            # finish) lands in the slow ring — WHERE the read stalled,
            # not just that it did
            self.slow_log.observe(tracer,
                                  {"ops": len(ops), "keys": len(uniq)})
        elif elapsed_ms >= self.slow_log.threshold_ms:
            self.slow_log.observe_simple(
                self._get_log_key, elapsed_ms,
                {"ops": len(ops), "keys": len(uniq)})
        return out

    # ---- ranged reads (the device-batched hot path) -------------------

    def _batched_scan(
        self,
        start_key: bytes,
        stop_key: Optional[bytes],
        now: int,
        hash_filter: FilterSpec,
        sort_filter: FilterSpec,
        validate_hash: bool,
        limiter: RangeReadLimiter,
        max_records: int,
        max_bytes: int,
        reverse: bool = False,
        with_values: bool = True,
        value_filter=None,
        pd_stats=None,
    ) -> Tuple[List[Tuple[bytes, bytes, int]], bool, Optional[bytes]]:
        """Core ranged read: iterate candidates, device-validate in batches.

        Returns (records, exhausted, resume_key) where records are
        (key, user_data, expire_ts) triples that passed every predicate,
        exhausted means the range completed, and resume_key is where a
        follow-up should continue when not exhausted.

        `value_filter`: normalized (type, pattern) pushdown value
        predicate ANDed into the keep mask; `pd_stats` accumulates its
        "pruned" count (rows key-alive but value-rejected).
        """
        sorted_runs = None if reverse else self.engine.lsm.sorted_runs()
        if sorted_runs is not None:
            return self._columnar_scan(sorted_runs, start_key, stop_key,
                                       now, hash_filter, sort_filter,
                                       validate_hash, limiter, max_records,
                                       max_bytes, with_values,
                                       value_filter, pd_stats)

        out: List[Tuple[bytes, bytes, int]] = []
        out_bytes = 0
        it = self.engine.iterate(start_key, stop_key, reverse)
        exhausted = True
        resume_key: Optional[bytes] = None
        while True:
            batch: List[Tuple[bytes, bytes, int]] = []
            for key, value, ets in it:
                batch.append((key, value, ets))
                limiter.add_count()
                if len(batch) >= PREDICATE_BATCH or not limiter.valid():
                    break
            if not batch:
                break
            keep = self._validate_batch(batch, now, hash_filter, sort_filter,
                                        validate_hash)
            stop_early = False
            for i, (key, value, ets) in enumerate(batch):
                if not keep[i]:
                    continue
                if value_filter is not None:
                    ud = extract_user_data(self.data_version, value)
                    if not host_match_filter(ud, value_filter[0],
                                             value_filter[1]):
                        if pd_stats is not None:
                            pd_stats["pruned"] = \
                                pd_stats.get("pruned", 0) + 1
                        continue
                    data = ud if with_values else b""
                else:
                    data = (extract_user_data(self.data_version, value)
                            if with_values else b"")
                out.append((key, data, ets))
                out_bytes += len(key) + len(data)
                if ((max_records > 0 and len(out) >= max_records)
                        or (max_bytes > 0 and out_bytes >= max_bytes)):
                    resume_key = _after(key) if not reverse else key
                    stop_early = True
                    break
            if stop_early:
                exhausted = False
                break
            if not limiter.valid():
                last_key = batch[-1][0]
                resume_key = _after(last_key) if not reverse else last_key
                exhausted = False
                break
            if len(batch) < PREDICATE_BATCH:
                break
        return out, exhausted, resume_key

    def _columnar_scan(
        self,
        sorted_runs,
        start_key: bytes,
        stop_key: Optional[bytes],
        now: int,
        hash_filter: FilterSpec,
        sort_filter: FilterSpec,
        validate_hash: bool,
        limiter: RangeReadLimiter,
        max_records: int,
        max_bytes: int,
        with_values: bool,
        value_filter=None,
        pd_stats=None,
    ) -> Tuple[List[Tuple[bytes, bytes, int]], bool, Optional[bytes]]:
        """Fast path: the store is a sequence of non-overlapping sorted L1
        runs with no overlay, so SST blocks stream columnar through the
        CACHED static device predicate — the TPU-first replacement for
        the reference's per-record iterator loop. The static mask
        (filters + partition-hash, `now`-independent) is evaluated on
        device once per block lifetime; this scan combines it with TTL
        expiry host-side (one vectorized AND over the expire_ts column)
        and materializes only survivors per record. Runs are visited in
        key order, skipping runs outside the range; boundary trimming
        ([start_key, stop_key)) is a host slice of the mask (at most 2
        partial blocks per scan).
        """
        from pegasus_tpu.ops.predicates import host_alive_mask

        out: List[Tuple[bytes, bytes, int]] = []
        out_bytes = 0
        exhausted = True
        resume_key: Optional[bytes] = None
        filter_key = hash_filter.key + sort_filter.key
        with self._mask_lock:
            self._register_flavor(validate_hash, filter_key,
                                  time.monotonic())

        def ranged_blocks():
            for run in sorted_runs:
                if stop_key is not None and (run.first_key or b"") >= stop_key:
                    continue
                if start_key and (run.last_key or b"") < start_key:
                    continue
                for bm_blk in run.iter_blocks(start_key, stop_key or None):
                    yield run, bm_blk

        # look-ahead windows: gather up to LOOKAHEAD blocks, evaluate
        # every window miss in ONE stacked device wave (a cold cache
        # after compaction would otherwise pay one serialized round-trip
        # PER block), then assemble host-side. Fetching one window past
        # the stop point costs unused masks, never correctness.
        LOOKAHEAD = 8
        blocks_iter = ranged_blocks()
        done_iter = False
        stopped = False
        while not stopped:
            window = []
            while not done_iter and len(window) < LOOKAHEAD:
                nxt = next(blocks_iter, None)
                if nxt is None:
                    done_iter = True
                    break
                run, (bm, blk) = nxt
                n = blk.count
                # boundary blocks: trim rows outside the range (bisect on
                # the block's sorted keys — O(log n) materializations)
                lo, hi = 0, n
                if start_key and bm.first_key < start_key:
                    lo = _lower_bound(blk, start_key)
                if stop_key is not None and bm.last_key >= stop_key:
                    hi = _lower_bound(blk, stop_key)
                # only in-range rows count against the iteration budget
                # (out-of-range rows in a boundary block were never
                # "examined")
                limiter.add_count(hi - lo)
                window.append(((run.path, bm.offset), blk, lo, hi))
            if not window:
                break
            keeps = self._static_keep_window(window, validate_hash,
                                             hash_filter, sort_filter,
                                             filter_key)
            for (ckey, blk, lo, hi), static_keep in zip(window, keeps):
                n = blk.count
                ets = blk.expire_ts
                alive = host_alive_mask(ets, now)
                expired = int(np.count_nonzero(~alive[lo:hi]))
                if expired:
                    self._abnormal_reads.increment(expired)
                keep = static_keep[:n] & alive
                if value_filter is not None:
                    # the pushdown value leg joins the mask algebra:
                    # cached per (block, pattern) like the static keep
                    vmask = self._value_mask(ckey, blk, value_filter)
                    before = int(np.count_nonzero(keep[lo:hi]))
                    keep = keep & vmask[:n]
                    if pd_stats is not None:
                        pd_stats["pruned"] = (
                            pd_stats.get("pruned", 0) + before
                            - int(np.count_nonzero(keep[lo:hi])))
                stop_early = False
                for i in np.flatnonzero(keep[lo:hi]):
                    idx = lo + int(i)
                    key = blk.key_at(idx)
                    data = (extract_user_data(self.data_version,
                                              blk.value_at(idx))
                            if with_values else b"")
                    out.append((key, data, int(ets[idx])))
                    out_bytes += len(key) + len(data)
                    if ((max_records > 0 and len(out) >= max_records)
                            or (max_bytes > 0 and out_bytes >= max_bytes)):
                        resume_key = _after(key)
                        stop_early = True
                        break
                if stop_early or not limiter.valid():
                    if not stop_early:
                        resume_key = _after(blk.key_at(n - 1))
                    exhausted = False
                    stopped = True
                    break
        return out, exhausted, resume_key

    def _validate_batch(self, batch: List[Tuple[bytes, bytes, int]],
                        now: int, hash_filter: FilterSpec,
                        sort_filter: FilterSpec,
                        validate_hash: bool) -> np.ndarray:
        keys = [b[0] for b in batch]
        ets = [b[2] for b in batch]
        # bucket the batch capacity to a power of two: arbitrary merge-path
        # batch sizes would otherwise each compile their own XLA program
        cap = 256
        while cap < len(batch):
            cap <<= 1
        block = build_record_block(keys, ets, capacity=cap)
        masks = scan_block_predicate(
            block, now, hash_filter=hash_filter, sort_filter=sort_filter,
            validate_hash=validate_hash, pidx=self.pidx,
            partition_version=self.partition_version)
        expired = int(np.asarray(masks.expired).sum())
        if expired:
            self._abnormal_reads.increment(expired)
        return np.asarray(masks.keep)

    def on_multi_get(self, req: MultiGetRequest) -> MultiGetResponse:
        """Parity: on_multi_get (pegasus_server_impl.cpp:496)."""
        from pegasus_tpu.utils import perf_context as perf

        self.hotkey_collectors["read"].capture([req.hash_key])
        t0 = time.perf_counter()
        pc = perf.current()
        if pc is None:
            pc = perf.start("multi_get")
        try:
            with perf.activate(pc):
                resp = self._on_multi_get(req)
                if pc is not None:
                    pc.ops += 1
                    pc.rows_survived += len(resp.kvs)
                    pc.placement = pc.placement or "native"
                    from pegasus_tpu.utils.tracing import current_span

                    sp = current_span()
                    if sp is not None:
                        perf.merge_span_perf(sp.tags, pc)
                return resp
        finally:
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            self._read_latency.set(elapsed_ms)
            with perf.activate(pc):
                self.slow_log.observe_simple(
                    f"multi_get.{self.app_id}.{self.pidx}", elapsed_ms,
                    {"hash_key": req.hash_key.decode(errors="replace")})

    def _on_multi_get(self, req: MultiGetRequest) -> MultiGetResponse:
        gate = self._read_gate()
        if gate:
            resp = MultiGetResponse()
            resp.error = gate
            return resp
        now = epoch_now()
        resp = MultiGetResponse()
        if not req.hash_key:
            resp.error = int(StorageStatus.INVALID_ARGUMENT)
            return resp

        # explicit sort keys -> point lookups (reference uses DB::MultiGet)
        if req.sort_keys:
            size = 0
            for sk in req.sort_keys:
                key = generate_key(req.hash_key, sk)
                hit = self.engine.get(key)
                if hit is None:
                    continue
                value, ets = hit
                if check_if_ts_expired(now, ets):
                    self._abnormal_reads.increment()
                    continue
                data = (b"" if req.no_value
                        else extract_user_data(self.data_version, value))
                resp.kvs.append(KeyValue(sk, data))
                size += len(sk) + len(data)
            self.cu.add_read(size)
            self.workload.note_point(1, len(req.sort_keys),
                                     [len(kv.value)
                                      for kv in resp.kvs[:8]])
            resp.error = int(StorageStatus.OK)
            return resp

        # range mode over [start_sortkey, stop_sortkey]
        start_key = generate_key(req.hash_key, req.start_sortkey)
        if not req.start_inclusive:
            start_key = _after(start_key)
        if req.stop_sortkey:
            stop_key = generate_key(req.hash_key, req.stop_sortkey)
            if req.stop_inclusive:
                stop_key = _after(stop_key)
        else:
            stop_key = generate_next_bytes(req.hash_key)
        if stop_key and start_key >= stop_key:
            resp.error = int(StorageStatus.OK)
            return resp

        limiter = RangeReadLimiter(clock_ns=self.clock_ns)
        records, exhausted, resume_key = self._batched_scan(
            start_key, stop_key or None, now,
            FilterSpec.none(),
            FilterSpec.make(req.sort_key_filter_type,
                            req.sort_key_filter_pattern),
            validate_hash=False, limiter=limiter,
            max_records=req.max_kv_count, max_bytes=req.max_kv_size,
            reverse=req.reverse, with_values=not req.no_value)
        size = 0
        for key, data, ets in records:
            _, sk = restore_key(key)
            resp.kvs.append(KeyValue(sk, data))
            size += len(sk) + len(data)
        if req.reverse:
            resp.kvs.reverse()  # response is ascending by sort key
        self.cu.add_read(size)
        # range-mode multi_get is the dominant ranged-read shape: its
        # examined-vs-returned ratio feeds the table's selectivity
        # profile like every other scan
        self.workload.note_scan(1, limiter.iteration_count,
                                len(records))
        resp.error = (int(StorageStatus.OK) if exhausted
                      else int(StorageStatus.INCOMPLETE))
        if (not exhausted and not req.reverse
                and resume_key is not None):
            # even a fully-filtered page (e.g. a long expired run) stays
            # resumable: the follow-up starts at this sort key
            resp.resume_sort_key = restore_key(resume_key)[1]
        return resp

    def on_sortkey_count(self, hash_key: bytes) -> Tuple[int, int]:
        """Parity: on_sortkey_count (pegasus_server_impl.cpp:1018)."""
        gate = self._read_gate()
        if gate:
            return gate, 0
        now = epoch_now()
        start_key = generate_key(hash_key, b"")
        stop_key = generate_next_bytes(hash_key)
        limiter = RangeReadLimiter(clock_ns=self.clock_ns)
        records, exhausted, _ = self._batched_scan(
            start_key, stop_key or None, now, FilterSpec.none(),
            FilterSpec.none(), validate_hash=False, limiter=limiter,
            max_records=-1, max_bytes=-1, with_values=False)
        if not exhausted:
            return int(StorageStatus.INCOMPLETE), len(records)
        return int(StorageStatus.OK), len(records)

    # ---- scan pushdown (ops/pushdown.py) ------------------------------

    def _pushdown_of(self, req: GetScannerRequest):
        """The request's PushdownSpec when this server will evaluate it,
        else None: no spec, an empty spec (nothing to push down), or the
        kill switch is off — the "pre-pushdown server" case the soft
        version gate is about (the spec is IGNORED, pushdown_applied
        stays False, and the client evaluates locally)."""
        spec = getattr(req, "pushdown", None)
        if spec is None:
            return None
        if not FLAGS.get("pegasus.server", "scan_pushdown_enabled"):
            return None
        spec.check()  # ValueError -> ERR_INVALID_PARAMETERS at the stub
        if spec.value_filter is None and not spec.aggregate:
            return None
        return spec

    def _value_mask(self, ckey, blk, vf) -> np.ndarray:
        """bool[count] value-filter keep mask for one SST block, cached
        per (block, filter) — the value-side leg of the static/dynamic
        predicate split. Forcing blk.value_heap materializes a lazy
        compressed heap, which the filter needs anyway; the mask then
        outlives the decode. The kernel wave is audited against the
        placement cost model like the key-mask waves."""
        vkey = (ckey, vf)
        with self._mask_lock:
            hit = self._vmask_cache.get(vkey)
            if hit is not None:
                self._vmask_cache.move_to_end(vkey)
                return hit
        heap = blk.value_heap
        t0 = time.perf_counter()
        mask = pushdown_ops.value_filter_mask(
            heap, blk.value_offs, header_length(self.data_version),
            vf[0], vf[1])
        measured = time.perf_counter() - t0
        from pegasus_tpu.ops.placement import predict_kernel_seconds
        from pegasus_tpu.server.workload import DRIFT
        from pegasus_tpu.utils import perf_context as perf

        predicted = predict_kernel_seconds("scan_pushdown",
                                           int(np.asarray(heap).size))
        DRIFT.note("scan_pushdown", predicted, measured)
        pc = perf.current()
        if pc is not None:
            pc.predicted_kernel_ms += predicted * 1000.0
            pc.measured_kernel_ms += measured * 1000.0
            pc.placement = pc.placement or "numpy"
        with self._mask_lock:
            self._vmask_cache[vkey] = mask
            while len(self._vmask_cache) > self._vmask_cache_cap:
                self._vmask_cache.popitem(last=False)
        return mask

    # ---- scanners -----------------------------------------------------

    def on_get_scanner(self, req: GetScannerRequest) -> ScanResponse:
        """Parity: on_get_scanner (pegasus_server_impl.cpp:1151)."""
        gate = self._read_gate()
        if gate:
            resp = ScanResponse()
            resp.error = gate
            return resp
        start_key = req.start_key or b""
        if start_key and not req.start_inclusive:
            start_key = _after(start_key)
        stop_key = req.stop_key or b""
        if stop_key and req.stop_inclusive:
            stop_key = _after(stop_key)
        return self._serve_scan_batch(req, start_key, stop_key)

    def on_scan(self, context_id: int) -> ScanResponse:
        """Parity: on_scan (pegasus_server_impl.cpp:1399)."""
        gate = self._read_gate()
        if gate:
            resp = ScanResponse()
            resp.error = gate
            return resp
        ctx = self._scan_cache.take(context_id)
        if ctx is None:
            resp = ScanResponse()
            resp.error = int(StorageStatus.NOT_FOUND)
            resp.context_id = SCAN_CONTEXT_ID_NOT_EXIST
            return resp
        return self._serve_scan_batch(ctx.request, ctx.resume_key,
                                      ctx.stop_key,
                                      agg_state=ctx.agg_state)

    def on_clear_scanner(self, context_id: int) -> None:
        self._scan_cache.remove(context_id)

    def _serve_scan_batch(self, req: GetScannerRequest, start_key: bytes,
                          stop_key: bytes,
                          agg_state=None) -> ScanResponse:
        from pegasus_tpu.utils import perf_context as perf
        from pegasus_tpu.utils.latency_tracer import LatencyTracer

        t0 = time.perf_counter()
        # stage chain for scan pages (plan -> block scan/decode ->
        # assemble): a slow page shows WHERE it stalled, and the stages
        # annotate the active distributed-tracing span
        tracer = LatencyTracer(f"scan.{self.app_id}.{self.pidx}")
        pc = perf.current()
        if pc is None:
            pc = perf.start("scan_page")
        tracer.perf = pc
        try:
            with perf.activate(pc):
                return self._serve_scan_batch_inner(req, start_key,
                                                    stop_key, tracer,
                                                    agg_state)
        finally:
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            self._read_latency.set(elapsed_ms)
            sp = tracer.span
            if pc is not None and sp is not None:
                perf.merge_span_perf(sp.tags, pc)
            self.slow_log.observe(tracer)

    def _serve_scan_batch_inner(self, req: GetScannerRequest,
                                start_key: bytes,
                                stop_key: bytes,
                                tracer=None,
                                agg_state=None) -> ScanResponse:
        pd = self._pushdown_of(req)
        if pd is not None and pd.aggregate:
            return self._pushdown_aggregate_page(req, pd, start_key,
                                                 stop_key, tracer,
                                                 agg_state)
        vf = pd.value_filter if pd is not None else None
        pd_stats: dict = {}
        now = epoch_now()
        resp = ScanResponse()
        limiter = RangeReadLimiter(clock_ns=self.clock_ns)
        batch_size = min(req.batch_size if req.batch_size > 0 else 1000,
                         SCAN_BATCH_CAP)
        if req.only_return_count:
            batch_size = -1  # count the whole (limiter-bounded) range
        hash_filter = FilterSpec.make(req.hash_key_filter_type,
                                      req.hash_key_filter_pattern)
        sort_filter = FilterSpec.make(req.sort_key_filter_type,
                                      req.sort_key_filter_pattern)
        if tracer is not None:
            tracer.add_point("plan")
        records, exhausted, resume_key = self._batched_scan(
            start_key, stop_key or None, now,
            hash_filter, sort_filter,
            validate_hash=(req.validate_partition_hash
                           and self.validate_partition_hash),
            limiter=limiter, max_records=batch_size,
            max_bytes=-1 if req.only_return_count else SCAN_BYTES_CAP,
            with_values=not req.no_value and not req.only_return_count,
            value_filter=vf, pd_stats=pd_stats)
        if tracer is not None:
            tracer.add_point("block_scan")
            if pd is not None:
                tracer.add_point("pushdown")
        if req.only_return_count:
            resp.kv_count = len(records)
        else:
            size = 0
            for key, data, ets in records:
                kv = KeyValue(key, data)
                if req.return_expire_ts:
                    kv.expire_ts_seconds = ets
                resp.kvs.append(kv)
                size += len(key) + len(data)
            self.cu.add_read(size)
        if tracer is not None:
            tracer.add_point("assemble")
        pruned = pd_stats.get("pruned", 0)
        pc = tracer.perf if tracer is not None else None
        if pc is not None:
            pc.ops += 1
            pc.rows_evaluated += limiter.iteration_count
            pc.rows_survived += len(records)
            pc.keys_resolved += len(records)
            pc.bytes_returned += sum(len(k) + len(d)
                                     for k, d, _e in records)
            pc.pushdown_rows_pruned += pruned
        self.workload.note_scan(1, limiter.iteration_count,
                                len(records))
        if pd is not None:
            self.workload.note_pushdown(1, pruned, 0)
            resp.pushdown_applied = True
        resp.error = int(StorageStatus.OK)
        if exhausted or req.one_page:
            # one_page: the client promised not to page further — no
            # context to cache, no clear_scanner round-trip later
            resp.context_id = SCAN_CONTEXT_ID_COMPLETED
        else:
            resp.context_id = self._scan_cache.put(ScanContext(
                request=req, resume_key=resume_key or start_key,
                stop_key=stop_key))
        return resp

    def _pushdown_aggregate_page(self, req: GetScannerRequest, pd,
                                 start_key: bytes, stop_key: bytes,
                                 tracer=None,
                                 agg_state=None) -> ScanResponse:
        """Aggregate-mode pushdown: fold one (limiter-bounded) slice of
        the range into the partition's PARTIAL aggregate instead of
        returning rows. The partial rides server-side in the scan
        context across pages and ships ONLY on the final page — one agg
        payload per partition on the wire, and a lost context (expiry,
        split bounce) loses the partial WITH the pages it counted, so
        the client's restart-from-original-start never double counts.

        Columnar arm: the same cached static masks + host TTL AND as
        _columnar_scan, but survivors feed AggState.fold_columnar — a
        count folds off the mask alone (a lazy compressed value heap
        stays undecoded unless the value filter already forced it);
        sum/top_k/sample gather straight from the raw value heap."""
        now = epoch_now()
        resp = ScanResponse()
        limiter = RangeReadLimiter(clock_ns=self.clock_ns)
        vf = pd.value_filter
        pd_stats: dict = {}
        state = (agg_state if agg_state is not None
                 else pushdown_ops.AggState(pd))
        folded0 = state.count
        hash_filter = FilterSpec.make(req.hash_key_filter_type,
                                      req.hash_key_filter_pattern)
        sort_filter = FilterSpec.make(req.sort_key_filter_type,
                                      req.sort_key_filter_pattern)
        validate = bool(req.validate_partition_hash
                        and self.validate_partition_hash)
        hdr = header_length(self.data_version)
        stop = stop_key or None
        if tracer is not None:
            tracer.add_point("plan")
        exhausted = True
        resume_key: Optional[bytes] = None
        sorted_runs = self.engine.lsm.sorted_runs()
        if sorted_runs is not None:
            from pegasus_tpu.ops.predicates import host_alive_mask

            filter_key = hash_filter.key + sort_filter.key
            with self._mask_lock:
                self._register_flavor(validate, filter_key,
                                      time.monotonic())

            # resident mesh arm: a fresh whole-range aggregate on an
            # attached table folds off the table-wide SPMD dispatch —
            # count/sum directly from the psum-shaped per-partition
            # counts/lanes, top_k/sample from the all-gathered mask via
            # the same AggState fold. Any decline (paging limiter, L0
            # overlay, stale slab, watchdog, cost model) falls through
            # to the host arm unchanged.
            if agg_state is None and not start_key and stop is None:
                from pegasus_tpu.parallel.mesh_resident import MESH_SERVING

                mesh = (MESH_SERVING.try_aggregate(
                            self, req, pd, validate, filter_key, now)
                        if MESH_SERVING.enabled else None)
                if mesh is not None:
                    state = mesh["agg_state"]
                    if mesh["expired"]:
                        self._abnormal_reads.increment(mesh["expired"])
                    if tracer is not None:
                        tracer.add_point("block_scan")
                        tracer.add_point("pushdown")
                    folded = mesh["folded"]
                    pruned = mesh["pruned"]
                    pc = tracer.perf if tracer is not None else None
                    if pc is not None:
                        pc.ops += 1
                        pc.rows_evaluated += mesh["rows_evaluated"]
                        pc.rows_survived += folded
                        pc.keys_resolved += folded
                        pc.rows_aggregated += folded
                        pc.pushdown_rows_pruned += pruned
                        pc.placement = "mesh"
                        pc.mesh_partitions += mesh["partitions"]
                        pc.mesh_wave_ms += mesh["wave_ms"]
                        pc.predicted_kernel_ms += mesh["predicted_ms"]
                        pc.measured_kernel_ms += mesh["measured_ms"]
                    self.workload.note_scan(1, mesh["rows_evaluated"],
                                            folded)
                    self.workload.note_pushdown(1, pruned, folded)
                    resp.pushdown_applied = True
                    resp.error = int(StorageStatus.OK)
                    resp.context_id = SCAN_CONTEXT_ID_COMPLETED
                    resp.agg = state.to_wire()
                    if tracer is not None:
                        tracer.add_point("assemble")
                    return resp

            def ranged_blocks():
                for run in sorted_runs:
                    if stop is not None and (run.first_key or b"") >= stop:
                        continue
                    if start_key and (run.last_key or b"") < start_key:
                        continue
                    for bm_blk in run.iter_blocks(start_key, stop):
                        yield run, bm_blk

            LOOKAHEAD = 8
            blocks_iter = ranged_blocks()
            done_iter = False
            stopped = False
            while not stopped:
                window = []
                while not done_iter and len(window) < LOOKAHEAD:
                    nxt = next(blocks_iter, None)
                    if nxt is None:
                        done_iter = True
                        break
                    run, (bm, blk) = nxt
                    lo, hi = 0, blk.count
                    if start_key and bm.first_key < start_key:
                        lo = _lower_bound(blk, start_key)
                    if stop is not None and bm.last_key >= stop:
                        hi = _lower_bound(blk, stop)
                    limiter.add_count(hi - lo)
                    window.append(((run.path, bm.offset), blk, lo, hi))
                if not window:
                    break
                keeps = self._static_keep_window(window, validate,
                                                 hash_filter, sort_filter,
                                                 filter_key)
                for (ckey, blk, lo, hi), static_keep in zip(window,
                                                            keeps):
                    n = blk.count
                    alive = host_alive_mask(blk.expire_ts, now)
                    expired = int(np.count_nonzero(~alive[lo:hi]))
                    if expired:
                        self._abnormal_reads.increment(expired)
                    keep = static_keep[:n] & alive
                    if vf is not None:
                        vmask = self._value_mask(ckey, blk, vf)
                        before = int(np.count_nonzero(keep[lo:hi]))
                        keep = keep & vmask[:n]
                        pd_stats["pruned"] = (
                            pd_stats.get("pruned", 0) + before
                            - int(np.count_nonzero(keep[lo:hi])))
                    sel = np.flatnonzero(keep[lo:hi]) + lo
                    if sel.size:
                        if pd.aggregate == "count":
                            state.fold_columnar(sel)
                        else:
                            state.fold_columnar(
                                sel, heap=blk.value_heap,
                                value_offs=blk.value_offs, hdr=hdr,
                                key_at=blk.key_at)
                    if not limiter.valid():
                        resume_key = _after(blk.key_at(n - 1))
                        exhausted = False
                        stopped = True
                        break
        else:
            # overlay / reverse-free generic arm: the iterator merge
            # already applies newest-wins shadowing and tombstones, so
            # scalar folds over its survivors are exact
            records, exhausted, resume_key = self._batched_scan(
                start_key, stop, now, hash_filter, sort_filter,
                validate, limiter, max_records=-1, max_bytes=-1,
                with_values=(pd.aggregate != "count"),
                value_filter=vf, pd_stats=pd_stats)
            for key, data, _ets in records:
                state.fold_row(key, data)
        if tracer is not None:
            tracer.add_point("block_scan")
            tracer.add_point("pushdown")
        folded = state.count - folded0
        pruned = pd_stats.get("pruned", 0)
        pc = tracer.perf if tracer is not None else None
        if pc is not None:
            pc.ops += 1
            pc.rows_evaluated += limiter.iteration_count
            pc.rows_survived += folded
            pc.keys_resolved += folded
            pc.rows_aggregated += folded
            pc.pushdown_rows_pruned += pruned
            pc.placement = pc.placement or "numpy"
        self.workload.note_scan(1, limiter.iteration_count, folded)
        self.workload.note_pushdown(1, pruned, folded)
        resp.pushdown_applied = True
        resp.error = int(StorageStatus.OK)
        if exhausted or req.one_page:
            resp.context_id = SCAN_CONTEXT_ID_COMPLETED
            resp.agg = state.to_wire()
        else:
            # NOT final: no agg on the wire; the partial continues
            # server-side under a fresh context id
            resp.context_id = self._scan_cache.put(ScanContext(
                request=req, resume_key=resume_key or start_key,
                stop_key=stop_key, agg_state=state))
        if tracer is not None:
            tracer.add_point("assemble")
        return resp

    # ---- batched multi-scan (the request-batching dispatch unit of
    # SURVEY §2.6: MANY concurrent scans share ONE device predicate pass;
    # zipfian traffic re-reads the same hot blocks, which are evaluated
    # once per batch instead of once per scan) ---------------------------

    def on_get_scanner_batch(self, reqs: List[GetScannerRequest]
                             ) -> List[ScanResponse]:
        """Serve a batch of scans with per-block dedup.

        Fast path requires the columnar store (light write overlays
        merge host-side) and plain range scans (no filters/count-only) —
        the YCSB-E shape; anything else falls back to per-request
        serving. Each UNIQUE block touched by the batch gets one device
        predicate evaluation (cached device uploads); per-request
        boundary trimming happens on the host against the materialized
        keep mask, so shared blocks need no per-scan device work at
        all. plan/finish split so a NODE-level coordinator can stack
        blocks across partitions into one dispatch."""
        state = self.plan_scan_batch(reqs)
        if state is None:
            return [self.on_get_scanner(r) for r in reqs]
        if "precomputed" in state:  # read gate rejected the whole batch
            return state["precomputed"]
        keep_masks = self.eval_planned_masks(state)
        return self.finish_scan_batch(state, keep_masks)

    def plan_scan_batch(self, reqs: List[GetScannerRequest],
                        now: Optional[int] = None, flavor=None):
        """Phase 1: qualify + block planning. None = caller must serve
        per-request. `flavor` = the (validate, filter_key) the caller
        already grouped by (scan_coordinator) — passing it skips the
        per-request re-derivation. The flush's PerfContext is created
        (or adopted from an ambient one — shell explain) here and rides
        the state through the mask-eval and finish phases."""
        from pegasus_tpu.utils import perf_context as perf

        pc = perf.current()
        if pc is None:
            pc = perf.start("scan_batch")
        with perf.activate(pc):
            return self._plan_scan_batch_inner(reqs, now, flavor, pc)

    def _plan_scan_batch_inner(self, reqs: List[GetScannerRequest],
                               now, flavor, ppc):
        from pegasus_tpu.utils.latency_tracer import LatencyTracer

        t0 = time.perf_counter()
        tracer = LatencyTracer(self._scan_log_key)
        tracer.perf = ppc
        gate = self._read_gate()
        if gate:
            out = []
            for _r in reqs:
                resp = ScanResponse()
                resp.error = gate
                out.append(resp)
            return {"precomputed": out, "t0": t0}
        lsm = self.engine.lsm
        # generation is read BEFORE the run set and re-checked after the
        # plans are built: an env-triggered compaction publishes off the
        # node lock (l1_runs swap -> generation bump -> overlay clear),
        # and a batch planned across that publish could pair the OLD
        # runs with the NEW (empty) overlay — silently dropping the
        # consumed overlay rows — or cache old-run plans under the new
        # generation. Reading gen first puts any such plans under the
        # OLD generation (correctly invalidated), and the final check
        # sends a torn batch to the per-request path, which reads
        # memtable-before-runs (the safe order against this publish).
        gen = lsm.generation
        runs = lsm.l1_runs
        # a light write overlay (memtable + small L0s) must NOT evict the
        # whole partition from the device path: its rows merge host-side
        # on top of the device-filtered base (the YCSB-E 5%-insert shape
        # leaves a handful of overlay rows per partition)
        overlay_count = len(lsm.memtable) + sum(t.total_count
                                                for t in lsm.l0)
        # the shared-mask trick needs every request to share the mask
        # inputs: ONE effective validate flag and ONE filter spec across
        # the batch (no count-only mode). A batch-wide SHARED filter —
        # the geo covering-cell / prefix-scan shape — rides the same
        # cached-mask machinery: the filter is simply part of the mask
        # key, so repeated popular filters hit like unfiltered scans.
        if flavor is not None:
            validates = {flavor[0]}
            filters = {flavor[1]}
        else:
            validates = {bool(r.validate_partition_hash
                              and self.validate_partition_hash)
                         for r in reqs}
            filters = {_normalize_filter_key(r) for r in reqs}
        known = (FT_NO_FILTER, FT_MATCH_ANYWHERE, FT_MATCH_PREFIX,
                 FT_MATCH_POSTFIX)
        # pushdown on the batched path: ONE shared value filter rides
        # the live-mask machinery (it is part of the live-cache key,
        # like the key filters are part of the mask key); aggregates
        # serve per-request (their reply shape is a partial, not a
        # page), as do mixed-filter batches
        pdl = [self._pushdown_of(r) for r in reqs]
        vfs = {pd.value_filter if pd is not None else None for pd in pdl}
        simple = (runs and overlay_count <= self.OVERLAY_MERGE_LIMIT
                  and len(validates) == 1 and len(filters) == 1
                  and all(f[0] in known and f[2] in known
                          for f in filters)
                  and not any(r.only_return_count for r in reqs)
                  and len(vfs) == 1
                  and not any(pd is not None and pd.aggregate
                              for pd in pdl))
        if not simple:
            return None
        now = epoch_now() if now is None else now
        validate = validates.pop()
        filter_key = filters.pop()
        vf = vfs.pop()
        overlay = self._overlay_snapshot(now, validate, filter_key,
                                         value_filter=vf) \
            if overlay_count else ([], {})
        # 1 — per request: the block list + boundary bounds, capped a bit
        # beyond batch_size so expiry/hash drops don't starve the page.
        # Plans are CACHED per (range, want-bucket, store generation):
        # zipfian traffic re-issues the same popular scans constantly,
        # and a plan is pure over the immutable run set (the generation
        # key invalidates on flush/ingest/compaction). The want bucket
        # (pow2) keeps variants bounded; an over-budgeted cached plan
        # only means a further frontier, never a wrong page.
        req_plans = []
        unique: "OrderedDict[tuple, tuple]" = OrderedDict()
        pc = self._plan_cache
        if pc is None or pc[0] is not lsm or pc[1] != gen:
            pc = self._plan_cache = (lsm, gen, {})
        cache = pc[2]
        for req in reqs:
            start_key = req.start_key or b""
            if start_key and not req.start_inclusive:
                start_key = _after(start_key)
            stop_key = req.stop_key or b""
            if stop_key and req.stop_inclusive:
                stop_key = _after(stop_key)
            want = min(req.batch_size if req.batch_size > 0 else 1000,
                       SCAN_BATCH_CAP)
            wb = 1 << (want - 1).bit_length() if want > 1 else 1
            pkey = (start_key, stop_key, wb)
            hit = cache.get(pkey)
            if hit is not None:
                plan, uniq_entries, geom, nat, frontier = hit
            else:
                plan = []
                uniq_entries = []
                budget = wb * 2 + 64
                for run in runs:
                    if stop_key and (run.first_key or b"") >= stop_key:
                        continue
                    if start_key and (run.last_key or b"") < start_key:
                        continue
                    for bm, blk in run.iter_blocks(start_key,
                                                   stop_key or None):
                        lo, hi = 0, blk.count
                        if start_key and bm.first_key < start_key:
                            lo = _lower_bound(blk, start_key)
                        if stop_key and bm.last_key >= stop_key:
                            hi = _lower_bound(blk, stop_key)
                        ckey = (run.path, bm.offset)
                        uniq_entries.append((ckey, run, bm, blk))
                        plan.append((ckey, blk, lo, hi))
                        budget -= hi - lo
                        if budget <= 0:
                            break
                    if budget <= 0:
                        break
                # plan geometry + native entry table, computed once per
                # cached plan — the native assembly (page.serve_batch)
                # concatenates these instead of re-resolving per-entry
                # pointer rows and numpy scalar reads every flush
                from pegasus_tpu.server.page import plan_geometry, plan_nat

                geom = plan_geometry(plan)
                nat = plan_nat(plan)
                # the resume frontier past a capped plan's last planned
                # row — plan-pure, so computed once here instead of a
                # per-request key_at on the serving path
                frontier = (_after(plan[-1][1].key_at(
                    plan[-1][1].count - 1)) if plan else None)
                if len(cache) >= 8192:
                    cache.pop(next(iter(cache)))
                cache[pkey] = (plan, uniq_entries, geom, nat, frontier)
            for ckey, run, bm, blk in uniq_entries:
                unique.setdefault(ckey, (run, bm, blk))
            req_plans.append((req, start_key, stop_key, want, plan,
                              geom, nat, frontier))
        if lsm.generation != gen:
            # a compaction published while this batch planned: the runs
            # and overlay above may be from different sides of the swap
            # — serve per-request instead (safe read order)
            return None
        tracer.add_point("plan")
        if ppc is not None:
            ppc.ops += len(reqs)
            ppc.blocks_planned += len(unique)
            ppc.runs_considered += len(runs)
        return {"reqs": reqs, "req_plans": req_plans, "unique": unique,
                "validate": validate, "now": now, "overlay": overlay,
                "filter_key": filter_key, "vf": vf, "pd_list": pdl,
                "t0": t0, "tracer": tracer, "perf": ppc}

    def planned_misses(self, state) -> "OrderedDict[tuple, object]":
        """Unique planned blocks whose STATIC masks are NOT cached (the
        device work remaining); uploads happen here via the block cache.
        Masks are `now`-independent (TTL applies host-side at assembly),
        so a cached block never needs re-evaluation — misses only occur
        on first touch after a flush/compaction or for a new filter.
        Planned misses are noted as HOT so the MaskPrefresher can warm
        sibling flavors ahead of the next scan."""
        keep_masks = {}
        misses: "OrderedDict[tuple, object]" = OrderedDict()
        validate = state["validate"]
        filter_key = state["filter_key"]
        wall = time.monotonic()
        with self._mask_lock:
            self._register_flavor(validate, filter_key, wall)
            for ckey, (run, bm, blk) in state["unique"].items():
                mkey = (ckey, self.partition_version, validate,
                        filter_key)
                cached = self._mask_cache.get(mkey)
                if cached is not None:
                    self._mask_cache.move_to_end(mkey)
                    keep_masks[ckey] = cached
                    continue
                misses[ckey] = (run, bm, blk)
        pv = self.partition_version
        encoded_resolved = []
        for ckey, (run, bm, blk) in list(misses.items()):
            # direct compute on compressed blocks: the static keep
            # (hash validation + hashkey/sortkey filters) evaluates
            # host-side against the ENCODED representation — the
            # hashkey filter once per dictionary entry, the sortkey
            # filter over the packed heap — so a compressed block's
            # first-touch mask costs no device round-trip at all
            keep = self._encoded_static_mask(run, bm, validate,
                                             filter_key, pv)
            if keep is not None:
                keep_masks[ckey] = keep
                encoded_resolved.append((ckey, keep))
                del misses[ckey]
                continue
            misses[ckey] = self._device_cached_block(ckey, blk)
        for ckey, keep in encoded_resolved:
            self.store_mask_for(ckey, validate, filter_key, keep,
                                computed_pv=pv)
        pc = state.get("perf")
        if pc is not None and encoded_resolved:
            # encoded-domain host probes (no decode, no device): the
            # "numpy" compute class; a later device wave overwrites
            pc.placement = "numpy"
        state["cached_keep"] = keep_masks
        return misses

    def _encoded_static_mask(self, run, bm, validate: bool, filter_key,
                             pv: int):
        """bool[n] static keep of one planned block via the encoded
        probe (ops/predicates.encoded_static_keep), or None when the
        run is uncompressed / the block can't take the path."""
        if getattr(run, "codec", None) is None:
            return None
        from pegasus_tpu.ops.predicates import encoded_static_keep

        try:
            enc = run.read_block_encoded(run.block_index(bm))
        except (StorageCorruptionError, OSError):
            # the probe's raw re-read DETECTED on-disk corruption:
            # escalate into the PR 5 quarantine/re-learn loop — falling
            # back to a stale cached decode would serve while hiding a
            # known-corrupt file until the next scrub pass
            raise
        except Exception:  # noqa: BLE001 - run replaced mid-plan: the
            return None    # device path serves from the decoded block
        if enc is None:
            return None
        return encoded_static_keep(enc, validate, self.pidx, pv,
                                   filter_key)

    def _register_flavor(self, validate: bool, filter_key,
                         wall: float) -> None:
        """Remember a scan flavor for background warming (caller holds
        _mask_lock). The no-filter flavor always registers; a FILTERED
        flavor registers once it RECURS within the window — one-shot
        filter patterns must not multiply background device work or
        evict the long-lived warm set. Flavors (not blocks) are
        remembered: compaction replaces the block set, and the warmer's
        job is exactly to re-evaluate the NEW blocks for the flavors
        serving has been using."""
        register = filter_key == _NO_FILTER_KEY
        if not register:
            last = self._filter_seen.get(filter_key)
            register = (last is not None
                        and wall - last <= self._filter_seen_window)
            self._filter_seen[filter_key] = wall
            self._filter_seen.move_to_end(filter_key)
            while len(self._filter_seen) > self._filter_seen_cap:
                self._filter_seen.popitem(last=False)
        if register:
            fl = (validate, filter_key)
            self._warm_flavors[fl] = wall
            self._warm_flavors.move_to_end(fl)
            while len(self._warm_flavors) > self._warm_flavors_cap:
                self._warm_flavors.popitem(last=False)

    def store_mask(self, state, ckey, keep) -> None:
        self.store_mask_for(ckey, state["validate"],
                            state["filter_key"], keep,
                            computed_pv=self.partition_version)

    def _effective_mask_cap(self) -> int:
        """Mask-cache capacity scaled to the data: every current L1 block
        x every warm flavor must fit, or the prefresher and the LRU fight
        forever (warm one mask, evict another still-wanted one) and the
        'each block evaluated once' invariant breaks on large
        partitions."""
        n_blocks = sum(len(run.blocks) for run in self.engine.lsm.l1_runs)
        flavors = max(1, len(self._warm_flavors))
        return max(self._mask_cache_cap, n_blocks * flavors + 256)

    def store_mask_for(self, ckey, validate: bool, filter_key,
                       keep, computed_pv: int) -> None:
        """Publish a static mask under the partition_version it was
        COMPUTED with. The prefresher evaluates on its own thread — if a
        split flipped the version mid-evaluation, publishing under the
        new version would serve pre-split masks (rows now owned by the
        sibling); drop instead."""
        keep = np.asarray(keep)
        if keep.base is not None:
            # slices of stacked multi-flavor eval outputs would pin the
            # whole [K, S*cap] base array per ~1KB cache entry
            keep = keep.copy()
        cap = self._effective_mask_cap()
        with self._mask_lock:
            if computed_pv != self.partition_version:
                return
            self._mask_cache[(ckey, computed_pv, validate,
                              filter_key)] = keep
            while len(self._mask_cache) > cap:
                self._mask_cache.popitem(last=False)

    WARM_BATCH_LIMIT = 256  # blocks loaded per warm pass (bounds IO)

    def hot_block_entries(self, wall: float, horizon_s: float):
        """(ckey, block, validate, filter_key) for CURRENT L1 blocks
        missing a static mask for a recently-used scan flavor — the
        MaskPrefresher's work list. After a flush/compaction replaces
        the SSTs, this is how the new blocks get their masks evaluated
        in the background before the next scan pays the device
        round-trip. Prunes flavors idle past the horizon."""
        with self._mask_lock:
            flavors = []
            for fl in list(self._warm_flavors):
                if wall - self._warm_flavors[fl] > horizon_s:
                    del self._warm_flavors[fl]
                    continue
                flavors.append(fl)
        if not flavors:
            return []
        # cache probing runs WITHOUT the lock (GIL-atomic dict gets; a
        # racing store just makes this pass warm one mask twice) so the
        # serving path never stalls behind a full-data-size iteration
        pv = self.partition_version
        cache_get = self._mask_cache.get
        missing = []
        for run in list(self.engine.lsm.l1_runs):
            for i, bm in enumerate(run.blocks):
                ckey = (run.path, bm.offset)
                for validate, filter_key in flavors:
                    if cache_get((ckey, pv, validate,
                                  filter_key)) is None:
                        missing.append((run, i, ckey, validate,
                                        filter_key))
                        if len(missing) >= self.WARM_BATCH_LIMIT:
                            break
                if len(missing) >= self.WARM_BATCH_LIMIT:
                    break
            if len(missing) >= self.WARM_BATCH_LIMIT:
                break
        # block loads (disk IO) also happen outside the lock
        out = []
        for run, i, ckey, validate, filter_key in missing:
            try:
                blk = run.read_block(i)
            except Exception:  # noqa: BLE001 - run replaced mid-pass
                continue
            out.append((ckey, blk, validate, filter_key))
        return out

    def eval_planned_masks(self, state):
        """Phase 2 (solo-node form): evaluate this partition's misses.
        Runs under the state's PerfContext so the stacked device eval
        records its placement verdict + predicted/measured kernel time
        on the flush's cost vector."""
        from pegasus_tpu.utils import perf_context as perf

        with perf.activate(state.get("perf")):
            misses = self.planned_misses(state)
            keep_masks = state["cached_keep"]
            for ckey, keep in self._eval_blocks_stacked(
                    misses, state["filter_key"], state["validate"]):
                keep_masks[ckey] = keep
                self.store_mask(state, ckey, keep)
        tracer = state.get("tracer")
        if tracer is not None:
            tracer.add_point("block_probe")
        return keep_masks

    def prepare_serve(self, state, keep_masks) -> list:
        """Phase 2.5: combine static keep with host TTL per unique
        block, compute each request's overlay window + plan frontier,
        and return the batch's fast-path (overlay-free) request windows
        `(plan, want, no_value, want_ets, live_masks, geom)` for
        native assembly (page.serve_batch's req_windows shape). The
        node-level coordinator concatenates these ACROSS partitions so
        one native call (page.serve_batch) packs every fast request of
        a whole flush. Everything is stashed in `state`; idempotent."""
        if "precomputed" in state or "windows" in state:
            return state.get("fast", [])
        import bisect as _bisect

        unique = state["unique"]
        now = state["now"]
        vf = state.get("vf")
        live_masks = {}
        live_ptrs = {}
        alive_all = {}
        exp_full = {}
        pushdown_pruned = 0
        cache = self._live_cache
        for ckey, (_run, _bm, blk) in unique.items():
            ets = blk.expire_ts
            static = keep_masks[ckey]
            # (block, flavor-mask, value-filter, second) live-mask
            # cache: TTL validity is one second, so every batch within
            # the second reuses the same static AND alive (AND value
            # mask) result instead of recomputing it — zipfian traffic
            # hits the same hot blocks thousands of times per second
            lkey = (ckey, id(static), vf)
            hit = cache.get(lkey)
            # the entry pins the static array it was built from (id()
            # alone could be a recycled address after a mask evict)
            if hit is not None and hit[0] == now and hit[1] is static:
                _now, _st, alive, exp, live, lptr, prn = hit
                alive_all[ckey] = alive
                exp_full[ckey] = exp
                live_masks[ckey] = live
                live_ptrs[ckey] = lptr
                pushdown_pruned += prn
                continue
            alive = blk.alive_mask(now)
            alive_all[ckey] = alive
            # whole-block expired count once per unique block; requests
            # spanning the full block (the common case) reuse the
            # scalar, boundary slices recount
            exp = len(alive) - int(np.count_nonzero(alive))
            exp_full[ckey] = exp
            live = static[:len(ets)] & alive
            prn = 0
            if vf is not None:
                # the shared pushdown value filter joins the live mask
                # (cached per block+pattern in _value_mask); pruned =
                # key-alive rows the VALUE predicate dropped
                before = int(np.count_nonzero(live))
                live = live & self._value_mask(ckey, blk,
                                               vf)[:len(ets)]
                prn = before - int(np.count_nonzero(live))
            pushdown_pruned += prn
            live_masks[ckey] = live
            # .ctypes.data costs ~a µs: resolve once per (block, flavor,
            # second), not once per request window (page.serve_batch
            # consumes these as the per-entry mask pointers)
            lptr = live.ctypes.data
            live_ptrs[ckey] = lptr
            if len(cache) >= 4096:
                cache.pop(next(iter(cache)))
            cache[lkey] = (now, static, alive, exp, live, lptr, prn)
        state["pushdown_pruned"] = pushdown_pruned
        overlay_keys, _overlay_map = state["overlay"]
        windows = []
        fast = []
        for req, start_key, stop_key, want, plan, geom, nat, pfrontier \
                in state["req_plans"]:
            capped = bool(plan) and geom[0] >= want * 2 + 64
            frontier = pfrontier if capped else None
            ov_lo = (_bisect.bisect_left(overlay_keys, start_key)
                     if start_key else 0)
            ov_hi = len(overlay_keys)
            if stop_key:
                ov_hi = _bisect.bisect_left(overlay_keys, stop_key,
                                            ov_lo)
            if frontier is not None:
                ov_hi = _bisect.bisect_left(overlay_keys, frontier,
                                            ov_lo, ov_hi)
            windows.append((capped, frontier, ov_lo, ov_hi))
            if ov_lo >= ov_hi:
                fast.append((plan, want, req.no_value,
                             req.return_expire_ts, live_masks, geom,
                             nat, live_ptrs))
        state["live_masks"] = live_masks
        state["alive_all"] = alive_all
        state["exp_full"] = exp_full
        state["windows"] = windows
        state["fast"] = fast
        tracer = state.get("tracer")
        if tracer is not None:
            if vf is not None:
                tracer.add_point("pushdown")
            tracer.add_point("decode")
        return fast

    def finish_scan_batch(self, state, keep_masks, served=None
                          ) -> List[ScanResponse]:
        """Phase 3: assemble responses from (shared) STATIC masks.

        TTL expiry is applied here, host-side: one vectorized AND of the
        static mask with the block's expire_ts column per unique block
        (`now` is the batch's single clock reading). This is the other
        half of the static/dynamic predicate split — the device never
        re-evaluates a block just because the clock ticked.

        `served`: this batch's slice of the coordinator's cross-
        partition native assembly (aligned with prepare_serve's fast
        list); None = run the native assembly here (solo callers)."""
        if "precomputed" in state:
            return state["precomputed"]
        reqs = state["reqs"]
        req_plans = state["req_plans"]
        unique = state["unique"]
        now = state["now"]
        t0 = state["t0"]

        from pegasus_tpu.server.page import build_page, serve_batch

        fast = self.prepare_serve(state, keep_masks)
        live_masks = state["live_masks"]
        alive_all = state["alive_all"]
        exp_full = state["exp_full"]
        windows = state["windows"]
        overlay_keys, overlay_map = state["overlay"]
        hdr = header_length(self.data_version)
        if served is None and fast:
            served = serve_batch(fast, None, SCAN_BYTES_CAP, hdr)
        served_iter = iter(served) if served is not None else None

        # per-(plan, second) expired-count cache: the count is flavor-
        # independent (alive depends only on block + now) and plans are
        # cached objects, so zipfian repeats of a popular scan within
        # one second skip the per-entry accounting loop entirely. The
        # plan object is pinned in the value so its id() cannot be
        # recycled while the entry lives; the whole dict resets each
        # second / generation, so nothing outlives the blocks it counts.
        ptag = (self.engine.lsm.generation, now)
        if self._plan_expired_cache[0] != ptag:
            self._plan_expired_cache = (ptag, {})
        pec = self._plan_expired_cache[1]
        total_expired = 0
        total_read_cu = 0
        total_rows = 0
        total_bytes = 0

        out = []
        for (req, start_key, stop_key, want, plan, _geom, _nat, _pf), \
                (capped, frontier, ov_lo, ov_hi) in zip(req_plans,
                                                        windows):
            kvs: list = []
            size = 0
            exhausted = True
            resume_key = None
            stop_early = False
            want_ets = req.return_expire_ts
            no_value = req.no_value

            def base_rows(plan=plan):
                for ckey, blk, lo, hi in plan:
                    keep = live_masks[ckey]
                    for i in np.flatnonzero(keep[lo:hi]):
                        idx = lo + int(i)
                        yield blk.key_at(idx), blk, idx

            hit = pec.get(id(plan))
            if hit is not None:
                req_expired = hit[1]
            else:
                req_expired = 0
                for ckey, blk_, lo, hi in plan:
                    # per-REQUEST expired accounting (the solo path
                    # counts per request served, not per block evaluated)
                    if lo == 0 and hi == blk_.count:
                        req_expired += exp_full[ckey]
                    else:
                        req_expired += int(np.count_nonzero(
                            ~alive_all[ckey][lo:hi]))
                pec[id(plan)] = (plan, req_expired)
            ov_i = ov_lo
            if ov_lo >= ov_hi:
                # fast path: no overlay rows shadow this window, so the
                # kept base rows ARE the answer — already assembled by
                # the batch native call (page.serve_batch -> packer.cpp
                # pegasus_scan_serve_batch); the vectorized-numpy path
                # below is the no-toolchain / arena-overflow fallback.
                served = (next(served_iter) if served_iter is not None
                          else None)
                if served is not None:
                    kvs, size, last_key, truncated = served
                    taken = len(kvs)
                    if ((taken >= want or truncated)
                            and last_key is not None):
                        resume_key = _after(last_key)
                        stop_early = True
                    chunks = None
                else:
                    chunks = []
            else:
                chunks = None
            if chunks is not None:
                taken = 0
                byte_est = 0
                truncated = False
                for ckey, blk, lo, hi in plan:
                    hit = np.flatnonzero(live_masks[ckey][lo:hi])
                    if hit.size > want - taken:
                        hit = hit[:want - taken]
                    if not hit.size:
                        continue
                    hit = hit + lo
                    # byte budget (keys + value-heap span upper bound):
                    # page blob offsets are uint32 and one RPC response
                    # must stay bounded whatever the values weigh. A
                    # keys-only scan serializes no values, so only key
                    # bytes count — else large-value blocks force
                    # needless pagination.
                    vo = blk.value_offs
                    chunk_bytes = int(hit.size) * blk.keys.shape[1]
                    if not no_value:
                        chunk_bytes += (int(vo[int(hit[-1]) + 1])
                                        - int(vo[int(hit[0])]))
                    if byte_est + chunk_bytes > SCAN_BYTES_CAP:
                        if byte_est == 0:
                            # a single oversized chunk: binary-search the
                            # row prefix that fits (per-row byte cumsum
                            # only for this rare path)
                            row_bytes = np.full(hit.size,
                                                blk.keys.shape[1],
                                                dtype=np.int64)
                            if not no_value:
                                row_bytes += (vo[hit + 1].astype(np.int64)
                                              - vo[hit].astype(np.int64))
                            fit = int(np.searchsorted(
                                np.cumsum(row_bytes), SCAN_BYTES_CAP,
                                side="right"))
                            hit = hit[:max(1, fit)]
                            chunks.append((blk, hit))
                            taken += int(hit.size)
                        truncated = True
                        break
                    byte_est += chunk_bytes
                    chunks.append((blk, hit))
                    taken += int(hit.size)
                    if taken >= want:
                        break
                kvs, size, last_key = build_page(
                    chunks, hdr, no_value=no_value, want_ets=want_ets)
                if (taken >= want or truncated) and last_key is not None:
                    resume_key = _after(last_key)
                    stop_early = True
            elif ov_lo < ov_hi:
                # merge path: interleave overlay rows in key order
                # (overlay rows SHADOW base rows: newest wins,
                # tombstones hide)
                base = base_rows()
                base_item = next(base, None)
                while len(kvs) < want:
                    ov_key = overlay_keys[ov_i] if ov_i < ov_hi else None
                    if base_item is None and ov_key is None:
                        break
                    take_overlay = (ov_key is not None
                                    and (base_item is None
                                         or ov_key <= base_item[0]))
                    if take_overlay:
                        if base_item is not None and ov_key == base_item[0]:
                            base_item = next(base, None)  # shadowed
                        ov_i += 1
                        entry = overlay_map[ov_key]
                        if entry is None:
                            continue  # tombstone / hidden overlay row
                        data = b"" if no_value else entry[0]
                        kv = KeyValue(ov_key, data)
                        if want_ets:
                            kv.expire_ts_seconds = entry[1]
                        key = ov_key
                    else:
                        key, blk, idx = base_item
                        base_item = next(base, None)
                        data = (b"" if no_value
                                else extract_user_data(self.data_version,
                                                       blk.value_at(idx)))
                        kv = KeyValue(key, data)
                        if want_ets:
                            kv.expire_ts_seconds = int(blk.expire_ts[idx])
                    kvs.append(kv)
                    size += len(key) + len(data)
                    if len(kvs) >= want or size >= SCAN_BYTES_CAP:
                        resume_key = _after(key)
                        stop_early = True
                        break
            if stop_early:
                exhausted = False
            elif capped:
                resume_key = frontier
                exhausted = False
            total_expired += req_expired
            total_rows += len(kvs)
            total_bytes += size
            # per-request CU floor preserved: units() per request,
            # summed, one counter touch per batch
            total_read_cu += cu_units(size)
            resp = ScanResponse()
            resp.kvs = kvs
            # pd_list aligns with reqs/req_plans order; len(out) is the
            # current request's index (appends happen once per loop)
            pd_list = state.get("pd_list")
            resp.pushdown_applied = bool(pd_list
                                         and pd_list[len(out)]
                                         is not None)
            resp.error = int(StorageStatus.OK)
            if exhausted or req.one_page:
                resp.context_id = SCAN_CONTEXT_ID_COMPLETED
            else:
                resp.context_id = self._scan_cache.put(ScanContext(
                    request=req, resume_key=resume_key or start_key,
                    stop_key=stop_key))
            out.append(resp)
        # batch-accumulated accounting: one metrics/capacity call per
        # state, not per request (identical totals)
        if total_expired:
            self._abnormal_reads.increment(total_expired)
        self.cu.add_read_units(total_read_cu)
        # mask-evaluated rows = every row of every unique planned block
        # (the kernels see whole blocks); survivors vs evaluated is the
        # table's scan SELECTIVITY — what a server-side pushdown saves
        rows_eval = sum(b.count for _r, _bm, b in unique.values())
        self.workload.note_scan(len(reqs), rows_eval, total_rows)
        pd_pruned = state.get("pushdown_pruned", 0)
        n_pushdown = sum(1 for pd in state.get("pd_list") or ()
                         if pd is not None)
        if n_pushdown:
            self.workload.note_pushdown(n_pushdown, pd_pruned, 0)
        pc = state.get("perf")
        if pc is not None:
            pc.rows_evaluated += rows_eval
            pc.rows_survived += total_rows
            pc.expired_rows += total_expired
            pc.bytes_returned += total_bytes
            pc.keys_resolved += total_rows
            pc.pushdown_rows_pruned += pd_pruned
            sp = (state["tracer"].span
                  if state.get("tracer") is not None else None)
            if sp is not None:
                from pegasus_tpu.utils import perf_context as perf

                perf.merge_span_perf(sp.tags, pc)
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        self._read_latency.set(elapsed_ms)
        tracer = state.get("tracer")
        if tracer is not None:
            tracer.add_point("finish")
            self.slow_log.observe(
                tracer,
                {"scans": len(reqs), "unique_blocks": len(unique)})
        else:
            self.slow_log.observe_simple(
                self._scan_log_key, elapsed_ms,
                {"scans": len(reqs), "unique_blocks": len(unique)})
        return out

    # overlay rows tolerated on the batched device path before falling
    # back to per-request merged serving
    OVERLAY_MERGE_LIMIT = 4096

    def _overlay_snapshot(self, now: int, validate: bool,
                          filter_key=None, value_filter=None):
        """(sorted_keys, key -> None|(user_data, ets)) for the memtable +
        L0 overlay, newest-wins, with the scan predicates (TTL, stale-
        split hash, and the batch's shared key filter) evaluated
        HOST-side — the overlay is tiny by the fast-path qualifier, so a
        device dispatch would cost more than it filters. A key failing
        the KEY filter is excluded entirely (its base copies fail the
        same filter in the device mask, so nothing needs shadowing); a
        row failing the pushdown VALUE filter must instead stay as a
        hidden SHADOW (None) — the base may hold an older value for the
        same key that would pass, and newest-wins must still hide it."""
        from pegasus_tpu.base.key_schema import check_key_hash, restore_key
        from pegasus_tpu.ops.predicates import host_match_filter
        from pegasus_tpu.storage.memtable import TOMBSTONE

        hft, hfp, sft, sfp = filter_key or (FT_NO_FILTER, b"",
                                            FT_NO_FILTER, b"")

        lsm = self.engine.lsm
        merged: dict = {}
        for key, value, ets in lsm.memtable.items_sorted():
            merged[key] = (None if value is TOMBSTONE
                           else (value, ets))
        for table in lsm.l0:  # newest first; first writer wins
            for key, value, ets in table.iterate():
                if key not in merged:
                    merged[key] = (None if value is None
                                   else (value, ets))
        out: dict = {}
        for key in sorted(merged):
            if hft != FT_NO_FILTER or sft != FT_NO_FILTER:
                hk, sk = restore_key(key)
                if not (host_match_filter(hk, hft, hfp)
                        and host_match_filter(sk, sft, sfp)):
                    continue  # fails the batch filter everywhere
            entry = merged[key]
            if entry is None:
                out[key] = None  # tombstone: shadows the base
                continue
            value, ets = entry
            if check_if_ts_expired(now, ets):
                self._abnormal_reads.increment()
                out[key] = None  # expired: hidden AND shadows the base
                continue
            if validate and not check_key_hash(key, self.pidx,
                                               self.partition_version):
                out[key] = None
                continue
            data = extract_user_data(self.data_version, value)
            if value_filter is not None and not host_match_filter(
                    data, value_filter[0], value_filter[1]):
                out[key] = None  # value-rejected: hidden, still shadows
                continue
            out[key] = (data, ets)
        return list(out), out  # insertion order is already sorted

    def _eval_blocks_stacked(self, misses, filter_key, validate):
        """Evaluate MANY blocks' static predicates in as few device
        dispatches as possible via the shared stacker (scan_coordinator):
        blocks sharing (width, cap) become one [B*cap, W] program —
        records are independent, so block boundaries carry no meaning
        there."""
        from pegasus_tpu.server.scan_coordinator import stacked_block_eval

        blocks = [(ckey, dev, self.pidx) for ckey, dev in misses.items()]
        yield from stacked_block_eval(blocks, validate,
                                      self.partition_version,
                                      filter_key=filter_key)

    def _static_keep_window(self, window, validate: bool,
                            hash_filter: FilterSpec,
                            sort_filter: FilterSpec,
                            filter_key) -> list:
        """Cached static keep masks for a window of blocks (solo-path
        form): filter match + partition-hash validation,
        `now`-independent. Window misses are evaluated in ONE stacked
        device wave — one round-trip per window instead of per block —
        and cached for every later scan to combine with TTL host-side.
        `window`: [(ckey, blk, lo, hi)]; returns masks aligned to it."""
        from pegasus_tpu.server.scan_coordinator import stacked_block_eval

        pv = self.partition_version
        keeps: list = [None] * len(window)
        misses = []
        with self._mask_lock:
            for j, (ckey, blk, _lo, _hi) in enumerate(window):
                mkey = (ckey, pv, validate, filter_key)
                cached = self._mask_cache.get(mkey)
                if cached is not None:
                    self._mask_cache.move_to_end(mkey)
                    keeps[j] = cached
                else:
                    misses.append((j, ckey, blk))
        if misses:
            blocks = [((j, ckey), self._device_cached_block(ckey, blk),
                       self.pidx) for j, ckey, blk in misses]
            for (j, ckey), keep in stacked_block_eval(
                    blocks, validate, pv, filter_key=filter_key):
                keep = np.asarray(keep)
                keeps[j] = keep
                self.store_mask_for(ckey, validate, filter_key, keep,
                                    computed_pv=pv)
        return keeps

    def _device_cached_block(self, cache_key, blk):
        """The shared device-upload cache used by both scan paths."""
        import jax.numpy as jnp

        from pegasus_tpu.ops.record_block import RecordBlock, block_from_columns
        from pegasus_tpu.storage.sstable import BLOCK_CAPACITY

        with self._mask_lock:
            dev_block = self._device_block_cache.get(cache_key)
            if dev_block is not None:
                self._device_block_cache.move_to_end(cache_key)
                return dev_block
        # upload outside the lock (serving and the prefresher may race
        # to a duplicate upload of the same block — harmless, last wins)
        n = blk.count
        cap = max(BLOCK_CAPACITY, n)
        nb = block_from_columns(blk.keys, blk.key_len, blk.expire_ts,
                                hash_lo=blk.hash_lo)
        pad = cap - n
        dev_block = RecordBlock(
            jnp.asarray(np.pad(nb.keys, ((0, pad), (0, 0)))),
            jnp.asarray(np.pad(nb.key_len, (0, pad))),
            jnp.asarray(np.pad(nb.hashkey_len, (0, pad))),
            jnp.asarray(np.pad(nb.expire_ts, (0, pad))),
            jnp.asarray(np.pad(nb.valid, (0, pad))),
            None if nb.hash_lo is None
            else jnp.asarray(np.pad(nb.hash_lo, (0, pad))))
        with self._mask_lock:
            self._device_block_cache[cache_key] = dev_block
            if len(self._device_block_cache) > self._device_block_cache_cap:
                self._device_block_cache.popitem(last=False)
        return dev_block

    # ---- maintenance --------------------------------------------------

    def flush(self) -> bool:
        with self._write_lock:
            return self.engine.flush()

    def checkpoint(self, dest_dir: str) -> int:
        """Frozen snapshot under the single-writer lock — checkpoint
        starts with a memtable flush and walks the run set, which must
        not interleave with the async env-compaction thread's publish
        (backup / learning / split all snapshot through here)."""
        with self._write_lock:
            return self.engine.checkpoint(dest_dir)

    def update_partition_count(self, new_count: int) -> None:
        """Partition-count flip after a split (parity: the group
        partition-count update in replica_split_manager.h:76-123): routing
        and the stale-key predicate switch to the new count; stale-half
        records are filtered from every scan immediately and physically
        dropped by the next manual compaction."""
        if new_count < self.partition_count:
            raise ValueError("partition count can only grow")
        self.partition_count = new_count
        self.partition_version = new_count - 1
        self.validate_partition_hash = (
            new_count > 1 and (new_count & (new_count - 1)) == 0)
        # cached masks were computed under the old partition_version; the
        # predicate takes pv dynamically so caches stay valid, but fused
        # prepared tensors embed nothing version-dependent either — keep.
        # The ROW/plan/point/live caches, by contrast, hold ROWS resolved
        # under the pre-flip routing: the hash gate keeps misrouted
        # requests off them, but half this partition's key range just
        # moved to the child — drop parent entries eagerly so no code
        # path (present or future) can observe a stale parent row, and
        # so dead-half rows stop occupying the node-shared byte cap.
        self._live_cache = {}
        self._plan_cache = None
        self._point_cache = None
        self._plan_expired_cache = (None, {})
        ROW_CACHE.invalidate_gid((self.app_id, self.pidx))

    def manual_compact(self, default_ttl: Optional[int] = None,
                       rules_filter=None,
                       now: Optional[int] = None) -> None:
        """Parity: pegasus_manual_compact_service (manual CompactRange).
        Defaults come from the table's app-envs (`default_ttl`,
        `user_specified_compaction`) unless overridden.

        `now` pins the filter timestamp (defaults to epoch_now() inside
        the engine). A table-wide trigger passes one shared timestamp
        so every sibling partition filters under IDENTICAL params —
        deterministic outputs, and the mesh-resident filter stage
        (parallel/mesh_resident.py) computes the whole table's drop
        masks in ONE dispatch that the siblings' compactions then read
        from cache.

        The writer critical section is NARROW: the overlay is frozen
        with one flush under _write_lock, the multi-second merge runs
        from that immutable snapshot with writes flowing, and
        _write_lock is retaken only for the publish cut-over (with
        lsm run-set revalidation inside _publish_l1) — so a write
        arriving mid-compaction no longer wedges transport dispatch
        and FD beacons for the whole merge. engine.compact_lock
        serializes compactions; the write path's auto-compaction
        skips its trigger while this runs (the manual run covers it).
        Cache eviction for the superseded runs happens through the
        store's publish hook (_on_store_publish)."""
        if default_ttl is None:
            default_ttl = self._default_ttl
        if rules_filter is None:
            rules_filter = self._compaction_rules
        with self.engine.compact_lock:
            with self._write_lock:
                # freeze the overlay: post-freeze writes land in the
                # fresh memtable / newer L0s, which the publish leaves
                # untouched (they keep shadowing the merged base)
                self.engine.flush()
            self.engine.manual_compact(
                default_ttl=default_ttl, pidx=self.pidx,
                partition_version=self.partition_version,
                validate_hash=self.validate_partition_hash,
                rules_filter=rules_filter, now=now,
                publish_lock=self._write_lock)
