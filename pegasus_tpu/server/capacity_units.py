"""Capacity-unit metering.

Parity: src/server/capacity_unit_calculator.h:50 — every request bills
read/write capacity units: 1 CU per started 4KB of key+value bytes
(min 1 per request), accumulated into per-partition counters.
"""

from __future__ import annotations

from pegasus_tpu.utils.metrics import MetricEntity

CU_SIZE = 4096


def units(size: int) -> int:
    """CU for ONE request of `size` bytes (min 1 — the per-request
    floor the reference bills, capacity_unit_calculator.h:50)."""
    return max(1, (size + CU_SIZE - 1) // CU_SIZE)


def client_write_units(raw_ops) -> int:
    """CU for one client write's wire ops [(op_code, request)], the
    SAME per-op math replica._apply_mutation bills at apply time. Used
    by the stub's write handlers to debit the requesting tenant ONCE
    at the primary (apply runs in later dispatches on every member,
    where no client tenant is ambient — and billing each member's
    apply would charge a tenant its own replication factor)."""
    from pegasus_tpu.rpc.codec import (
        OP_INCR,
        OP_MULTI_PUT,
        OP_MULTI_REMOVE,
        OP_PUT,
        OP_REMOVE,
    )

    cu = 0
    for op, req in raw_ops:
        if op == OP_PUT:
            cu += units(len(req[0]) + len(req[1]))
        elif op == OP_REMOVE:
            cu += units(len(req[0]))
        elif op == OP_MULTI_PUT:
            cu += units(len(req.hash_key) + sum(
                len(kv.key) + len(kv.value) for kv in req.kvs))
        elif op == OP_MULTI_REMOVE:
            cu += units(len(req.hash_key) + sum(
                len(sk) for sk in req.sort_keys))
        elif op == OP_INCR:
            cu += units(len(req.key))
        # CAS/CAM/ingest: unbilled at apply too — parity preserved
    return cu


class CapacityUnitCalculator:
    """Per-partition CU counters + the per-tenant budget feed: every
    billed unit ALSO debits the thread's ambient tenant (server/
    tenancy.py post-debit buckets), so the multi-tenant governor rides
    the exact accounting the reference already does — one funnel, two
    ledgers."""

    def __init__(self, entity: MetricEntity) -> None:
        self._read_cu = entity.counter("recent_read_cu")
        self._write_cu = entity.counter("recent_write_cu")
        from pegasus_tpu.server.tenancy import TENANTS

        self._tenants = TENANTS

    def add_read(self, size: int) -> None:
        cu = units(size)
        self._read_cu.increment(cu)
        self._tenants.charge_ambient(cu)

    def add_read_units(self, cu: int) -> None:
        """Batch accounting: the caller pre-summed units(size) per
        request (hot scan path — one counter touch per batch)."""
        if cu:
            self._read_cu.increment(cu)
            self._tenants.charge_ambient(cu)

    def add_write(self, size: int) -> None:
        cu = units(size)
        self._write_cu.increment(cu)
        self._tenants.charge_ambient(cu)

    def add_write_units(self, cu: int) -> None:
        """Batch accounting: the caller pre-summed units(size) per
        request (mutation apply — one counter touch per mutation)."""
        if cu:
            self._write_cu.increment(cu)
            self._tenants.charge_ambient(cu)

    @property
    def read_cu(self) -> int:
        return self._read_cu.value()

    @property
    def write_cu(self) -> int:
        return self._write_cu.value()
